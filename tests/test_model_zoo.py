"""Vision model zoo smoke tests (mirrors reference
tests/python/unittest/test_gluon_model_zoo.py: construct + tiny forward).

Full 224x224 forwards for every model would dominate CI time; each family
is exercised once at full size and once per variant at construction level.
"""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.gluon.model_zoo import vision

ALL_MODELS = sorted(vision._models)


def test_get_model_unknown_raises():
    with pytest.raises(mx.base.MXNetError):
        vision.get_model("resnet9999_v9")


def test_pretrained_gated():
    with pytest.raises(mx.base.MXNetError):
        vision.get_model("resnet18_v1", pretrained=True)


@pytest.mark.parametrize("name", ALL_MODELS)
def test_construct_all(name):
    net = vision.get_model(name, classes=7)
    assert net is not None


@pytest.mark.parametrize("name,size", [
    # the two heaviest variants (12-15s each, round-10 --durations
    # profile) run in ci stage_unit only; tier-1 keeps one model per
    # family (resnet18 also covered by test_resnet18_hybridize_and_grad)
    pytest.param("resnet18_v1", 32, marks=pytest.mark.slow),
    # round-11 budget profile: resnet50_v2 was the heaviest remaining
    # non-slow zoo forward (15 s); bottleneck blocks are still covered
    # here by mobilenet/squeezenet and by resnet18 hybridize+grad
    pytest.param("resnet50_v2", 32, marks=pytest.mark.slow),
    # round-11: mobilenet0.25 (10 s) joins v2 in stage_unit-only;
    # squeezenet + vgg11 keep zoo forwards in tier-1
    pytest.param("mobilenet0.25", 32, marks=pytest.mark.slow),
    pytest.param("mobilenetv2_0.25", 32, marks=pytest.mark.slow),
    ("squeezenet1.1", 64),
])
def test_forward_small(name, size):
    net = vision.get_model(name, classes=5)
    net.initialize()
    x = nd.random.uniform(shape=(2, 3, size, size))
    out = net(x)
    assert out.shape == (2, 5)
    assert np.isfinite(out.asnumpy()).all()


@pytest.mark.slow   # 12s (round-21 tier-1 budget repair); ci
def test_resnet18_hybridize_and_grad():  # stage_unit still runs it
    net = vision.get_model("resnet18_v1", classes=4)
    net.initialize()
    net.hybridize()
    x = nd.random.uniform(shape=(2, 3, 32, 32))
    from incubator_mxnet_tpu import autograd
    with autograd.record():
        out = net(x)
        loss = (out ** 2).sum()
    loss.backward()
    grads = [p.grad() for _, p in net.collect_params().items()
             if p.grad_req != "null"]
    assert all(np.isfinite(g.asnumpy()).all() for g in grads)
    total = sum(float(np.abs(g.asnumpy()).sum()) for g in grads)
    assert total > 0


@pytest.mark.slow   # 10s (round-21 tier-1 budget repair, like its
def test_vgg11_forward_224():  # densenet sibling); ci stage_unit
    # still runs it every time
    net = vision.get_model("vgg11", classes=3)
    net.initialize()
    out = net(nd.random.uniform(shape=(1, 3, 224, 224)))
    assert out.shape == (1, 3)


@pytest.mark.slow
def test_densenet121_forward_224():
    net = vision.get_model("densenet121", classes=3)
    net.initialize()
    out = net(nd.random.uniform(shape=(1, 3, 224, 224)))
    assert out.shape == (1, 3)


def test_alexnet_forward_224():
    net = vision.get_model("alexnet", classes=3)
    net.initialize()
    out = net(nd.random.uniform(shape=(1, 3, 224, 224)))
    assert out.shape == (1, 3)


@pytest.mark.slow
def test_inception_forward_299():
    net = vision.get_model("inceptionv3", classes=3)
    net.initialize()
    out = net(nd.random.uniform(shape=(1, 3, 299, 299)))
    assert out.shape == (1, 3)
