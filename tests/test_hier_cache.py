"""Hierarchical KV-cache tier tests (serve/paged_kv.py KVTierStore +
serve/engine.py demote/promote plumbing).

The load-bearing claims: (1) re-admission by COPY is bit-identical to
both an always-resident cache and a full recompute — quantized and
unquantized pools, greedy and seeded-temperature sampling; (2) the
page-state contract survives churn: every page is free XOR live XOR
demoted at EVERY step (``audit_pages`` + ``KVTierStore.audit``); (3)
a corrupted demoted payload is convicted by crc at promotion and the
admission falls back to recompute LOUDLY — never a garbage token; (4)
a full/failing disk degrades the tier to a loud no-op, not an outage;
(5) the cascade drop demotes published full-page descendants instead
of deleting them (the silent-work-loss regression); (6) the jit-once
contract extends to the tiers: ONE promotion program, ONE demotion
gather program, decode/prefill untouched."""

import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.events import EventType, FlightRecorder
from incubator_mxnet_tpu.models import gpt as g
from incubator_mxnet_tpu.serve import (InferenceEngine, Request, Router)
from incubator_mxnet_tpu.serve.paged_kv import KVTierStore

VOCAB = 64
PS = 8


@pytest.fixture(scope="module")
def model():
    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=VOCAB, max_length=64)
    m.initialize()
    return m


def _personas(n, pages=3, seed=7):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, size=(pages * PS,)).astype(np.int32)
            for _ in range(n)]


# LRU-hostile revisit order over a pool that holds ~one persona:
# every revisit finds its prefix evicted from HBM
_ORDER = [0, 1, 2, 0, 1, 2, 3, 4, 5, 0, 1, 2]


def _tiered(model, tmp_path, dram_bytes=1 << 20, disk=True, **kw):
    tiers = {"dram_bytes": dram_bytes}
    if disk:
        tiers["disk_dir"] = os.path.join(str(tmp_path), "tiers")
        tiers["disk_bytes"] = 1 << 30
    return InferenceEngine(model, num_slots=1, page_size=PS,
                           num_pages=kw.pop("num_pages", 7),
                           max_len=64, prefix_cache=True,
                           kv_tiers=tiers, **kw)


def _flat(model, num_pages=7, **kw):
    return InferenceEngine(model, num_slots=1, page_size=PS,
                           num_pages=num_pages, max_len=64,
                           prefix_cache=True, **kw)


def _drive(eng, heads, order=_ORDER, temperature=0.0, audit=False,
           tail_seed=11, seed_base=None):
    """One run() per visit (solo slot): deterministic admission order,
    so LRU eviction and tier traffic replay identically on every
    engine. Returns the per-visit token streams."""
    srng = np.random.RandomState(tail_seed)
    toks = []
    for i, p in enumerate(order):
        tail = srng.randint(0, VOCAB, size=(5,)).astype(np.int32)
        req = Request(np.concatenate([heads[p], tail]),
                      max_new_tokens=4, temperature=temperature,
                      seed=(None if seed_base is None
                            else seed_base + i))
        eng.run([req], poll_sleep=1e-4)
        assert req.outcome is not None and req.outcome.ok
        if audit:
            eng.audit_pages()
        toks.append(list(req.token_ids))
    return toks


# --------------------------------------------------------------------- #
# promotion parity — the headline correctness claim
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("kv_quant,temperature",
                         [(None, 0.0), (None, 0.8),
                          ("int8", 0.0), ("int8", 0.8)],
                         ids=["f32-greedy", "f32-temp",
                              "int8-greedy", "int8-temp"])
def test_promotion_parity(model, tmp_path, kv_quant, temperature):
    """Tiered serving vs TWO oracles over the same LRU-hostile
    workload: an always-resident cache (pool big enough that nothing
    is ever evicted) and a full recompute (same small pool, no tiers).
    All three token streams must be IDENTICAL — a promoted page is the
    page, not an approximation of it."""
    kw = {} if kv_quant is None else {"kv_quant": kv_quant}
    seed_base = None if temperature == 0.0 else 1000
    heads = _personas(6)

    tiered = _tiered(model, tmp_path, **kw)
    got = _drive(tiered, heads, temperature=temperature,
                 seed_base=seed_base)
    resident = _flat(model, num_pages=32, **kw)
    want_resident = _drive(resident, heads, temperature=temperature,
                           seed_base=seed_base)
    recompute = _flat(model, **kw)
    want_recompute = _drive(recompute, heads, temperature=temperature,
                            seed_base=seed_base)

    assert got == want_resident
    assert got == want_recompute
    # the tiers actually cycled (otherwise this test proves nothing)
    assert tiered.tier_demotions > 0
    assert tiered.tier_promotions > 0
    assert tiered.tier_hit_tokens >= tiered.tier_promotions * PS
    # jit-once: one promotion program, one gather program, decode and
    # prefill untouched by all the tier traffic
    assert tiered.promote_trace_count == 1
    assert tiered.demote_trace_count == 1
    assert tiered.decode_trace_count == 1
    assert all(v == 1 for v in tiered.prefill_trace_counts.values())
    tiered.audit_pages()


def test_promotion_hits_skip_prefill_compute(model, tmp_path):
    """A tier-hit admission recomputes ONLY the un-cached suffix: its
    prefill chunk queries must be bounded by the suffix, not the whole
    prompt (re-admit by copy, not by compute)."""
    eng = _tiered(model, tmp_path)
    heads = _personas(6)
    _drive(eng, heads)
    toks0 = eng.prefill_tokens if hasattr(eng, "prefill_tokens") else None
    hit0, hit_toks0 = eng.tier_hits, eng.tier_hit_tokens
    srng = np.random.RandomState(99)
    tail = srng.randint(0, VOCAB, size=(5,)).astype(np.int32)
    req = Request(np.concatenate([heads[3], tail]), max_new_tokens=4)
    eng.run([req], poll_sleep=1e-4)
    # persona 3 was visited once then evicted under later pressure:
    # this revisit must be served from the tiers, all 3 full pages
    assert eng.tier_hits == hit0 + 1
    assert eng.tier_hit_tokens == hit_toks0 + 3 * PS
    eng.audit_pages()


# --------------------------------------------------------------------- #
# cascade drop demotes published descendants (silent-work-loss fix)
# --------------------------------------------------------------------- #

def test_cascade_drop_demotes_descendants(model, tmp_path):
    """Reclaiming a shallow ancestor cascades through its published
    full-page DESCENDANTS: before the tiers existed those descendants
    were deleted outright — hours of prefill silently discarded.  Now
    the whole family must land in the tiers and a deep revisit must
    re-admit the full 3-page chain by copy."""
    eng = _tiered(model, tmp_path)
    rng = np.random.RandomState(21)
    family = rng.randint(0, VOCAB, size=(3 * PS,)).astype(np.int32)
    tail = rng.randint(0, VOCAB, size=(5,)).astype(np.int32)
    prompt = np.concatenate([family, tail])
    eng.run([Request(prompt.copy(), max_new_tokens=4)],
            poll_sleep=1e-4)
    assert eng.prefix_probe(prompt) == 3 * PS

    # pressure: two unrelated personas churn the 7-page pool, evicting
    # the family root — the cascade must demote all three pages
    _drive(eng, _personas(2, seed=23), order=[0, 1, 0, 1])
    assert eng.prefix_probe(prompt) == 0
    assert eng.tier_probe(prompt) == 3 * PS

    prom0, hit_toks0 = eng.tier_promotions, eng.tier_hit_tokens
    req = Request(prompt.copy(), max_new_tokens=4)
    eng.run([req], poll_sleep=1e-4)
    assert eng.tier_promotions == prom0 + 3
    assert eng.tier_hit_tokens == hit_toks0 + 3 * PS
    # parity: the re-admitted family decodes exactly like a fresh
    # engine that never lost it
    fresh = _flat(model, num_pages=32)
    ref = Request(prompt.copy(), max_new_tokens=4)
    fresh.run([ref], poll_sleep=1e-4)
    fresh.run([req2 := Request(prompt.copy(), max_new_tokens=4)],
              poll_sleep=1e-4)
    assert list(req.token_ids) == list(req2.token_ids)
    eng.audit_pages()


# --------------------------------------------------------------------- #
# page-state audit under churn
# --------------------------------------------------------------------- #

def test_audit_every_step_under_churn(model, tmp_path):
    """free XOR live XOR demoted at EVERY step of an LRU-hostile
    workload — demotions and promotions land mid-run, between decode
    steps, with requests in flight."""
    eng = _tiered(model, tmp_path, dram_bytes=128 << 10)
    heads = _personas(6)
    srng = np.random.RandomState(11)
    for p in _ORDER:
        tail = srng.randint(0, VOCAB, size=(5,)).astype(np.int32)
        req = Request(np.concatenate([heads[p], tail]),
                      max_new_tokens=4)
        eng.run([req], poll_sleep=1e-4,
                before_step=lambda e, i: e.audit_pages())
        assert req.outcome is not None and req.outcome.ok
    assert eng.tier_demotions > 0 and eng.tier_promotions > 0
    snap = eng.health_snapshot()
    # the tiny DRAM budget forces the disk tier into play too
    assert snap["tier_disk_demotions"] > 0
    eng.audit_pages()


def test_store_audit_catches_byte_drift():
    store = KVTierStore(PS, dram_bytes=1 << 20)
    prompt = np.arange(2 * PS, dtype=np.int32)
    pay = (np.ones((2, PS, 4), np.float32),)
    assert store.put(prompt[:PS].tobytes(), prompt[PS:2 * PS], 1,
                     pay, pay)
    store.audit()
    for _k, ent in store.entries():
        ent.nbytes += 64                 # corrupt the accounting
    with pytest.raises(MXNetError):
        store.audit()


# --------------------------------------------------------------------- #
# integrity: crc fallback, disk-full degradation
# --------------------------------------------------------------------- #

def test_crc_fallback_no_garbage(model, tmp_path):
    """Rot one demoted payload: the promotion must be refused by crc
    (counted, evented), the admission must RECOMPUTE, and the emitted
    tokens must equal an untiered engine's — bit rot below HBM can
    cost time, never correctness."""
    from incubator_mxnet_tpu.serve.chaos import CorruptDemotedPage
    eng = _tiered(model, tmp_path)
    heads = _personas(6)
    _drive(eng, heads)
    CorruptDemotedPage(at_step=0, seed=5).on_step(eng, 0)
    fb0 = eng.tier_crc_fallbacks
    got = _drive(eng, heads, tail_seed=77)
    assert eng.tier_crc_fallbacks > fb0
    flat = _flat(model)
    _drive(flat, heads)
    want = _drive(flat, heads, tail_seed=77)
    assert got == want
    eng.audit_pages()


def test_disk_full_degrades_loudly(model, tmp_path):
    """Every spill fails ENOSPC (dram_bytes=0 → all demotions must hit
    disk): the tier degrades to a loud no-op — errors counted, pages
    dropped, serving bit-identical to an untiered engine."""
    eng = _tiered(model, tmp_path, dram_bytes=0)

    def _enospc(*a, **kw):
        raise OSError(28, "No space left on device")

    eng._tiers._write_step = _enospc
    heads = _personas(6)
    got = _drive(eng, heads, audit=True)
    assert eng._tiers.disk_errors > 0
    assert eng._tiers.dropped > 0
    assert len(eng._tiers) == 0          # nothing half-admitted
    assert eng.tier_promotions == 0
    flat = _flat(model)
    want = _drive(flat, heads)
    assert got == want
    snap = eng.health_snapshot()
    assert snap["tier_disk_errors"] == eng._tiers.disk_errors


def test_store_disk_spill_and_reload(tmp_path):
    """DRAM overflow spills the LRU entry to disk through the audited
    manifest writer; a reload round-trips bit-identically; a stale
    tier directory is wiped at construction (tier contents are
    process-lifetime)."""
    d = os.path.join(str(tmp_path), "t")
    store = KVTierStore(PS, dram_bytes=600, disk_dir=d,
                        disk_bytes=1 << 20)
    rng = np.random.RandomState(3)
    prompt = np.arange(4 * PS, dtype=np.int32)
    pays = []
    for i in range(3):
        pay = (rng.randn(2, PS, 4).astype(np.float32),)
        pays.append(pay)
        assert store.put(prompt[:i * PS].tobytes(),
                         prompt[i * PS:(i + 1) * PS], i, pay, pay)
    tiers = sorted(e.tier for _k, e in store.entries())
    assert "disk" in tiers and "dram" in tiers
    store.audit()
    for key, ent in list(store.entries()):
        if ent.tier == "disk":
            k_pay, v_pay, _ka, _va = store.load(key, ent)
            np.testing.assert_array_equal(k_pay[0], pays[ent.depth][0])
            np.testing.assert_array_equal(v_pay[0], pays[ent.depth][0])
    assert len(os.listdir(d)) > 0
    fresh = KVTierStore(PS, dram_bytes=600, disk_dir=d)
    assert len(fresh) == 0
    assert [f for f in os.listdir(d)
            if not f.startswith(".")] == []


# --------------------------------------------------------------------- #
# events, probes, router affinity
# --------------------------------------------------------------------- #

def test_tier_events_emitted(model, tmp_path):
    rec = FlightRecorder(histograms=False)
    eng = _tiered(model, tmp_path, recorder=rec)
    _drive(eng, _personas(6))
    demotes = rec.events(etype=EventType.CACHE_DEMOTE)
    promotes = rec.events(etype=EventType.CACHE_PROMOTE)
    misses = rec.events(etype=EventType.CACHE_TIER_MISS)
    assert len(demotes) == eng.tier_demotions + \
        eng.health_snapshot()["tier_disk_demotions"]
    assert len(promotes) == eng.tier_promotions
    assert len(misses) == eng.tier_misses
    assert eng.tier_misses > 0           # first-ever visits miss
    assert all(e.data["tier"] in ("dram", "disk") for e in demotes)


def test_tier_probe_and_router_affinity(model, tmp_path):
    """Routing's second affinity axis: a replica that holds a prefix
    only in its TIERS (evicted from HBM) still wins placement over a
    stone-cold replica — re-admission by copy beats recompute
    anywhere else."""
    cold = _flat(model)
    warm = _tiered(model, tmp_path)
    rng = np.random.RandomState(31)
    persona = rng.randint(0, VOCAB, size=(3 * PS,)).astype(np.int32)
    tail = rng.randint(0, VOCAB, size=(5,)).astype(np.int32)
    prompt = np.concatenate([persona, tail])
    warm.run([Request(prompt.copy(), max_new_tokens=4)],
             poll_sleep=1e-4)
    # evict the persona from HBM into the tiers
    warm._reclaim_prefix(3)
    assert warm.prefix_probe(prompt) == 0
    assert warm.tier_probe(prompt) == 3 * PS
    assert cold.tier_probe(prompt) == 0

    rt = Router([cold, warm], seed=3)
    assert rt.submit(Request(prompt.copy(), max_new_tokens=4))
    rt._dispatch()
    assert len(rt._inflight) == 1
    assert rt._inflight[0].replica == 1
    assert rt.tier_affinity_routed == 1 and rt.affinity_routed == 0
    assert rt.health_snapshot()["tier_affinity_routed"] == 1


# --------------------------------------------------------------------- #
# lifecycle: weight swaps flush the tiers; config validation
# --------------------------------------------------------------------- #

def test_warm_start_flushes_tiers(model, tmp_path):
    """Demoted K/V was computed under the OLD weights — serving it
    after a warm_start would silently mix models, exactly like the
    prefix index (which already flushes)."""
    eng = _tiered(model, tmp_path, dram_bytes=128 << 10)
    _drive(eng, _personas(6))
    assert len(eng._tiers) > 0
    flushes0 = eng._tiers.flushes
    params = {str(i): p.data().asnumpy()
              for i, p in enumerate(eng._eng_params)}
    eng.warm_start(params=params)
    assert len(eng._tiers) == 0
    assert eng._tiers.flushes == flushes0 + 1
    assert eng._tiers.tier_bytes() == {"dram": 0, "disk": 0}
    eng.audit_pages()


def test_kv_tiers_config_validation(model, tmp_path):
    with pytest.raises(MXNetError):
        InferenceEngine(model, num_slots=1, page_size=PS, max_len=64,
                        prefix_cache=False,
                        kv_tiers={"dram_bytes": 1 << 20})
    with pytest.raises(MXNetError):
        InferenceEngine(model, num_slots=1, page_size=PS, max_len=64,
                        prefix_cache=True,
                        kv_tiers={"dram_bytes": 1 << 20,
                                  "flux_capacitor": True})
