"""``mx.sym`` — the symbolic front end.

Op functions are generated from the SAME registry as ``mx.nd`` (one
registration serves both front ends, the reference's NNVM contract —
`python/mxnet/symbol/register.py`; file-level citation, SURVEY.md caveat),
but build graph nodes instead of executing.
"""

from __future__ import annotations

import inspect
import sys as _sys
from typing import Optional

from ..base import MXNetError
from ..ops import registry as _registry
from .symbol import (Group, Symbol, Variable, _Node, _auto_name, fromjson,
                     load, load_json, var)
from . import executor
from .executor import Executor
from . import passes
from .passes import apply_pass, list_passes, register_pass, rewrite

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "fromjson", "Executor", "executor", "save_block_symbol",
           "trace_block", "passes", "apply_pass", "list_passes",
           "register_pass", "rewrite"]


# tensor params the reference's symbolic API auto-creates as Variables
# named "{node}_{param}" when not passed (python/mxnet/symbol/register.py
# generated wrappers; file-level citation, SURVEY.md caveat). A
# whitelist, so required ATTR slots (axis, shape, ...) can never be
# captured as phantom variables.
_IMPLICIT_PARAM_NAMES = frozenset({
    "weight", "bias", "gamma", "beta", "moving_mean", "moving_var",
    "parameters", "state", "state_cell",
})


def _implicit_wanted(p, params, values):
    """Should the missing tensor param ``p`` become an implicit
    Variable? Required (no-default) tensors: always. Optional ones are
    gated exactly as the reference gates them, with the gating attr
    read at its OWN signature default (Deconvolution declares
    no_bias=True, so it gets no phantom bias)."""
    if p.default is inspect.Parameter.empty:
        return True
    defaults = {q.name: q.default for q in params
                if q.default is not inspect.Parameter.empty}
    if p.name == "bias":
        return not values.get("no_bias", defaults.get("no_bias", False))
    if p.name == "state_cell":
        return values.get("mode", defaults.get("mode")) == "lstm"
    if p.name == "gamma":  # LeakyReLU: learnable slope only for prelu
        return values.get("act_type",
                          defaults.get("act_type")) == "prelu"
    return False


def _invoke_symbol(op_name: str, *args, name: Optional[str] = None,
                   **kwargs) -> Symbol:
    """Compose a graph node (the symbolic twin of imperative_invoke)."""
    spec = _registry.get(op_name)
    if spec.wrap_list and len(args) == 1 and isinstance(args[0],
                                                        (list, tuple)):
        args = tuple(args[0])

    params = list(inspect.signature(spec.fn).parameters.values())
    has_varargs = any(p.kind is p.VAR_POSITIONAL for p in params)

    from ..name import current as _current_name_mgr
    mgr = _current_name_mgr()
    if mgr is not None:
        final_name = mgr.get(name, op_name.lower())
    else:
        final_name = name or _auto_name(op_name)

    inputs = []   # (node, out_idx) in positional order
    attrs = {}

    if has_varargs:
        # concat/stack/add_n: every positional arg is a tensor input
        for a in args:
            if not isinstance(a, Symbol):
                raise MXNetError(
                    f"{op_name}: variadic inputs must all be Symbols")
            inputs.append(a._heads[0])
        attrs.update({k: v for k, v in kwargs.items()
                      if not isinstance(v, Symbol)})
    else:
        # walk declared parameters in order: the LEADING run of
        # Symbol-valued params are graph inputs (ops declare tensors
        # first, the reference's convention); everything after the first
        # gap/non-Symbol is a static attribute
        values = {}
        for i, a in enumerate(args):
            if i >= len(params):
                raise MXNetError(f"{op_name}: too many positional args")
            values[params[i].name] = a
        values.update(kwargs)
        collecting = True
        for p in params:
            if p.kind is inspect.Parameter.VAR_KEYWORD:
                continue
            if p.name not in values:
                # reference parity: unprovided PARAMETER tensors become
                # implicit Variables "{node}_{param}" — required ones
                # always (weight, gamma, ...); optional ones per the
                # op's own gating attr (bias unless no_bias at ITS
                # declared default, state_cell only for lstm, LeakyReLU
                # gamma only for prelu). Running statistics are marked
                # __aux__ so the executor folds their updates and
                # checkpoints write aux: keys.
                if (collecting and p.name in _IMPLICIT_PARAM_NAMES
                        and _implicit_wanted(p, params, values)):
                    v_attrs = ({"__aux__": 1}
                               if p.name in ("moving_mean", "moving_var")
                               else {})
                    inputs.append(
                        Variable(f"{final_name}_{p.name}",
                                 **v_attrs)._heads[0])
                    continue
                collecting = False  # missing slot ends the tensor prefix
                continue
            v = values.pop(p.name)
            if isinstance(v, Symbol):
                if not collecting:
                    raise MXNetError(
                        f"{op_name}: tensor argument {p.name!r} follows a "
                        "non-tensor gap — pass earlier tensor args too")
                inputs.append(v._heads[0])
            elif v is None and collecting:
                # explicit None for an optional tensor slot (e.g. bias)
                collecting = False
            else:
                attrs[p.name] = v
                collecting = False
        leftover_syms = [k for k, v in values.items()
                         if isinstance(v, Symbol)]
        if leftover_syms:
            raise MXNetError(
                f"{op_name}: unexpected Symbol kwargs {leftover_syms}")
        attrs.update(values)

    from ..attribute import current_attrs as _scope_attrs
    # scope attrs are ANNOTATIONS (placement hints etc.), kept apart
    # from op kwargs so execution never sees them
    node = _Node(op_name, final_name, inputs, attrs,
                 annotations=_scope_attrs() or None)
    return Symbol([(node, i) for i in range(node.num_outputs())])


def _make_symbol_function(op_name: str, public_name: str):
    def sym_function(*args, **kwargs):
        return _invoke_symbol(op_name, *args, **kwargs)

    sym_function.__name__ = public_name
    sym_function.__qualname__ = public_name
    sym_function.__doc__ = _registry.describe_op(op_name)
    return sym_function


_THIS = _sys.modules[__name__]
for _name in _registry.list_all_names():
    if not hasattr(_THIS, _name):
        _spec = _registry.get(_name)
        setattr(_THIS, _name, _make_symbol_function(_spec.name, _name))


# ------------------------------------------------------------------ #
# Gluon bridge: HybridBlock → Symbol (the reference's hybridize/export
# trace — `gluon/block.py` _build_cache + `HybridBlock.export`)
# ------------------------------------------------------------------ #
def trace_block(block, num_inputs: int = 1):
    """Trace an initialized HybridBlock symbolically.

    Returns (symbol, input_names). Parameters appear as variables named by
    their full prefixed name; non-differentiable params (running stats)
    are marked auxiliary.
    """
    from .. import autograd

    input_names = ["data"] if num_inputs == 1 else \
        [f"data{i}" for i in range(num_inputs)]
    sym_inputs = [Variable(n) for n in input_names]
    with autograd._ModeScope(recording=False, training=False):
        out = block(*sym_inputs)
    if isinstance(out, (list, tuple)):
        out = Group(list(out))
    return out, input_names


def save_block_symbol(block, path: str, epoch: int = 0,
                      num_inputs: int = 1) -> None:
    """HybridBlock.export backend: write ``<path>-symbol.json`` +
    ``<path>-NNNN.params`` with the reference's ``arg:``/``aux:`` key
    prefixes (`src/ndarray/ndarray.cc` Save format, SURVEY.md §5.4)."""
    from ..ndarray import save as nd_save

    sym, _ = trace_block(block, num_inputs)
    sym.save(f"{path}-symbol.json")
    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    payload = {}
    for name, p in block._collect_params_with_prefix().items():
        full = p.name
        if full in aux_names:
            payload["aux:" + full] = p.data()
        elif full in arg_names:
            payload["arg:" + full] = p.data()
    nd_save(f"{path}-{epoch:04d}.params", payload)


# contrib namespace (parity: mx.sym.contrib) — imported last so
# _make_symbol_function exists
from . import contrib  # noqa: E402,F401
