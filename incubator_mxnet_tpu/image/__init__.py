"""Image utilities (re-design of `python/mxnet/image/image.py`; file-level
citation — SURVEY.md caveat). Decoding uses cv2/PIL when present; raw .npy
is the hermetic fallback (zero-egress environments)."""

from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.ndarray import _as_jax

__all__ = ["imread", "imdecode", "decode_to_numpy", "imresize",
           "resize_short", "fixed_crop", "center_crop", "random_crop",
           "color_normalize", "ImageIter"]


def decode_to_numpy(buf: bytes, flag=1, to_rgb=True) -> np.ndarray:
    """Decode an encoded image buffer to a HWC uint8 numpy array.

    The single codec chain (cv2 → PIL → raw NPY0) shared by
    ``mx.image.imdecode`` and the RecordIO data pipeline — host-side only,
    no device transfer (the data pipeline stacks batches before
    ``device_put``)."""
    arr = None
    if bytes(buf[:4]) == b"NPY0":
        import io as _io
        arr = np.load(_io.BytesIO(bytes(buf[4:])))
    else:
        try:
            import cv2
            raw = np.frombuffer(buf, np.uint8)
            arr = cv2.imdecode(raw, flag)
            if to_rgb and arr is not None and arr.ndim == 3:
                arr = cv2.cvtColor(arr, cv2.COLOR_BGR2RGB)
        except ImportError:
            try:
                from PIL import Image
                import io as _io
                arr = np.asarray(Image.open(_io.BytesIO(bytes(buf))))
            except ImportError:
                raise MXNetError("no image decoder available (cv2/PIL)")
    if arr is None:
        raise MXNetError("image decode failed")
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def imdecode(buf: bytes, flag=1, to_rgb=True) -> NDArray:
    """Decode an encoded image buffer (parity: mx.image.imdecode)."""
    return NDArray(_as_jax(decode_to_numpy(buf, flag, to_rgb)))


def imread(filename: str, flag=1, to_rgb=True) -> NDArray:
    if filename.endswith(".npy"):
        arr = np.load(filename)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return NDArray(_as_jax(arr))
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


def _np(img):
    return img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)


def imresize(src, w, h, interp=1) -> NDArray:
    x = _np(src)
    rows = (np.arange(h) * x.shape[0] / h).astype(np.int32)
    cols = (np.arange(w) * x.shape[1] / w).astype(np.int32)
    return NDArray(_as_jax(x[rows][:, cols]))


def resize_short(src, size, interp=1) -> NDArray:
    x = _np(src)
    H, W = x.shape[:2]
    if H < W:
        h, w = size, int(W * size / H)
    else:
        h, w = int(H * size / W), size
    return imresize(x, w, h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1) -> NDArray:
    x = _np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (h, w) != tuple(size):
        return imresize(x, size[0], size[1], interp)
    return NDArray(_as_jax(x))


def center_crop(src, size, interp=1):
    x = _np(src)
    H, W = x.shape[:2]
    w, h = size
    x0 = max((W - w) // 2, 0)
    y0 = max((H - h) // 2, 0)
    return fixed_crop(x, x0, y0, w, h), (x0, y0, w, h)


def random_crop(src, size, interp=1):
    from .. import random as _random
    x = _np(src)
    H, W = x.shape[:2]
    w, h = size
    rng = _random.np_rng()
    x0 = rng.randint(0, max(W - w, 0) + 1)
    y0 = rng.randint(0, max(H - h, 0) + 1)
    return fixed_crop(x, x0, y0, w, h), (x0, y0, w, h)


def color_normalize(src, mean, std=None) -> NDArray:
    x = _np(src).astype(np.float32)
    x = x - np.asarray(mean, np.float32)
    if std is not None:
        x = x / np.asarray(std, np.float32)
    return NDArray(_as_jax(x))


class ImageIter:
    """Python image iterator over .lst/.rec sources (parity surface:
    mx.image.ImageIter). Thin wrapper over io.ImageRecordIter for .rec."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 shuffle=False, **kwargs):
        if path_imgrec is None:
            raise MXNetError("ImageIter requires path_imgrec in this build")
        from ..io import ImageRecordIter
        self._inner = ImageRecordIter(
            path_imgrec=path_imgrec, data_shape=data_shape,
            batch_size=batch_size, shuffle=shuffle, **kwargs)

    def __iter__(self):
        return iter(self._inner)

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()
