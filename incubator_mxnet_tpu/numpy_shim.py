"""``mx.np`` — NumPy-compatible array API (re-design of
`python/mxnet/numpy/` ≥1.6; file-level citation — SURVEY.md caveat).

The reference re-implements the NumPy surface op-by-op on its own runtime.
The TPU-native build sits on jnp, which *is* a NumPy-compatible tracer —
so ``mx.np`` is a forwarding namespace: any ``numpy``-named function is
resolved on ``jax.numpy``, executed through the imperative dispatcher (so
``autograd.record()`` sees it as a tape node, exactly like a registry op),
and returns :class:`~incubator_mxnet_tpu.ndarray.NDArray`.

This gives the full jnp surface (hundreds of functions) with MXNet
autograd/async semantics instead of a hand-ported subset.
"""

from __future__ import annotations

import numpy as _onp

import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray
from .ndarray.register import imperative_invoke
from .ops.registry import OpSpec

# numpy-API constants / dtypes re-exported verbatim
pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
float32 = "float32"
float64 = "float64"
float16 = "float16"
bfloat16 = "bfloat16"
int8 = "int8"
int32 = "int32"
int64 = "int64"
uint8 = "uint8"
bool_ = "bool"

ndarray = NDArray  # parity: mx.np.ndarray is the array type

_spec_cache = {}

# jnp callables that are not array-valued ops (predicates/introspection):
# call directly and return python/numpy values, no tape node
_PASSTHROUGH = {"shape", "ndim", "size", "result_type", "promote_types",
                "can_cast", "issubdtype", "isscalar", "iterable",
                "broadcast_shapes"}


def _unwrap(x):
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _make_spec(name: str, fn) -> OpSpec:
    spec = _spec_cache.get(name)
    if spec is None:
        import jax

        def op(*arrays, **params):
            return fn(*arrays, **params)

        op.__doc__ = fn.__doc__
        spec = OpSpec("np." + name, op)
        # variadic/multi-output jnp fns (split, meshgrid…) return sequences;
        # detect at call time inside imperative_invoke via tuple normalize
        spec.num_outputs = None
        _spec_cache[name] = spec
    return spec


def array(obj, dtype=None, ctx=None):
    """Parity: ``mx.np.array``."""
    from .ndarray import array as _nd_array

    return _nd_array(obj, dtype=dtype, ctx=ctx)


def __getattr__(name: str):
    fn = getattr(jnp, name, None)
    if fn is None:
        raise AttributeError(f"mx.np has no attribute {name!r} "
                             "(not in jax.numpy)")
    if not callable(fn):
        return fn
    if name in _PASSTHROUGH:
        def passthrough(*args, **kwargs):
            return fn(*_unwrap(args), **kwargs)

        passthrough.__name__ = name
        return passthrough

    spec = _make_spec(name, fn)

    def np_function(*args, **kwargs):
        try:
            return imperative_invoke(spec, *args, **kwargs)
        except MXNetError:
            # fns with non-array leading args (e.g. np.arange(5)) fail the
            # array path; fall back to a direct call, still wrapping outputs
            res = fn(*_unwrap(args), **{k: _unwrap(v)
                                        for k, v in kwargs.items()})
            if isinstance(res, (tuple, list)):
                return type(res)(NDArray(r) for r in res)
            return NDArray(res)

    np_function.__name__ = name
    np_function.__doc__ = fn.__doc__
    return np_function


class _NpRandom:
    """``mx.np.random`` — numpy.random-style surface over the
    framework's key-threaded samplers (reference:
    ``python/mxnet/numpy/random.py``, file-level citation — SURVEY.md
    caveat). ``size`` is the numpy spelling of ``shape``; draws go
    through the registered sampler ops, so the global seeded stream and
    autograd semantics match ``mx.nd.random``."""

    @staticmethod
    def _nd():
        from .ndarray import random as ndr
        return ndr

    def seed(self, s):
        from . import random as _r
        _r.seed(s)

    def rand(self, *size):
        return self._nd().uniform(0.0, 1.0, shape=size or None)

    def randn(self, *size):
        return self._nd().randn(*size)

    def uniform(self, low=0.0, high=1.0, size=None):
        return self._nd().uniform(low, high, shape=size)

    def normal(self, loc=0.0, scale=1.0, size=None):
        return self._nd().normal(loc, scale, shape=size)

    def randint(self, low, high=None, size=None, dtype="int32"):
        if high is None:
            low, high = 0, low
        return self._nd().randint(low, high, shape=size, dtype=dtype)

    def gamma(self, shape, scale=1.0, size=None):
        return self._nd().gamma(shape, scale, shape=size)

    def exponential(self, scale=1.0, size=None):
        return self._nd().exponential(1.0 / scale, shape=size)

    def laplace(self, loc=0.0, scale=1.0, size=None):
        return self._nd().laplace(loc, scale, shape=size)

    def beta(self, a, b, size=None):
        # Beta(a, b) = G1 / (G1 + G2) with G1~Gamma(a), G2~Gamma(b):
        # composed from the registered gamma sampler so the draw stays
        # on the seeded stream and the tape
        g1 = self._nd().gamma(a, 1.0, shape=size)
        g2 = self._nd().gamma(b, 1.0, shape=size)
        return g1 / (g1 + g2)

    @staticmethod
    def _size_total(size):
        total = 1
        for d in (size if isinstance(size, tuple)
                  else (size,) if size else ()):
            total *= d
        return total

    def choice(self, a, size=None, replace=True, p=None):
        n = int(a) if not hasattr(a, "shape") else a.shape[0]
        if p is not None:
            pa = p if hasattr(p, "shape") else array(p)
            if pa.shape[0] != n:
                raise MXNetError(
                    f"choice: 'a' ({n}) and 'p' ({pa.shape[0]}) must "
                    f"have the same size")
            if replace:
                idx = self._nd().multinomial(pa, shape=size)
            else:
                # weighted sampling WITHOUT replacement = Gumbel top-k:
                # argsort(log p + Gumbel noise) descending, take k
                total = self._size_total(size)
                if total > n:
                    raise MXNetError(
                        "choice: cannot take more samples than "
                        "population when replace=False")
                from .ndarray import log as nd_log, topk
                u = self._nd().uniform(1e-20, 1.0, shape=(n,))
                g = -nd_log(-nd_log(u))
                scores = nd_log(pa + 1e-38) + g
                idx = topk(scores, k=total, ret_typ="indices",
                           is_ascend=False)
                idx = idx.reshape(size) if size else idx[0]
        else:
            if not replace:
                total = self._size_total(size)
                if total > n:
                    raise MXNetError(
                        "choice: cannot take more samples than "
                        "population when replace=False")
                perm = self._nd().shuffle(array(_onp.arange(n)))
                idx = perm[:total].reshape(size) if size else perm[0]
            else:
                idx = self._nd().randint(0, n, shape=size)
        if hasattr(a, "shape"):
            from .ndarray import take
            return take(a, idx, axis=0)
        return idx

    def shuffle(self, x):
        """In place along axis 0, returns None (numpy contract)."""
        x._data = self._nd().shuffle(x)._data
        return None

    def permutation(self, x):
        if isinstance(x, int):
            return self._nd().shuffle(array(_onp.arange(x)))
        return self._nd().shuffle(x)


random = _NpRandom()
