"""KVStore plugin registry (re-design of `python/mxnet/kvstore/base.py`
``KVStoreBase`` — the ≥1.7 pluggable backend registry that let horovod/
byteps register as kvstore types; SURVEY.md §2.3. Here backends are XLA
collective strategies instead of external comm libraries)."""

from __future__ import annotations

from ..base import Registry

_REGISTRY = Registry("kvstore")


def register(name, aliases=()):
    return _REGISTRY.register(name, aliases=aliases)


def get(name):
    return _REGISTRY.get(name)


def exists(name) -> bool:
    return name in _REGISTRY


class KVStoreBase:
    """Minimal backend interface: broadcast + pushpull (the ≥1.7 contract)."""

    def broadcast(self, key, value, out):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError

    @property
    def type(self):
        return type(self).__name__

    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1
