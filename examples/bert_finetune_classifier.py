"""BERT sentence-classification fine-tuning (reference: GluonNLP
scripts/bert/finetune_classifier.py — the MRPC/SST recipe).

Runs a tiny config on synthetic sentence-pair data by default so it
works anywhere; the structure (BERTClassifier head, slanted-triangular
LR, grad-clip via the optimizer, accuracy metric) mirrors the
reference's loop.

    python examples/bert_finetune_classifier.py --steps 20
    python examples/bert_finetune_classifier.py --sharding fsdp --dp 2
"""

import argparse

import numpy as np

import _common  # noqa: F401  (accelerator-or-CPU bootstrap)

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import metric as metric_mod
from incubator_mxnet_tpu import nd, parallel
from incubator_mxnet_tpu.gluon import loss as gloss
from incubator_mxnet_tpu.models import BERTClassifier, bert as bert_mod
from incubator_mxnet_tpu.optimizer import lr_scheduler
from incubator_mxnet_tpu.parallel import mesh as pmesh


def synthetic_batches(rng, n, batch_size, seq_len, vocab, num_classes):
    """Sentence pairs whose label is derivable from the tokens (so the
    tiny model can actually learn): label = first token % num_classes."""
    for _ in range(n):
        ids = rng.randint(4, vocab, (batch_size, seq_len))
        tt = np.zeros((batch_size, seq_len), np.int32)
        tt[:, seq_len // 2:] = 1  # second sentence segment
        vl = np.full((batch_size,), seq_len, np.int32)
        y = ids[:, 0] % num_classes
        yield (nd.array(ids, dtype="int32"), nd.array(tt, dtype="int32"),
               nd.array(vl, dtype="int32"), nd.array(y, dtype="int32"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--classes", type=int, default=3)
    ap.add_argument("--lr", type=float, default=5e-4)
    ap.add_argument("--sharding", choices=("replicated", "fsdp"),
                    default="replicated")
    ap.add_argument("--dp", type=int, default=-1)
    args = ap.parse_args()

    mx.random.seed(0)
    vocab = 256
    bert = bert_mod.bert_tiny(vocab_size=vocab, max_length=args.seq_len)
    clf = BERTClassifier(bert, num_classes=args.classes, dropout=0.1)
    clf.initialize()

    mesh = pmesh.build_mesh(axis_sizes={"dp": args.dp})
    sce = gloss.SoftmaxCrossEntropyLoss()

    def clf_loss(model, ids, tt, vl, y):
        return sce(model(ids, tt, vl), y).mean()

    # warmup + polynomial decay, the reference recipe's schedule
    sched = lr_scheduler.PolyScheduler(
        max_update=args.steps, base_lr=args.lr, final_lr=0.0,
        warmup_steps=max(args.steps // 10, 1))

    trainer = parallel.SPMDTrainer(
        clf, forward_loss=clf_loss, optimizer="adam",
        optimizer_params={"learning_rate": args.lr,
                          "lr_scheduler": sched},
        mesh=mesh, sharding=args.sharding)

    acc = metric_mod.Accuracy()
    rng = np.random.RandomState(0)
    for step, batch in enumerate(synthetic_batches(
            rng, args.steps, args.batch_size, args.seq_len, vocab,
            args.classes)):
        loss = trainer.step(*batch)
        if step % 5 == 0 or step == args.steps - 1:
            import incubator_mxnet_tpu.autograd as ag
            with ag.predict_mode():
                logits = clf(*batch[:3])
            acc.reset()
            acc.update(batch[3], logits)
            print(f"step {step:4d}  loss {float(loss.asnumpy()):.4f}  "
                  f"train-acc {acc.get()[1]:.3f}")
    print("done")


if __name__ == "__main__":
    main()
