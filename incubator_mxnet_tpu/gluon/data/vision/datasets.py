"""Vision datasets (re-design of
`python/mxnet/gluon/data/vision/datasets.py`; file-level citation —
SURVEY.md caveat).

No-network contract: datasets read standard local files (IDX for MNIST,
pickled batches for CIFAR); when files are absent, ``synthetic=True``
generates a deterministic class-structured stand-in so examples, tests and
benchmarks run hermetically (this environment has zero egress).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ....base import MXNetError
from ..dataset import Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset", "ImageRecordDataset"]


def _synthetic_images(n, shape, classes, seed):
    """Deterministic class-separable images: class-keyed gaussian blobs."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, n).astype(np.int32)
    protos = rng.rand(classes, *shape).astype(np.float32)
    imgs = protos[labels] * 0.8 + rng.rand(n, *shape).astype(np.float32) * 0.2
    return (imgs * 255).astype(np.uint8), labels


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform, synthetic, n_synth, shape,
                 classes, seed):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        if self._files_exist():
            self._get_data()
        elif synthetic:
            self._data, self._label = _synthetic_images(
                n_synth if train else max(n_synth // 6, 1),
                shape, classes, seed + (0 if train else 1))
        else:
            raise MXNetError(
                f"{type(self).__name__}: files not found under "
                f"{self._root!r} and this environment has no network; "
                f"place the standard files there or pass synthetic=True")

    def _files_exist(self) -> bool:
        raise NotImplementedError

    def _get_data(self):
        raise NotImplementedError

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        img = self._data[idx]
        label = self._label[idx]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class MNIST(_DownloadedDataset):
    """MNIST from IDX files (train-images-idx3-ubyte[.gz] etc.)."""

    _TRAIN = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _TEST = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None, synthetic=False, synthetic_size=6000):
        super().__init__(root, train, transform, synthetic, synthetic_size,
                         (28, 28, 1), 10, seed=42)

    def _names(self):
        return self._TRAIN if self._train else self._TEST

    def _find(self, name):
        for suffix in ("", ".gz"):
            p = os.path.join(self._root, name + suffix)
            if os.path.exists(p):
                return p
        return None

    def _files_exist(self):
        return all(self._find(n) is not None for n in self._names())

    @staticmethod
    def _read_idx(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            raw = f.read()
        zero, dtype_code, ndim = struct.unpack(">HBB", raw[:4])
        dims = struct.unpack(">" + "I" * ndim, raw[4:4 + 4 * ndim])
        return np.frombuffer(raw, dtype=np.uint8,
                             offset=4 + 4 * ndim).reshape(dims)

    def _get_data(self):
        img_name, lbl_name = self._names()
        imgs = self._read_idx(self._find(img_name))
        self._data = imgs.reshape(-1, 28, 28, 1)
        self._label = self._read_idx(self._find(lbl_name)).astype(np.int32)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None, synthetic=False, synthetic_size=6000):
        super().__init__(root=root, train=train, transform=transform,
                         synthetic=synthetic, synthetic_size=synthetic_size)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 from the python pickle batches."""

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None, synthetic=False, synthetic_size=6000):
        super().__init__(root, train, transform, synthetic, synthetic_size,
                         (32, 32, 3), 10, seed=7)

    def _batch_files(self):
        if self._train:
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]

    def _files_exist(self):
        base = os.path.join(self._root, "cifar-10-batches-py")
        return all(os.path.exists(os.path.join(base, f))
                   for f in self._batch_files())

    def _get_data(self):
        base = os.path.join(self._root, "cifar-10-batches-py")
        data, labels = [], []
        for fname in self._batch_files():
            with open(os.path.join(base, fname), "rb") as f:
                batch = pickle.load(f, encoding="latin1")
            data.append(batch["data"])
            labels.extend(batch["labels"])
        arr = np.concatenate(data).reshape(-1, 3, 32, 32)
        self._data = arr.transpose(0, 2, 3, 1).astype(np.uint8)
        self._label = np.asarray(labels, np.int32)


class ImageFolderDataset(Dataset):
    """class-per-subfolder image dataset (parity:
    gluon.data.vision.ImageFolderDataset). Requires pillow or cv2 for
    decoding; raw-file mode otherwise."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        exts = {".jpg", ".jpeg", ".png", ".bmp"}
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if os.path.splitext(fname)[1].lower() in exts:
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        path, label = self.items[idx]
        from ....image import imread
        img = imread(path, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class CIFAR100(_DownloadedDataset):
    """CIFAR-100 from the python pickle batches (parity:
    gluon.data.vision.CIFAR100). ``fine_label`` selects the 100-way
    fine labels (True, default) or the 20 coarse superclasses."""

    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 transform=None, fine_label=True, synthetic=False,
                 synthetic_size=6000):
        self._fine = fine_label
        super().__init__(root, train, transform, synthetic, synthetic_size,
                         (32, 32, 3), 100 if fine_label else 20, seed=9)

    def _files_exist(self):
        base = os.path.join(self._root, "cifar-100-python")
        fname = "train" if self._train else "test"
        return os.path.exists(os.path.join(base, fname))

    def _get_data(self):
        base = os.path.join(self._root, "cifar-100-python")
        fname = "train" if self._train else "test"
        with open(os.path.join(base, fname), "rb") as f:
            batch = pickle.load(f, encoding="latin1")
        arr = np.asarray(batch["data"]).reshape(-1, 3, 32, 32)
        self._data = arr.transpose(0, 2, 3, 1).astype(np.uint8)
        key = "fine_labels" if self._fine else "coarse_labels"
        self._label = np.asarray(batch[key], np.int32)


class ImageRecordDataset(RecordFileDataset):
    """Image + label dataset over an im2rec-packed RecordIO file
    (parity: gluon.data.vision.ImageRecordDataset). Each record is an
    IRHeader-packed (label, image-bytes) pair from tools/im2rec.py."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....io.recordio import unpack
        from ....image import imdecode
        record = super().__getitem__(idx)
        header, img_bytes = unpack(record)
        img = imdecode(img_bytes, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
