"""Capture a multi-device profiler trace of the full sharded training step.

Runs the same dp/fsdp/tp-sharded BERT pretraining step as
``__graft_entry__.dryrun_multichip`` on an N-virtual-device CPU host mesh
(``--xla_force_host_platform_device_count``), under ``jax.profiler.trace``,
then writes ``<outdir>/SUMMARY.md`` via tools/trace_summary.py.

This is the evidence VERDICT r4 item 5 asks for: the reference hides
gradient-allreduce latency behind backprop via its P3 store
(ref: src/kvstore/p3store_dist.h); here XLA's scheduler owns that
interleaving, and this trace shows the collectives the partitioner
actually inserts for the sharded step plus how much of their time is
exposed.  Multi-chip hardware is not available (1-chip tunnel), so the
virtual host mesh is the only way to capture a trace with real
collectives in it; trace_summary labels the resulting overlap number as
an upper bound.

Usage: python tools/multichip_trace.py [N_DEVICES] [OUTDIR]
"""

import os
import re
import sys


def main(n_devices=8, outdir=None):
    outdir = outdir or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "trace_r5cpu")
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) == n_devices, (
        f"{len(jax.devices())} devices; run in a fresh process")

    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.models import bert as bert_mod
    from incubator_mxnet_tpu.parallel import mesh as pmesh

    dp = 2 if n_devices % 2 == 0 else 1
    rem = n_devices // dp
    fsdp = 2 if rem % 2 == 0 else 1
    tp = rem // fsdp
    mesh = pmesh.build_mesh(axis_sizes={"dp": dp, "fsdp": fsdp, "tp": tp})

    mx.random.seed(0)
    model = bert_mod.bert_tiny(vocab_size=512, max_length=64)
    model.initialize()
    pre = bert_mod.BERTForPretraining(model)
    pre.initialize()

    B, T, M = 4 * dp * fsdp, 64, 8
    rng = np.random.RandomState(0)
    batch = (
        nd.array(rng.randint(0, 512, (B, T)), dtype="int32"),
        nd.array(rng.randint(0, 2, (B, T)), dtype="int32"),
        nd.array(np.full((B,), T), dtype="int32"),
        nd.array(rng.randint(0, T, (B, M)), dtype="int32"),
        nd.array(rng.randint(0, 512, (B, M)), dtype="int32"),
        nd.ones((B, M)),
        nd.array(rng.randint(0, 2, (B,)), dtype="int32"),
    )

    trainer = parallel.SPMDTrainer(
        pre, forward_loss=bert_mod.pretraining_loss, optimizer="lamb",
        optimizer_params={"learning_rate": 1e-3}, mesh=mesh,
        sharding="fsdp")
    # warmup compiles the step; the capture below is steady-state only
    float(trainer.step(*batch).asnumpy())

    with jax.profiler.trace(outdir):
        for _ in range(5):
            loss = trainer.step(*batch)
        loss_val = float(loss.asnumpy())  # the only real fence
    print(f"captured 5 sharded steps (dp{dp}/fsdp{fsdp}/tp{tp}, "
          f"B={B}) loss={loss_val:.4f} -> {outdir}")

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_summary

    md = trace_summary.summarize(outdir)
    header = (
        f"Capture: 5 steady-state `SPMDTrainer` BERT-pretraining steps "
        f"(fwd+bwd+allreduce+LAMB in one jit) on a {n_devices}-virtual-"
        f"device CPU host mesh, dp={dp} fsdp={fsdp} tp={tp}, B={B} "
        f"T=64.\n\n")
    md = md.replace("# Trace summary\n\n",
                    "# Trace summary (virtual multi-device)\n\n" + header)
    out_md = os.path.join(outdir, "SUMMARY.md")
    with open(out_md, "w") as f:
        f.write(md)
    print(f"wrote {out_md}")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 8,
         sys.argv[2] if len(sys.argv) > 2 else None)
