"""RNN op + gluon.rnn layer/cell tests (mirrors reference
tests/python/unittest/test_gluon_rnn.py strategy: numpy oracles, fused
vs cell-unroll consistency, gradient flow)."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.ops.rnn import rnn_param_size


def _np_lstm_ref(x, h0, c0, wi, wh, bi, bh):
    """Single-layer LSTM oracle in numpy, gate order i,f,g,o."""
    T, B, _ = x.shape
    H = h0.shape[-1]
    h, c = h0.copy(), c0.copy()
    sig = lambda v: 1 / (1 + np.exp(-v))
    outs = []
    for t in range(T):
        g = x[t] @ wi.T + bi + h @ wh.T + bh
        i, f, gg, o = np.split(g, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(gg)
        h = sig(o) * np.tanh(c)
        outs.append(h)
    return np.stack(outs), h, c


def test_fused_lstm_matches_numpy():
    rng = np.random.RandomState(0)
    T, B, I, H = 5, 3, 4, 6
    x = rng.randn(T, B, I).astype(np.float32)
    wi = rng.randn(4 * H, I).astype(np.float32) * 0.1
    wh = rng.randn(4 * H, H).astype(np.float32) * 0.1
    bi = rng.randn(4 * H).astype(np.float32) * 0.1
    bh = rng.randn(4 * H).astype(np.float32) * 0.1
    params = np.concatenate([wi.ravel(), wh.ravel(), bi, bh])
    assert params.size == rnn_param_size(1, I, H, "lstm")
    h0 = np.zeros((1, B, H), np.float32)
    c0 = np.zeros((1, B, H), np.float32)

    out, hN, cN = nd.RNN(nd.array(x), nd.array(params), nd.array(h0),
                         nd.array(c0), state_size=H, num_layers=1,
                         mode="lstm", state_outputs=True)
    ref_out, ref_h, ref_c = _np_lstm_ref(x, h0[0], c0[0], wi, wh, bi, bh)
    np.testing.assert_allclose(out.asnumpy(), ref_out, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(hN.asnumpy()[0], ref_h, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(cN.asnumpy()[0], ref_c, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("mode,cls", [("lstm", gluon.rnn.LSTM),
                                      ("gru", gluon.rnn.GRU),
                                      ("rnn_tanh", gluon.rnn.RNN)])
def test_layer_forward_shapes(mode, cls):
    T, B, I, H, L = 4, 2, 5, 7, 2
    layer = cls(H, num_layers=L, bidirectional=True)
    layer.initialize()
    x = nd.random.uniform(shape=(T, B, I))
    out = layer(x)
    assert out.shape == (T, B, 2 * H)
    states = layer.begin_state(batch_size=B)
    out, st = layer(x, states)
    assert out.shape == (T, B, 2 * H)
    assert st[0].shape == (L * 2, B, H)
    if mode == "lstm":
        assert len(st) == 2


def test_layer_ntc_layout():
    layer = gluon.rnn.GRU(6, layout="NTC")
    layer.initialize()
    x = nd.random.uniform(shape=(3, 5, 4))  # (B,T,C)
    out = layer(x)
    assert out.shape == (3, 5, 6)


def test_lstm_layer_matches_cell_unroll():
    """Fused scan path vs step-by-step LSTMCell unroll."""
    T, B, I, H = 6, 2, 3, 4
    layer = gluon.rnn.LSTM(H, input_size=I)
    layer.initialize()
    cell = gluon.rnn.LSTMCell(H, input_size=I)
    cell.initialize()
    # share weights: copy layer params into cell
    lp = {"_".join(n.rsplit("_", 2)[-2:]): p
          for n, p in layer.collect_params().items()}
    cell.i2h_weight.set_data(lp["i2h_weight"].data())
    cell.h2h_weight.set_data(lp["h2h_weight"].data())
    cell.i2h_bias.set_data(lp["i2h_bias"].data())
    cell.h2h_bias.set_data(lp["h2h_bias"].data())

    x = nd.random.uniform(shape=(T, B, I))
    fused = layer(x)
    outs, _ = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    np.testing.assert_allclose(fused.asnumpy(), outs.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_rnn_gradient_flows():
    layer = gluon.rnn.LSTM(4, num_layers=2, dropout=0.3)
    layer.initialize()
    x = nd.random.uniform(shape=(5, 2, 3))
    with autograd.record():
        out = layer(x)
        loss = (out ** 2).sum()
    loss.backward()
    for _, p in layer.collect_params().items():
        g = p.grad().asnumpy()
        assert np.isfinite(g).all()
        assert np.abs(g).sum() > 0


def test_gru_cell_and_residual():
    cell = gluon.rnn.ResidualCell(gluon.rnn.GRUCell(5, input_size=5))
    cell.initialize()
    x = nd.random.uniform(shape=(2, 5))
    states = cell.begin_state(batch_size=2)
    out, st = cell(x, states)
    assert out.shape == (2, 5)


def test_sequential_and_bidirectional_cells():
    stack = gluon.rnn.SequentialRNNCell()
    stack.add(gluon.rnn.LSTMCell(4, input_size=3))
    stack.add(gluon.rnn.GRUCell(4, input_size=4))
    stack.initialize()
    x = nd.random.uniform(shape=(7, 2, 3))
    outs, states = stack.unroll(7, x, layout="TNC", merge_outputs=True)
    assert outs.shape == (7, 2, 4)
    assert len(states) == 3  # lstm h,c + gru h

    bi = gluon.rnn.BidirectionalCell(gluon.rnn.GRUCell(4, input_size=3),
                                     gluon.rnn.GRUCell(4, input_size=3))
    bi.initialize()
    outs, _ = bi.unroll(7, x, layout="TNC", merge_outputs=True)
    assert outs.shape == (7, 2, 8)


def test_rnn_layer_hybridize():
    layer = gluon.rnn.LSTM(4, num_layers=1)
    layer.initialize()
    x = nd.random.uniform(shape=(3, 2, 5))
    eager = layer(x)
    layer.hybridize()
    compiled = layer(x)
    np.testing.assert_allclose(eager.asnumpy(), compiled.asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_symbolic_rnn_state_outputs_arity():
    """Regression: mx.sym.RNN with state_outputs must expose all heads."""
    x = mx.sym.Variable("x")
    p = mx.sym.Variable("p")
    h = mx.sym.Variable("h")
    c = mx.sym.Variable("c")
    s = mx.sym.RNN(x, p, h, c, state_size=4, num_layers=1, mode="lstm",
                   state_outputs=True)
    assert len(s.list_outputs()) == 3
    s2 = mx.sym.RNN(x, p, h, state_size=4, num_layers=1, mode="gru",
                    state_outputs=True)
    assert len(s2.list_outputs()) == 2
    s3 = mx.sym.RNN(x, p, h, state_size=4, num_layers=1, mode="gru",
                    state_outputs=False)
    assert len(s3.list_outputs()) == 1


def test_bidirectional_valid_length():
    """Regression: backward direction must see valid frames, not padding."""
    T, B, C, H = 5, 2, 3, 4
    rng = np.random.RandomState(3)
    x = rng.randn(T, B, C).astype(np.float32)
    vl = np.array([5.0, 2.0], np.float32)

    bi = gluon.rnn.BidirectionalCell(gluon.rnn.GRUCell(H, input_size=C),
                                     gluon.rnn.GRUCell(H, input_size=C))
    bi.initialize()
    out, _ = bi.unroll(T, nd.array(x), layout="TNC", merge_outputs=True,
                       valid_length=nd.array(vl))
    out = out.asnumpy()
    # sequence 1 has 2 valid steps: outputs at t>=2 masked to 0
    assert np.allclose(out[2:, 1, :], 0.0)
    # backward half of t=0 for seq 1 must be nonzero (computed from the 2
    # valid frames) — the plain-reversal bug zeroed it
    assert np.abs(out[0, 1, H:]).sum() > 0

    # oracle: running the same cells on just the valid 2 frames must match
    sub, _ = bi.unroll(2, nd.array(x[:2, 1:2]), layout="TNC",
                       merge_outputs=True)
    np.testing.assert_allclose(out[:2, 1, :], sub.asnumpy()[:, 0, :],
                               rtol=1e-5, atol=1e-5)


def test_bucketing_init_optimizer_reaches_precompiled_buckets():
    def sym_gen(key):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        o = mx.sym.FullyConnected(data, mx.sym.Variable("w"),
                                  mx.sym.Variable("b"), num_hidden=3,
                                  name="fc")
        return mx.sym.SoftmaxOutput(o, label, name="softmax"), \
            ["data"], ["softmax_label"]

    bm = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                context=mx.cpu())
    bm.bind(data_shapes=[("data", (2, 4))],
            label_shapes=[("softmax_label", (2,))])
    bm.init_params()

    class _Batch:
        def __init__(self, key, n):
            self.bucket_key = key
            self.data = [nd.ones((n, 4))]
            self.label = [nd.zeros((n,))]
            self.provide_data = [("data", (n, 4))]
            self.provide_label = [("softmax_label", (n,))]

    bm.forward(_Batch(4, 4), is_train=True)   # compile bucket 4 pre-opt
    bm.init_optimizer(optimizer="sgd",
                      optimizer_params={"learning_rate": 0.5})
    bm.forward(_Batch(4, 4), is_train=True)
    bm.backward()
    bm.update()  # regression: raised "call init_optimizer first"
