"""``mx.nd.contrib`` — contrib op namespace (parity:
`python/mxnet/ndarray/contrib.py`: ops registered as ``_contrib_X`` are
surfaced as ``nd.contrib.X``)."""

from __future__ import annotations

import sys as _sys

from ..ops import registry as _registry
from .register import make_op_function

_THIS = _sys.modules[__name__]

for _name in _registry.list_all_names():
    if _name.startswith("_contrib_"):
        _short = _name[len("_contrib_"):]
        if not hasattr(_THIS, _short):
            setattr(_THIS, _short, make_op_function(_registry.get(_name),
                                                    _short))


# control-flow constructs (Python-callable, not registry ops)
from ..ops.control_flow import foreach, while_loop, cond  # noqa: E402,F401
