"""Graph execution for the Symbol front end.

Re-design of the legacy symbolic executor
(`src/executor/graph_executor.{h,cc}`, `attach_op_execs_pass.cc`,
`src/c_api/c_api_executor.cc`; file-level citations — SURVEY.md caveat).

The reference's `GraphExecutor::Bind` runs NNVM passes (InferShape →
InferType → Gradient → PlanMemory) and pushes per-node closures to the
dependency engine. Here:

  - shape/type inference  → ``jax.eval_shape`` over the graph interpreter;
  - Gradient pass         → ``jax.vjp`` of the whole interpreted program;
  - PlanMemory + bulking  → XLA buffer assignment + fusion under ``jit``;
  - topo dispatch         → one compiled XLA program per (shapes, is_train)
    signature, the CachedOp contract applied to the symbolic path.

``evaluate`` is the *imperative* interpreter: it walks the DAG through
``imperative_invoke`` so autograd records tape nodes — this is what
``SymbolBlock``/`sym.eval` use inside Gluon. ``Executor`` is the *compiled*
path used by `Module`/`simple_bind`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .. import random as _random
from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray import NDArray
from ..ndarray.ndarray import _as_jax, _to_jnp_dtype
from ..ndarray.register import imperative_invoke
from ..ops import registry as _registry
from .symbol import Symbol, _topo

__all__ = ["evaluate", "Executor", "infer_shapes", "infer_types"]


def _node_kwargs(node) -> dict:
    return {k: v for k, v in node.attrs.items() if not k.startswith("__")}


def evaluate(sym: Symbol, bindings: Dict[str, NDArray], training=None):
    """Interpret the graph imperatively over NDArrays (tape-recording).

    Multi-output symbols return a list; single output returns one NDArray.
    """
    nodes = _topo(sym._heads)
    vals: Dict[int, tuple] = {}
    for node in nodes:
        if node.is_variable:
            if node.name not in bindings:
                raise MXNetError(
                    f"symbol input {node.name!r} is not bound; provided: "
                    f"{sorted(bindings)}")
            v = bindings[node.name]
            vals[id(node)] = (v if isinstance(v, NDArray) else NDArray(
                _as_jax(v)),)
        else:
            spec = _registry.get(node.op)
            ins = [vals[id(src)][idx] for src, idx in node.inputs]
            kwargs = _node_kwargs(node)
            if spec.training_aware and training is not None:
                kwargs.setdefault("training", training)
            out = imperative_invoke(spec, *ins, **kwargs)
            vals[id(node)] = tuple(out) if isinstance(out, (list, tuple)) \
                else (out,)
    outs = [vals[id(n)][i] for n, i in sym._heads]
    return outs if len(outs) > 1 else outs[0]


def _interpret_pure(sym: Symbol, input_vals: Dict[str, jax.Array],
                    training: bool, key: Optional[jax.Array]):
    """Pure jnp interpreter (jit-traceable). Returns (head values,
    {aux_name: updated value}) — aux updates implement the reference's
    in-place running-stat mutation functionally (BatchNorm contract)."""
    nodes = _topo(sym._heads)
    vals: Dict[int, tuple] = {}
    aux_updates: Dict[str, jax.Array] = {}
    key_idx = 0
    for node in nodes:
        if node.is_variable:
            vals[id(node)] = (input_vals[node.name],)
            continue
        spec = _registry.get(node.op)
        ins = [vals[id(src)][idx] for src, idx in node.inputs]
        kwargs = _node_kwargs(node)
        if spec.training_aware:
            kwargs.setdefault("training", training)
        if spec.needs_key:
            if key is None:
                raise MXNetError(
                    f"stochastic op {node.op} requires a key")
            kwargs["key"] = jax.random.fold_in(key, key_idx)
            key_idx += 1
        out = spec.fn(*ins, **kwargs)
        out = tuple(out) if isinstance(out, (list, tuple)) else (out,)
        vals[id(node)] = out
        # BatchNorm training: fold batch stats into the aux running stats
        # (reference: aux-state mutation inside batch_norm.cc)
        if node.op == "BatchNorm" and training:
            mm_node, _ = node.inputs[3]
            mv_node, _ = node.inputs[4]
            momentum = float(node.attrs.get("momentum", 0.9))
            if mm_node.is_variable and mm_node.attrs.get("__aux__"):
                aux_updates[mm_node.name] = (
                    momentum * vals[id(mm_node)][0]
                    + (1 - momentum) * out[1])
            if mv_node.is_variable and mv_node.attrs.get("__aux__"):
                aux_updates[mv_node.name] = (
                    momentum * vals[id(mv_node)][0]
                    + (1 - momentum) * out[2])
    heads = [vals[id(n)][i] for n, i in sym._heads]
    return heads, aux_updates


def _graph_needs_key(sym: Symbol) -> bool:
    return any(not n.is_variable and _registry.get(n.op).needs_key
               for n in _topo(sym._heads))


def _prod(xs):
    out = 1
    for x in xs:
        out *= x
    return out


# Parameter-shape rules for parametric ops: given the DATA input shape and
# node attrs, return {input_position: shape} for the op's parameter slots.
# This is the inverse-inference half of the reference's per-op FInferShape
# functions (SURVEY.md §2.1 NNVM passes) — the forward half is XLA abstract
# evaluation.
def _rule_fc(din, attrs):
    nh = int(attrs["num_hidden"])
    flatten = attrs.get("flatten", True)
    in_units = _prod(din[1:]) if flatten else din[-1]
    return {1: (nh, in_units), 2: (nh,)}


def _rule_conv(din, attrs):
    kernel = tuple(attrs["kernel"])
    nf = int(attrs["num_filter"])
    ng = int(attrs.get("num_group", 1))
    return {1: (nf, din[1] // ng) + kernel, 2: (nf,)}


def _rule_deconv(din, attrs):
    kernel = tuple(attrs["kernel"])
    nf = int(attrs["num_filter"])
    ng = int(attrs.get("num_group", 1))
    return {1: (din[1], nf // ng) + kernel, 2: (nf,)}


def _rule_bn(din, attrs):
    ax = int(attrs.get("axis", 1)) % len(din)
    c = (din[ax],)
    return {1: c, 2: c, 3: c, 4: c}


def _rule_ln(din, attrs):
    ax = int(attrs.get("axis", -1)) % len(din)
    c = (din[ax],)
    return {1: c, 2: c}


def _rule_embedding(din, attrs):
    return {1: (int(attrs["input_dim"]), int(attrs["output_dim"]))}


_PARAM_SHAPE_RULES = {
    "FullyConnected": _rule_fc,
    "Convolution": _rule_conv,
    "Deconvolution": _rule_deconv,
    "BatchNorm": _rule_bn,
    "LayerNorm": _rule_ln,
    "InstanceNorm": _rule_ln,
    "Embedding": _rule_embedding,
}


def _node_eval_shape(node, in_structs):
    spec = _registry.get(node.op)
    kwargs = _node_kwargs(node)
    if spec.training_aware:
        kwargs["training"] = False

    if spec.needs_key:
        key_struct = jax.ShapeDtypeStruct((2,), jnp.uint32)

        def f(key, *arrs):
            return spec.fn(*arrs, key=key, **kwargs)

        out = jax.eval_shape(f, key_struct, *in_structs)
    else:
        out = jax.eval_shape(lambda *arrs: spec.fn(*arrs, **kwargs),
                             *in_structs)
    return tuple(out) if isinstance(out, (tuple, list)) else (out,)


def _propagate(sym: Symbol, known: Dict[str, tuple],
               dtypes: Optional[Dict[str, str]] = None):
    """Fixpoint partial shape/type propagation over the DAG (the reference's
    NNVM `InferShape`/`InferType` passes). Returns
    ({var_name: ShapeDtypeStruct}, [head structs]) or raises MXNetError
    listing the under-determined variables."""
    dtypes = dtypes or {}
    nodes = _topo(sym._heads)
    var_shape: Dict[str, tuple] = {k: tuple(v) for k, v in known.items()}
    structs: Dict[tuple, jax.ShapeDtypeStruct] = {}

    def var_struct(node):
        s = var_shape.get(node.name, node.attrs.get("__shape__"))
        if s is None:
            return None
        dt = dtypes.get(node.name, node.attrs.get("__dtype__", "float32"))
        return jax.ShapeDtypeStruct(tuple(s), _to_jnp_dtype(dt))

    changed = True
    while changed:
        changed = False
        for node in nodes:
            if (id(node), 0) in structs:
                continue
            if node.is_variable:
                st = var_struct(node)
                if st is not None:
                    structs[(id(node), 0)] = st
                    changed = True
                continue
            in_keys = [(id(src), i) for src, i in node.inputs]
            if all(k in structs for k in in_keys):
                outs = _node_eval_shape(node,
                                        [structs[k] for k in in_keys])
                for i, o in enumerate(outs):
                    structs[(id(node), i)] = o
                changed = True
                continue
            # inverse inference: fill unknown parameter variables from the
            # (known) data input
            rule = _PARAM_SHAPE_RULES.get(node.op)
            if rule and node.inputs and \
                    (id(node.inputs[0][0]), node.inputs[0][1]) in structs:
                din = structs[(id(node.inputs[0][0]),
                               node.inputs[0][1])].shape
                for pos, shape in rule(din, node.attrs).items():
                    if pos >= len(node.inputs):
                        continue
                    src, _ = node.inputs[pos]
                    if src.is_variable and src.name not in var_shape \
                            and not src.attrs.get("__shape__"):
                        var_shape[src.name] = tuple(shape)
                        changed = True

    missing = [n.name for n in nodes
               if n.is_variable and (id(n), 0) not in structs]
    if missing:
        raise MXNetError(f"shape inference under-determined for {missing}")
    var_structs = {n.name: structs[(id(n), 0)]
                   for n in nodes if n.is_variable}
    head_structs = [structs[(id(n), i)] for n, i in sym._heads]
    return var_structs, head_structs


def infer_shapes(sym: Symbol, known: Dict[str, tuple],
                 dtypes: Optional[Dict[str, str]] = None) -> dict:
    """Partial-input shape inference (the reference's `InferShape` pass)."""
    var_structs, heads = _propagate(sym, known, dtypes)
    return {"args": {n: tuple(s.shape) for n, s in var_structs.items()},
            "outs": [tuple(o.shape) for o in heads]}


def infer_types(sym: Symbol, known: Dict[str, str]) -> dict:
    var_nodes = [n for n in _topo(sym._heads) if n.is_variable]
    shapes = {n.name: tuple(n.attrs.get("__shape__") or (1,))
              for n in var_nodes}
    var_structs, heads = _propagate(sym, shapes, dtypes=known)
    return {"args": {n: str(s.dtype) for n, s in var_structs.items()},
            "outs": [str(o.dtype) for o in heads]}


def _as_req_map(grad_req, arg_names: Sequence[str]) -> Dict[str, str]:
    if isinstance(grad_req, str):
        return {n: grad_req for n in arg_names}
    if isinstance(grad_req, (list, tuple)):
        return dict(zip(arg_names, grad_req))
    if isinstance(grad_req, dict):
        return {n: grad_req.get(n, "null") for n in arg_names}
    raise MXNetError(f"bad grad_req {grad_req!r}")


class _LazyOutputs:
    """Sequence proxy over ``Executor.outputs`` that defers the fwd-only
    compilation until actually read (training steps that go straight to
    ``backward`` never pay for it)."""

    __slots__ = ("_exe",)

    def __init__(self, exe):
        self._exe = exe

    def __iter__(self):
        return iter(self._exe.outputs)

    def __len__(self):
        return len(self._exe.outputs)

    def __getitem__(self, i):
        return self._exe.outputs[i]

    def __repr__(self):
        return repr(self._exe.outputs)


class Executor:
    """Bound symbolic program (parity: ``mx.executor.Executor``).

    One jitted XLA program per (is_train) mode; recompiles transparently on
    shape change (the CachedOp per-signature contract, SURVEY.md §7.2).
    """

    def __init__(self, symbol: Symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()

        self.arg_dict: Dict[str, NDArray] = self._to_dict(
            args, self._arg_names, "args")
        self.aux_dict: Dict[str, NDArray] = self._to_dict(
            aux_states, self._aux_names, "aux_states")
        self._req = _as_req_map(grad_req, self._arg_names)
        if args_grad is None:
            args_grad = {n: NDArray(jnp.zeros_like(self.arg_dict[n]._data))
                         for n in self._arg_names
                         if self._req.get(n, "null") != "null"}
        self.grad_dict: Dict[str, NDArray] = self._to_dict(
            args_grad, [n for n in self._arg_names
                        if self._req.get(n, "null") != "null"], "args_grad")

        self._outputs: List[NDArray] = []
        self._pending = None
        self._jit_cache: Dict[Any, Any] = {}

    @staticmethod
    def _to_dict(vals, names, what) -> Dict[str, NDArray]:
        if vals is None:
            return {}
        if isinstance(vals, dict):
            return {k: v if isinstance(v, NDArray) else NDArray(_as_jax(v))
                    for k, v in vals.items()}
        if isinstance(vals, (list, tuple)):
            if len(vals) != len(names):
                raise MXNetError(
                    f"{what}: expected {len(names)} entries ({names}), "
                    f"got {len(vals)}")
            return {n: v if isinstance(v, NDArray) else NDArray(_as_jax(v))
                    for n, v in zip(names, vals)}
        raise MXNetError(f"{what} must be list or dict")

    @classmethod
    def simple_bind(cls, symbol: Symbol, ctx=None, grad_req="write",
                    **shapes):
        """Allocate argument/gradient buffers from inferred shapes
        (parity: ``sym.simple_bind``)."""
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
        if arg_shapes is None:
            raise MXNetError(
                "simple_bind: could not infer all shapes; provide shapes "
                f"for {symbol.list_arguments()}")
        args = [NDArray(jnp.zeros(s, jnp.float32)) for s in arg_shapes]
        aux = [NDArray(jnp.zeros(s, jnp.float32)) for s in aux_shapes]
        return cls(symbol, ctx, args, None, grad_req, aux)

    # -- execution ---------------------------------------------------- #
    def _compiled(self, is_train: bool):
        if is_train not in self._jit_cache:
            sym = self._symbol

            def fn(arg_vals, aux_vals, key):
                vals = dict(arg_vals)
                vals.update(aux_vals)
                heads, aux_up = _interpret_pure(
                    sym, vals, training=is_train, key=key)
                return tuple(heads), aux_up

            self._jit_cache[is_train] = jax.jit(fn)
        return self._jit_cache[is_train]

    def forward(self, is_train: bool = False, **kwargs):
        """Run the compiled program (parity: ``Executor.forward``). Under
        ``is_train=True`` the vjp closure is stashed for ``backward``."""
        for name, val in kwargs.items():
            arr = val if isinstance(val, NDArray) else NDArray(_as_jax(val))
            if name in self.arg_dict or name not in self.aux_dict:
                self.arg_dict[name] = arr
            else:
                self.aux_dict[name] = arr
        missing = [n for n in self._arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError(f"executor: unbound arguments {missing}")

        if self._pending is not None and self._outputs is None:
            # previous training step never consumed (no backward/outputs
            # read): run it now so its aux (running-stat) updates land
            _ = self.outputs

        arg_vals = {n: self.arg_dict[n]._data for n in self._arg_names}
        aux_vals = {n: self.aux_dict[n]._data for n in self._aux_names}
        key = _random.new_key() if _graph_needs_key(self._symbol) else None

        diff_names = tuple(n for n in self._arg_names
                           if self._req.get(n, "null") != "null")
        if is_train and diff_names:
            # lazy: the fused fwd+bwd XLA program runs at backward();
            # reading .outputs (or aux stats) first forces the fwd-only
            # program instead. Module.fit ignores the proxy and gets ONE
            # fused fwd+bwd per step.
            self._pending = (arg_vals, aux_vals, key, diff_names)
            self._outputs = None
            return _LazyOutputs(self)
        heads, aux_up = self._compiled(is_train)(arg_vals, aux_vals, key)
        self._pending = None
        for name, val in aux_up.items():
            self.aux_dict[name]._data = val
        self._outputs = [NDArray(h) for h in heads]
        return self._outputs

    @property
    def outputs(self) -> List[NDArray]:
        """Forward outputs; under a pending training step this runs the
        fwd-only compiled program (backward() recomputes fwd fused with
        bwd — full rematerialization, the XLA-idiomatic trade)."""
        if self._outputs is None and self._pending is not None:
            arg_vals, aux_vals, key, _ = self._pending
            heads, aux_up = self._compiled(True)(arg_vals, aux_vals, key)
            for name, val in aux_up.items():
                self.aux_dict[name]._data = val
            self._outputs = [NDArray(h) for h in heads]
        return self._outputs if self._outputs is not None else []

    def _compiled_train(self, diff_names, seed_ones):
        """One jitted program computing heads, aux updates AND argument
        gradients (the reference's fwd+bwd GraphExecutor dispatch collapsed
        into a single XLA compilation — SURVEY.md §3.3 TPU translation)."""
        ck = ("train", diff_names, seed_ones)
        if ck not in self._jit_cache:
            sym = self._symbol

            def fn(diff_vals, const_vals, aux_vals, key, cots):
                def diff_fn(dv):
                    vals = dict(const_vals)
                    vals.update(dv)
                    vals.update(aux_vals)
                    heads, aux_up = _interpret_pure(sym, vals, training=True,
                                                    key=key)
                    return tuple(heads), aux_up

                heads, vjp, aux_up = jax.vjp(diff_fn, diff_vals,
                                             has_aux=True)
                seed = tuple(jnp.ones_like(h) for h in heads) \
                    if seed_ones else cots
                grads = vjp(seed)[0]
                return heads, aux_up, grads

            self._jit_cache[ck] = jax.jit(fn)
        return self._jit_cache[ck]

    def backward(self, out_grads=None):
        """Accumulate argument gradients per grad_req (parity:
        ``Executor.backward``; `kAddTo` semantics under grad_req='add')."""
        if self._pending is None:
            if not any(self._req.get(n, "null") != "null"
                       for n in self._arg_names):
                # nothing differentiable (all grad_req='null'): reference
                # Executor.backward is a no-op here, not an error
                return self.grad_dict
            raise MXNetError("backward called before forward(is_train=True)")
        arg_vals, aux_vals, key, diff_names = self._pending
        seed_ones = out_grads is None
        if seed_ones:
            if len(self._symbol.list_outputs()) != 1:
                raise MXNetError("multi-output executor needs explicit "
                                 "out_grads")
            cots = ()
        else:
            if isinstance(out_grads, (NDArray, jax.Array)):
                out_grads = [out_grads]
            cots = tuple(g._data if isinstance(g, NDArray) else _as_jax(g)
                         for g in out_grads)
        diff_vals = {n: arg_vals[n] for n in diff_names}
        const_vals = {n: v for n, v in arg_vals.items()
                      if n not in diff_names}
        fn = self._compiled_train(diff_names, seed_ones)
        heads, aux_up, grads = fn(diff_vals, const_vals, aux_vals, key, cots)
        self._pending = None  # consumed
        for name, val in aux_up.items():
            self.aux_dict[name]._data = val
        self._outputs = [NDArray(h) for h in heads]
        for name, g in grads.items():
            req = self._req.get(name, "null")
            if req == "null":
                continue
            if req == "add" and name in self.grad_dict:
                self.grad_dict[name]._data = self.grad_dict[name]._data + g
            elif name in self.grad_dict:
                self.grad_dict[name]._data = g
            else:
                self.grad_dict[name] = NDArray(g)
        return self.grad_dict

    # -- parity accessors --------------------------------------------- #
    @property
    def arg_arrays(self) -> List[NDArray]:
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self) -> List[Optional[NDArray]]:
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self) -> List[NDArray]:
        return [self.aux_dict[n] for n in self._aux_names]

    @staticmethod
    def _set_in_place(dst: NDArray, val, what: str, name: str):
        """Write into an existing buffer so by-reference sharing survives
        (BucketingModule's shared executors capture these objects)."""
        arr = val._data if isinstance(val, NDArray) else _as_jax(val)
        if tuple(arr.shape) != tuple(dst.shape):
            raise MXNetError(
                f"{what} {name!r}: shape {tuple(arr.shape)} does not match "
                f"bound shape {tuple(dst.shape)}")
        dst._data = arr.astype(dst._data.dtype)

    def copy_params_from(self, arg_params: Dict[str, NDArray],
                         aux_params: Optional[Dict[str, NDArray]] = None,
                         allow_extra_params: bool = False):
        for name, val in arg_params.items():
            if name in self._arg_names:
                if name in self.arg_dict:
                    self._set_in_place(self.arg_dict[name], val,
                                       "argument", name)
                else:
                    self.arg_dict[name] = val if isinstance(val, NDArray) \
                        else NDArray(_as_jax(val))
            elif not allow_extra_params:
                raise MXNetError(f"unknown argument {name!r}")
        for name, val in (aux_params or {}).items():
            if name in self._aux_names:
                if name in self.aux_dict:
                    self._set_in_place(self.aux_dict[name], val,
                                       "aux state", name)
                else:
                    self.aux_dict[name] = val if isinstance(val, NDArray) \
                        else NDArray(_as_jax(val))
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux state {name!r}")

    def reshape(self, partial_shaping=False, **shapes):
        """Rebind with new shapes, SHARING parameter arrays with this
        executor (parity: ``Executor.reshape`` shares contents — updates
        through either executor stay visible to both)."""
        new = Executor.simple_bind(self._symbol, self._ctx,
                                   grad_req=self._req, **shapes)
        for name, old in self.arg_dict.items():
            if name in new.arg_dict and \
                    tuple(new.arg_dict[name].shape) == tuple(old.shape):
                new.arg_dict[name] = old
            elif not partial_shaping and name not in shapes:
                raise MXNetError(
                    f"reshape: parameter {name!r} changed shape; pass "
                    f"partial_shaping=True to allow re-initialization")
        for name, old in self.aux_dict.items():
            if name in new.aux_dict and \
                    tuple(new.aux_dict[name].shape) == tuple(old.shape):
                new.aux_dict[name] = old
        return new
