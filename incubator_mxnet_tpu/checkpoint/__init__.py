"""Elastic checkpointing subsystem.

The production-TPU redesign of the reference's synchronous whole-tree
``.params`` saves (SURVEY.md §5.4): async double-buffered sharded
snapshots with atomic commit, preemption-safe final saves, bit-exact
training resume, and serve warm-restart. See docs/CHECKPOINTING.md.

Quick use::

    from incubator_mxnet_tpu import checkpoint as ckpt

    mgr = ckpt.CheckpointManager("/ckpts/run0", keep=3)
    trainer.install_preemption(mgr, iterator=it)   # SIGTERM-safe
    for step in range(n):
        ...train...
        if step % 100 == 0:
            trainer.save_checkpoint(mgr, iterator=it)

    # preempted? new process:
    step = trainer.restore_checkpoint(mgr, iterator=it)   # bit-exact
"""

from .manager import CheckpointManager, gather_tree
from .manifest import (FORMAT_VERSION, MANIFEST_NAME, gc_steps, list_steps,
                       load_step, step_dir, write_step)
from .capsule import (CAPSULE_MAGIC, dump_capsule_bytes, fill_state,
                      flatten_state, is_capsule_bytes,
                      load_capsule_bytes, load_capsule_file,
                      restore_spmd, restore_trainer, restore_updater,
                      save_capsule_file, spmd_capsule, trainer_capsule,
                      updater_capsule)

__all__ = [
    "CheckpointManager", "gather_tree",
    "write_step", "load_step", "list_steps", "gc_steps", "step_dir",
    "FORMAT_VERSION", "MANIFEST_NAME",
    "CAPSULE_MAGIC", "dump_capsule_bytes", "load_capsule_bytes",
    "is_capsule_bytes", "save_capsule_file", "load_capsule_file",
    "trainer_capsule", "restore_trainer", "spmd_capsule", "restore_spmd",
    "updater_capsule", "restore_updater", "flatten_state", "fill_state",
]
