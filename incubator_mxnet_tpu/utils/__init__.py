"""Utility subsystems: serialization, docs, misc helpers."""

from . import serialization  # noqa: F401
