"""Collective communication wrappers.

The reference's comm layer is explicit code paths per transport: CPU reduce
(`CommCPU`), GPU P2P/tree reduce (`CommDevice`/`CommDeviceTree`), NCCL
(`kvstore_nccl.h`), ZMQ parameter server (ps-lite) — SURVEY.md §5.8. Here
every collective is an XLA op on a mesh axis; the compiler schedules it on
ICI within a slice and DCN across slices, and overlap with compute comes
from XLA's latency-hiding scheduler (the reference's P3 priority scheduling
has no manual analogue — SURVEY.md §2.3).

Two API levels:
  - in-step (traced) collectives for use inside `shard_map`-ped functions:
    thin aliases of `jax.lax` collectives, kept here so model code imports
    one namespace;
  - host-level eager helpers (`host_allreduce`) used by the KVStore facade
    for cross-process reduction outside a compiled step.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# ----------------------------------------------------------------------- #
# traced collectives (inside shard_map / pmapped code)
# ----------------------------------------------------------------------- #
psum = lax.psum
pmean = lax.pmean
pmax = lax.pmax
pmin = lax.pmin
ppermute = lax.ppermute
all_gather = lax.all_gather
all_to_all = lax.all_to_all
axis_index = lax.axis_index


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0,
                   tiled: bool = True):
    """Sum across ``axis_name`` and scatter shards along
    ``scatter_dimension`` (reference capability: the reduce half of a
    ring allreduce; used for ZeRO-style grad sharding)."""
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=tiled)


# ----------------------------------------------------------------------- #
# host-level eager collectives (the KVStore facade's transport)
# ----------------------------------------------------------------------- #
def host_allreduce(x: jax.Array, op: str = "sum",
                   compression: Optional[str] = None) -> jax.Array:
    """Eager cross-process allreduce over DCN.

    Replaces the reference's dist_sync push path (worker → ps-lite server
    aggregate → pull, SURVEY.md §3.4): every process contributes its local
    array; all processes get the elementwise reduction. Single-process is
    the identity (the in-process multi-device reduction already happened in
    the caller).
    """
    if jax.process_count() == 1:
        return x
    from jax.experimental import multihost_utils

    if op != "sum":
        raise ValueError(f"unsupported host_allreduce op {op!r}")
    if compression == "bf16" and x.dtype == jnp.float32:
        # REAL wire savings (unlike the reference's 2-bit emulation in
        # kvstore): halve the bytes crossing DCN by gathering bf16,
        # accumulate in f32 — the TPU-idiomatic compressed collective
        gathered = multihost_utils.process_allgather(
            x.astype(jnp.bfloat16))
        return jnp.sum(gathered.astype(jnp.float32), axis=0)
    gathered = multihost_utils.process_allgather(x)  # (n_proc, ...)
    return jnp.sum(gathered, axis=0)


def host_broadcast(x: jax.Array, root: int = 0) -> jax.Array:
    """Broadcast ``x`` from the root process to all processes (the
    reference's init-time weight broadcast via kvstore init/pull)."""
    if jax.process_count() == 1:
        return x
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(
        x, is_source=jax.process_index() == root)


def host_barrier(tag: str = "barrier"):
    """Cross-process barrier (reference: ps-lite ``Barrier``)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)
