"""Round 19 (ISSUE 19): in-program overlapped gradient collectives.

The pipelined SPMD step (parallel/pipelined.py) restructures the one-
program train step so each gradient bucket's collective is issued
BETWEEN block pullbacks instead of after the whole backward. Its
correctness surface, asserted here:

- bitwise parity with the GSPMD step on clean streams (dp2 AND fsdp2,
  single-step and accumulated k in {1,4,8}) — losses, params, optimizer
  state;
- the compiled program's grad-collective order equals the
  ``plan_grad_buckets`` plan order (deterministic-rendezvous contract),
  re-derived from lowered StableHLO, with backward dots strictly
  between the first and last bucket (the structural overlap gate);
- one compile per (mesh, microbatch-shape) family — an accumulation-
  count change never retraces;
- the PR-8 guard veto matrix (test_train_perf.py) holds unchanged on
  the pipelined path, including int8 mode where the verdict reads the
  DEQUANTIZED gradients;
- the profile-driven remat plan (models/_remat.plan_remat_from_profile)
  keeps bitwise parity with the baseline's model-level remat.

The tiny-Dense fsdp pairs assert allclose rather than bitwise: with
MXTPU_FSDP_MIN_SIZE=0 every (16,8)/(4,16) weight shards, and GSPMD's
partitioner picks per-dot between partial+all-reduce+slice (matching
the pipelined psum+slice scheme) and all-to-all+full-batch contraction
(a different summation split) by cost model — an ulp-level artifact of
the artificial shapes. The real-model fsdp pairs (gpt_mini/bert_tiny,
default MIN_SIZE: only embedding tables shard) ARE bitwise and are
asserted so below.
"""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, parallel
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.models._remat import plan_remat_from_profile
from incubator_mxnet_tpu.parallel import mesh as pmesh
from incubator_mxnet_tpu.parallel.collectives import plan_grad_buckets
from incubator_mxnet_tpu.parallel.pipelined import PipelineSpec

BUCKET_BYTES = "256"          # tiny nets: force a multi-bucket plan


def _build_net(seed=0):
    mx.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"),
            nn.Dense(4, in_units=16))
    net.initialize()
    return net


def _flagged_mse(block, x, y, flag):
    out = block(x)
    return ((out - y) ** 2).mean() * flag.mean()


def _mse_spec(net):
    """PipelineSpec mirroring _flagged_mse: local partial sums + counts,
    finalize reproduces mean(sq) * mean(flag) on the globals."""
    import jax.numpy as jnp

    def head(x, X, y, flag):
        sq = (x._data - y._data) ** 2
        f = flag._data
        return (jnp.sum(sq), jnp.float32(sq.size),
                jnp.sum(f), jnp.float32(f.size))

    def fin(n1, d1, n2, d2):
        return (n1 / d1) * (n2 / d2)

    return PipelineSpec(blocks=[net[0], net[1]], head=head, finalize=fin)


def _setup(sharding, axes, pipelined, seed=7, **kw):
    import jax
    net = _build_net(seed=seed)
    mesh = pmesh.build_mesh(devices=jax.devices()[:2], axis_sizes=axes)
    if pipelined:
        tr = parallel.SPMDTrainer(
            net, pipeline=_mse_spec(net), optimizer="adam",
            optimizer_params={"learning_rate": 0.01}, mesh=mesh,
            sharding=sharding, **kw)
    else:
        tr = parallel.SPMDTrainer(
            net, forward_loss=_flagged_mse, optimizer="adam",
            optimizer_params={"learning_rate": 0.01}, mesh=mesh,
            sharding=sharding, **kw)
    return net, tr


def _snap(net):
    return [p.data().asnumpy().copy()
            for p in net.collect_params().values()]


def _run_steps(tr, X, y, n=5, nan_at=None):
    losses = []
    for s in range(n):
        flag = np.ones((X.shape[0],), np.float32)
        if s == nan_at:
            flag[0] = np.nan
        L = tr.step(nd.array(X), nd.array(y), nd.array(flag))
        losses.append(np.asarray(L.asnumpy()).copy())
    return losses


def _data(seed=1, n=8):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 8).astype(np.float32),
            rng.randn(n, 4).astype(np.float32))


def _pair(sharding, axes, nan_at=None, collective=None, **kw):
    X, y = _data()
    net0, tr0 = _setup(sharding, axes, False)
    kw1 = dict(kw)
    if collective:
        kw1["grad_collective"] = collective
    net1, tr1 = _setup(sharding, axes, True, **kw1)
    l0 = _run_steps(tr0, X, y, nan_at=nan_at)
    l1 = _run_steps(tr1, X, y, nan_at=nan_at)
    return net0, tr0, l0, net1, tr1, l1


# --------------------------------------------------------------------- #
# bitwise parity + veto matrix (single-step path)
# --------------------------------------------------------------------- #

@pytest.mark.slow
@pytest.mark.parametrize("nan_at", [None, 2])
def test_pipelined_dp2_bitwise_clean_and_veto(monkeypatch, nan_at):
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", BUCKET_BYTES)
    net0, tr0, l0, net1, tr1, l1 = _pair("replicated", {"dp": 2},
                                         nan_at=nan_at)
    for a, b in zip(l0, l1):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_snap(net0), _snap(net1)):
        np.testing.assert_array_equal(a, b)
    assert tr1.pipelined_step_trace_count == 1
    if nan_at is not None:
        # the veto composed identically on both paths
        assert tr0.step_count == tr1.step_count == 4


@pytest.mark.slow
@pytest.mark.parametrize("nan_at", [None, 1])
def test_pipelined_fsdp2_dense_matches_and_vetoes(monkeypatch, nan_at):
    monkeypatch.setenv("MXTPU_FSDP_MIN_SIZE", "0")
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", BUCKET_BYTES)
    net0, tr0, l0, net1, tr1, l1 = _pair("fsdp", {"dp": 1, "fsdp": 2},
                                         nan_at=nan_at)
    # losses stay bitwise; params allclose only (see module docstring:
    # GSPMD's per-dot contraction choice on these artificial shapes)
    for a, b in zip(l0, l1):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_snap(net0), _snap(net1)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    assert tr1.pipelined_step_trace_count == 1
    if nan_at is not None:
        assert tr0.step_count == tr1.step_count == 4


@pytest.mark.slow
def test_pipelined_ring_collective_bitwise_dp2(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", BUCKET_BYTES)
    net0, tr0, l0, net1, tr1, l1 = _pair("replicated", {"dp": 2},
                                         collective="ring")
    for a, b in zip(l0, l1):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_snap(net0), _snap(net1)):
        np.testing.assert_array_equal(a, b)
    # ring lowers to collective-permute chains, not all-reduce
    rep = tr1.pipelined_structure()
    assert rep["collective_op"] == "collective_permute"
    assert rep["n_grad_collective_groups"] >= 1


# --------------------------------------------------------------------- #
# compiled order == plan order, interleaving (the structural gate)
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("sharding,axes", [
    ("replicated", {"dp": 2}),
    ("fsdp", {"dp": 1, "fsdp": 2}),
])
def test_pipelined_order_matches_plan_and_interleaves(monkeypatch,
                                                      sharding, axes):
    monkeypatch.setenv("MXTPU_FSDP_MIN_SIZE", "0")
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", BUCKET_BYTES)
    X, y = _data()
    net, tr = _setup(sharding, axes, True)
    _run_steps(tr, X, y, n=2, nan_at=1)     # veto step runs SAME program
    # the issue ledger is the plan order (trace-time contract) ...
    params = tr._params
    members = [(i, int(params[i]._data._data.size),
                int(params[i]._data._data.dtype.itemsize),
                str(params[i]._data._data.dtype)) for i in tr._train_idx]
    plan = plan_grad_buckets(members, 256)
    assert len(plan) > 1                    # a real multi-bucket schedule
    assert tr.pipelined_bucket_order == [b.key for b in plan]
    # ... and the COMPILED program agrees: collectives in plan order,
    # backward dots strictly between the first and last bucket
    rep = tr.pipelined_structure()
    assert rep["n_buckets"] == len(plan)
    assert rep["order_matches_plan"]
    assert rep["interleaved"]
    assert rep["n_backward_dots_between"] >= 1


# --------------------------------------------------------------------- #
# accumulation: k in {1,4,8}, one trace, parity, guard verdict
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("sharding,axes", [
    ("replicated", {"dp": 2}),
    ("fsdp", {"dp": 1, "fsdp": 2}),
])
@pytest.mark.slow
def test_pipelined_accum_one_trace_and_parity(monkeypatch, sharding,
                                              axes):
    monkeypatch.setenv("MXTPU_FSDP_MIN_SIZE", "0")
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", BUCKET_BYTES)
    X, y = _data(seed=2, n=16)
    net0, tr0 = _setup(sharding, axes, False)
    net1, tr1 = _setup(sharding, axes, True)
    for k in (1, 4, 8):
        micros = [(nd.array(X[m * 2:(m + 1) * 2]),
                   nd.array(y[m * 2:(m + 1) * 2]),
                   nd.array(np.ones(2, np.float32))) for m in range(k)]
        L0 = tr0.step_microbatches(micros)
        L1 = tr1.step_microbatches(micros)
        np.testing.assert_array_equal(L0.asnumpy(), L1.asnumpy())
    if sharding == "fsdp":
        for a, b in zip(_snap(net0), _snap(net1)):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    else:
        for a, b in zip(_snap(net0), _snap(net1)):
            np.testing.assert_array_equal(a, b)
    assert tr1.pipelined_accum_step_trace_count == 1
    rep = tr1.pipelined_structure(accum=True)
    assert rep["order_matches_plan"] and rep["interleaved"]


@pytest.mark.slow
def test_pipelined_accum_nonfinite_micro_vetoes_round(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", BUCKET_BYTES)
    from incubator_mxnet_tpu.train import StepOutcome
    X, y = _data(seed=3, n=16)
    net, tr = _setup("replicated", {"dp": 2}, True)

    def micros(nan_at=None):
        out = []
        for m in range(4):
            flag = np.ones((4,), np.float32)
            if m == nan_at:
                flag[0] = np.nan
            out.append((nd.array(X[m * 4:(m + 1) * 4]),
                        nd.array(y[m * 4:(m + 1) * 4]), nd.array(flag)))
        return out

    tr.step_microbatches(micros())
    before = _snap(net)
    tr.step_microbatches(micros(nan_at=1))
    assert tr.last_outcome is StepOutcome.SKIPPED_NONFINITE
    for a, b in zip(_snap(net), before):
        np.testing.assert_array_equal(a, b)
    tr.step_microbatches(micros())
    assert tr.last_outcome is StepOutcome.APPLIED
    assert tr.pipelined_accum_step_trace_count == 1


# --------------------------------------------------------------------- #
# int8 traced allreduce: guard reads dequantized grads, structure holds
# --------------------------------------------------------------------- #

@pytest.mark.slow
def test_pipelined_int8_guard_on_dequantized_grads(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", BUCKET_BYTES)
    X, y = _data()
    net, tr = _setup("replicated", {"dp": 2}, True, int8_allreduce=True)
    _run_steps(tr, X, y, n=3, nan_at=1)
    # the NaN poisons amax -> scale -> every dequantized member, and the
    # guard (reading dequantized grads) vetoed exactly that step
    assert tr.step_count == 2
    assert all(e["op"] == "int8_psum" for e in tr.pipelined_issue_ledger)
    rep = tr.pipelined_structure()
    assert rep["order_matches_plan"] and rep["interleaved"]


def test_int8_composes_with_psum_only():
    with pytest.raises(MXNetError, match="psum"):
        _setup("replicated", {"dp": 2}, True, int8_allreduce=True,
               grad_collective="ring")


# --------------------------------------------------------------------- #
# rejection surfaces
# --------------------------------------------------------------------- #

def test_pipelined_rejects_tensor_parallel_mesh():
    import jax
    net = _build_net()
    mesh = pmesh.build_mesh(devices=jax.devices()[:2],
                            axis_sizes={"tp": 2})
    tr = parallel.SPMDTrainer(
        net, pipeline=_mse_spec(net), optimizer="adam",
        optimizer_params={"learning_rate": 0.01}, mesh=mesh,
        sharding="replicated")
    X, y = _data()
    with pytest.raises(MXNetError, match="dp/fsdp"):
        tr.step(nd.array(X), nd.array(y),
                nd.array(np.ones(8, np.float32)))


def test_pipelined_rejects_norm_optimizer_under_fsdp(monkeypatch):
    monkeypatch.setenv("MXTPU_FSDP_MIN_SIZE", "0")
    import jax
    net = _build_net()
    mesh = pmesh.build_mesh(devices=jax.devices()[:2],
                            axis_sizes={"dp": 1, "fsdp": 2})
    tr = parallel.SPMDTrainer(
        net, pipeline=_mse_spec(net), optimizer="lamb",
        optimizer_params={"learning_rate": 0.01}, mesh=mesh,
        sharding="fsdp")
    X, y = _data()
    with pytest.raises(MXNetError, match="norm-based"):
        tr.step(nd.array(X), nd.array(y),
                nd.array(np.ones(8, np.float32)))


def test_pipelined_rejects_param_mutating_forward():
    mx.random.seed(0)
    net = nn.Sequential()
    net.add(nn.Dense(16, in_units=8), nn.BatchNorm(in_channels=16),
            nn.Dense(4, in_units=16))
    net.initialize()
    import jax
    import jax.numpy as jnp
    mesh = pmesh.build_mesh(devices=jax.devices()[:2],
                            axis_sizes={"dp": 2})

    def head(x, X, y, flag):
        sq = (x._data - y._data) ** 2
        return (jnp.sum(sq), jnp.float32(sq.size))

    spec = PipelineSpec(blocks=[net[0], net[1], net[2]], head=head,
                        finalize=lambda n, d: n / d)
    tr = parallel.SPMDTrainer(
        net, pipeline=spec, optimizer="adam",
        optimizer_params={"learning_rate": 0.01}, mesh=mesh,
        sharding="replicated")
    X, y = _data()
    with pytest.raises(MXNetError, match="mutating"):
        tr.step(nd.array(X), nd.array(y),
                nd.array(np.ones(8, np.float32)))


def test_pipeline_spec_validation_errors():
    net = _build_net()
    params = list(net.collect_params().values())
    train_idx = list(range(len(params)))
    # a block listed twice -> overlap error
    spec = PipelineSpec(blocks=[net[0], net[0]], head=lambda x: (x,),
                        finalize=lambda n: n)
    with pytest.raises(MXNetError, match="disjoint"):
        spec.segment_params(params, train_idx)
    # an uncovered trainable -> loud error naming it
    spec = PipelineSpec(blocks=[net[0]], head=lambda x: (x,),
                        finalize=lambda n: n)
    with pytest.raises(MXNetError, match="does not cover"):
        spec.segment_params(params, train_idx)
    # a tie into a pipeline block (not stem<->head) -> rejected
    spec = PipelineSpec(blocks=[net[0], net[1]], head=lambda x: (x,),
                        finalize=lambda n: n, head_modules=[net[1]])
    with pytest.raises(MXNetError, match="stem and head"):
        spec.segment_params(params, train_idx)


# --------------------------------------------------------------------- #
# profile-driven remat plan
# --------------------------------------------------------------------- #

def test_plan_remat_from_profile_heuristic():
    # no attribution (cpu_mode trace) -> never guess
    assert plan_remat_from_profile({}, 4) == [False] * 4
    assert plan_remat_from_profile(
        {"compute_us": 0.0, "exposed_us": 50.0}, 3) == [False] * 3
    # collectives already hidden -> no remat
    assert plan_remat_from_profile(
        {"compute_us": 1000.0, "exposed_us": 10.0}, 4) == [False] * 4
    # mild exposure -> selective "dots" everywhere
    assert plan_remat_from_profile(
        {"compute_us": 1000.0, "exposed_us": 100.0}, 4) == ["dots"] * 4
    # heavy exposure -> full remat on the earliest ceil(frac*n) blocks
    plan = plan_remat_from_profile(
        {"compute_us": 1000.0, "exposed_us": 500.0}, 4)
    assert plan == [True, True, "dots", "dots"]
    assert plan_remat_from_profile(
        {"compute_us": 100.0, "exposed_us": 500.0}, 2) == [True, True]
    assert plan_remat_from_profile({"compute_us": 1.0}, 0) == []


def test_remat_plan_requires_pipeline():
    net = _build_net()
    with pytest.raises(MXNetError, match="pipeline"):
        parallel.SPMDTrainer(
            net, forward_loss=_flagged_mse, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            remat_plan=["dots", "dots"])


# --------------------------------------------------------------------- #
# real models: gpt/bert pipeline specs (heavier compiles -> slow tier)
# --------------------------------------------------------------------- #

def _gpt_pair(sharding, axes, weighted=False, remat=False,
              remat_plan=None, steps=3, seed=3):
    import jax
    from incubator_mxnet_tpu.models.gpt import (gpt_mini, lm_loss,
                                                lm_pipeline)
    T = 16
    mesh = pmesh.build_mesh(devices=jax.devices()[:2], axis_sizes=axes)
    mx.random.seed(seed)
    m0 = gpt_mini(max_length=T, remat=remat)
    m0.initialize()
    mx.random.seed(seed)
    m1 = gpt_mini(max_length=T)
    m1.initialize()
    tr0 = parallel.SPMDTrainer(m0, forward_loss=lm_loss,
                               optimizer="adam",
                               optimizer_params={"learning_rate": 1e-3},
                               mesh=mesh, sharding=sharding)
    tr1 = parallel.SPMDTrainer(m1,
                               pipeline=lm_pipeline(m1, weighted=weighted),
                               optimizer="adam",
                               optimizer_params={"learning_rate": 1e-3},
                               mesh=mesh, sharding=sharding,
                               remat_plan=remat_plan)
    rng = np.random.RandomState(0)
    B, V = 4, 512
    losses = []
    for s in range(steps):
        ids = nd.array(rng.randint(0, V, (B, T)).astype(np.int32))
        lbl = nd.array(rng.randint(0, V, (B, T)).astype(np.int32))
        batch = (ids, lbl)
        if weighted:
            batch += (nd.array(rng.rand(B, T).astype(np.float32)),)
        L0 = tr0.step(*batch)
        L1 = tr1.step(*batch)
        losses.append((L0.asnumpy().copy(), L1.asnumpy().copy()))
    return m0, tr0, m1, tr1, losses


def _assert_model_parity(m0, m1, losses):
    for a, b in losses:
        np.testing.assert_array_equal(a, b)
    # name counters differ between instances; compare positionally
    for (_, a), (_, b) in zip(
            [(k, p.data().asnumpy()) for k, p in
             m0.collect_params().items()],
            [(k, p.data().asnumpy()) for k, p in
             m1.collect_params().items()]):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
@pytest.mark.parametrize("sharding,axes", [
    ("replicated", {"dp": 2}),
    ("fsdp", {"dp": 1, "fsdp": 2}),
])
def test_gpt_lm_pipeline_bitwise(monkeypatch, sharding, axes):
    """gpt_mini: the real tied-embedding LM spec is bitwise on dp2 AND
    fsdp2 (default MXTPU_FSDP_MIN_SIZE: the embedding table shards, and
    the tied-head cotangent rides the owning bucket's collective as an
    extra operand, summed post-reduction — the AR-then-add parity
    rule). The fsdp2 bitwise claim is pinned at THIS T=16 shape
    regime: GSPMD's per-dot contraction choice for sharded params is
    shape-dependent, and at e.g. T=32 it diverges from the pipelined
    program at ulp (step_bench gates that regime at allclose; see
    docs/TRAINING_PERF.md)."""
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", "262144")
    m0, tr0, m1, tr1, losses = _gpt_pair(sharding, axes)
    _assert_model_parity(m0, m1, losses)
    assert tr1.pipelined_step_trace_count == 1
    rep = tr1.pipelined_structure()
    assert rep["order_matches_plan"] and rep["interleaved"]


@pytest.mark.slow
def test_gpt_lm_pipeline_weighted_bitwise(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", "262144")
    m0, tr0, m1, tr1, losses = _gpt_pair("replicated", {"dp": 2},
                                         weighted=True)
    _assert_model_parity(m0, m1, losses)


@pytest.mark.slow
@pytest.mark.parametrize("rm", ["dots", True])
def test_gpt_pipelined_remat_bitwise_vs_baseline_remat(monkeypatch, rm):
    """remat-vs-remat parity: pipelined remat_plan=[rm]*N is bitwise the
    baseline model(remat=rm) — jax.checkpoint changes XLA fusion at the
    ulp level vs NO checkpoint in both worlds equally, so the honest
    comparison is checkpoint against checkpoint."""
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", "262144")
    m0, tr0, m1, tr1, losses = _gpt_pair(
        "replicated", {"dp": 2}, remat=rm,
        remat_plan=[rm] * 2)
    _assert_model_parity(m0, m1, losses)


@pytest.mark.slow
@pytest.mark.parametrize("sharding,axes", [
    ("replicated", {"dp": 2}),
    ("fsdp", {"dp": 1, "fsdp": 2}),
])
def test_bert_pretraining_pipeline_bitwise(monkeypatch, sharding, axes):
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", "262144")
    import jax
    from incubator_mxnet_tpu.models.bert import (BERTForPretraining,
                                                 bert_tiny,
                                                 pretraining_loss,
                                                 pretraining_pipeline)
    B, T, V, M = 4, 16, 1024, 6
    mesh = pmesh.build_mesh(devices=jax.devices()[:2], axis_sizes=axes)
    mx.random.seed(5)
    b0 = BERTForPretraining(bert_tiny(vocab_size=V, max_length=T,
                                      dropout=0.0))
    b0.initialize()
    mx.random.seed(5)
    b1 = BERTForPretraining(bert_tiny(vocab_size=V, max_length=T,
                                      dropout=0.0))
    b1.initialize()
    tr0 = parallel.SPMDTrainer(b0, forward_loss=pretraining_loss,
                               optimizer="adam",
                               optimizer_params={"learning_rate": 1e-3},
                               mesh=mesh, sharding=sharding)
    tr1 = parallel.SPMDTrainer(b1, pipeline=pretraining_pipeline(b1),
                               optimizer="adam",
                               optimizer_params={"learning_rate": 1e-3},
                               mesh=mesh, sharding=sharding)
    rng = np.random.RandomState(1)
    losses = []
    for _ in range(3):
        batch = (
            nd.array(rng.randint(0, V, (B, T)).astype(np.int32)),
            nd.array(rng.randint(0, 2, (B, T)).astype(np.int32)),
            nd.array(np.full((B,), T, np.int32)),
            nd.array(np.stack([rng.choice(T, M, replace=False)
                               for _ in range(B)]).astype(np.int32)),
            nd.array(rng.randint(0, V, (B, M)).astype(np.int32)),
            nd.array((rng.rand(B, M) > 0.2).astype(np.float32)),
            nd.array(rng.randint(0, 2, (B,)).astype(np.int32)),
        )
        losses.append((tr0.step(*batch).asnumpy().copy(),
                       tr1.step(*batch).asnumpy().copy()))
    _assert_model_parity(b0, b1, losses)
    assert tr1.pipelined_step_trace_count == 1
    rep = tr1.pipelined_structure()
    assert rep["order_matches_plan"] and rep["interleaved"]
