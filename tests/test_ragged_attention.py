"""Ragged paged-KV decode attention tests.

Reference test idiom §4.2 (cross-backend consistency): the Pallas
kernel runs in INTERPRET mode on CPU and must match (a) the pure-jnp
gather reference and (b) the repo's existing dense masked SDPA — the
same masked-row contract as ops.pallas_attention, now over a paged
pool with arbitrary (shuffled) page tables."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_mxnet_tpu.ops.ragged_attention import (
    _ragged_pallas, ragged_attention_reference, ragged_paged_attention)


def _make_case(rng, S, H, D, page_size, max_pages, lengths,
               num_pages=None, dtype=np.float32):
    """Random pools + a SHUFFLED page table (non-identity page order —
    the thing a paged cache must get right) for the given lengths."""
    lengths = np.asarray(lengths, np.int32)
    n_live = [-(-int(l) // page_size) for l in lengths]
    if num_pages is None:
        num_pages = 1 + sum(n_live)
    q = rng.randn(S, H, D).astype(dtype)
    k_pool = rng.randn(num_pages, H, page_size, D).astype(dtype)
    v_pool = rng.randn(num_pages, H, page_size, D).astype(dtype)
    perm = rng.permutation(np.arange(1, num_pages))  # page 0 = null
    pt = np.zeros((S, max_pages), np.int32)
    used = 0
    for s in range(S):
        pt[s, :n_live[s]] = perm[used:used + n_live[s]]
        used += n_live[s]
    return (jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(pt), jnp.asarray(lengths))


def _dense_sdpa_oracle(q, k_pool, v_pool, pt, lengths):
    """Gather each slot's pages into a dense (S, K, H, D) window and run
    the repo's dense masked SDPA — the equivalence target the ISSUE
    names (the serving kernel must agree with the training-side
    attention math)."""
    from incubator_mxnet_tpu.ops.attention import _sdpa_dense
    S, H, D = q.shape
    ps = k_pool.shape[2]
    K = pt.shape[1] * ps
    k = jnp.moveaxis(k_pool[pt], 2, 1).reshape(S, H, K, D)
    v = jnp.moveaxis(v_pool[pt], 2, 1).reshape(S, H, K, D)
    mask = (jnp.arange(K)[None, :] <
            lengths[:, None])[:, None, None, :]          # (S,1,1,K)
    # _sdpa_dense wants (B, T, H, D); one query row per slot
    out = _sdpa_dense(q[:, None], k.transpose(0, 2, 1, 3),
                      v.transpose(0, 2, 1, 3), mask, D ** -0.5)
    return out[:, 0]                                     # (S, H, D)


LENGTH_CASES = [
    # the ISSUE's required row lengths: {0, 1, page_size, page_size+1,
    # Tmax} and mixed occupancy, page boundaries included
    [0, 1, 8, 9, 32],
    [0, 0, 0, 0, 0],        # empty batch: all rows masked
    [32, 32, 32, 32, 32],   # full batch at Tmax
    [7, 8, 9, 15, 16],      # straddling page boundaries
]


@pytest.mark.parametrize("lengths", LENGTH_CASES)
@pytest.mark.parametrize("impl", ["pallas_interpret", "jnp"])
def test_ragged_matches_dense_sdpa(lengths, impl):
    rng = np.random.RandomState(0)
    S, H, D, ps = len(lengths), 3, 8, 8
    max_pages = 4                                       # Tmax = 32
    q, kp, vp, pt, ln = _make_case(rng, S, H, D, ps, max_pages, lengths)
    if impl == "pallas_interpret":
        got = _ragged_pallas(q, kp, vp, pt, ln, D ** -0.5, True)
    else:
        got = ragged_attention_reference(q, kp, vp, pt, ln)
    ref = _dense_sdpa_oracle(q, kp, vp, pt, ln)
    # fully-masked rows: exactly zero (kernel contract); _sdpa_dense
    # emits the uniform mean of V there, so compare only live rows
    # against the oracle and pin dead rows to zero explicitly
    got_np, ref_np = np.asarray(got), np.asarray(ref)
    for s, l in enumerate(lengths):
        if l == 0:
            np.testing.assert_array_equal(got_np[s], 0.0)
        else:
            np.testing.assert_allclose(got_np[s], ref_np[s],
                                       rtol=2e-5, atol=2e-5)


def test_pallas_interpret_matches_jnp_reference_exhaustive():
    """Kernel vs jnp reference agree everywhere (both contracts include
    the zero-row rule, so no row exclusions), across odd page sizes and
    a pool with unused pages."""
    rng = np.random.RandomState(1)
    for ps, lengths in [(4, [0, 1, 4, 5, 13]), (16, [16, 1, 0, 33, 48])]:
        max_pages = -(-max(lengths) // ps) if max(lengths) else 1
        q, kp, vp, pt, ln = _make_case(rng, len(lengths), 2, 16, ps,
                                       max_pages, lengths,
                                       num_pages=64)
        a = _ragged_pallas(q, kp, vp, pt, ln, 16 ** -0.5, True)
        b = ragged_attention_reference(q, kp, vp, pt, ln)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


def test_null_page_contents_never_leak():
    """Dead page-table entries point at page 0; poisoning page 0 with
    huge values must not change any output — the null-page invariant
    the whole serve/ design rests on."""
    rng = np.random.RandomState(2)
    ps = 8
    q, kp, vp, pt, ln = _make_case(rng, 4, 2, 8, ps, 4, [0, 3, 8, 20])
    base = ragged_attention_reference(q, kp, vp, pt, ln)
    kp2 = kp.at[0].set(1e9)
    vp2 = vp.at[0].set(-1e9)
    poisoned = ragged_attention_reference(q, kp2, vp2, pt, ln)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(poisoned))
    a = _ragged_pallas(q, kp2, vp2, pt, ln, 8 ** -0.5, True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(base),
                               rtol=2e-5, atol=2e-5)


def test_partial_tail_page_masked():
    """Tokens past ``length`` inside the last live page must not attend:
    rewriting the tail of that page changes nothing."""
    rng = np.random.RandomState(3)
    ps = 8
    q, kp, vp, pt, ln = _make_case(rng, 2, 2, 8, ps, 2, [5, 11])
    base = np.asarray(_ragged_pallas(q, kp, vp, pt, ln, 8 ** -0.5, True))
    # slot 0's only page is pt[0,0]; positions 5..7 are dead
    page = int(pt[0, 0])
    kp2 = kp.at[page, :, 5:, :].set(123.0)
    vp2 = vp.at[page, :, 5:, :].set(-321.0)
    got = np.asarray(_ragged_pallas(q, kp2, vp2, pt, ln, 8 ** -0.5,
                                    True))
    np.testing.assert_array_equal(base, got)


def test_dispatcher_and_dtype():
    """The public dispatcher runs the jnp path on the CPU backend (and
    the kernel under MXTPU_FLASH_INTERPRET=1 — parity covered above);
    bf16 inputs accumulate in f32 and track the f32 result."""
    rng = np.random.RandomState(4)
    q, kp, vp, pt, ln = _make_case(rng, 3, 2, 8, 8, 3, [1, 9, 24])
    out = ragged_paged_attention(q, kp, vp, pt, ln)
    ref = ragged_attention_reference(q, kp, vp, pt, ln)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
    b16 = ragged_paged_attention(q.astype(jnp.bfloat16),
                                 kp.astype(jnp.bfloat16),
                                 vp.astype(jnp.bfloat16), pt, ln)
    assert b16.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(b16, np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)


def test_kernel_page_table_permutation_invariance():
    """Two page tables describing the same token sequence through
    different physical pages must give identical outputs (pages are
    identity-free — the slot-reuse guarantee)."""
    rng = np.random.RandomState(5)
    S, H, D, ps, max_pages = 1, 2, 8, 4, 3
    tokens_k = rng.randn(12, H, D).astype(np.float32)
    tokens_v = rng.randn(12, H, D).astype(np.float32)
    q = jnp.asarray(rng.randn(S, H, D).astype(np.float32))
    outs = []
    for pages in ([1, 2, 3], [5, 2, 7]):
        kp = np.zeros((8, H, ps, D), np.float32)
        vp = np.zeros((8, H, ps, D), np.float32)
        for j, p in enumerate(pages):
            kp[p] = tokens_k[j * ps:(j + 1) * ps].transpose(1, 0, 2)
            vp[p] = tokens_v[j * ps:(j + 1) * ps].transpose(1, 0, 2)
        pt = jnp.asarray(np.asarray([pages], np.int32))
        outs.append(np.asarray(_ragged_pallas(
            q, jnp.asarray(kp), jnp.asarray(vp), pt,
            jnp.asarray([12], np.int32), D ** -0.5, True)))
    np.testing.assert_array_equal(outs[0], outs[1])
