"""A tiny, DETERMINISTIC supervised-training target for the chaos
harness (tools/train_chaos_bench.py kill9/hang scenarios,
tests/test_supervisor.py).

Run as ``python -m incubator_mxnet_tpu.train.example_target`` under a
``train.Supervisor``; configured entirely by environment variables so
the supervisor's argv stays trivial:

  MXTPU_TGT_CKPT_DIR     checkpoint root (required)
  MXTPU_TGT_RESULTS      jsonl loss log, one {"step","loss"} per line
  MXTPU_TGT_STEPS        total steps to train (default 24)
  MXTPU_TGT_SAVE_EVERY   snapshot cadence in steps (default 2)
  MXTPU_TGT_KILL_AT      comma list of step indices to kill -9 SELF at
                         (each fires once across restarts, via marker
                         files under the checkpoint root)
  MXTPU_TGT_HANG_AT      step index to hang (sleep) at — drives the
                         supervisor's zero-progress watchdog; fires
                         once, same marker protocol
  MXTPU_TGT_SEED         model/data seed (default 0)

The training itself is the resilience oracle: data for step ``s`` is
drawn from ``RandomState(seed + 1000 + s)``, so every run — killed,
resumed, or uninterrupted — computes the SAME loss at the same step
index. The harness asserts the supervised run's per-step loss map is
bit-identical to an uninterrupted run's (the PR-3 capsule restore
contract, now exercised through real ``kill -9`` + restart)."""

from __future__ import annotations

import json
import os
import time


def _env(name, default=None):
    v = os.environ.get(name)
    return default if v in (None, "") else v


def main() -> int:
    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd
    from incubator_mxnet_tpu.amp.loss_scaler import LossScaler
    from incubator_mxnet_tpu.checkpoint import CheckpointManager
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.train.chaos import KillSelf, SlowStep

    ckpt_dir = _env("MXTPU_TGT_CKPT_DIR")
    if not ckpt_dir:
        raise SystemExit("MXTPU_TGT_CKPT_DIR is required")
    results = _env("MXTPU_TGT_RESULTS")
    steps = int(_env("MXTPU_TGT_STEPS", 24))
    save_every = int(_env("MXTPU_TGT_SAVE_EVERY", 2))
    seed = int(_env("MXTPU_TGT_SEED", 0))
    kill_at = [int(s) for s in
               str(_env("MXTPU_TGT_KILL_AT", "")).split(",") if s]
    hang_at = _env("MXTPU_TGT_HANG_AT")

    mx.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(32, in_units=16, activation="relu"),
            nn.Dense(8, in_units=32))
    net.initialize()
    trainer = gluon.Trainer(
        net.collect_params(), "adam", {"learning_rate": 0.01},
        kvstore=None, loss_scaler=LossScaler(init_scale=4.0,
                                             scale_window=50))

    injectors = [KillSelf(at_step=k,
                          marker=os.path.join(ckpt_dir, f"killed_{k}"))
                 for k in kill_at]
    if hang_at is not None:
        h = int(hang_at)
        marker = os.path.join(ckpt_dir, f"hung_{h}")
        if not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write("hanging\n")
            injectors.append(SlowStep(start=h, end=h + 1, sleep_s=3600.0))

    manager = CheckpointManager(ckpt_dir, keep=3)
    start = 0
    if manager.latest_step() is not None:
        start = trainer.restore_checkpoint(manager)

    def batch(s):
        rng = np.random.RandomState(seed + 1000 + s)
        return (nd.array(rng.randn(16, 16).astype(np.float32)),
                nd.array(rng.randn(16, 8).astype(np.float32)))

    def emit(rec):
        # lazy per-line append: the file first APPEARS with the first
        # trained step, so the supervisor's progress signal never ticks
        # during cold start (jax init + restore + compiles) — the
        # startup grace, not the hang clock, covers that window
        if results:
            with open(results, "a") as f:
                f.write(json.dumps(rec) + "\n")

    for s in range(start, steps):
        for inj in injectors:
            inj.on_step_begin(s, trainer)
        x, y = batch(s)
        with autograd.record():
            L = ((net(x) - y) ** 2).mean()
        trainer.backward(L)
        trainer.step(x.shape[0])
        emit({"step": s, "loss": float(np.asarray(L._data)),
              "outcome": str(trainer.last_outcome), "t": time.time()})
        if (s + 1) % save_every == 0 or s + 1 == steps:
            trainer.save_checkpoint(manager, step=s + 1)
    manager.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
