"""Worker program for the multi-host test (run via tools/launch.py
--launcher local with 2 processes; mirrors the reference's
tests/nightly/dist_sync_kvstore.py).

Each process gets 4 virtual CPU devices (global mesh: 8 devices over 2
processes). Exercises: jax.distributed bootstrap from the launcher env,
kvstore('dist_sync') push/pull aggregation across ranks, and two fused
SPMDTrainer steps over the GLOBAL mesh, asserting identical parameters on
every rank afterwards."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from incubator_mxnet_tpu.parallel import mesh as pmesh  # noqa: E402

pmesh.initialize()  # reads MXTPU_* env set by tools/launch.py

import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import nd, gluon, parallel  # noqa: E402
from incubator_mxnet_tpu import kvstore as kvs  # noqa: E402


def main():
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())
    rank = jax.process_index()

    # ---- kvstore dist_sync: push sums across ranks ------------------- #
    store = kvs.create("dist_sync")
    assert store.rank == rank and store.num_workers == 2
    store.init("w", nd.array(np.zeros(4, np.float32)))
    store.push("w", nd.array(np.full(4, float(rank + 1), np.float32)))
    out = nd.zeros((4,))
    store.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0)  # 1 + 2

    # bf16-compressed cross-process reduction: real wire savings, values
    # exact here (small integers are bf16-representable)
    store2 = kvs.create("dist_sync")
    store2.set_gradient_compression({"type": "bf16"})
    store2.init("g", nd.array(np.zeros(4, np.float32)))
    store2.push("g", nd.array(np.full(4, float(rank + 1), np.float32)))
    out2 = nd.zeros((4,))
    store2.pull("g", out=out2)
    np.testing.assert_allclose(out2.asnumpy(), 3.0)

    # ---- 2-bit compression: wire bytes = N/4, convergence via error
    # feedback (reference: src/kvstore/gradient_compression.cc) -------- #
    from incubator_mxnet_tpu.parallel import collectives as coll
    n = 103  # deliberately not divisible by 4
    packed, deq, res = coll.quantize_2bit(
        jax.numpy.ones((n,), jax.numpy.float32), None, 0.5)
    assert packed.size == (n + 3) // 4 and packed.dtype == jax.numpy.uint8, \
        (packed.size, packed.dtype)  # the array that crosses DCN

    store3 = kvs.create("dist_sync")
    store3.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    store3.init("h", nd.array(np.zeros(4, np.float32)))
    # every rank pushes a constant 0.3 with threshold 0.5: push 1 rounds
    # UP to 0.5 (0.3 >= threshold/2) leaving residual -0.2; push 2 sees
    # 0.3 - 0.2 = 0.1 -> 0 with residual 0.1 — classic error feedback
    g = nd.array(np.full(4, 0.3, np.float32))
    store3.push("h", g)
    out3 = nd.zeros((4,))
    store3.pull("h", out=out3)
    np.testing.assert_allclose(out3.asnumpy(), 1.0)  # 0.5 x 2 workers
    store3.push("h", g)
    store3.pull("h", out=out3)
    np.testing.assert_allclose(out3.asnumpy(), 0.0)
    # over many pushes the error-fed quantized stream tracks the true
    # sum: 20 pushes of 0.3 x 2 workers = 12.0 within one threshold step
    store3b = kvs.create("dist_sync")
    store3b.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    store3b.init("acc", nd.array(np.zeros(4, np.float32)))
    acc = np.zeros(4, np.float32)
    for _ in range(20):
        store3b.push("acc", g)
        o = nd.zeros((4,))
        store3b.pull("acc", out=o)
        acc += o.asnumpy()
    np.testing.assert_allclose(acc, 12.0, atol=1.0)

    # ---- fused SPMD step over the global 8-device mesh --------------- #
    mx.random.seed(42)  # identical init on every rank (SPMD contract)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, in_units=8, activation="relu"),
            gluon.nn.Dense(4, in_units=16))
    net.initialize()
    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(16,))

    mesh = pmesh.build_mesh(axis_sizes={"dp": 8})
    tr = parallel.SPMDTrainer(
        net, loss=gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        mesh=mesh)
    for _ in range(2):
        loss = tr.step(nd.array(X), nd.array(y))
    loss_val = float(loss.asnumpy())
    assert np.isfinite(loss_val), loss_val

    # ---- identical params across ranks ------------------------------- #
    from jax.experimental import multihost_utils
    for name, p in sorted(net.collect_params().items()):
        local = np.asarray(p.data()._data)  # replicated → addressable
        gathered = multihost_utils.process_allgather(local)
        np.testing.assert_allclose(gathered[0], gathered[1], rtol=0,
                                   atol=0, err_msg=name)

    print(f"DIST_WORKER_OK rank={rank} loss={loss_val:.4f}", flush=True)


if __name__ == "__main__":
    main()
