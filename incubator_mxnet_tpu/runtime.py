"""Runtime feature introspection.

Re-design of `src/libinfo.cc` + `python/mxnet/runtime.py` (file-level
citations — SURVEY.md caveat): the reference exposes its compiled feature
flags (`USE_CUDA`, `USE_CUDNN`, `USE_MKLDNN`, `USE_DIST_KVSTORE`, …) through
``mx.runtime.feature_list()`` / ``Features``. The TPU build's "features" are
runtime properties of the JAX/XLA install instead of compile-time #ifdefs,
so they are probed lazily here.
"""

from __future__ import annotations

from typing import Dict, List

__all__ = ["Feature", "Features", "feature_list", "is_enabled"]


class Feature:
    """One named capability flag (parity: `mx.runtime.Feature`)."""

    def __init__(self, name: str, enabled: bool):
        self.name = name
        self.enabled = bool(enabled)

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _fetch_fence() -> bool:
    from .ndarray.ndarray import _needs_fetch_fence

    return _needs_fetch_fence()


def _probe() -> Dict[str, bool]:
    import jax

    platforms = {d.platform for d in jax.devices()}
    try:
        import jax.experimental.pallas  # noqa: F401

        has_pallas = True
    except Exception:  # pragma: no cover
        has_pallas = False
    try:
        from .io import _native

        has_native_io = _native.lib() is not None
    except Exception:  # pragma: no cover
        has_native_io = False
    try:
        from .io import _native_image

        has_native_jpeg = _native_image.lib() is not None
    except Exception:  # pragma: no cover
        has_native_jpeg = False
    try:
        import cv2  # noqa: F401

        has_opencv = True
    except Exception:
        has_opencv = False
    return {
        # accelerator backends (reference: CUDA/CUDNN rows)
        "TPU": "tpu" in platforms,
        "GPU": "gpu" in platforms or "cuda" in platforms,
        "CPU": True,
        # compiler / kernel paths (reference: MKLDNN/TENSORRT/NVRTC rows)
        "XLA": True,
        "PALLAS": has_pallas,
        # distribution (reference: DIST_KVSTORE/NCCL rows)
        "DIST_KVSTORE": True,  # jax.distributed + XLA collectives, always in
        "ICI_COLLECTIVES": "tpu" in platforms,
        # IO (reference: OPENCV/LIBJPEG rows)
        "OPENCV": has_opencv,
        "NATIVE_RECORDIO": has_native_io,
        "NATIVE_JPEG": has_native_jpeg,
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": True,
        "PROFILER": True,
        "AMP": True,
        # False on tunneled backends (axon) where block_until_ready is a
        # no-op; wait_to_read/wait_all add a device-fetch fence there
        # (see ndarray._needs_fetch_fence) so user timing stays correct
        "TRUSTWORTHY_BLOCK_UNTIL_READY": not _fetch_fence(),
    }


class Features:
    """Mapping of feature name → :class:`Feature` (parity:
    ``mx.runtime.Features``, backed by `MXLibInfoFeatures`)."""

    def __init__(self):
        self._features = {k: Feature(k, v) for k, v in _probe().items()}

    def __getitem__(self, name: str) -> Feature:
        return self._features[name]

    def __contains__(self, name: str) -> bool:
        return name in self._features

    def keys(self):
        return self._features.keys()

    def values(self):
        return self._features.values()

    def is_enabled(self, name: str) -> bool:
        return self._features[name].enabled

    def __repr__(self):
        return ", ".join(repr(f) for f in self._features.values())


def feature_list() -> List[Feature]:
    """Parity: ``mx.runtime.feature_list()``."""
    return list(Features().values())


def is_enabled(name: str) -> bool:
    return Features().is_enabled(name)
