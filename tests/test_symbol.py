"""Symbol front end + executor tests (SURVEY.md §2.2 "Symbol frontend",
§3.3 symbolic bind path; reference tests/python/unittest/test_symbol.py
strategy)."""

import json
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd
from incubator_mxnet_tpu import gluon


def _mlp():
    data = mx.sym.Variable("data")
    w1 = mx.sym.Variable("w1")
    b1 = mx.sym.Variable("b1")
    w2 = mx.sym.Variable("w2")
    h = mx.sym.FullyConnected(data, w1, b1, num_hidden=8, name="fc1")
    h = mx.sym.relu(h, name="act1")
    out = mx.sym.FullyConnected(h, w2, None, no_bias=True, num_hidden=3,
                                name="fc2")
    return out


def test_compose_and_introspect():
    out = _mlp()
    assert out.list_arguments() == ["data", "w1", "b1", "w2"]
    assert out.list_outputs() == ["fc2_output"]
    assert out.name == "fc2"
    internals = out.get_internals()
    assert "act1_output" in internals.list_outputs()
    fc1 = internals["act1_output"]
    assert fc1.list_arguments() == ["data", "w1", "b1"]


def test_infer_shape_and_type():
    out = _mlp()
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(
        data=(4, 16), w1=(8, 16), b1=(8,), w2=(3, 8))
    assert out_shapes == [(4, 3)]
    assert arg_shapes[0] == (4, 16)
    assert aux_shapes == []
    # partial inference: param shapes derive from data shape alone (the
    # reference's InferShape pass contract)
    arg_shapes2, out_shapes2, _ = out.infer_shape(data=(4, 16))
    assert out_shapes2 == [(4, 3)]
    assert arg_shapes2[arg_shapes2.index((8, 16))] == (8, 16)
    # genuinely under-determined (free variable) → (None, None, None)
    free = mx.sym.Variable("a") + mx.sym.Variable("b")
    assert free.infer_shape(a=(2, 2)) == (None, None, None)


def test_eval_matches_numpy():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = (a * 2.0 + b) / 4.0
    av, bv = nd.ones((2, 2)), nd.full((2, 2), 6.0)
    (res,) = c.eval(a=av, b=bv)
    assert np.allclose(res.asnumpy(), 2.0)


def test_json_roundtrip(tmp_path):
    out = _mlp()
    path = str(tmp_path / "mlp-symbol.json")
    out.save(path)
    loaded = mx.sym.load(path)
    assert loaded.list_arguments() == out.list_arguments()
    assert loaded.list_outputs() == out.list_outputs()
    arg_shapes, out_shapes, _ = loaded.infer_shape(
        data=(2, 16), w1=(8, 16), b1=(8,), w2=(3, 8))
    assert out_shapes == [(2, 3)]
    payload = json.loads(loaded.tojson())
    assert {n["op"] for n in payload["nodes"]} == \
        {"null", "FullyConnected", "relu"}


def test_group_and_multi_output():
    a = mx.sym.Variable("a")
    s1 = mx.sym.relu(a, name="r")
    s2 = mx.sym.exp(a, name="e")
    g = mx.sym.Group([s1, s2])
    assert g.list_outputs() == ["r_output", "e_output"]
    outs = g.eval(a=nd.array([[-1.0, 1.0]]))
    assert np.allclose(outs[0].asnumpy(), [[0.0, 1.0]])
    assert np.allclose(outs[1].asnumpy(), np.exp([[-1.0, 1.0]]))
    # split: variadic-output node
    sp = mx.sym.split(mx.sym.Variable("x"), num_outputs=2, axis=1)
    assert len(sp.list_outputs()) == 2
    second = sp[1]
    (v,) = second.eval(x=nd.array(np.arange(8.0).reshape(2, 4)))
    assert v.shape == (2, 2)


def test_executor_forward_backward():
    out = _mlp()
    rng = np.random.RandomState(0)
    args = {"data": nd.array(rng.randn(4, 16)),
            "w1": nd.array(rng.randn(8, 16) * 0.1),
            "b1": nd.zeros((8,)),
            "w2": nd.array(rng.randn(3, 8) * 0.1)}
    exe = out.bind(args=args, grad_req="write")
    (y,) = exe.forward(is_train=True)
    assert y.shape == (4, 3)
    exe.backward(nd.ones((4, 3)))
    # compare against autograd on the same imperative composition
    xs = {k: v.copy() for k, v in args.items()}
    for v in xs.values():
        v.attach_grad()
    with autograd.record():
        h = nd.relu(nd.FullyConnected(xs["data"], xs["w1"], xs["b1"],
                                      num_hidden=8))
        o = nd.FullyConnected(h, xs["w2"], None, no_bias=True, num_hidden=3)
    o.backward(nd.ones((4, 3)))
    for name in ("data", "w1", "b1", "w2"):
        assert np.allclose(exe.grad_dict[name].asnumpy(),
                           xs[name].grad.asnumpy(), atol=1e-5), name


def test_executor_grad_add_and_null():
    x = mx.sym.Variable("x")
    y = mx.sym.sum(x * x)
    exe = y.bind(args={"x": nd.array([1.0, 2.0])},
                 grad_req={"x": "add"})
    exe.forward(is_train=True)
    exe.backward()
    exe.forward(is_train=True)
    exe.backward()
    assert np.allclose(exe.grad_dict["x"].asnumpy(), [4.0, 8.0])
    exe2 = y.bind(args={"x": nd.array([1.0, 2.0])}, grad_req="null")
    exe2.forward(is_train=False)
    assert exe2.grad_arrays == [None]


def test_simple_bind_and_reshape():
    out = _mlp()
    exe = out.simple_bind(data=(4, 16), w1=(8, 16), b1=(8,), w2=(3, 8))
    exe.forward(is_train=False)
    assert exe.outputs[0].shape == (4, 3)
    exe2 = exe.reshape(data=(6, 16), w1=(8, 16), b1=(8,), w2=(3, 8))
    exe2.forward(is_train=False)
    assert exe2.outputs[0].shape == (6, 3)


def test_symbolic_batchnorm_aux_update():
    data = mx.sym.Variable("data")
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4, in_units=3),
            gluon.nn.BatchNorm(in_channels=4))
    net.initialize()
    sym_out = net(data)
    aux = sym_out.list_auxiliary_states()
    assert len(aux) == 2 and any("running_mean" in a for a in aux)
    args = {n: p.data() for n, p in
            ((p.name, p) for p in net.collect_params().values())
            if n in sym_out.list_arguments()}
    aux_states = {p.name: p.data() for p in net.collect_params().values()
                  if p.name in aux}
    args["data"] = nd.array(np.random.RandomState(0).randn(8, 3))
    exe = sym_out.bind(args=args, aux_states=aux_states, grad_req="null")
    before = {k: v.asnumpy().copy() for k, v in exe.aux_dict.items()}
    exe.forward(is_train=True)
    changed = any(not np.allclose(exe.aux_dict[k].asnumpy(), before[k])
                  for k in before)
    assert changed, "running stats should update under is_train=True"


def test_gluon_export_symbolblock_import(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu", in_units=5),
            gluon.nn.Dense(3, in_units=8))
    net.initialize()
    x = nd.array(np.random.RandomState(1).randn(2, 5))
    ref = net(x).asnumpy()

    path = str(tmp_path / "mlp")
    net.export(path, epoch=3)
    assert os.path.exists(path + "-symbol.json")
    assert os.path.exists(path + "-0003.params")

    blk = gluon.SymbolBlock.imports(path + "-symbol.json", ["data"],
                                    path + "-0003.params")
    out = blk(x)
    assert np.allclose(out.asnumpy(), ref, atol=1e-5)


def test_symbolblock_autograd_through_graph(tmp_path):
    net = gluon.nn.Dense(2, in_units=3)
    net.initialize()
    path = str(tmp_path / "d")
    net.export(path)
    blk = gluon.SymbolBlock.imports(path + "-symbol.json", ["data"],
                                    path + "-0000.params")
    x = nd.ones((1, 3))
    with autograd.record():
        y = blk(x).sum()
    y.backward()
    w = [p for p in blk.collect_params().values()
         if p.name.endswith("weight")][0]
    assert np.allclose(w.grad().asnumpy(), np.ones((2, 3)))


def test_scalar_sugar_ops():
    x = nd.array([1.0, 2.0])
    assert np.allclose(nd._rdiv_scalar(x, scalar=4.0).asnumpy(), [4.0, 2.0])
    s = mx.sym.Variable("s")
    expr = 1.0 - s
    (v,) = expr.eval(s=x)
    assert np.allclose(v.asnumpy(), [0.0, -1.0])


def test_name_manager_attr_scope_and_viz():
    import incubator_mxnet_tpu as mx

    with mx.name.Prefix("stage1_"):
        a = mx.sym.Variable("data")
        b = mx.sym.relu(a)
    assert b.name.startswith("stage1_")

    with mx.AttrScope(ctx_group="dev1"):
        c = mx.sym.relu(a)
    assert c.attr("ctx_group") == "dev1"
    # annotations are metadata, NOT op kwargs: the graph still executes
    import numpy as np
    from incubator_mxnet_tpu import nd
    out_val = c.eval(data=nd.array(np.array([-1.0, 2.0], np.float32)))
    if isinstance(out_val, (list, tuple)):
        out_val = out_val[0]
    np.testing.assert_allclose(out_val.asnumpy(), [0.0, 2.0])
    with mx.AttrScope(g="1"):
        with mx.AttrScope(g="2"):
            d = mx.sym.relu(a)
    assert d.attr("g") == "2"
    # annotations round-trip through tojson/fromjson
    d2 = mx.sym.fromjson(d.tojson())
    assert d2.attr("g") == "2"
    # _set_attr updates annotations; attr_dict merges them
    d2._set_attr(stage="3")
    assert d2.attr("stage") == "3"
    assert d2.attr_dict()[d2.name]["g"] == "2"

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, mx.sym.Variable("w1"),
                                mx.sym.Variable("b1"), num_hidden=16,
                                name="fc1")
    act = mx.sym.relu(fc1, name="act1")
    out = mx.sym.FullyConnected(act, mx.sym.Variable("w2"),
                                mx.sym.Variable("b2"), num_hidden=3,
                                name="fc2")
    total = mx.viz.print_summary(out, shape={"data": (1, 8)})
    assert total == (16 * 8 + 16) + (3 * 16 + 3)
    # plot_network: graphviz digraph when available, gated error otherwise
    try:
        g = mx.viz.plot_network(out)
        assert hasattr(g, "source")
    except mx.MXNetError as err:
        assert "graphviz" in str(err)


def test_load_reference_written_symbol_json(tmp_path):
    """A -symbol.json as the REFERENCE writes it (nnvm json.cc: every
    attr value stringified, mxnet_version in top-level attrs) must load
    and execute. Hand-built fixture — the reference mount is empty, so
    the format is pinned here rather than by diffing real output."""
    import json as _json

    ref = {
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "fc1_weight",
             "attrs": {"__dtype__": "0"}, "inputs": []},
            {"op": "null", "name": "fc1_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc1",
             "attrs": {"num_hidden": "4", "no_bias": "False",
                       "flatten": "True"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
            {"op": "Activation", "name": "relu1",
             "attrs": {"act_type": "relu"}, "inputs": [[3, 0, 0]]},
            {"op": "Pooling", "name": "pool_skip",  # attrs w/ tuples
             "attrs": {"kernel": "(1, 1)", "pool_type": "max",
                       "stride": "(1, 1)"}, "inputs": []},
        ],
        "arg_nodes": [0, 1, 2],
        "node_row_ptr": [0, 1, 2, 3, 4, 5, 6],
        "heads": [[4, 0, 0]],
        "attrs": {"mxnet_version": ["int", 10902]},
    }
    p = tmp_path / "net-symbol.json"
    p.write_text(_json.dumps(ref))

    sym = mx.sym.load(str(p))
    rng = np.random.RandomState(0)
    ex = sym.bind(None, {
        "data": nd.array(rng.randn(2, 5).astype(np.float32)),
        "fc1_weight": nd.array(rng.randn(4, 5).astype(np.float32)),
        "fc1_bias": nd.array(np.zeros(4, np.float32)),
    })
    out = ex.forward()[0].asnumpy()
    assert out.shape == (2, 4) and (out >= 0).all()
    # write-back keeps the reference's all-strings attr convention
    # (nnvm reads node attrs as Map<string, string>), and a reload of
    # our own output still executes identically (lossless round trip)
    fc_node = [n for n in _json.loads(sym.tojson())["nodes"]
               if n["name"] == "fc1"][0]
    assert fc_node["attrs"]["num_hidden"] == "4"
    assert fc_node["attrs"]["no_bias"] == "False"
    sym2 = mx.sym.load_json(sym.tojson())
    ex2 = sym2.bind(None, {
        "data": nd.array(rng.randn(2, 5).astype(np.float32)),
        "fc1_weight": nd.array(rng.randn(4, 5).astype(np.float32)),
        "fc1_bias": nd.array(np.zeros(4, np.float32)),
    })
    assert ex2.forward()[0].shape == (2, 4)
    # dunder user attrs are string-typed by contract: never coerced
    wn = [n for n in _json.loads(sym.tojson())["nodes"]
          if n["name"] == "fc1_weight"][0]
    assert wn["attrs"]["__dtype__"] == "0"


def test_implicit_parameter_variables():
    """Reference parity: mx.sym.FullyConnected(data, num_hidden=k)
    auto-creates fc_weight/fc_bias Variables (no_bias suppresses bias);
    BatchNorm auto-creates gamma/beta/moving stats."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc1", num_hidden=3)
    args = fc.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias"], args

    fc_nb = mx.sym.FullyConnected(data, name="fc2", num_hidden=3,
                                  no_bias=True)
    assert fc_nb.list_arguments() == ["data", "fc2_weight"]

    bn = mx.sym.BatchNorm(fc, name="bn1")
    assert "bn1_gamma" in bn.list_arguments()
    # running stats are AUX states (the executor folds their updates,
    # checkpoints write aux: keys), not trainable arguments
    assert "bn1_moving_var" in bn.list_auxiliary_states()
    assert "bn1_moving_mean" not in bn.list_arguments()

    # gating attrs are read at their own signature defaults:
    # Deconvolution declares no_bias=True -> no phantom bias
    dc = mx.sym.Deconvolution(data, name="dc", kernel=(2, 2),
                              num_filter=4)
    assert dc.list_arguments() == ["data", "dc_weight"]
    # lstm mode auto-creates state_cell; prelu auto-creates gamma
    r = mx.sym.RNN(mx.sym.Variable("x"), name="rnn0", mode="lstm",
                   state_size=8, num_layers=1)
    assert "rnn0_state_cell" in r.list_arguments()
    pr = mx.sym.LeakyReLU(data, name="pr", act_type="prelu")
    assert "pr_gamma" in pr.list_arguments()
    lr = mx.sym.LeakyReLU(data, name="lk")      # plain leaky: no gamma
    assert lr.list_arguments() == ["data"]

    # executes end to end with the implicit names bound
    rng = np.random.RandomState(0)
    ex = fc.bind(None, {
        "data": nd.array(rng.randn(2, 5).astype(np.float32)),
        "fc1_weight": nd.array(rng.randn(3, 5).astype(np.float32)),
        "fc1_bias": nd.array(np.ones(3, np.float32)),
    })
    out = ex.forward()[0].asnumpy()
    assert out.shape == (2, 3)
