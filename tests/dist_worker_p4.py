"""4-process worker: a dp2 x fsdp2 x tp2 mesh whose dp AND fsdp axes
cross process boundaries (VERDICT r3 next-round #7 — the 2-process test
only exercised a pure-dp mesh).

Topology: 4 processes x 2 virtual CPU devices = 8 global devices.
Device i lives on process i//2; with the canonical axis order the mesh
assigns dp = i//4 (crosses processes 0,1 vs 2,3), fsdp = (i//2) % 2
(crosses 0 vs 1 and 2 vs 3), tp = i % 2 (intra-process). Two fused SPMD
steps on a tensor-parallel-sharded MLP; every rank must end with
identical parameters and the same loss the single-process 8-device run
produces (the parent test computes that reference and compares).

Mirrors the scope growth of the reference's
tests/nightly/dist_sync_kvstore.py / dist_device_sync_kvstore.py.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from incubator_mxnet_tpu.parallel import mesh as pmesh  # noqa: E402

pmesh.initialize()

import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import nd, gluon, parallel  # noqa: E402


def build_and_train():
    """Shared by this worker and the parent's single-process reference:
    same seed, same mesh shape, same data -> same trajectory."""
    mx.random.seed(7)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(32, in_units=16, activation="relu"),
            gluon.nn.Dense(4, in_units=32))
    net.initialize()
    # column-parallel then row-parallel over tp (Megatron layout)
    net._children["0"].weight._sharding = P("tp", None)
    net._children["0"].bias._sharding = P("tp")
    net._children["1"].weight._sharding = P(None, "tp")

    rng = np.random.RandomState(3)
    X = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 4, size=(8,))

    mesh = pmesh.build_mesh(axis_sizes={"dp": 2, "fsdp": 2, "tp": 2})
    tr = parallel.SPMDTrainer(
        net, loss=gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        mesh=mesh)
    loss = None
    for _ in range(2):
        loss = tr.step(nd.array(X), nd.array(y))
    return net, mesh, float(loss.asnumpy())


def main():
    assert jax.process_count() == 4, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    net, mesh, loss_val = build_and_train()
    assert np.isfinite(loss_val), loss_val

    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding

    # tp/fsdp-sharded params are not fully addressable per process —
    # re-shard to fully replicated first, then compare across ranks
    rep = jax.jit(lambda x: x,
                  out_shardings=NamedSharding(mesh, P()))
    for name, p in sorted(net.collect_params().items()):
        full = np.asarray(jax.device_get(
            rep(p.data()._data).addressable_data(0)))
        gathered = multihost_utils.process_allgather(full)
        for r in range(1, 4):
            np.testing.assert_allclose(gathered[r], gathered[0],
                                       rtol=1e-6, atol=1e-7)
    print(f"DIST4_LOSS {loss_val:.6f}")
    print("DIST4_WORKER_OK", jax.process_index())


if __name__ == "__main__":
    main()
