"""``mx.contrib`` (parity: python/mxnet/contrib/). Quantization is the
main subsystem. ONNX import/export lives in ``contrib.onnx``: the graph
translation layer is always available (and tested); actually reading or
writing .onnx files additionally needs the ``onnx`` wheel and raises the
documented gate otherwise (SURVEY.md §7.3)."""

from . import quantization
from . import onnx
from . import text
from .quantization import quantize_net
from .svrg import SVRGModule

__all__ = ["quantization", "quantize_net", "onnx", "text", "SVRGModule"]
