"""Paged KV cache: a shared page pool + host-side page allocator.

Layout (one pool pair per transformer layer):

    k_pool / v_pool : (num_pages, H, page_size, D)

chosen so each (page, head) slice is a contiguous (page_size, D) tile —
the ragged kernel's per-head dot operand (ops/ragged_attention.py) —
and so a tp mesh can shard the H axis with the existing
``parallel.mesh`` machinery without splitting any page.

Invariants (enforced by the engine, asserted in tests):
  - **Page 0 is the NULL page.** The allocator never hands it out; every
    dead page-table entry points at it; inactive slots' decode writes
    land in it. Its contents are garbage BY DESIGN — correctness relies
    on every read of it being masked by the slot's length, never on what
    it holds.
  - A slot at length L references exactly ceil(L / page_size) live
    pages, contiguous in its page-table row; entries past that are 0.
  - Pages are identity-free: eviction returns them to the free list and
    any slot may reuse them without clearing (the next writer overwrites
    the prefix it needs; the tail is masked).

The allocator is deliberately host-side Python (a free list), matching
the scheduler split: device programs are occupancy-oblivious, all
allocation decisions ride in as int32 data.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError

NULL_PAGE = 0

__all__ = ["NULL_PAGE", "PageAllocator", "init_kv_pools",
           "write_token_kv", "write_prompt_kv"]


class PageAllocator:
    """Free-list allocator over pages 1..num_pages-1 (page 0 = null)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise MXNetError("need >= 2 pages (page 0 is the null page)")
        self.num_pages = num_pages
        # LIFO reuse keeps the working set of hot pages small
        self._free = list(range(num_pages - 1, 0, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self) -> int:
        if not self._free:
            raise MXNetError("KV page pool exhausted — admission control "
                             "should have prevented this (engine bug)")
        return self._free.pop()

    def free(self, pages) -> None:
        for p in pages:
            if p == NULL_PAGE:
                raise MXNetError("attempted to free the null page")
            self._free.append(int(p))


def init_kv_pools(num_layers, num_pages, num_heads, page_size, head_dim,
                  dtype="float32"):
    """Fresh zeroed (k_pool, v_pool) pairs, one per layer."""
    dt = jnp.dtype(dtype)
    mk = lambda: jnp.zeros((num_pages, num_heads, page_size, head_dim), dt)
    return [(mk(), mk()) for _ in range(num_layers)]


def write_token_kv(pool, new, pages, offsets):
    """Scatter one decode token's K (or V) per slot into the pool.

    pool: (P, H, ps, D); new: (S, H, D); pages/offsets: (S,) int32 —
    slot s writes ``new[s]`` to ``pool[pages[s], :, offsets[s], :]``.
    Inactive slots carry pages[s] == NULL_PAGE, so their write lands in
    the null page (harmless, never read unmasked). Static shapes; safe
    under jit."""
    H = pool.shape[1]
    return pool.at[pages[:, None], jnp.arange(H)[None, :],
                   offsets[:, None], :].set(new.astype(pool.dtype))


def write_prompt_kv(pool, kv, pages):
    """Scatter a whole prompt's K (or V) into its pages (prefill).

    pool: (P, H, ps, D); kv: (Tpad, H, D) with Tpad == len(pages) * ps;
    pages: (n_pages,) int32 with dead (beyond the prompt) entries
    NULL_PAGE — those whole-page writes land in the null page. Duplicate
    null indices are fine: the store order is unspecified but the value
    is never read unmasked."""
    n_pages = pages.shape[0]
    ps = pool.shape[2]
    paged = kv.reshape(n_pages, ps, kv.shape[1], kv.shape[2]) \
        .transpose(0, 2, 1, 3)                  # (n_pages, H, ps, D)
    return pool.at[pages].set(paged.astype(pool.dtype))
