"""Module / BucketingModule tests (SURVEY.md §3.3 symbolic fit path;
reference tests/python/unittest/test_module.py strategy)."""

import logging

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def _mlp_sym(num_hidden=16, num_classes=3):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    h = mx.sym.FullyConnected(data, mx.sym.Variable("fc1_weight"),
                              mx.sym.Variable("fc1_bias"),
                              num_hidden=num_hidden, name="fc1")
    h = mx.sym.relu(h)
    o = mx.sym.FullyConnected(h, mx.sym.Variable("fc2_weight"),
                              mx.sym.Variable("fc2_bias"),
                              num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(o, label, normalization="batch",
                                name="softmax")


def _toy_iter(n=96, dim=8, classes=3, batch=16, seed=0, shuffle=True):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, dim).astype(np.float32)
    W = rng.randn(dim, classes).astype(np.float32)
    y = np.argmax(X @ W, axis=1).astype(np.float32)
    return mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=shuffle,
                             label_name="softmax_label")


def test_module_bind_init_forward():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = _toy_iter()
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    batch = next(iter(it))
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (16, 3)
    probs = out.asnumpy()
    assert np.allclose(probs.sum(axis=1), 1.0, atol=1e-5)


def test_module_fit_converges():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = _toy_iter()
    mod.fit(it, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5}, num_epoch=8,
            initializer=mx.initializer.Xavier())
    score = mod.score(_toy_iter(), "acc")
    assert dict(score)["accuracy"] > 0.8, score


def test_module_predict_and_params_roundtrip(tmp_path):
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = _toy_iter()
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    preds = mod.predict(_toy_iter(shuffle=False))
    assert preds.shape == (96, 3)

    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 2)
    sym, arg, aux = mx.model.load_checkpoint(prefix, 2)
    assert "fc1_weight" in arg
    mod2 = mx.mod.Module(sym, context=mx.cpu())
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.set_params(arg, aux)
    preds2 = mod2.predict(_toy_iter(shuffle=False))
    assert np.allclose(preds.asnumpy(), preds2.asnumpy(), atol=1e-5)


def test_module_input_grads():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = _toy_iter()
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             inputs_need_grad=True)
    mod.init_params()
    batch = next(iter(it))
    mod.forward(batch, is_train=True)
    mod.backward()
    (dgrad,) = mod.get_input_grads()
    assert dgrad.shape == (16, 8)
    assert float(np.abs(dgrad.asnumpy()).sum()) > 0


def test_bucketing_module():
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        flat = mx.sym.reshape(data, shape=(-1, seq_len * 4))
        o = mx.sym.FullyConnected(flat, mx.sym.Variable("out_weight"),
                                  None, no_bias=True, num_hidden=2,
                                  name="out")
        # weight shape depends on bucket — realistic NMT models share only
        # embedding/RNN params; here we share nothing but exercise the
        # bucket-switch machinery with a bucket-invariant param
        w = mx.sym.Variable("scale_weight")
        o = mx.sym.broadcast_mul(o, w)
        return mx.sym.SoftmaxOutput(o, label, name="softmax"), \
            ("data",), ("softmax_label",)

    # bucket-invariant symbol: use mean over seq axis so params share
    def sym_gen_shared(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        pooled = mx.sym.mean(data, axis=1)
        o = mx.sym.FullyConnected(pooled, mx.sym.Variable("out_weight"),
                                  mx.sym.Variable("out_bias"),
                                  num_hidden=2, name="out")
        return mx.sym.SoftmaxOutput(o, label, name="softmax"), \
            ("data",), ("softmax_label",)

    bm = mx.mod.BucketingModule(sym_gen_shared, default_bucket_key=8,
                                context=mx.cpu())
    bm.bind(data_shapes=[("data", (4, 8, 4))],
            label_shapes=[("softmax_label", (4,))])
    bm.init_params()
    bm.init_optimizer(optimizer="sgd",
                      optimizer_params={"learning_rate": 0.1})

    rng = np.random.RandomState(0)
    for seq_len in (8, 5, 8, 3):
        batch = mx.io.DataBatch(
            data=[nd.array(rng.randn(4, seq_len, 4))],
            label=[nd.array(rng.randint(0, 2, (4,)).astype(np.float32))])
        batch.bucket_key = seq_len
        batch.provide_data = [("data", (4, seq_len, 4))]
        batch.provide_label = [("softmax_label", (4,))]
        bm.forward(batch, is_train=True)
        bm.backward()
        bm.update()
    assert set(bm._buckets) == {8, 5, 3}
    # params are shared by reference across buckets
    arg, _ = bm.get_params()
    assert "out_weight" in arg


def test_module_load_restores_checkpoint(tmp_path):
    """Regression: Module.load must actually apply checkpoint params."""
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = _toy_iter()
    mod.fit(it, num_epoch=1, optimizer_params={"learning_rate": 0.1})
    prefix = str(tmp_path / "ckpt")
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
    arg0, _ = mod.get_params()

    mod2 = mx.mod.Module.load(prefix, 1, load_optimizer_states=True)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params()
    arg1, _ = mod2.get_params()
    for name in arg0:
        np.testing.assert_allclose(arg0[name].asnumpy(),
                                   arg1[name].asnumpy(), rtol=1e-6)
    mod2.init_optimizer()
    assert mod2.optimizer_initialized


def test_module_init_params_allow_missing_enforced():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = _toy_iter()
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    partial = {"fc1_weight": nd.zeros((16, 8))}
    with pytest.raises(mx.base.MXNetError):
        mod.init_params(arg_params=partial, allow_missing=False)
    mod.init_params(arg_params=partial, allow_missing=True)
    assert mod.params_initialized


def test_executor_reshape_preserves_params():
    sym = _mlp_sym()
    exe = sym.simple_bind(mx.cpu(), data=(16, 8), softmax_label=(16,))
    exe.arg_dict["fc1_weight"]._data = exe.arg_dict["fc1_weight"]._data + 1.5
    exe2 = exe.reshape(partial_shaping=True, data=(32, 8),
                       softmax_label=(32,))
    np.testing.assert_allclose(exe2.arg_dict["fc1_weight"].asnumpy(),
                               exe.arg_dict["fc1_weight"].asnumpy())


def test_bucketing_set_params_propagates_to_existing_buckets():
    """Regression: set_params after a non-default bucket was compiled must
    update that bucket too (by-reference parameter sharing)."""
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        w = mx.sym.Variable("fc_weight")
        b = mx.sym.Variable("fc_bias")
        o = mx.sym.FullyConnected(data, w, b, num_hidden=3, name="fc")
        return mx.sym.SoftmaxOutput(o, label, name="softmax"), \
            ["data"], ["softmax_label"]

    bm = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                context=mx.cpu())
    bm.bind(data_shapes=[("data", (2, 4))],
            label_shapes=[("softmax_label", (2,))])
    bm.init_params()

    class _Batch:
        def __init__(self, key, n):
            self.bucket_key = key
            self.data = [nd.ones((n, 4))]
            self.label = [nd.zeros((n,))]
            self.provide_data = [("data", (n, 4))]
            self.provide_label = [("softmax_label", (n,))]

    bm.forward(_Batch(4, 4), is_train=False)  # compile bucket 4
    out_before = bm.get_outputs()[0].asnumpy()

    arg, aux = bm.get_params()
    new_args = {n: nd.array(np.full(a.shape, 0.3, np.float32))
                for n, a in arg.items()}
    bm.set_params(new_args, aux)
    bm.forward(_Batch(4, 4), is_train=False)
    out_after = bm.get_outputs()[0].asnumpy()
    assert not np.allclose(out_before, out_after)
    # identical per-class weights -> uniform softmax
    np.testing.assert_allclose(out_after, np.full_like(out_after, 1 / 3),
                               atol=1e-5)


def test_module_checkpoint_reference_format_roundtrip(tmp_path):
    """A full reference-style checkpoint PAIR — stringified-attr
    -symbol.json + MXNet 1.x binary .params with arg:/aux: prefixes —
    must round-trip through Module with identical predictions."""
    import struct

    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = _toy_iter()
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    preds = mod.predict(_toy_iter(shuffle=False))

    prefix = str(tmp_path / "refmt")
    sym0, arg0, aux0 = mod._symbol, *mod.get_params()
    mx.model.save_checkpoint(prefix, 3, sym0, arg0, aux0,
                             format="mxnet")
    # the params file is byte-level reference layout (list magic 0x112)
    raw = open(f"{prefix}-0003.params", "rb").read()
    assert struct.unpack("<Q", raw[:8])[0] == 0x112

    sym, arg, aux = mx.model.load_checkpoint(prefix, 3)
    assert "fc1_weight" in arg
    mod2 = mx.mod.Module(sym, context=mx.cpu())
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.set_params(arg, aux)
    preds2 = mod2.predict(_toy_iter(shuffle=False))
    assert np.allclose(preds.asnumpy(), preds2.asnumpy(), atol=1e-5)


def test_module_fit_with_column_labels_and_libsvm(tmp_path):
    """(B, 1)-shaped labels (what row-shaped iterators like LibSVMIter
    emit) must train and score correctly: SoftmaxOutput's fused
    backward squeezes the trailing class axis (a broadcast there
    silently produced (B, B, C) cotangents) and the classification
    metrics ravel labels like the reference."""
    mx.random.seed(0)          # init must not depend on test order
    rng = np.random.RandomState(0)
    p = tmp_path / "train.libsvm"
    with open(p, "w") as f:
        for _ in range(64):
            x = np.zeros(6, np.float32)
            nz = rng.choice(6, 3, replace=False)
            x[nz] = rng.randn(3)
            f.write(f"{int(x.sum() > 0)} "
                    + " ".join(f"{i}:{x[i]:.4f}" for i in nz) + "\n")
    it = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(6,),
                          batch_size=8)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
    net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                               name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=5, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    it.reset()
    acc = dict(mod.score(it, "acc"))["accuracy"]
    assert 0.7 < acc <= 1.0, acc

    # metrics accept (B, 1) labels without over-counting
    pred = nd.array(np.array([[0.9, 0.1], [0.2, 0.8]], np.float32))
    lab = nd.array(np.array([[0.0], [1.0]], np.float32))
    # perfect deterministic predictions: each metric must be EXACTLY 1
    for m in (mx.metric.Accuracy(), mx.metric.F1(), mx.metric.MCC(),
              mx.metric.TopKAccuracy(top_k=1)):
        m.update([lab], [pred])
        assert abs(m.get()[1] - 1.0) < 1e-6, (type(m).__name__, m.get())
