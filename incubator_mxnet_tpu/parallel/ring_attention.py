"""Ring attention: exact attention over sequences sharded across devices.

The reference has NO long-context parallelism (SURVEY.md §5.7 — BERT-era
≤512 windows); this module is the TPU-native capability that subsumes it.
Sequence length is sharded over the mesh ``sp`` axis; each device holds a
Q/K/V block and K/V blocks rotate around the ring via ``lax.ppermute`` on
ICI while a numerically-stable streaming softmax (the flash-attention
recurrence) accumulates partial outputs. Compute on the current block
overlaps with the transfer of the next (XLA schedules the ppermute
asynchronously), so attention of length ``sp × T_blk`` runs with per-device
memory of one block — the Ring Attention construction (see PAPERS.md).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError, pcast_varying, shard_map

__all__ = ["ring_self_attention", "ring_attention_block",
           "ring_flash_attention", "ring_flash_attention_block",
           "active_ring_mesh"]


def active_ring_mesh(seq_len: int):
    """The model-side gate for sequence-parallel attention dispatch:
    returns the ACTIVE SPMD mesh when it has an ``sp`` axis that divides
    ``seq_len`` and we are NOT recording on the eager tape (the ring call
    bypasses it), else None. Shared by every seq_parallel model."""
    from .. import autograd as _ag
    from .spmd import _ACTIVE_MESH
    mesh = _ACTIVE_MESH.get()
    if mesh is None or mesh.shape.get("sp", 1) <= 1 \
            or seq_len % mesh.shape["sp"] or _ag.is_recording():
        return None
    return mesh

_NEG_INF = -1e30


def _stream_block(q, k, v, acc, row_max, row_sum, mask, scale=1.0):
    """One flash-attention accumulation step.

    q: (B, Tq, H, D); k/v: (B, Tk, H, D); acc: (B, Tq, H, D);
    row_max/row_sum: (B, Tq, H); mask: additive, either (Tq, Tk) shared
    or (B, Tq, Tk) per-batch (the valid_length form), or None.
    """
    # dot operands keep their input dtype (bf16 rides the MXU at full
    # rate); scores/statistics accumulate in f32 with the scale applied
    # to the f32 scores (scaling a bf16 q would round it)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        # (Tq, Tk) shared mask or (B, Tq, Tk) per-batch (valid_length)
        scores = scores + (mask[None, None] if mask.ndim == 2
                           else mask[:, None])
    blk_max = scores.max(axis=-1)                       # (B,H,Tq)
    blk_max = jnp.moveaxis(blk_max, 1, -1)              # (B,Tq,H)
    new_max = jnp.maximum(row_max, blk_max)
    corr = jnp.exp(row_max - new_max)                   # (B,Tq,H)
    p = jnp.exp(scores - jnp.moveaxis(new_max, -1, 1)[..., None])  # (B,H,Tq,Tk)
    blk_out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
    blk_sum = jnp.moveaxis(p.sum(axis=-1), 1, -1)       # (B,Tq,H)
    acc = acc * corr[..., None] + blk_out
    row_sum = row_sum * corr + blk_sum
    return acc, new_max, row_sum


def ring_attention_block(q, k, v, valid_length=None,
                         axis_name: str = "sp",
                         causal: bool = False, scale: Optional[float] = None,
                         *, vary_axes: tuple = ()):
    """Per-shard ring attention body (call inside ``shard_map``).

    q, k, v: local blocks (B, T_blk, H, D); the global sequence is the
    concatenation over the ``axis_name`` mesh axis. ``valid_length``
    (B,) GLOBAL key lengths (the encoder key-padding form) masks keys at
    global positions >= the length. Returns the local output block.
    """
    B, Tq, H, D = q.shape
    n = lax.axis_index(axis_name)
    size = lax.psum(1, axis_name)
    if scale is None:
        scale = D ** -0.5

    acc = jnp.zeros(q.shape, jnp.float32)
    row_max = jnp.full((B, Tq, H), _NEG_INF, jnp.float32)
    row_sum = jnp.zeros((B, Tq, H), jnp.float32)
    # constants enter the loop unvarying over the mesh axes while the loop
    # body produces device-varying values; align the carry's varying type
    # over EVERY axis the shard_map shards q over (sp plus the batch axis
    # when present — a dp x sp mesh otherwise trips the fori_loop carry
    # type check)
    cast_axes = (axis_name,) + tuple(a for a in vary_axes
                                     if a and a != axis_name)
    acc, row_max, row_sum = jax.tree_util.tree_map(
        lambda x: pcast_varying(x, cast_axes),
        (acc, row_max, row_sum))
    qf = q  # input dtype into the block einsums (f32 accumulation inside)

    pos_q = n * Tq + jnp.arange(Tq)

    def body(step, carry):
        acc, row_max, row_sum, k_cur, v_cur = carry
        # after `step` rotations device n holds the block of device n-step
        src = (n - step) % size
        pos_k = src * Tq + jnp.arange(k_cur.shape[1])
        mask = None
        if causal:
            mask = jnp.where(pos_k[None, :] <= pos_q[:, None], 0.0,
                             _NEG_INF)
        if valid_length is not None:
            vl_mask = jnp.where(
                pos_k[None, :] < valid_length.astype(jnp.int32)[:, None],
                0.0, _NEG_INF)                        # (B, Tk)
            vl_mask = jnp.broadcast_to(vl_mask[:, None],
                                       (vl_mask.shape[0], Tq,
                                        vl_mask.shape[1]))
            mask = vl_mask if mask is None else mask[None] + vl_mask
        acc, row_max, row_sum = _stream_block(
            qf, k_cur, v_cur, acc, row_max, row_sum, mask, scale=scale)
        # rotate k/v one hop around the ring (device i -> i+1)
        perm = [(i, (i + 1) % size) for i in range(size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return acc, row_max, row_sum, k_nxt, v_nxt

    carry = (acc, row_max, row_sum, k, v)
    carry = lax.fori_loop(0, size, body, carry)
    acc, row_max, row_sum = carry[:3]
    out = acc / jnp.maximum(row_sum[..., None], 1e-30)
    # fully-masked rows (vl==0): row_max never rose from the additive
    # -inf floor and p degenerated to uniform — zero them (the same
    # masked-row contract as ops.pallas_attention / _sdpa_blockwise)
    out = jnp.where((row_max > _NEG_INF / 2)[..., None], out, 0.0)
    return out.astype(q.dtype)


def _ring_shard_map(make_block_fn, q, k, v, mesh, axis_name, batch_axis,
                    valid_length=None):
    """Shared wrapper: validate the mesh/sequence contract and shard_map
    the per-block ring function over (batch_axis, axis_name).

    ``make_block_fn(batch_axis_or_None) -> block_fn`` — a builder, so
    every engine resolves the mesh's actual batch axis (the dense block
    needs it for its fori_loop carry varying-type alignment).
    ``valid_length`` (B,) global key lengths ride along batch-sharded."""
    from . import mesh as _mesh_mod

    if mesh is None:
        mesh = _mesh_mod.default_mesh()
    if axis_name not in mesh.shape:
        raise MXNetError(f"mesh has no axis {axis_name!r}")
    sp = mesh.shape[axis_name]
    if q.shape[1] % sp != 0:
        raise MXNetError(
            f"sequence length {q.shape[1]} not divisible by {axis_name} "
            f"axis size {sp}")
    if batch_axis is None:
        b_axes = ()
    elif isinstance(batch_axis, str):
        b_axes = (batch_axis,)
    else:
        b_axes = tuple(batch_axis)
    b_axes = tuple(a for a in b_axes
                   if a in mesh.shape and mesh.shape[a] > 1)
    b_entry = b_axes if len(b_axes) > 1 else (
        b_axes[0] if b_axes else None)
    block_fn = make_block_fn(b_axes)  # resolve the per-mesh batch axes
    spec = PartitionSpec(b_entry, axis_name, None, None)
    in_specs = [spec, spec, spec]
    args = [q, k, v]
    if valid_length is not None:
        in_specs.append(PartitionSpec(b_entry))
        args.append(valid_length)
    mapped = shard_map(block_fn, mesh=mesh,
                       in_specs=tuple(in_specs), out_specs=spec)
    return mapped(*args)


def ring_self_attention(q, k, v, mesh: Optional[Mesh] = None,
                        axis_name: str = "sp", causal: bool = False,
                        scale: Optional[float] = None,
                        batch_axis: Optional[str] = "dp",
                        valid_length=None):
    """Exact self-attention with the sequence sharded over ``axis_name``.

    q, k, v: global (B, T, H, D) arrays; T must divide by the ``sp`` axis
    size. Returns (B, T, H, D). Differentiable (jax traces through the
    ppermute ring), jit-safe, and composable with data parallelism via
    ``batch_axis``.
    """
    def fn_builder(b_axes):
        return partial(ring_attention_block, axis_name=axis_name,
                       causal=causal, scale=scale, vary_axes=b_axes)
    return _ring_shard_map(fn_builder, q, k, v, mesh, axis_name,
                           batch_axis, valid_length=valid_length)


# --------------------------------------------------------------------- #
# ring attention with the Pallas flash kernel as the per-block engine
# --------------------------------------------------------------------- #

def _merge_partials(o1, lse1, o2, lse2):
    """Associatively combine two attention partial results carrying
    logsumexp (the flash merge rule): both (B,H,T,D)/(B,H,T)."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    den = jnp.maximum(w1 + w2, 1e-38)
    out = (o1.astype(jnp.float32) * w1[..., None]
           + o2.astype(jnp.float32) * w2[..., None]) / den[..., None]
    return out, m + jnp.log(den)


def _block_vl(step, n, size, B, Tq, causal):
    """Key-validity gating for ring step ``step``: the held block is
    (n - step) mod size — under causality fully visible iff it precedes
    ours, else fully masked (vl=0 ⇒ kernel masks everything ⇒ merge
    weight ~0 in forward, zero gradient in backward)."""
    if not causal:
        return jnp.full((B,), Tq, jnp.int32)
    allowed = (n - step) % size < n
    return jnp.where(allowed, Tq, 0) * jnp.ones((B,), jnp.int32)


def _block_bwd_any(q, k, v, vl, out, lse, g, causal, scale, interpret):
    """Per-block backward against the GLOBAL logsumexp — the ring/flash
    backward identity: p_ij = exp(s_ij - LSE_i) is exact for every block
    once LSE is the full-row normalizer. Pallas kernels on TPU (or
    interpret mode), the shared residual-based dense math otherwise."""
    from ..ops.pallas_attention import (_dense_block_bwd, _dense_hpp,
                                        _flash_backward, _pallas_runnable,
                                        _use_dense)

    if _pallas_runnable(interpret):
        dense = _use_dense(q.shape[2], k.shape[2])
        return _flash_backward(q, k, v, vl, out, lse, g, causal=causal,
                               scale=scale, interpret=interpret,
                               dense=dense,
                               hpp=_dense_hpp(q.shape[1], bwd=True)
                               if dense else None)
    return _dense_block_bwd(q, k, v, vl, out, lse, g, causal, scale)


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, interpret):
    from ..ops.pallas_attention import block_attn_lse

    B, Tq, H, D = q.shape
    n = lax.axis_index(axis_name)
    size = lax.psum(1, axis_name)

    qt = q.transpose(0, 2, 1, 3)                       # (B, H, T, D)
    full_vl = jnp.full((B,), Tq, jnp.int32)

    out0, lse0 = block_attn_lse(qt, k.transpose(0, 2, 1, 3),
                                v.transpose(0, 2, 1, 3), full_vl,
                                causal, scale, interpret)
    out0 = out0.astype(jnp.float32)

    def body(step, carry):
        out, lse, k_cur, v_cur = carry
        perm = [(i, (i + 1) % size) for i in range(size)]
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        vl = _block_vl(step, n, size, B, Tq, causal)
        o_b, lse_b = block_attn_lse(qt, k_cur.transpose(0, 2, 1, 3),
                                    v_cur.transpose(0, 2, 1, 3), vl,
                                    False, scale, interpret)
        out, lse = _merge_partials(out, lse, o_b.astype(jnp.float32),
                                   lse_b)
        return out, lse, k_cur, v_cur

    out, lse, _, _ = lax.fori_loop(1, size, body, (out0, lse0, k, v))
    return out.transpose(0, 2, 1, 3).astype(q.dtype), lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_flash_attention_block(q, k, v, axis_name: str = "sp",
                               causal: bool = False,
                               scale: Optional[float] = None,
                               interpret: bool = False):
    """Ring attention with the Pallas flash kernel per block (call inside
    shard_map; q/k/v local blocks (B, T_blk, H, D)).

    Forward: each ring step computes its block's (out, logsumexp) with
    ``block_attn_lse`` and merges partials with the flash merge rule.
    Backward: a SECOND ring where each step runs the per-block flash
    backward against the global logsumexp (the p = exp(s - LSE)
    identity), accumulating dq locally while the dk/dv accumulators
    ride the ring home with their blocks — the Ring Attention backward
    schedule (PAPERS.md)."""
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                  interpret)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, interpret):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                    interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, interpret, res, g):
    q, k, v, out, lse = res
    B, Tq, H, D = q.shape
    n = lax.axis_index(axis_name)
    size = lax.psum(1, axis_name)

    qt = q.transpose(0, 2, 1, 3)
    gt = g.transpose(0, 2, 1, 3).astype(jnp.float32)
    ot = out.transpose(0, 2, 1, 3)
    full_vl = jnp.full((B,), Tq, jnp.int32)

    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dq0, dk0, dv0 = _block_bwd_any(qt, kt, vt, full_vl, ot, lse, gt,
                                   causal, scale, interpret)

    def body(step, carry):
        dq, dk_cur, dv_cur, k_cur, v_cur = carry
        perm = [(i, (i + 1) % size) for i in range(size)]
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
        vl = _block_vl(step, n, size, B, Tq, causal)
        dq_b, dk_b, dv_b = _block_bwd_any(qt, k_cur, v_cur, vl, ot, lse,
                                          gt, False, scale, interpret)
        return (dq + dq_b.astype(jnp.float32),
                dk_cur + dk_b.astype(jnp.float32),
                dv_cur + dv_b.astype(jnp.float32), k_cur, v_cur)

    dq, dk_cur, dv_cur, _, _ = lax.fori_loop(
        1, size, body, (dq0.astype(jnp.float32), dk0.astype(jnp.float32),
                        dv0.astype(jnp.float32), kt, vt))
    # one final hop brings each block's accumulated dk/dv home
    perm = [(i, (i + 1) % size) for i in range(size)]
    dk_home = lax.ppermute(dk_cur, axis_name, perm)
    dv_home = lax.ppermute(dv_cur, axis_name, perm)
    return (dq.transpose(0, 2, 1, 3).astype(q.dtype),
            dk_home.transpose(0, 2, 1, 3).astype(k.dtype),
            dv_home.transpose(0, 2, 1, 3).astype(v.dtype))


ring_flash_attention_block.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q, k, v, mesh: Optional[Mesh] = None,
                         axis_name: str = "sp", causal: bool = False,
                         scale: Optional[float] = None,
                         batch_axis: Optional[str] = "dp",
                         interpret: bool = False):
    """ring_self_attention with the Pallas flash kernel as the per-block
    engine (TPU hot path; ``interpret=True`` runs the same kernels on
    CPU). Same contract: global (B, T, H, D), T divisible by the sp
    size, differentiable end to end."""
    return _ring_shard_map(
        lambda b_axes: partial(ring_flash_attention_block,
                               axis_name=axis_name, causal=causal,
                               scale=scale, interpret=interpret),
        q, k, v, mesh, axis_name, batch_axis)
