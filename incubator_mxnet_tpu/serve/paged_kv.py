"""Paged KV cache: a shared page pool + host-side page allocator.

Layout (one pool pair per transformer layer):

    k_pool / v_pool : (num_pages, H, page_size, D)

chosen so each (page, head) slice is a contiguous (page_size, D) tile —
the ragged kernel's per-head dot operand (ops/ragged_attention.py) —
and so a tp mesh can shard the H axis with the existing
``parallel.mesh`` machinery without splitting any page.

Invariants (enforced by the engine, asserted in tests):
  - **Page 0 is the NULL page.** The allocator never hands it out; every
    dead page-table entry points at it; inactive slots' decode writes
    land in it. Its contents are garbage BY DESIGN — correctness relies
    on every read of it being masked by the slot's length, never on what
    it holds.
  - A slot at length L references exactly ceil(L / page_size) live
    pages, contiguous in its page-table row; entries past that are 0.
  - Pages are identity-free: eviction returns them to the free list and
    any slot may reuse them without clearing (the next writer overwrites
    the prefix it needs; the tail is masked).
  - **Pages are reference-counted.** A page may be mapped read-only into
    several slots' page tables at once (prefix sharing) and retained by
    the host-side prefix index; it returns to the free list only when
    the last reference drops. A shared page is NEVER written: decode
    writes land at positions >= the slot's prompt length, past every
    shared prefix page, and the first partial page after a matched
    prefix is COPIED into a private page before the slot writes it
    (copy-on-write at page granularity).

The allocator and the prefix index are deliberately host-side Python,
matching the scheduler split: device programs are occupancy-oblivious,
all allocation/sharing decisions ride in as int32 data.
"""

from __future__ import annotations

import dataclasses
import os
import zlib
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..ops.quantization import (quantize_symmetric, requantize_symmetric,
                                symmetric_scale)

NULL_PAGE = 0

__all__ = ["NULL_PAGE", "PageAllocator", "PrefixIndex", "KVTierStore",
           "init_kv_pools",
           "write_token_kv", "write_prompt_kv", "write_block_kv",
           "KVQuantSpec", "kv_quant_spec", "page_scales",
           "write_token_kv_q", "write_prompt_kv_q", "write_block_kv_q"]


# --------------------------------------------------------------------- #
# quantized pool layout (int8 / fp8 payload + per-page symmetric scale)
#
# A quantized pool keeps the SAME (num_pages, H, page_size, D) page
# layout with a narrow payload dtype, plus ONE float32 absolute-max
# statistic per page per pool (``amax``, shape (num_pages,)) from which
# the page's symmetric dequantization scale derives
# (ops.quantization.symmetric_scale: amax / qmax, 1.0 on an untouched
# page). The amax array is PAGE METADATA: it rides next to the page
# table as data into every program that reads or writes pages (and on
# TPU down the same scalar-prefetch path — ops/ragged_attention.py), a
# shared prefix page's scale is shared exactly like the page itself,
# and the host resets a page's amax when the allocator hands it out
# (pages are identity-free; a recycled page must not inherit its
# previous owner's range).
#
# Incremental writes and the monotone-scale contract: decode and
# chunked prefill fill a page a few rows at a time, so a page's scale
# can only GROW (amax is scatter-max'd). When a write raises a page's
# amax, the page's existing codes are REQUANTIZED in place by
# ``old_scale / new_scale <= 1`` (ops.quantization.requantize_symmetric
# — a pure code rescale, never a dequant round trip), then the new rows
# are quantized at the new scale. Each rescale adds at most half a
# quantum of error to already-written rows; scales stabilize after the
# first few writes in practice (measured in BENCH_QUANT.json).
# --------------------------------------------------------------------- #

@dataclasses.dataclass(frozen=True)
class KVQuantSpec:
    """One quantized-KV flavour: the pool payload dtype and its
    saturation bound (int8: ±127; fp8_e4m3: ±448)."""
    name: str
    dtype: object
    qmax: float


def kv_quant_spec(kv_quant) -> Optional[KVQuantSpec]:
    """Resolve an engine's ``kv_quant`` knob: None/'none' → None
    (unquantized f32/bf16 pools), 'int8' → int8 payload (the portable
    default — the MXU int8 path on TPU, exact small-int arithmetic on
    CPU), 'fp8_e4m3' → float8 payload (TPU-targeted; needs a jax with
    float8 dtypes)."""
    if kv_quant is None or kv_quant == "none":
        return None
    if isinstance(kv_quant, KVQuantSpec):
        return kv_quant
    if kv_quant == "int8":
        return KVQuantSpec("int8", jnp.int8, 127.0)
    if kv_quant == "fp8_e4m3":
        if not hasattr(jnp, "float8_e4m3fn"):
            raise MXNetError("kv_quant='fp8_e4m3' needs a jax build "
                             "with float8 dtypes")
        return KVQuantSpec("fp8_e4m3", jnp.float8_e4m3fn, 448.0)
    raise MXNetError(f"kv_quant must be None|'int8'|'fp8_e4m3', got "
                     f"{kv_quant!r}")


def page_scales(amax, spec: KVQuantSpec):
    """(P,) per-page dequantization scales from the amax metadata."""
    return symmetric_scale(amax, spec.qmax)


def write_token_kv_q(pool, amax, new, pages, offsets, spec: KVQuantSpec):
    """Quantized twin of ``write_token_kv``: scatter one K (or V) row
    per entry into an int8/fp8 pool, growing the per-page scales.

    pool: (P, H, ps, D) codes; amax: (P,) f32; new: (N, H, D) float;
    pages/offsets: (N,) int32. Returns ``(pool, amax)`` updated.

    Three phases, all safe under duplicate page indices (several rows
    of one call landing in the same page — the verify window's block
    write flattens into this):
      1. scatter-max the new rows' |max| into ``amax`` (duplicates
         combine correctly by construction);
      2. requantize every TOUCHED page's existing codes by
         ``old_scale / new_scale`` — duplicate entries compute
         IDENTICAL page contents (same gathered codes, same final
         scale), so the unspecified scatter order cannot diverge;
      3. quantize the new rows at the final scale and scatter them at
         their (page, offset) cells — distinct cells except dead
         entries, which all land in the null page (garbage by design,
         same contract as the unquantized write)."""
    H = pool.shape[1]
    a_n = jnp.max(jnp.abs(new.astype(jnp.float32)), axis=(1, 2))  # (N,)
    new_amax = amax.at[pages].max(a_n)
    old_s = symmetric_scale(amax, spec.qmax)
    new_s = symmetric_scale(new_amax, spec.qmax)
    ratio = (old_s / new_s)[pages]                       # (N,) <= 1
    touched = requantize_symmetric(
        pool[pages], ratio[:, None, None, None], spec.dtype, spec.qmax)
    pool = pool.at[pages].set(touched)
    q = quantize_symmetric(new, new_s[pages][:, None, None],
                           spec.dtype, spec.qmax)        # (N, H, D)
    pool = pool.at[pages[:, None], jnp.arange(H)[None, :],
                   offsets[:, None], :].set(q)
    return pool, new_amax


def write_block_kv_q(pool, amax, new, pages, offsets, spec: KVQuantSpec):
    """Quantized twin of ``write_block_kv``: a (S, W) block of rows
    (the speculative verify window) flattened into the per-row
    quantized scatter — duplicate pages inside one slot's window are
    exactly the case ``write_token_kv_q``'s phases are built for."""
    S, W, H, D = new.shape
    return write_token_kv_q(pool, amax, new.reshape(S * W, H, D),
                            pages.reshape(S * W),
                            offsets.reshape(S * W), spec)


def write_prompt_kv_q(pool, amax, kv, pages, spec: KVQuantSpec):
    """Quantized twin of ``write_prompt_kv``: scatter a whole prompt's
    K (or V) into its pages with a FRESH per-page scale (each page's
    amax is overwritten, not grown — prefill is the page's first write,
    so a recycled page's stale range dies here). Dead entries all index
    the null page; whichever dead page's amax wins the duplicate
    scatter is garbage by design, like the payload."""
    n_pages = pages.shape[0]
    ps = pool.shape[2]
    paged = kv.astype(jnp.float32).reshape(n_pages, ps, kv.shape[1],
                                           kv.shape[2])
    a_p = jnp.max(jnp.abs(paged), axis=(1, 2, 3))        # (n_pages,)
    amax = amax.at[pages].set(a_p)
    s = symmetric_scale(a_p, spec.qmax)
    q = quantize_symmetric(paged, s[:, None, None, None],
                           spec.dtype, spec.qmax)
    q = q.transpose(0, 2, 1, 3)                 # (n_pages, H, ps, D)
    return pool.at[pages].set(q), amax


class PageAllocator:
    """Reference-counted free-list allocator over pages 1..num_pages-1
    (page 0 = null). ``alloc`` hands out a page at refcount 1;
    ``incref`` adds a sharer; ``free``/``decref`` drops one reference
    and returns the page to the free list when the last one goes.

    Corruption is refused loudly instead of silently poisoning the free
    list: freeing the null page, double-freeing a page already back on
    the free list, or dropping a refcount below zero all raise."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise MXNetError("need >= 2 pages (page 0 is the null page)")
        self.num_pages = num_pages
        # LIFO reuse keeps the working set of hot pages small
        self._free = list(range(num_pages - 1, 0, -1))
        self._rc = [0] * num_pages
        self._held: List[int] = []

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def held(self) -> Tuple[int, ...]:
        """Pages taken out of circulation by ``hold`` (chaos-harness
        allocator pressure) — accounted for by the engine's page audit."""
        return tuple(self._held)

    def hold(self, n: int) -> List[int]:
        """Take up to ``n`` pages out of circulation (refcount 1, owned
        by the holder): the deterministic allocator-pressure fault of
        serve/chaos.py — admission and tail allocation see a genuinely
        smaller pool, through the allocator's own bookkeeping so the
        page audit stays exact. Returns the pages actually held."""
        pages = [self.alloc() for _ in range(min(max(n, 0),
                                                 self.free_count))]
        self._held.extend(pages)
        return pages

    def release_held(self, pages=None) -> int:
        """Return held pages (default: all of them) to the free list."""
        if pages is None:
            pages = list(self._held)
        for p in pages:
            self._held.remove(p)
            self.decref(p)
        return len(pages)

    def _check(self, page) -> int:
        p = int(page)
        if p == NULL_PAGE:
            raise MXNetError("the null page (page 0) is never allocated, "
                             "shared, or freed")
        if not 0 < p < self.num_pages:
            raise MXNetError(f"page {p} outside pool [1, "
                             f"{self.num_pages})")
        return p

    def refcount(self, page) -> int:
        return self._rc[self._check(page)]

    def alloc(self) -> int:
        if not self._free:
            raise MXNetError("KV page pool exhausted — admission control "
                             "should have prevented this (engine bug)")
        p = self._free.pop()
        self._rc[p] = 1
        return p

    def incref(self, page) -> None:
        """Add a reference to a LIVE page (prefix sharing / index
        retention). Sharing a page that is on the free list would hand
        the same page to two owners — refused."""
        p = self._check(page)
        if self._rc[p] <= 0:
            raise MXNetError(f"incref on free page {p} — a page must be "
                             f"live to be shared")
        self._rc[p] += 1

    def decref(self, page) -> bool:
        """Drop one reference; returns True when the page went back to
        the free list. A decref on a page whose refcount is already zero
        is a double free (or a below-zero drop) and raises."""
        p = self._check(page)
        if self._rc[p] <= 0:
            raise MXNetError(
                f"double free: page {p} already has refcount 0 (it is "
                f"on the free list) — refusing to corrupt the free list")
        self._rc[p] -= 1
        if self._rc[p] == 0:
            self._free.append(p)
            return True
        return False

    def free(self, pages) -> None:
        for p in pages:
            self.decref(p)


@dataclasses.dataclass(eq=False)        # identity semantics: entries are
class _PrefixEntry:                     # tracked by object, and ndarray
    page: int                           # fields break generated __eq__
    tokens: np.ndarray          # the page's token ids (full page)
    depth: int                  # page index within its prompt chain
    last_use: int


class PrefixIndex:
    """Host-side hash-radix index over page-aligned prompt prefixes.

    A radix node is keyed by the BYTES OF THE WHOLE TOKEN PREFIX that
    precedes its pages (int32, fixed width — byte-prefix equality is
    token-prefix equality) and holds the SIBLING entries extending that
    prefix (several prompt families may diverge at the same depth), so
    lookups walk page by page exactly like a radix tree without storing
    child pointers. Each entry holds its page's own tokens for
    verification and the shared page id; the index owns one allocator
    reference per entry.

    Matching returns the longest cached page-aligned prefix as
    read-only shared pages plus (when the boundary page's leading
    tokens match) a partial page to copy — capped at ``t0 - 1`` tokens
    so the LAST prompt token is always recomputed: its logits seed
    first-token sampling, which cached K/V alone cannot provide.

    ``flush`` drops every entry (cached K/V is weight-dependent — the
    engine flushes on ``warm_start``); ``reclaim`` evicts
    least-recently-used entries whose pages nobody else references,
    which is how admission turns cache retention back into free pages
    under pressure."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        # radix node: preceding-prefix bytes -> sibling entries
        self._nodes: Dict[bytes, List[_PrefixEntry]] = {}
        self._clock = 0
        self.flushes = 0

    def __len__(self) -> int:
        return sum(len(b) for b in self._nodes.values())

    def held_pages(self) -> List[int]:
        return [e.page for b in self._nodes.values() for e in b]

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, prompt_ids, mutate: bool = True) \
            -> Tuple[List[int], Optional[Tuple[int, int]], int]:
        """Longest cached page-aligned prefix of ``prompt_ids``.

        Returns ``(shared, partial, cached_len)``: ``shared`` is the
        list of full pages to map read-only (the caller must incref
        them), ``partial`` is ``(src_page, n_tokens)`` for a boundary
        page whose first ``n_tokens`` match (to copy into a private
        page), or None, and ``cached_len == page_size * len(shared) +
        n_tokens`` is the number of prompt tokens whose K/V is already
        cached (always <= t0 - 1).

        ``mutate=False`` skips the LRU ``last_use`` ticks — the
        ``probe`` read, identical traversal, zero side effects."""
        ps = self.page_size
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        t0 = prompt.size
        shared: List[int] = []
        m = 0
        while True:
            siblings = self._nodes.get(prompt[:m * ps].tobytes())
            if not siblings:
                break
            rest = prompt[m * ps:]
            full = None
            if rest.size > ps:
                for ent in siblings:
                    if np.array_equal(ent.tokens, rest[:ps]):
                        full = ent
                        break
            if full is not None:
                # whole page matches and the prompt continues past it
                if mutate:
                    full.last_use = self._tick()
                shared.append(full.page)
                m += 1
                continue
            # boundary page: the sibling with the longest common
            # leading run, capped so at least one prompt token is left
            # to recompute (its logits seed first-token sampling)
            lim = min(ps, rest.size, t0 - 1 - m * ps)
            best, best_n = None, 0
            for ent in siblings:
                n = 0
                while n < lim and ent.tokens[n] == rest[n]:
                    n += 1
                if n > best_n:
                    best, best_n = ent, n
            if best is not None:
                if mutate:
                    best.last_use = self._tick()
                return shared, (best.page, best_n), m * ps + best_n
            break
        return shared, None, m * ps

    def probe(self, prompt_ids) -> int:
        """READ-ONLY twin of ``match``: how many leading tokens of
        ``prompt_ids`` are cached right now. Touches NOTHING — no
        refcounts (it returns no pages to pin), no LRU clock ticks —
        so a fleet router may probe every replica per admission
        without perturbing any replica's eviction order
        (serve/router.py's cache-affinity read; asserted
        side-effect-free in tests/test_router.py). One traversal
        serves both callers (``match(..., mutate=False)``), so the
        affinity estimate can never drift from what admission will
        actually reuse."""
        return self.match(prompt_ids, mutate=False)[2]

    def insert(self, prompt_ids, pages, allocator: PageAllocator) -> int:
        """Publish the prompt's FULL pages (``pages[j]`` holds tokens
        ``[j*ps, (j+1)*ps)``); the index increfs each newly-published
        page. An existing sibling with the same content is kept (first
        writer wins — duplicate K/V pages earn no second entry).
        Returns the number of new entries."""
        ps = self.page_size
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        added = 0
        for j in range(prompt.size // ps):
            key = prompt[:j * ps].tobytes()
            toks = prompt[j * ps:(j + 1) * ps]
            siblings = self._nodes.setdefault(key, [])
            dup = next((e for e in siblings
                        if np.array_equal(e.tokens, toks)), None)
            if dup is not None:
                dup.last_use = self._tick()
                continue
            allocator.incref(pages[j])
            siblings.append(_PrefixEntry(
                page=int(pages[j]), tokens=toks.copy(), depth=j,
                last_use=self._tick()))
            added += 1
        return added

    def reclaimable(self, allocator: PageAllocator) -> int:
        """Pages that ``reclaim`` could return to the free list right
        now: entries whose page nobody but the index references."""
        return sum(1 for b in self._nodes.values() for e in b
                   if allocator.refcount(e.page) == 1)

    def _drop(self, key: bytes, ent: _PrefixEntry,
              allocator: PageAllocator, demote=None) -> int:
        """Remove one entry and its now-unreachable descendants (every
        entry under nodes whose key extends this entry's prefix).
        Returns pages actually returned to the free list — descendant
        pages still referenced by live slots merely lose the index's
        ref.

        ``demote(key, ent)`` (when given) is called for every entry
        whose page is ABOUT to go back to the free list — the victim
        AND each cascaded descendant — while the page is still live,
        so the caller can capture its payload into a lower cache tier
        before the KV is lost. Entries whose page survives through a
        live slot's reference are NOT demoted: their KV is still
        resident in HBM."""
        freed = 0
        child_prefix = key + ent.tokens.tobytes()
        for k in [k for k in self._nodes if k.startswith(child_prefix)]:
            for e in self._nodes.pop(k):
                if demote is not None and allocator.refcount(e.page) == 1:
                    demote(k, e)
                if allocator.decref(e.page):
                    freed += 1
        bucket = self._nodes[key]
        bucket.remove(ent)
        if not bucket:
            del self._nodes[key]
        if demote is not None and allocator.refcount(ent.page) == 1:
            demote(key, ent)
        if allocator.decref(ent.page):
            freed += 1
        return freed

    def reclaim(self, n: int, allocator: PageAllocator,
                demote=None) -> int:
        """Evict least-recently-used index-only entries until ``n``
        pages returned to the free list (or candidates run out).
        ``demote`` is threaded to ``_drop`` so an engine with cache
        tiers can capture every evicted page's payload."""
        freed = 0
        order = sorted(
            [(k, e) for k, b in self._nodes.items() for e in b],
            key=lambda kv: (kv[1].last_use, -kv[1].depth))
        for key, ent in order:
            if freed >= n:
                break
            bucket = self._nodes.get(key)
            if bucket is None or ent not in bucket:
                continue                      # cascaded away already
            if allocator.refcount(ent.page) != 1:
                continue                      # a live slot still maps it
            freed += self._drop(key, ent, allocator, demote)
        return freed

    def flush(self, allocator: PageAllocator) -> None:
        """Drop every entry (cached K/V is weight-dependent): pages held
        only by the index go back to the free list; pages still mapped
        by live slots survive through the slots' own references."""
        for bucket in self._nodes.values():
            for e in bucket:
                allocator.decref(e.page)
        self._nodes.clear()
        self.flushes += 1


# --------------------------------------------------------------------- #
# hierarchical cache tiers (host DRAM → disk) beneath the prefix index
#
# When LRU reclaim would DELETE an evicted-but-published page, the
# engine demotes its payload here instead: int8/fp8 codes plus the
# per-page amax for quantized pools, the raw-dtype page for unquantized
# ones. A later prefix probe that misses HBM but hits a tier re-admits
# the page by COPY into a freshly allocated page — host-side data
# movement, never a new program and never a prefill recompute.
#
# A demoted page has NO page id and NO refcount: _TierEntry carries the
# payload itself, deliberately without a ``page`` field, so "free XOR
# live XOR demoted" is structural — the only way back into the page
# pool is ``KVTierStore.load`` + the engine's promote copy into a page
# the allocator just handed out. The store therefore must never touch
# a PageAllocator (tools/mxlint's page-refcount pass enforces both
# directions: tier internals outside this class, and allocator
# mutation inside it, are findings).
# --------------------------------------------------------------------- #

@dataclasses.dataclass(eq=False)        # identity semantics, like
class _TierEntry:                       # _PrefixEntry (ndarray fields)
    tokens: np.ndarray          # the page's token ids (full page)
    depth: int                  # page index within its prompt chain
    last_use: int
    nbytes: int                 # payload bytes (accounting unit)
    tier: str                   # "dram" | "disk"
    # DRAM payload (None once spilled to disk):
    k_payload: Optional[Tuple[np.ndarray, ...]]   # per-layer (H, ps, D)
    v_payload: Optional[Tuple[np.ndarray, ...]]
    kamax: Optional[np.ndarray]  # (L,) f32 page amax, quantized pools
    vamax: Optional[np.ndarray]
    crc: int                    # crc32 over the DRAM payload bytes
    step: Optional[int] = None  # manifest step id (disk tier only)
    pinned: bool = False        # admission in flight — not evictable


def payload_crc(k_payload, v_payload, kamax, vamax, seed: int = 0) -> int:
    """crc32 over one page payload's bytes, chained from ``seed`` —
    the ONE integrity primitive for KV bytes at rest and on the wire:
    tier entries checksum each page independently (seed 0), the page
    transport (serve/transport.py) chains page crcs through the whole
    capsule so a reordered, dropped, or substituted page breaks every
    later link, not just its own."""
    c = seed & 0xFFFFFFFF
    for arr in (*k_payload, *v_payload):
        c = zlib.crc32(np.ascontiguousarray(arr).tobytes(), c)
    for arr in (kamax, vamax):
        if arr is not None:
            c = zlib.crc32(np.ascontiguousarray(arr).tobytes(), c)
    return c


def payload_nbytes(k_payload, v_payload, kamax, vamax) -> int:
    """Wire/at-rest size of one page payload — the accounting unit
    behind tier byte budgets and capsule ``kv_migrated_bytes_total``
    (int8 codes + f32 scales ≈ 1/4 the raw-dtype bytes)."""
    n = sum(a.nbytes for a in (*k_payload, *v_payload))
    for arr in (kamax, vamax):
        if arr is not None:
            n += arr.nbytes
    return n


_payload_crc = payload_crc              # internal alias (pre-transport name)


class KVTierStore:
    """Bounded host-DRAM pool of demoted prefix pages, spilling its own
    LRU overflow to a disk tier built on the checkpoint manifest's
    audited write path (crc32 per shard, write-to-tmp + atomic rename).

    Keys mirror ``PrefixIndex``: preceding-token-prefix bytes → sibling
    entries, so a tier lookup continues exactly where the HBM radix
    walk stopped. Only FULL pages are tiered (a boundary partial page
    is cheap to recompute and its COW copy needs the source resident).

    Integrity: every DRAM entry carries a crc32 of its payload,
    verified at promotion; the disk tier inherits the manifest's
    per-shard crc32. A failed check drops the entry and returns None —
    the engine falls back to recomputing prefill, loudly, never
    admitting bytes it cannot verify.

    Crash safety: tier contents are weight-dependent and process-
    lifetime. Construction wipes any step directories left under
    ``disk_dir`` by an earlier process (a kill mid-promotion or
    mid-demotion leaves either a committed-but-orphaned step or a
    ``.tmp`` — both stale by definition)."""

    def __init__(self, page_size: int, dram_bytes: int,
                 disk_dir: Optional[str] = None,
                 disk_bytes: Optional[int] = None,
                 recorder=None, component: str = "engine"):
        from ..events import EventType, resolve_recorder
        self._EventType = EventType
        self.page_size = int(page_size)
        self.dram_bytes = int(dram_bytes)
        if self.dram_bytes < 0:
            raise MXNetError("kv tier dram_bytes must be >= 0")
        self.disk_dir = disk_dir
        self.disk_bytes = None if disk_bytes is None else int(disk_bytes)
        self.flight = resolve_recorder(recorder)
        self._component = component
        self._entries: Dict[bytes, List[_TierEntry]] = {}
        self._clock = 0
        self._dram_used = 0
        self._disk_used = 0
        self._disk_seq = 0
        # counters (mirrored into engine health_snapshot / metrics)
        self.demotions = 0          # HBM → DRAM admissions
        self.disk_demotions = 0     # DRAM → disk spills
        self.promotions = 0         # entries handed back for re-admission
        self.dropped = 0            # evicted off the bottom tier
        self.crc_failures = 0       # payload failed its integrity check
        self.disk_errors = 0        # disk tier write/read failed (OSError)
        self.flushes = 0
        # seam for fault injection (serve/chaos.py DiskFullDemotion)
        from ..checkpoint import manifest as _manifest
        self._manifest = _manifest
        self._write_step = _manifest.write_step
        if self.disk_dir is not None:
            self._wipe_disk_dir()

    # -- basics -------------------------------------------------------- #

    def __len__(self) -> int:
        return sum(len(b) for b in self._entries.values())

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def entries(self):
        """Read-only iteration seam: yields ``(key, entry)`` pairs.
        Used by the chaos harness (to pick a victim payload to corrupt)
        and by tests — NOT a license to mutate the store's accounting;
        structural changes go through ``put``/``remove``/``flush``."""
        for key, bucket in self._entries.items():
            for ent in bucket:
                yield key, ent

    def tier_bytes(self) -> Dict[str, int]:
        """Payload bytes resident per tier (the ``kv_tier_bytes``
        gauge's data source)."""
        return {"dram": self._dram_used, "disk": self._disk_used}

    # -- disk tier plumbing -------------------------------------------- #

    def _wipe_disk_dir(self):
        import shutil
        os.makedirs(self.disk_dir, exist_ok=True)
        for name in os.listdir(self.disk_dir):
            path = os.path.join(self.disk_dir, name)
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)

    def _spill_to_disk(self, key: bytes, ent: _TierEntry) -> bool:
        """DRAM → disk via the manifest's audited write path. Returns
        False (and drops the entry — plain eviction, loudly counted)
        when the disk tier is unconfigured or the write fails."""
        if self.disk_dir is None:
            return False
        k = np.stack([np.asarray(a) for a in ent.k_payload])
        v = np.stack([np.asarray(a) for a in ent.v_payload])
        step = self._disk_seq
        self._disk_seq += 1
        arrays = {"k": k, "v": v}
        if ent.kamax is not None:
            arrays["kamax"] = ent.kamax
            arrays["vamax"] = ent.vamax
        entries = {
            name: {"shape": tuple(arr.shape), "dtype": str(arr.dtype),
                   "spec": None,
                   "shards": [([[0, s] for s in arr.shape], arr)]}
            for name, arr in arrays.items()}
        meta = {"key_hex": key.hex(), "tokens": ent.tokens.tolist(),
                "depth": ent.depth, "crc": ent.crc}
        try:
            self._write_step(self.disk_dir, step, entries, meta=meta)
        except (OSError, MXNetError) as e:
            self.disk_errors += 1
            self.flight.emit(self._component,
                             self._EventType.CACHE_DEMOTE,
                             entity=f"tier:{key.hex()[:16]}",
                             tier="disk", ok=False, error=str(e)[:200])
            return False
        ent.tier = "disk"
        ent.step = step
        ent.k_payload = ent.v_payload = None
        ent.kamax = ent.vamax = None
        self._dram_used -= ent.nbytes
        self._disk_used += ent.nbytes
        self.disk_demotions += 1
        self.flight.emit(self._component, self._EventType.CACHE_DEMOTE,
                         entity=f"tier:{key.hex()[:16]}",
                         tier="disk", ok=True, nbytes=ent.nbytes,
                         depth=ent.depth)
        return True

    def _load_disk(self, key: bytes, ent: _TierEntry):
        try:
            arrays, meta = self._manifest.load_step(self.disk_dir,
                                                    ent.step)
        except MXNetError:
            self.crc_failures += 1
            return None
        except OSError:
            self.disk_errors += 1
            return None
        k = tuple(arrays["k"][i] for i in range(arrays["k"].shape[0]))
        v = tuple(arrays["v"][i] for i in range(arrays["v"].shape[0]))
        kamax = arrays.get("kamax")
        vamax = arrays.get("vamax")
        if _payload_crc(k, v, kamax, vamax) != meta.get("crc"):
            self.crc_failures += 1
            return None
        return k, v, kamax, vamax

    def _delete_disk_step(self, ent: _TierEntry):
        import shutil
        if ent.step is None or self.disk_dir is None:
            return
        shutil.rmtree(self._manifest.step_dir(self.disk_dir, ent.step),
                      ignore_errors=True)

    # -- bounded eviction ---------------------------------------------- #

    def _lru(self, tier: str):
        cands = [(k, e) for k, b in self._entries.items() for e in b
                 if e.tier == tier and not e.pinned]
        if not cands:
            return None
        return min(cands, key=lambda kv: (kv[1].last_use, -kv[1].depth))

    def _enforce_bounds(self):
        """Spill DRAM overflow to disk, drop disk overflow entirely.
        Pinned entries (an admission is mid-promotion) never move —
        bounds may transiently overshoot while a chain is pinned."""
        while self._dram_used > self.dram_bytes:
            victim = self._lru("dram")
            if victim is None:
                break
            key, ent = victim
            if not self._spill_to_disk(key, ent):
                self._discard(key, ent)
                self.dropped += 1
        while (self.disk_bytes is not None
               and self._disk_used > self.disk_bytes):
            victim = self._lru("disk")
            if victim is None:
                break
            self._discard(*victim)
            self.dropped += 1

    def _discard(self, key: bytes, ent: _TierEntry):
        bucket = self._entries[key]
        bucket.remove(ent)
        if not bucket:
            del self._entries[key]
        if ent.tier == "dram":
            self._dram_used -= ent.nbytes
        else:
            self._disk_used -= ent.nbytes
            self._delete_disk_step(ent)

    # -- the tier API the engine drives -------------------------------- #

    def put(self, key: bytes, tokens, depth: int,
            k_payload, v_payload, kamax=None, vamax=None) -> bool:
        """Admit one demoted page's payload into the DRAM tier.
        Duplicate content under the same key refreshes the existing
        entry instead (first writer wins, like ``PrefixIndex.insert``).
        Returns True when a NEW entry was stored."""
        toks = np.asarray(tokens, np.int32).reshape(-1).copy()
        bucket = self._entries.setdefault(key, [])
        dup = next((e for e in bucket
                    if np.array_equal(e.tokens, toks)), None)
        if dup is not None:
            dup.last_use = self._tick()
            return False
        k_payload = tuple(np.asarray(a) for a in k_payload)
        v_payload = tuple(np.asarray(a) for a in v_payload)
        kamax = None if kamax is None else np.asarray(kamax, np.float32)
        vamax = None if vamax is None else np.asarray(vamax, np.float32)
        nbytes = sum(a.nbytes for a in (*k_payload, *v_payload))
        nbytes += sum(a.nbytes for a in (kamax, vamax) if a is not None)
        ent = _TierEntry(
            tokens=toks, depth=int(depth), last_use=self._tick(),
            nbytes=nbytes, tier="dram", k_payload=k_payload,
            v_payload=v_payload, kamax=kamax, vamax=vamax,
            crc=_payload_crc(k_payload, v_payload, kamax, vamax))
        bucket.append(ent)
        self._dram_used += nbytes
        self.demotions += 1
        self._enforce_bounds()
        return True

    def match_chain(self, prompt_ids, start_page: int,
                    mutate: bool = True) -> List[Tuple[bytes,
                                                       _TierEntry]]:
        """Continue a prefix walk from page ``start_page`` (where the
        HBM index stopped) through the tiers: consecutive FULL-page
        matches only, each requiring the prompt to continue past the
        page (the last prompt token is always recomputed — its logits
        seed first-token sampling, exactly ``PrefixIndex.match``'s
        cap). Returns the ``(key, entry)`` chain, possibly empty."""
        ps = self.page_size
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        chain: List[Tuple[bytes, _TierEntry]] = []
        m = int(start_page)
        while True:
            siblings = self._entries.get(prompt[:m * ps].tobytes())
            if not siblings:
                break
            rest = prompt[m * ps:]
            if rest.size <= ps:
                break
            hit = next((e for e in siblings
                        if np.array_equal(e.tokens, rest[:ps])), None)
            if hit is None:
                break
            if mutate:
                hit.last_use = self._tick()
            chain.append((prompt[:m * ps].tobytes(), hit))
            m += 1
        return chain

    def probe(self, prompt_ids, start_page: int) -> int:
        """READ-ONLY twin of ``match_chain``: pages the tiers could
        re-admit, with zero side effects (no LRU ticks) — the router's
        second affinity axis."""
        return len(self.match_chain(prompt_ids, start_page,
                                    mutate=False))

    def pin(self, chain) -> None:
        """Protect a matched chain from eviction while its admission
        is in flight (demotions triggered by the SAME admission's
        reclaim must not spill or drop the pages it is promoting)."""
        for _, ent in chain:
            ent.pinned = True

    def unpin(self, chain) -> None:
        for _, ent in chain:
            ent.pinned = False
        self._enforce_bounds()

    def load(self, key: bytes, ent: _TierEntry):
        """Fetch one entry's payload for promotion, verifying its
        integrity: the DRAM crc32, or the manifest's per-shard crc plus
        the stored payload crc for a disk entry. Returns ``(k_payload,
        v_payload, kamax, vamax)`` or None — on ANY failure the entry
        is removed (its bytes are untrustworthy) and the caller must
        fall back to recomputing prefill."""
        if ent.tier == "dram":
            if _payload_crc(ent.k_payload, ent.v_payload,
                            ent.kamax, ent.vamax) != ent.crc:
                self.crc_failures += 1
                self._discard(key, ent)
                return None
            return ent.k_payload, ent.v_payload, ent.kamax, ent.vamax
        out = self._load_disk(key, ent)
        if out is None:
            self._discard(key, ent)
        return out

    def remove(self, key: bytes, ent: _TierEntry) -> None:
        """Retire an entry whose page was just promoted back into the
        pool (it is live again — keeping the tier copy would violate
        free XOR live XOR demoted)."""
        self._discard(key, ent)

    def flush(self) -> None:
        """Drop every entry in every tier (cached K/V is weight-
        dependent: the engine flushes tiers on ``warm_start`` and
        quarantine, alongside the HBM prefix index)."""
        for key, bucket in list(self._entries.items()):
            for ent in list(bucket):
                self._discard(key, ent)
        self._entries.clear()
        self._dram_used = self._disk_used = 0
        self.flushes += 1

    def audit(self) -> Dict[str, int]:
        """Structural self-check, called from the engine's
        ``audit_pages``: byte accounting matches the entries, DRAM
        entries hold payloads and no step, disk entries the reverse,
        and the DRAM bound holds whenever nothing is pinned. Raises
        MXNetError on any violation; returns ``tier_bytes()``."""
        dram = disk = 0
        pinned = False
        for key, bucket in self._entries.items():
            for ent in bucket:
                pinned = pinned or ent.pinned
                if ent.tier == "dram":
                    if ent.k_payload is None or ent.step is not None:
                        raise MXNetError(
                            f"tier audit: dram entry {key.hex()[:16]} "
                            f"missing payload or carrying a disk step")
                    dram += ent.nbytes
                elif ent.tier == "disk":
                    if ent.k_payload is not None or ent.step is None:
                        raise MXNetError(
                            f"tier audit: disk entry {key.hex()[:16]} "
                            f"holding a payload or missing its step")
                    disk += ent.nbytes
                else:
                    raise MXNetError(f"tier audit: unknown tier "
                                     f"{ent.tier!r}")
        if dram != self._dram_used or disk != self._disk_used:
            raise MXNetError(
                f"tier audit: byte accounting drift (dram {dram} vs "
                f"{self._dram_used}, disk {disk} vs {self._disk_used})")
        if not pinned and self._dram_used > self.dram_bytes:
            raise MXNetError(
                f"tier audit: dram tier over budget with nothing "
                f"pinned ({self._dram_used} > {self.dram_bytes})")
        return self.tier_bytes()


def init_kv_pools(num_layers, num_pages, num_heads, page_size, head_dim,
                  dtype="float32", quant: Optional[KVQuantSpec] = None):
    """Fresh zeroed (k_pool, v_pool) pairs, one per layer. With a
    ``quant`` spec the payload dtype is the spec's narrow dtype (the
    per-page amax metadata is the ENGINE's to own — host-resettable
    page metadata, not pool state)."""
    dt = jnp.dtype(quant.dtype) if quant is not None else jnp.dtype(dtype)
    mk = lambda: jnp.zeros((num_pages, num_heads, page_size, head_dim), dt)
    return [(mk(), mk()) for _ in range(num_layers)]


def write_token_kv(pool, new, pages, offsets):
    """Scatter one K (or V) row per entry into the pool.

    pool: (P, H, ps, D); new: (N, H, D); pages/offsets: (N,) int32 —
    entry n writes ``new[n]`` to ``pool[pages[n], :, offsets[n], :]``.
    Serves both the decode step (one token per SLOT, N = num_slots;
    inactive slots carry pages[n] == NULL_PAGE) and chunked prefill
    (one row per CHUNK TOKEN of a single slot, N = chunk length; padded
    tokens carry NULL_PAGE) — either way dead writes land in the null
    page, harmless and never read unmasked. Static shapes; safe under
    jit."""
    H = pool.shape[1]
    return pool.at[pages[:, None], jnp.arange(H)[None, :],
                   offsets[:, None], :].set(new.astype(pool.dtype))


def write_block_kv(pool, new, pages, offsets):
    """Scatter a (S, W) BLOCK of K (or V) rows into the pool — the
    speculative verify step's write: W consecutive positions per slot
    (the last accepted token plus up to W-1 draft candidates).

    pool: (P, H, ps, D); new: (S, W, H, D); pages/offsets: (S, W)
    int32 — entry (s, w) writes ``new[s, w]`` to
    ``pool[pages[s, w], :, offsets[s, w], :]``. Dead entries (inactive
    slots, positions past a slot's real draft window) carry
    ``pages == NULL_PAGE`` and land harmlessly in the null page, same
    contract as ``write_token_kv`` (which this flattens into). Static
    shapes; safe under jit."""
    S, W, H, D = new.shape
    return write_token_kv(pool, new.reshape(S * W, H, D),
                          pages.reshape(S * W), offsets.reshape(S * W))


def write_prompt_kv(pool, kv, pages):
    """Scatter a whole prompt's K (or V) into its pages (prefill).

    pool: (P, H, ps, D); kv: (Tpad, H, D) with Tpad == len(pages) * ps;
    pages: (n_pages,) int32 with dead (beyond the prompt) entries
    NULL_PAGE — those whole-page writes land in the null page. Duplicate
    null indices are fine: the store order is unspecified but the value
    is never read unmasked."""
    n_pages = pages.shape[0]
    ps = pool.shape[2]
    paged = kv.reshape(n_pages, ps, kv.shape[1], kv.shape[2]) \
        .transpose(0, 2, 1, 3)                  # (n_pages, H, ps, D)
    return pool.at[pages].set(paged.astype(pool.dtype))
