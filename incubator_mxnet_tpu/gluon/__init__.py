"""Gluon: the high-level imperative/hybrid API (re-design of
`python/mxnet/gluon/` — SURVEY.md §2.2)."""

from . import parameter
from .parameter import Parameter, ParameterDict, Constant
from . import block
from .block import Block, HybridBlock, SymbolBlock
from . import trainer
from .trainer import Trainer
from . import loss
from . import nn

from . import utils
from .utils import split_and_load

__all__ = ["Parameter", "ParameterDict", "Constant", "Block", "HybridBlock",
           "SymbolBlock", "Trainer", "loss", "nn", "split_and_load"]


def __getattr__(name):
    if name in ("data", "rnn", "model_zoo", "contrib"):
        import importlib
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
