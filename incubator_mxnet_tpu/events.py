"""Flight recorder: structured, causally-ordered lifecycle events.

This is the stdlib-only IMPLEMENTATION module, deliberately at the
package top level so the training/checkpoint/supervisor emitters can
import it without executing ``serve/__init__`` (which eagerly pulls
the whole serving stack). ``serve.events`` re-exports everything —
serving-side code and docs address the recorder by that name.

When a chaos scenario or a production incident goes wrong, aggregate
counters (``health_snapshot()`` / ``/metrics``) can say THAT something
failed but never WHY: there is no causal record of what happened to
request X, or why replica 2 browned out at step 841. This module is
that record — the serving/training tier's black box:

  - ``EventType`` / ``Event``: a compact, timestamped schema covering
    the request lifecycle (SUBMIT → ADMIT → PREFILL_CHUNK →
    DECODE_STEP → PREEMPT/REQUEUE/DISPATCH → exactly-one TERMINAL)
    plus the control-plane transitions around it (BROWNOUT levels,
    REPLICA_HEALTH, CHECKPOINT_COMMIT, TRAIN_STEP outcomes,
    SUPERVISOR_RESTART/GIVEUP, CHAOS injections). Every event carries
    a recorder-wide monotone ``seq`` — a total causal order even when
    two events share a clock reading.
  - ``FlightRecorder``: bounded per-component ring buffers (a deque
    per component, ``capacity`` events each) behind ONE emission API —
    ``emit()``. Emission is exactly-once by construction because every
    call site funnels through an existing single-writer point (the
    ``_record_terminal`` / ``StepRecorder.record`` pattern), and the
    mxlint ``terminal-outcome`` pass statically rejects direct ring
    writes outside this class. Overhead is benched under the <=2%
    leave-on bar (BENCH_SERVE.json ``recorder_overhead``, strict-
    alternation methodology per docs/PERF_NOTES.md round 10).
  - postmortems: on a structured failure (chaos invariant breach,
    ``HALTED_POISONED``, supervisor give-up, ``FAILED_REPLICA`` at the
    requeue bound) the recorder dumps a JSON naming the faulted entity
    and its trailing events — kept in ``recorder.postmortems`` and
    written to ``postmortem_dir`` when set (docs/OBSERVABILITY.md).
  - latency histograms: TTFT / TPOT / queue-delay / end-to-end
    observations are ingested FROM the event stream itself (the ADMIT
    and TERMINAL events' derived fields), so the Prometheus histograms
    ``serve/metrics.py`` renders can never disagree with the event
    timeline they summarize.

Everything here is stdlib host-side bookkeeping: no jax, no device
work, nothing enters a compiled program.
"""

from __future__ import annotations

import bisect
import enum
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["EventType", "Event", "FlightRecorder", "NULL_RECORDER",
           "resolve_recorder", "token_gaps", "terminal_fields",
           "validate_event_dict", "validate_postmortem",
           "SCHEMA_VERSION", "LATENCY_METRICS", "DEFAULT_BUCKETS"]

SCHEMA_VERSION = 1


class EventType(enum.Enum):
    """The event vocabulary (docs/OBSERVABILITY.md has the field
    catalog per type). Request lifecycle first, control plane after."""

    SUBMIT = "SUBMIT"                   # request entered admission
    ADMIT = "ADMIT"                     # request took a slot
    PREFILL_CHUNK = "PREFILL_CHUNK"     # one prefill program ran
    DECODE_STEP = "DECODE_STEP"         # one decode/verify step ran
    PREEMPT = "PREEMPT"                 # slot reclaimed by higher tier
    REQUEUE = "REQUEUE"                 # re-queued (preempt/failover)
    DISPATCH = "DISPATCH"               # router → replica assignment
    TERMINAL = "TERMINAL"               # exactly-one final outcome
    BROWNOUT = "BROWNOUT"               # degrade-level transition
    REPLICA_HEALTH = "REPLICA_HEALTH"   # SERVING/DEGRADED/DEAD move
    CHECKPOINT_COMMIT = "CHECKPOINT_COMMIT"
    TRAIN_STEP = "TRAIN_STEP"           # one StepOutcome recorded
    SUPERVISOR_RESTART = "SUPERVISOR_RESTART"
    SUPERVISOR_GIVEUP = "SUPERVISOR_GIVEUP"
    CHAOS = "CHAOS"                     # injector fired
    CACHE_DEMOTE = "CACHE_DEMOTE"       # prefix page HBM → DRAM/disk
    CACHE_PROMOTE = "CACHE_PROMOTE"     # prefix page re-admitted by copy
    CACHE_TIER_MISS = "CACHE_TIER_MISS"  # tier consulted, no usable page
    MIGRATE_OUT = "MIGRATE_OUT"         # slot captured off a replica
    MIGRATE_IN = "MIGRATE_IN"           # capsule installed on a replica
    MIGRATE_FAIL = "MIGRATE_FAIL"       # transfer failed → replay path
    SCALE_UP = "SCALE_UP"               # replica admitted to the fleet
    SCALE_DOWN = "SCALE_DOWN"           # replica drained out / retired
    UPGRADE = "UPGRADE"                 # rolling weight-swap phase
    WARMUP = "WARMUP"                   # cold replica warming / serving

    def __str__(self) -> str:
        return self.value


class Event:
    """One recorded event. ``seq`` is the recorder-wide causal order;
    ``ts`` is ``time.perf_counter()`` seconds (a span event may pass
    its start time explicitly and carry ``dur_s`` in ``data``).
    ``entity`` names the subject when it is not a request (a replica,
    a trainer, an injector); ``data`` holds only JSON-safe scalars."""

    __slots__ = ("seq", "ts", "component", "etype", "entity",
                 "request_id", "data")

    def __init__(self, seq, ts, component, etype, entity, request_id,
                 data):
        self.seq = seq
        self.ts = ts
        self.component = component
        self.etype = etype
        self.entity = entity
        self.request_id = request_id
        self.data = data

    def to_dict(self) -> dict:
        d = {"seq": self.seq, "ts": self.ts,
             "component": self.component, "etype": self.etype.value}
        if self.entity is not None:
            d["entity"] = self.entity
        if self.request_id is not None:
            d["request_id"] = self.request_id
        if self.data:
            d["data"] = self.data
        return d

    def __repr__(self) -> str:
        return (f"Event({self.seq}, {self.etype.value}, "
                f"{self.component}, rid={self.request_id}, "
                f"{self.data})")


# --------------------------------------------------------------------- #
# latency histograms (the /metrics surface — serve/metrics.py renders)
# --------------------------------------------------------------------- #

# Prometheus-style bucket upper bounds (seconds). One shared family:
# TTFT/TPOT/queue-delay/e2e span the same ms→tens-of-seconds range.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

LATENCY_METRICS = ("ttft", "tpot", "queue_delay", "e2e")


class _Hist:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_bounds: int):
        self.counts = [0] * (n_bounds + 1)   # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0


class HistogramSet:
    """Per-(metric, tier) latency histograms over one shared bucket
    family. Cells are created lazily, so the snapshot only carries
    series that actually observed something."""

    def __init__(self, bounds=DEFAULT_BUCKETS):
        self.bounds = tuple(float(b) for b in bounds)
        self._cells: Dict[tuple, _Hist] = {}

    def observe(self, metric: str, tier: str, value: float) -> None:
        cell = self._cells.get((metric, tier))
        if cell is None:
            cell = self._cells[(metric, tier)] = _Hist(len(self.bounds))
        cell.counts[bisect.bisect_left(self.bounds, value)] += 1
        cell.sum += value
        cell.count += 1

    def snapshot(self) -> dict:
        """Detached copy: {"bounds": [...], "metrics": {metric: {tier:
        {"counts": per-bucket (not cumulative, +Inf last), "sum",
        "count"}}}} — the shape ``render_metrics`` consumes."""
        metrics: dict = {}
        for (metric, tier), cell in self._cells.items():
            metrics.setdefault(metric, {})[tier] = {
                "counts": list(cell.counts),
                "sum": cell.sum,
                "count": cell.count,
            }
        return {"bounds": list(self.bounds), "metrics": metrics}


# --------------------------------------------------------------------- #
# the recorder
# --------------------------------------------------------------------- #

class FlightRecorder:
    """Bounded per-component event rings + postmortem dumps + latency
    histograms, behind the one ``emit()`` API.

    ``capacity`` bounds EACH component's ring (oldest events fall off —
    a flight recorder keeps the trailing window, not the whole flight).
    ``postmortem_dir`` (optional) makes ``postmortem()`` also write a
    JSON file; in-memory dumps are always kept in ``postmortems``
    (bounded). ``histograms=False`` skips latency ingestion (training/
    checkpoint recorders have no request latencies to observe)."""

    def __init__(self, capacity: int = 4096,
                 postmortem_dir: Optional[str] = None,
                 histograms: bool = True, max_postmortems: int = 8):
        self.capacity = int(capacity)
        self.postmortem_dir = postmortem_dir
        self._rings: Dict[str, deque] = {}
        self._seq = itertools.count(1)
        self.hist = HistogramSet() if histograms else None
        self.postmortems: deque = deque(maxlen=int(max_postmortems))
        self.dropped_postmortems = 0
        self.emitted = 0                 # lifetime emissions (rings wrap)
        # a recorder may be SHARED across threads (the checkpoint
        # writer thread emits commits onto the trainer's timeline), so
        # emission and the reads that iterate the rings serialize on
        # one lock. RLock, not Lock: the SIGTERM preemption drain runs
        # a final save — and therefore an emit — ON the main thread,
        # possibly interrupting a main-thread emit already holding the
        # lock (the CheckpointManager RLock precedent). Cost is one
        # uncontended acquire per emit, inside the <=2% bar
        # (BENCH_SERVE.json recorder_overhead re-banked with it).
        self._lock = threading.RLock()

    @property
    def enabled(self) -> bool:
        return True

    # -- emission ------------------------------------------------------ #
    def emit(self, component: str, etype: EventType,
             entity: Optional[str] = None,
             request_id: Optional[int] = None,
             ts: Optional[float] = None, **data) -> Event:
        """Record one event. THE single write path into the rings (the
        mxlint ``terminal-outcome`` pass rejects direct ``_rings``
        access outside this class). Latency ingestion rides specific
        event fields so histograms and the timeline can never
        disagree:

          ADMIT     ``queue_delay_s`` → the queue-delay histogram
          TERMINAL  ``ttft_s`` / ``e2e_s`` → their histograms, and
                    ``tpot_gaps`` (a list — observed then REPLACED by
                    its count ``tpot_n`` so the stored event stays
                    compact) → the TPOT histogram
        """
        if ts is None:
            ts = time.perf_counter()
        with self._lock:
            return self._emit_locked(component, etype, entity,
                                     request_id, ts, data)

    def _emit_locked(self, component, etype, entity, request_id, ts,
                     data) -> Event:
        if self.hist is not None:
            tier = data.get("tier", "")
            if etype is EventType.TERMINAL:
                gaps = data.pop("tpot_gaps", None)
                if gaps:
                    for g in gaps:
                        self.hist.observe("tpot", tier, g)
                    data["tpot_n"] = len(gaps)
                if data.get("ttft_s") is not None:
                    self.hist.observe("ttft", tier, data["ttft_s"])
                if data.get("e2e_s") is not None:
                    self.hist.observe("e2e", tier, data["e2e_s"])
            elif etype in (EventType.ADMIT, EventType.DISPATCH) and \
                    data.get("queue_delay_s") is not None:
                # ADMIT = engine slot admission; DISPATCH = the
                # router's client-level admission analog — each
                # observes once per (re)admission/(re)dispatch
                self.hist.observe("queue_delay", tier,
                                  data["queue_delay_s"])
        seq = next(self._seq)
        ev = Event(seq, ts, component, etype, entity, request_id, data)
        ring = self._rings.get(component)
        if ring is None:
            ring = self._rings[component] = deque(maxlen=self.capacity)
        ring.append(ev)
        self.emitted = seq               # == emission count (seq draws
        return ev                        # happen under the lock)

    # -- reads --------------------------------------------------------- #
    def components(self) -> List[str]:
        return sorted(self._rings)

    def events(self, component: Optional[str] = None,
               etype: Optional[EventType] = None) -> List[Event]:
        """Detached, seq-ordered view (one component, or all merged).
        Taken under the recorder lock — a concurrent emit can neither
        tear the iteration nor interleave a ring out of seq order."""
        with self._lock:
            if component is not None:
                evs = sorted(self._rings.get(component, ()),
                             key=lambda e: e.seq)
            else:
                evs = [e for ring in self._rings.values()
                       for e in ring]
                evs.sort(key=lambda e: e.seq)
        if etype is not None:
            evs = [e for e in evs if e.etype is etype]
        return evs

    def hist_snapshot(self) -> Optional[dict]:
        if self.hist is None:
            return None
        with self._lock:
            return self.hist.snapshot()

    def dump_events(self, path: str) -> str:
        """Write the merged event timeline as JSON — the input format
        ``tools/trace_export.py`` converts to a Perfetto trace."""
        payload = {"schema_version": SCHEMA_VERSION,
                   "events": [e.to_dict() for e in self.events()]}
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
        return path

    # -- postmortems --------------------------------------------------- #
    def postmortem(self, reason: str, entity: str,
                   context: Optional[dict] = None,
                   tail: int = 256) -> dict:
        """Dump the trailing timeline around a structured failure —
        the dict every consumer validates with ``validate_postmortem``.
        Always kept in-memory (bounded: the OLDEST dumps survive — the
        first failure is the root cause, later ones are usually its
        echo); written to ``postmortem_dir`` when configured."""
        evs = self.events()[-int(tail):]
        pm = {"schema_version": SCHEMA_VERSION,
              "reason": str(reason),
              "entity": str(entity),
              "ts": time.perf_counter(),
              "context": dict(context or {}),
              "events": [e.to_dict() for e in evs]}
        with self._lock:                 # RLock: events() above nests
            if len(self.postmortems) == self.postmortems.maxlen:
                self.dropped_postmortems += 1
            else:
                self.postmortems.append(pm)
        if self.postmortem_dir:
            os.makedirs(self.postmortem_dir, exist_ok=True)
            safe = "".join(c if c.isalnum() or c in "-_" else "_"
                           for c in entity)[:64]
            path = os.path.join(
                self.postmortem_dir,
                f"postmortem_{safe}_{self.emitted}.json")
            with open(path, "w") as f:
                json.dump(pm, f, indent=1)
                f.write("\n")
            pm["path"] = path
        return pm


class _NullFlightRecorder:
    """The disabled recorder: every API is a no-op with the same
    shape, so call sites stay branch-free (``recorder=False``)."""

    hist = None
    postmortems: deque = deque()
    capacity = 0
    emitted = 0
    dropped_postmortems = 0

    @property
    def enabled(self) -> bool:
        return False

    def emit(self, component, etype, entity=None, request_id=None,
             ts=None, **data):
        return None

    def components(self):
        return []

    def events(self, component=None, etype=None):
        return []

    def hist_snapshot(self):
        return None

    def dump_events(self, path):
        raise ValueError("flight recorder is disabled (recorder=False)")

    def postmortem(self, reason, entity, context=None, tail=256):
        return None


NULL_RECORDER = _NullFlightRecorder()


def resolve_recorder(recorder, **defaults):
    """The one constructor-knob convention: ``None`` → a fresh
    ``FlightRecorder`` (the leave-on default), ``False`` → the shared
    no-op recorder, an existing recorder → itself."""
    if recorder is None:
        return FlightRecorder(**defaults)
    if recorder is False:
        return NULL_RECORDER
    return recorder


# --------------------------------------------------------------------- #
# derivations shared by every emitter (and tools/serve_bench.py)
# --------------------------------------------------------------------- #

def token_gaps(stamps) -> List[float]:
    """Inter-token gaps from a request's absolute per-token stamps
    (``Request.token_stamps`` — universal since round 9): the latency a
    USER sees between consecutive tokens, including stalls caused by
    other slots' prefills. The ONE implementation behind the TPOT
    histograms and the bench's inter-token percentiles."""
    return [b - a for a, b in zip(stamps, stamps[1:])]


def terminal_fields(request) -> dict:
    """The TERMINAL event's derived latency fields for one finished
    request — computed in ONE place so the engine's and router's
    ``_record_terminal`` (and therefore the histograms) can never
    drift: end-to-end latency, time-to-first-token, and the TPOT gap
    list (ingested by the recorder, stored as a count)."""
    data = {"outcome": request.outcome.value,
            "tier": request.tier.value,
            "tokens": len(request.token_ids)}
    if request.detail:
        data["detail"] = request.detail[:200]
    if request.retry_after_s is not None:
        data["retry_after_s"] = request.retry_after_s
    st = request.token_stamps
    if request.submit_time is not None and \
            request.finish_time is not None:
        data["e2e_s"] = request.finish_time - request.submit_time
        if st:
            data["ttft_s"] = st[0] - request.submit_time
    gaps = token_gaps(st)
    if gaps:
        data["tpot_gaps"] = gaps
    return data


# --------------------------------------------------------------------- #
# schema validation (tests + the obssmoke CI gate)
# --------------------------------------------------------------------- #

_EVENT_TYPES = {e.value for e in EventType}


def validate_event_dict(d: dict) -> None:
    """Raise ValueError unless ``d`` is a well-formed serialized event
    (the ``Event.to_dict`` shape, JSON-safe)."""
    if not isinstance(d, dict):
        raise ValueError(f"event must be a dict, got {type(d)}")
    for key, typ in (("seq", int), ("ts", (int, float)),
                     ("component", str), ("etype", str)):
        if key not in d:
            raise ValueError(f"event missing required field {key!r}: "
                             f"{d}")
        if not isinstance(d[key], typ):
            raise ValueError(f"event field {key!r} has wrong type: "
                             f"{d[key]!r}")
    if d["etype"] not in _EVENT_TYPES:
        raise ValueError(f"unknown event type {d['etype']!r}")
    if "data" in d:
        try:
            json.dumps(d["data"])
        except (TypeError, ValueError) as e:
            raise ValueError(f"event data is not JSON-safe: {e}")


def validate_postmortem(pm: dict) -> None:
    """Raise ValueError unless ``pm`` is a well-formed postmortem dump:
    reason + entity + a causally-ordered (seq strictly increasing)
    event timeline of valid events."""
    if not isinstance(pm, dict):
        raise ValueError(f"postmortem must be a dict, got {type(pm)}")
    for key in ("schema_version", "reason", "entity", "events"):
        if key not in pm:
            raise ValueError(f"postmortem missing field {key!r}")
    if pm["schema_version"] != SCHEMA_VERSION:
        raise ValueError(f"postmortem schema_version "
                         f"{pm['schema_version']} != {SCHEMA_VERSION}")
    if not isinstance(pm["events"], list):
        raise ValueError("postmortem events must be a list")
    prev = 0
    for ev in pm["events"]:
        validate_event_dict(ev)
        if ev["seq"] <= prev:
            raise ValueError(
                f"postmortem events out of causal order: seq "
                f"{ev['seq']} after {prev}")
        prev = ev["seq"]
