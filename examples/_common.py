"""Shared example bootstrap: use the configured accelerator, fall back
to CPU when its backend (e.g. a TPU tunnel) cannot initialize —
imported for its side effect before the framework import."""

import jax

try:
    jax.devices()
except RuntimeError:
    jax.config.update("jax_platforms", "cpu")
