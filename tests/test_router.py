"""Fleet router tests (serve/router.py).

The load-bearing claims: (1) replica death is a STRUCTURED re-queue —
zero lost requests, zero double-finishes, the replayed request's
already-emitted tokens preserved and its final stream bit-identical to
a fault-free run (resume-from-suffix under position-keyed sampling);
(2) re-queues are bounded: ``max_requeues`` exhaustion terminates
``FAILED_REPLICA`` with partial tokens kept and a retry hint; (3)
cache-affinity routing sends a request where its prefix lives and
spills least-loaded otherwise — via the READ-ONLY ``prefix_probe``
(no refcount, no LRU tick); (4) the heartbeat/circuit-breaker loop
(SERVING → DEGRADED → half-open probes → SERVING) is deterministic
under the router's seed and never loses a request; (5) every
shed/deadline-class outcome carries a ``retry_after_s`` hint at both
engine and router level (``health_snapshot`` is the consistent
scheduling/scrape read).

The kill MATRIX: {prefill, mid-decode, mid-speculative-verify} ×
occupancy {1, half, full}. Every cell builds + compiles two fleets
(~10-20s each), so the whole matrix rides in ``slow`` (ci stage_unit
runs it; the fleetsmoke CI stage ALSO kills replicas end-to-end on
every run) — tier-1 keeps the host-only routing/breaker/hint units
plus the cheap single-fleet serving regressions, inside the 870s
wall-clock budget on the slow boxes PR 4 documented."""

import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.models import gpt as g
from incubator_mxnet_tpu.serve import (InferenceEngine, Outcome, Request,
                                       ReplicaState, Router, build_fleet)
from incubator_mxnet_tpu.serve.chaos import (KillReplica, SlowReplica,
                                             assert_fleet_health_consistent,
                                             run_fleet_chaos)

VOCAB = 64


@pytest.fixture(scope="module")
def model():
    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=VOCAB, max_length=64)
    m.initialize()
    return m


ENG_KW = dict(num_slots=2, page_size=8, max_len=64, chunk_pages=1,
              prefix_cache=True)


def _fleet(model, spec_k=0, n=2, **router_kw):
    kw = dict(ENG_KW, spec_k=spec_k)
    router_kw.setdefault("seed", 3)
    return build_fleet(model, n, engine_kw=kw, **router_kw)


def _templated(rng, i, length=18):
    unit = rng.randint(0, VOCAB, size=(4 + i % 3,)).astype(np.int32)
    return np.tile(unit, 1 + (length - 1) // unit.size)[:length]


def _workload(kind, n, seed=42):
    """Greedy (parity-assertable) requests. ``mixed`` = persona-shared
    + unique ragged prompts; ``templated`` = repetitive prompts the
    n-gram drafter predicts (so speculative engines actually run
    verify steps — the mid-verify kill needs one)."""
    rng = np.random.RandomState(seed)
    persona = rng.randint(0, VOCAB, size=(14,)).astype(np.int32)
    reqs = []
    for i in range(n):
        if kind == "templated":
            prompt = _templated(rng, i)
        elif i % 2 == 0:
            prompt = np.concatenate(
                [persona, rng.randint(0, VOCAB,
                                      size=(3 + i % 4,)).astype(np.int32)])
        else:
            prompt = rng.randint(0, VOCAB,
                                 size=(5 + 3 * (i % 3),)).astype(np.int32)
        reqs.append(Request(prompt, max_new_tokens=8 + 2 * (i % 3)))
    return reqs


_BASELINES = {}


def _baseline(model, kind, n):
    """Fault-free fleet run of the same workload/config — the parity
    oracle. Cached per (kind, n): the tokens are deterministic."""
    key = (kind, n)
    if key not in _BASELINES:
        rt = _fleet(model)
        reqs = _workload(kind, n)
        run_fleet_chaos(rt, reqs, [])
        assert all(r.outcome is not None and r.outcome.ok for r in reqs)
        _BASELINES[key] = [list(r.token_ids) for r in reqs]
    return _BASELINES[key]


# --------------------------------------------------------------------- #
# the replica-kill matrix
# --------------------------------------------------------------------- #

_OCC = {"one": 1, "half": 2, "full": 6}


def _run_kill(model, phase, occupancy):
    kind = "templated" if phase == "verify" else "mixed"
    n = _OCC[occupancy]
    base = _baseline(model, kind, n)
    spec_k = 2 if phase == "verify" else 0
    rt = _fleet(model, spec_k=spec_k)
    reqs = _workload(kind, n)
    at = 1 if phase == "prefill" else 3
    inj = KillReplica(replica=0, at_step=at, phase=phase)
    run_fleet_chaos(rt, reqs, [inj])     # audits survivors every step

    assert inj.fired, "kill never fired — scenario exercised nothing"
    assert rt.replica_deaths == 1
    assert rt.replicas[0].state is ReplicaState.DEAD
    # zero lost requests, exactly one terminal each (double-finish
    # would have raised inside the router), tally consistent
    assert all(r.outcome is not None for r in reqs)
    assert_fleet_health_consistent(rt, reqs)
    # the default requeue budget absorbs a single death: every request
    # completes, bit-identical to the fault-free fleet run
    assert all(r.outcome.ok for r in reqs), \
        [(r.outcome, r.detail) for r in reqs if not r.outcome.ok]
    for r, b in zip(reqs, base):
        assert list(r.token_ids) == b
    # emitted-token-prefix preservation across the re-queue
    for client, pre in inj.inflight_at_kill:
        assert list(client.token_ids[:len(pre)]) == pre
    if phase in ("decode", "verify"):
        assert inj.inflight_at_kill, "nothing was mid-stream at kill"
        assert rt.requeues >= 1
    # the surviving replica held the compile contract through failover
    eng = rt.replicas[1].engine
    assert eng.decode_trace_count <= 1
    assert eng.verify_trace_count <= 1
    assert all(v == 1 for v in eng.prefill_trace_counts.values())
    eng.audit_pages()


@pytest.mark.slow   # ~20s: two fleets built + compiled; fleetsmoke
@pytest.mark.parametrize("phase,occupancy", [  # (ci, every run) kills
    ("decode", "half"),                        # replicas end-to-end too
])
def test_kill_matrix(model, phase, occupancy):
    _run_kill(model, phase, occupancy)


# each cell builds + compiles two fleets (~10s): one representative
# cell rides tier-1, the rest of the 3x3 matrix is `slow` (ci
# stage_unit runs it; fleetsmoke covers the prefill/verify phases too)
@pytest.mark.slow
@pytest.mark.parametrize("phase,occupancy", [
    ("decode", "one"), ("decode", "full"),
    ("prefill", "one"), ("prefill", "half"), ("prefill", "full"),
    ("verify", "one"), ("verify", "half"), ("verify", "full"),
])
def test_kill_matrix_slow(model, phase, occupancy):
    _run_kill(model, phase, occupancy)


@pytest.mark.slow   # serving fleet (~10s); requeue_exhaustion in
def test_max_requeues_exhaustion_is_failed_replica(model):  # fleetsmoke
    # covers the same bound every CI run
    """max_requeues=0: the first death immediately terminates its
    in-flight requests FAILED_REPLICA — partial tokens kept (a prefix
    of the fault-free stream), retry hint attached, nothing lost."""
    n = 2
    base = _baseline(model, "mixed", n)
    rt = _fleet(model, max_requeues=0)
    reqs = _workload("mixed", n)
    inj = KillReplica(replica=0, at_step=3, phase="decode")
    run_fleet_chaos(rt, reqs, [inj])
    assert inj.fired and inj.inflight_at_kill
    assert_fleet_health_consistent(rt, reqs)
    hit = {id(c) for c, _ in inj.inflight_at_kill}
    for r, b in zip(reqs, base):
        assert r.outcome is not None
        if id(r) in hit:
            assert r.outcome == Outcome.FAILED_REPLICA
            assert r.retry_after_s is not None and r.retry_after_s > 0
            assert list(r.token_ids) == b[:len(r.token_ids)]
        else:
            assert r.outcome.ok and list(r.token_ids) == b


# --------------------------------------------------------------------- #
# routing: probe, affinity, spill
# --------------------------------------------------------------------- #

def test_prefix_probe_is_read_only(model):
    """probe reports the cached prefix WITHOUT moving refcounts or the
    LRU clock — the router may probe every replica per admission
    without perturbing any replica's eviction order."""
    eng = InferenceEngine(model, **ENG_KW)
    rng = np.random.RandomState(7)
    persona = rng.randint(0, VOCAB, size=(17,)).astype(np.int32)
    req = Request(persona, max_new_tokens=4)
    eng.run([req])
    assert req.outcome is not None and req.outcome.ok
    probe_prompt = np.concatenate(
        [persona, rng.randint(0, VOCAB, size=(5,)).astype(np.int32)])
    rc0 = list(eng._alloc._rc)
    clock0 = eng._prefix._clock
    hits0, lookups0 = eng.prefix_hits, eng.prefix_lookups
    got = eng.prefix_probe(probe_prompt)
    assert got == 16        # two full pages cached (17 rounds down)
    assert list(eng._alloc._rc) == rc0, "probe moved a refcount"
    assert eng._prefix._clock == clock0, "probe ticked the LRU clock"
    assert (eng.prefix_hits, eng.prefix_lookups) == (hits0, lookups0)
    miss = rng.randint(0, VOCAB, size=(9,)).astype(np.int32)
    # a vocabulary-disjoint prompt can still share a 1-token run with
    # the cached page; assert only full-page misses return < page_size
    assert eng.prefix_probe(miss) < eng.page_size
    cold = InferenceEngine(model, **dict(ENG_KW, prefix_cache=False))
    assert cold.prefix_probe(persona) == 0
    eng.audit_pages()


def test_affinity_routes_to_warm_replica(model):
    """The replica whose PrefixIndex matches the longest prefix wins
    admission; nobody-warm spills least-loaded. Pure host-side routing
    — asserted via dispatch bookkeeping, no decode step runs."""
    rt = _fleet(model)
    rng = np.random.RandomState(11)
    persona = rng.randint(0, VOCAB, size=(17,)).astype(np.int32)
    # warm replica 1's cache directly (engine-level request — the
    # router only tallies ITS OWN clients)
    warm = Request(persona.copy(), max_new_tokens=4)
    rt.replicas[1].engine.run([warm])
    assert rt.replicas[1].engine.prefix_probe(persona) > 0
    tail = rng.randint(0, VOCAB, size=(6,)).astype(np.int32)
    assert rt.submit(Request(np.concatenate([persona, tail]),
                             max_new_tokens=4))
    rt._dispatch()
    assert len(rt._inflight) == 1
    assert rt._inflight[0].replica == 1
    assert rt.affinity_routed == 1 and rt.spill_routed == 0


def test_spill_balances_backlog(model):
    """With no prefix anywhere, dispatch spreads by backlog instead of
    piling onto one replica."""
    rt = _fleet(model)
    rng = np.random.RandomState(13)
    for _ in range(4):
        assert rt.submit(Request(rng.randint(0, VOCAB, size=(6,))
                                 .astype(np.int32), max_new_tokens=4))
    rt._dispatch()
    assert len(rt._inflight) == 4
    per = [sum(1 for t in rt._inflight if t.replica == i)
           for i in range(2)]
    assert per == [2, 2]
    assert rt.spill_routed == 4 and rt.affinity_routed == 0


def test_round_robin_mode(model):
    rt = _fleet(model, affinity=False)
    rng = np.random.RandomState(17)
    for _ in range(4):
        assert rt.submit(Request(rng.randint(0, VOCAB, size=(6,))
                                 .astype(np.int32), max_new_tokens=4))
    rt._dispatch()
    assert [t.replica for t in rt._inflight] == [0, 1, 0, 1]


# --------------------------------------------------------------------- #
# backpressure: one retry_after_s contract at both levels
# --------------------------------------------------------------------- #

def test_engine_retryable_outcomes_carry_hints(model):
    """EVERY shed/deadline-class terminal the engine records carries
    retry_after_s — depth shed, shutdown shed, and deadline expiry
    (the PR 5 gap: hints used to ride only on queue-level SHED)."""
    eng = InferenceEngine(model, **ENG_KW, max_queue=0)
    rng = np.random.RandomState(19)
    shed = Request(rng.randint(0, VOCAB, size=(6,)).astype(np.int32),
                   max_new_tokens=4)
    assert not eng.submit(shed)
    assert shed.outcome == Outcome.SHED
    assert shed.retry_after_s is not None and shed.retry_after_s > 0

    eng2 = InferenceEngine(model, **ENG_KW)
    drain = Request(rng.randint(0, VOCAB, size=(6,)).astype(np.int32),
                    max_new_tokens=4)
    assert eng2.submit(drain)
    eng2.shutdown("drain test")
    assert drain.outcome == Outcome.SHED
    assert drain.retry_after_s is not None and drain.retry_after_s > 0

    eng3 = InferenceEngine(model, **ENG_KW)
    late = Request(rng.randint(0, VOCAB, size=(6,)).astype(np.int32),
                   max_new_tokens=4, deadline_s=1e-4)
    assert eng3.submit(late)
    time.sleep(2e-3)
    eng3._expire_queue()
    assert late.outcome == Outcome.DEADLINE_EXPIRED
    assert late.retry_after_s is not None and late.retry_after_s > 0


def test_hints_round_trip_through_router(model):
    """A non-success outcome minted ANYWHERE — router admission,
    router give-up, or inside a replica engine — reaches the client
    with its hint intact (one machine-readable backoff contract)."""
    rng = np.random.RandomState(23)

    # router-level SHED (queue bound)
    rt = _fleet(model, max_queue=0)
    r1 = Request(rng.randint(0, VOCAB, size=(6,)).astype(np.int32),
                 max_new_tokens=4)
    assert not rt.submit(r1)
    assert r1.outcome == Outcome.SHED
    assert r1.retry_after_s is not None and r1.retry_after_s > 0

    # router-level FAILED_REPLICA (no live replica at admission)
    rt2 = _fleet(model)
    for rep in rt2.replicas:
        rep.kill("unit kill")
    rt2.step()                              # deaths observed, no work
    assert all(rep.state is ReplicaState.DEAD for rep in rt2.replicas)
    r2 = Request(rng.randint(0, VOCAB, size=(6,)).astype(np.int32),
                 max_new_tokens=4)
    assert not rt2.submit(r2)
    assert r2.outcome == Outcome.FAILED_REPLICA
    assert r2.retry_after_s is not None and r2.retry_after_s > 0

    # engine-level DEADLINE_EXPIRED propagated through the router:
    # queued behind a full fleet, the deadline passes in the ROUTER
    # queue (same outcome class either way — hint must survive)
    rt3 = _fleet(model, replica_queue_depth=0)
    reqs = _workload("mixed", 2, seed=29)
    late = Request(rng.randint(0, VOCAB, size=(6,)).astype(np.int32),
                   max_new_tokens=4, deadline_s=0.04)
    run_fleet_chaos(rt3, reqs + [late], [])
    assert late.outcome == Outcome.DEADLINE_EXPIRED
    assert late.retry_after_s is not None and late.retry_after_s > 0
    assert_fleet_health_consistent(rt3, reqs + [late])


def test_fleet_snapshot_consistent_and_detached(model):
    rt = _fleet(model)
    eng = rt.replicas[0].engine
    snap = eng.health_snapshot()
    for key in ("outcomes", "queue_depth", "free_slots",
                "ewma_service_s", "estimated_queue_delay_s"):
        assert key in snap
    snap["outcomes"]["EOS"] = 999
    assert eng.health["EOS"] == 0, "snapshot aliases the live dict"
    fsnap = rt.health_snapshot()
    assert [e["state"] for e in fsnap["replicas"]] == ["SERVING"] * 2
    fsnap["outcomes"]["SHED"] = 999
    assert rt.health["SHED"] == 0


# --------------------------------------------------------------------- #
# breaker: heartbeat -> DEGRADED -> half-open probes -> SERVING
# --------------------------------------------------------------------- #

def test_breaker_opens_probes_and_recovers(model):
    """Deterministic breaker unit loop on an EMPTY fleet (idle engine
    steps are host-only — no compiles): consecutive slow steps open
    the breaker, probe failures grow the backoff exponentially (with
    seeded jitter), healthy probes close it."""
    rt = _fleet(model, heartbeat_timeout_s=0.005, breaker_failures=2,
                probe_backoff_s=0.01, probe_backoff_max_s=0.08,
                probe_recovery=2)
    rep = rt.replicas[0]
    rep.delay_s = 0.02                   # slower than the heartbeat
    rt.step()
    assert rep.state is ReplicaState.SERVING
    rt.step()
    assert rep.state is ReplicaState.DEGRADED
    assert rt.breaker_opens == 1
    b0 = rep.backoff_s
    # failed probe: backoff doubles (jitter only stretches the WAIT)
    time.sleep(rep.next_probe_t - time.perf_counter() + 1e-3)
    rt.step()
    assert rep.state is ReplicaState.DEGRADED
    assert rep.backoff_s == pytest.approx(2 * b0)
    # recovery: two healthy probes close the breaker
    rep.delay_s = 0.0
    for _ in range(2):
        time.sleep(max(0.0, rep.next_probe_t - time.perf_counter())
                   + 1e-3)
        rt.step()
    assert rep.state is ReplicaState.SERVING
    assert rt.recoveries == 1
    assert rt.probes >= 3


def test_degraded_replica_gets_no_new_admissions(model):
    rt = _fleet(model, heartbeat_timeout_s=0.005, breaker_failures=1)
    rep = rt.replicas[0]
    rep.delay_s = 0.02
    rt.step()
    assert rep.state is ReplicaState.DEGRADED
    rng = np.random.RandomState(31)
    for _ in range(3):
        assert rt.submit(Request(rng.randint(0, VOCAB, size=(6,))
                                 .astype(np.int32), max_new_tokens=4))
    rt._dispatch()
    assert all(t.replica == 1 for t in rt._inflight)


@pytest.mark.slow   # ~15s serving run; fleetsmoke covers the same loop
def test_slow_replica_loses_nothing(model):
    """End-to-end: a replica slowed past the heartbeat degrades and
    recovers; every request still completes bit-identical (slowness
    must never corrupt, lose, or re-route into divergence)."""
    n = 4
    base = _baseline(model, "mixed", n)
    rt = _fleet(model, heartbeat_timeout_s=0.05, breaker_failures=2,
                probe_backoff_s=0.02, probe_recovery=1)
    reqs = _workload("mixed", n)
    inj = SlowReplica(replica=0, start=3, end=12, sleep_s=0.1)
    run_fleet_chaos(rt, reqs, [inj],
                    arrival_times=[0.01 * i for i in range(n)])
    assert inj.fired
    assert rt.replica_deaths == 0
    assert rt.replicas[0].breaker_opens >= 1
    assert_fleet_health_consistent(rt, reqs)
    assert all(r.outcome is not None and r.outcome.ok for r in reqs)
    for r, b in zip(reqs, base):
        assert list(r.token_ids) == b


@pytest.mark.slow   # serving fleet (~10s); ci stage_unit runs it
def test_engine_shed_is_backpressure_not_replica_failure(model):
    """Engines whose OWN admission bound is tighter than the router's
    capacity view shed at submit. That is backpressure: the request
    must wait for capacity (bounded by the stall give-up), NOT burn
    the requeue budget in an instant-retry loop and terminate a
    healthy fleet's overload as FAILED_REPLICA."""
    rt = build_fleet(model, 2,
                     engine_kw=dict(ENG_KW, num_slots=1, max_queue=1),
                     replica_queue_depth=4, seed=3)
    reqs = _workload("mixed", 6, seed=37)
    run_fleet_chaos(rt, reqs, [])
    assert_fleet_health_consistent(rt, reqs)
    assert all(r.outcome is not None and r.outcome.ok for r in reqs), \
        [(r.outcome, r.detail) for r in reqs if not r.outcome.ok]
    assert rt.requeues == 0
    assert rt.replica_deaths == 0


def test_router_withdraws_engine_queue_starved_attempt(model):
    """An attempt parked in a replica's OWN admission queue that the
    engine can never admit (pool held) must not wedge run() forever:
    the router's stall give-up withdraws it (bounded), the fleet twin
    of the engine's starved-queue-head path."""
    rt = build_fleet(model, 1, engine_kw=dict(ENG_KW), stall_steps=10,
                     seed=3)
    eng = rt.replicas[0].engine
    held = eng._alloc.hold(eng._alloc.free_count)   # total starvation
    req = Request(np.arange(6, dtype=np.int32), max_new_tokens=4)
    rt.run([req], poll_sleep=1e-4)
    assert req.outcome == Outcome.FAILED_UNSERVABLE
    assert "starved" in req.detail
    assert_fleet_health_consistent(rt, [req])
    eng._alloc.release_held(held)
    eng.audit_pages()


def test_heterogeneous_fleet_routes_by_servability(model):
    """A request only the bigger replica can hold must never be
    spilled onto a smaller one (whose engine would fail it
    FAILED_UNSERVABLE terminally while a sibling could serve it)."""
    small = InferenceEngine(model, num_slots=2, page_size=8,
                            max_len=32)
    big = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    rt = Router([small, big], seed=3)
    req = Request(np.arange(20, dtype=np.int32), max_new_tokens=20)
    assert rt.submit(req)               # 40 positions: big only
    rt._dispatch()
    assert len(rt._inflight) == 1
    assert rt._inflight[0].replica == 1


def test_torn_death_after_final_token_completes_not_crashes(model):
    """A replica dying AFTER emitting a request's final token but
    BEFORE recording the terminal (torn-engine death) leaves the
    harvested client already satisfied: the router must mint the
    success terminal instead of building a max_new_tokens=0 replay
    (whose validation error would escape run())."""
    from incubator_mxnet_tpu.serve.router import _Tracked
    rt = _fleet(model)
    full = Request(np.arange(5, dtype=np.int32), max_new_tokens=3)
    full.token_ids = [7, 8, 9]           # budget already satisfied
    att = rt._make_attempt(_Tracked(client=full))
    assert att is None
    assert full.outcome == Outcome.MAX_TOKENS

    eos = Request(np.arange(5, dtype=np.int32), max_new_tokens=8,
                  eos_id=2)
    eos.token_ids = [7, 2]               # stop token in the stream
    eos.token_times = [0.1, 0.1]
    eos.token_stamps = [1.0, 2.0]
    att = rt._make_attempt(_Tracked(client=eos))
    assert att is None
    assert eos.outcome == Outcome.EOS
    assert eos.token_ids == [7, 2]

    # the REQUEUE-BOUND path must re-mint too: a complete stream dying
    # at max_requeues would otherwise report retryable FAILED_REPLICA
    # for work the client already has
    rt0 = _fleet(model, max_requeues=0)
    done = Request(np.arange(5, dtype=np.int32), max_new_tokens=2)
    done.token_ids = [4, 5]
    rt0._requeue(_Tracked(client=done), "replica died")
    assert done.outcome == Outcome.MAX_TOKENS


def test_engine_withdraw_is_identity_based(model):
    """withdraw must find its target behind a same-shape neighbour:
    Request's generated __eq__ compares ndarray fields, so a
    value-based deque.remove would raise mid-scan (and a swallowed
    ValueError would silently misreport 'not in queue', turning the
    router's bounded starvation give-up into an indefinite wait)."""
    eng = InferenceEngine(model, **ENG_KW)
    r1 = Request(np.arange(6, dtype=np.int32), max_new_tokens=4)
    r2 = Request(np.arange(6, dtype=np.int32), max_new_tokens=4)
    assert eng.submit(r1) and eng.submit(r2)
    assert eng.withdraw(r2)          # parked behind same-shape r1
    assert not eng.withdraw(r2)      # already gone
    assert len(eng._queue) == 1 and eng._queue[0] is r1


def test_dispatch_pass_respects_capacity_allowance(model):
    """One dispatch pass must not park an unbounded burst on a single
    warm replica: each dispatch consumes a free-slot allowance or a
    queue place in the pass's capacity view, so affinity is capped at
    free_slots + replica_queue_depth per pass and the rest spill."""
    rt = _fleet(model, replica_queue_depth=1)
    rng = np.random.RandomState(41)
    persona = rng.randint(0, VOCAB, size=(17,)).astype(np.int32)
    rt.replicas[0].engine.run([Request(persona.copy(),
                                       max_new_tokens=4)])
    reqs = [Request(np.concatenate(
        [persona, rng.randint(0, VOCAB, size=(4,)).astype(np.int32)]),
        max_new_tokens=4) for _ in range(8)]
    for r in reqs:
        assert rt.submit(r)
    rt._dispatch()
    per0 = sum(1 for t in rt._inflight if t.replica == 0)
    assert per0 <= 3         # 2 free slots + queue depth 1
    assert len(rt._inflight) == 6    # 3 more spilled to replica 1
    assert len(rt._queue) == 2       # the rest wait for capacity


# --------------------------------------------------------------------- #
# structural guards
# --------------------------------------------------------------------- #

def test_router_refuses_double_finish(model):
    rt = _fleet(model)
    req = Request(np.arange(5, dtype=np.int32), max_new_tokens=2)
    rt._record_terminal(req, Outcome.SHED, "once")
    with pytest.raises(MXNetError, match="double-finish"):
        rt._record_terminal(req, Outcome.SHED, "twice")


def test_unservable_fails_fast_at_router(model):
    rt = _fleet(model)
    big = Request(np.zeros((40,), np.int32), max_new_tokens=60)
    assert not rt.submit(big)           # 100 positions > max_len 64
    assert big.outcome == Outcome.FAILED_UNSERVABLE


def test_empty_fleet_refused():
    with pytest.raises(MXNetError, match="at least one replica"):
        Router([])


def test_large_seed_constructs(model):
    # the jitter stream's golden-ratio offset must wrap into numpy's
    # u32 seed domain (a Unix-timestamp seed used to crash __init__)
    _fleet(model, seed=1_700_000_000)
