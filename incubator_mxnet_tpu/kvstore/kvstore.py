"""KVStore: the push/pull parameter-sync surface.

Re-design of `include/mxnet/kvstore.h` + `src/kvstore/kvstore_local.h` /
`comm.h` / `kvstore_nccl.h` / `kvstore_dist.h` (file-level citations —
SURVEY.md caveat, §5.8).

Mapping (SURVEY.md §2.3):
  - ``local`` / ``device`` / ``nccl`` → in-process reduction across the
    NDArrays handed to push (the reference reduced across GPUs; here the
    arrays may live on different TPU chips of one host and XLA moves data
    over ICI). The eager path is correctness-oriented; the *fast* path for
    data parallelism is one fused SPMD train step (parallel/train_step.py),
    where push/pull becomes a ``psum`` INSIDE the compiled program.
  - ``dist_sync`` / ``dist_async`` / ``dist_sync_device`` → multi-host SPMD:
    rank/num_workers come from jax.distributed; per-step reduction uses
    ``parallel.collectives.host_allreduce`` over DCN. There are no
    scheduler/server processes (SURVEY.md §3.4 TPU translation) — the
    server-side-optimizer mode is subsumed by running the optimizer SPMD.

Server-side optimizer (``set_optimizer``) and gradient compression are
retained as API: the optimizer runs locally post-reduction (mathematically
identical to the reference's sync server mode).
"""

from __future__ import annotations

import pickle
from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray import NDArray
from . import base as _base

__all__ = ["KVStore", "create"]


def _is_list(v) -> bool:
    return isinstance(v, (list, tuple))


class KVStore(_base.KVStoreBase):
    """Key-value store for parameter synchronization (parity:
    `mx.kv.create`)."""

    def __init__(self, kv_type: str = "local"):
        self._type = kv_type
        self._data: Dict = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._residuals: Dict = {}  # per-key 2-bit error-feedback state
        self._distributed = kv_type.startswith("dist")
        if self._distributed:
            # multi-host SPMD: process index/count from the JAX runtime
            self._rank = jax.process_index()
            self._num_workers = jax.process_count()
        else:
            self._rank = 0
            self._num_workers = 1

    # -- properties ----------------------------------------------------- #
    @property
    def type(self) -> str:
        return self._type

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def num_workers(self) -> int:
        return self._num_workers

    # -- init / push / pull --------------------------------------------- #
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            v0 = v[0] if _is_list(v) else v
            self._data[k] = v0.copy()
            # a (re-)initialized key starts a fresh compression stream:
            # stale error-feedback residual must not bias the first push
            self._residuals.pop(k, None)

    def _normalize(self, key, value):
        if _is_list(key):
            return list(key), list(value)
        return [key], [value]

    def _reduce(self, vals, key=None) -> NDArray:
        """Sum a list of (possibly differently-placed) arrays — the analogue
        of CommDevice/CommCPU reduce (reference src/kvstore/comm.h). XLA
        handles cross-device moves; topology tuning is the compiler's job
        (SURVEY.md §2.3 tree-reduce row).

        Gradient compression mirrors the reference's placement
        (src/kvstore/gradient_compression.cc): the intra-process reduce is
        uncompressed; the worker→server hop compresses. '2bit' quantizes
        with a persistent per-key error-feedback residual and, across
        processes, ships REAL packed 2-bit codes (N/4 bytes on DCN);
        'bf16' halves the wire bytes of the cross-process hop."""
        if not _is_list(vals):
            vals = [vals]
        ctype = (self._compression_params or {}).get("type", "2bit")
        compressing = self._compression_params is not None
        if compressing and ctype == "bf16":
            # apply the bf16 rounding on every hop (numerics contract);
            # the cross-process hop below additionally sends bf16 bytes
            vals = [NDArray(v._data.astype(jnp.bfloat16)
                            .astype(v._data.dtype)) for v in vals]
        dev = list(vals[0]._data.devices())[0]
        total = vals[0]._data
        for v in vals[1:]:
            total = total + jax.device_put(v._data, dev)
        if compressing and ctype == "2bit":
            from ..parallel.collectives import host_allreduce_2bit
            threshold = self._compression_params.get("threshold", 0.5)
            total, new_res = host_allreduce_2bit(
                total, self._residuals.get(key), threshold)
            self._residuals[key] = new_res
        elif self._distributed:
            from ..parallel.collectives import host_allreduce
            total = host_allreduce(
                total,
                compression="bf16" if ctype == "bf16" else None)
        return NDArray(total)

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            reduced = self._reduce(v, key=k)
            if k not in self._data:
                self._data[k] = reduced
                continue
            if self._updater is not None:
                # server-side-optimizer semantics: weight kept in store,
                # updater applies grad (reference kvstore_dist_server.h)
                self._updater(self._str_or_int(k), reduced, self._data[k])
            else:
                self._data[k] = reduced

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            if k not in self._data:
                raise MXNetError(f"key {k} not initialized in kvstore")
            src = self._data[k]
            targets = o if _is_list(o) else [o]
            for t in targets:
                t._data = jax.device_put(
                    src._data, list(t._data.devices())[0]).astype(t.dtype)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (the ≥1.7 KVStoreBase contract)."""
        keys, values = self._normalize(key, value)
        _, outs = self._normalize(key, out if out is not None else value)
        for k, v, o in zip(keys, values, outs):
            reduced = self._reduce(v, key=k)
            targets = o if _is_list(o) else [o]
            for t in targets:
                t._data = jax.device_put(
                    reduced._data, list(t._data.devices())[0]).astype(t.dtype)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out=out, priority=priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (reference: sparse embedding flow —
        SURVEY.md §2.3 last row). Implemented as a device-side gather."""
        if row_ids is None:
            return self.pull(key, out=out, priority=priority)
        keys, outs = self._normalize(key, out)
        rids = row_ids if _is_list(row_ids) else [row_ids] * len(keys)
        from ..ndarray.sparse import RowSparseNDArray
        import numpy as _np
        for k, o, r in zip(keys, outs, rids):
            src = self._data[k]
            # sorted + deduped (the RowSparseNDArray invariant)
            idx = jnp.asarray(_np.unique(_np.asarray(r._data))
                              .astype(_np.int32))
            gathered = jnp.take(src._data, idx, axis=0, mode="clip")
            targets = o if _is_list(o) else [o]
            for t in targets:
                if isinstance(t, RowSparseNDArray):
                    # fill the sparse components in place: only the
                    # requested rows travel (the reference's sparse-pull
                    # bandwidth contract)
                    t._sp_indices = idx.astype(jnp.int32)
                    t._sp_values = gathered.astype(t.dtype)
                    t._data = jnp.zeros(t.shape, t.dtype).at[idx].set(
                        t._sp_values)
                else:
                    t._data = jnp.zeros_like(t._data).at[idx].set(
                        gathered.astype(t.dtype))

    # -- optimizer ------------------------------------------------------- #
    def set_optimizer(self, optimizer):
        """Run the optimizer 'on the store' (reference ships a pickled
        optimizer to server processes — `MXKVStoreSendCommmandToServers`;
        here the store is in-process, so the pickle round-trip just
        validates serializability)."""
        from .. import optimizer as opt_mod
        optimizer = pickle.loads(pickle.dumps(optimizer))
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def _str_or_int(self, k):
        try:
            return int(k)
        except (TypeError, ValueError):
            return k

    def set_gradient_compression(self, compression_params):
        params = dict(compression_params)
        ctype = params.get("type", "2bit")
        if ctype not in ("2bit", "bf16"):
            raise MXNetError(
                f"unsupported gradient compression type {ctype!r}; "
                f"supported: '2bit' (packed 2-bit codes + error-feedback "
                f"residual on the cross-process hop), "
                f"'bf16' (compressed cross-process collective)")
        self._compression_params = params
        self._residuals.clear()  # new compression config = fresh stream

    # -- misc parity ----------------------------------------------------- #
    def barrier(self):
        """Global barrier (reference: ps-lite Barrier). For SPMD, sync all
        local device work; cross-host barriers ride the collective in the
        train step."""
        for v in self._data.values():
            jax.block_until_ready(v._data)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


# register built-in types (reference type strings kept verbatim)
for _t in ("local", "device", "nccl", "dist_sync", "dist_async",
           "dist_sync_device", "dist_async_device", "horovod", "byteps"):
    _base.register(_t)(KVStore)


def create(name: str = "local") -> KVStore:
    """Create a KVStore (parity: ``mx.kv.create``). All reference type
    strings are accepted; see module docstring for the mapping."""
    if not isinstance(name, str):
        raise MXNetError("kvstore name must be a string")
    if not _base.exists(name):
        raise MXNetError(f"unknown kvstore type {name!r}")
    cls = _base.get(name)
    return cls(name)
