"""Gluon utilities (re-design of `python/mxnet/gluon/utils.py` —
file-level citation, SURVEY.md caveat)."""

from __future__ import annotations

from typing import List, Sequence

from ..base import MXNetError
from ..context import Context
from ..ndarray import NDArray
from ..ndarray import ndarray as _ndmod


def split_data(data: NDArray, num_slice: int, batch_axis: int = 0,
               even_split: bool = True) -> List[NDArray]:
    """Split ``data`` into ``num_slice`` slices along ``batch_axis``
    (parity: gluon.utils.split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data._op("slice_axis", axis=batch_axis,
                               begin=begin, end=end))
    return slices


def split_and_load(data, ctx_list: Sequence[Context], batch_axis: int = 0,
                   even_split: bool = True) -> List[NDArray]:
    """Split a batch across contexts (parity: gluon.utils.split_and_load;
    the reference's per-GPU scatter — SURVEY.md §2.3 data-parallel row).

    On TPU the idiomatic fast path shards one global array over the mesh
    (parallel/), but the per-context list API is kept for source parity.
    """
    if not isinstance(data, NDArray):
        data = _ndmod.NDArray(_ndmod._as_jax(data))
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays: Sequence[NDArray], max_norm: float,
                     check_isfinite: bool = True):
    """Rescale arrays so their joint L2 norm is at most ``max_norm``
    (parity: gluon.utils.clip_global_norm)."""
    import jax.numpy as jnp

    total = None
    for a in arrays:
        sq = jnp.sum(a._data.astype(jnp.float32) ** 2)
        total = sq if total is None else total + sq
    norm = jnp.sqrt(total)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    for a in arrays:
        a._data = (a._data.astype(jnp.float32) * scale).astype(a.dtype)
    norm_val = float(norm)
    if check_isfinite and not (norm_val == norm_val and abs(norm_val) != float("inf")):
        import warnings
        warnings.warn(f"nan or inf is detected. Clipping results will be "
                      f"undefined: norm={norm_val}")
    return norm_val


def check_sha1(filename, sha1_hash):
    """True iff the file's SHA-1 matches (parity: gluon.utils.check_sha1)."""
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        for chunk in iter(lambda: f.read(1048576), b""):
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Download ``url`` to ``path`` (parity: gluon.utils.download).
    This environment has no egress; the surface exists for ported code
    and raises the underlying URLError when the network is absent."""
    import os as _os
    import urllib.request

    fname = path or url.split("/")[-1]
    if _os.path.isdir(fname):
        fname = _os.path.join(fname, url.split("/")[-1])
    if not overwrite and _os.path.exists(fname) and (
            sha1_hash is None or check_sha1(fname, sha1_hash)):
        return fname
    d = _os.path.dirname(_os.path.abspath(fname))
    if d:
        _os.makedirs(d, exist_ok=True)
    last = None
    for _ in range(max(1, retries)):
        try:
            urllib.request.urlretrieve(url, fname)
            if sha1_hash and not check_sha1(fname, sha1_hash):
                raise OSError(f"sha1 mismatch for {fname}")
            return fname
        except Exception as e:  # retry transient network errors
            last = e
    raise last
