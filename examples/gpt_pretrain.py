"""GPT causal-LM pretraining + generation with the fused SPMD trainer
(reference class: the GluonNLP language-model scripts; decoder-side
complement to examples/bert_pretrain.py).

Runs a tiny config on synthetic data by default so it works anywhere;
``--size small`` with real TPU hardware is the benchmark configuration
(see bench.py --workload gpt for the measured variant). After training
it greedily decodes a few tokens from a prompt through the KV-cached
incremental path.

    python examples/gpt_pretrain.py --steps 10
    python examples/gpt_pretrain.py --sharding fsdp --dp 2 --tp 2 --flash
"""

import argparse

import numpy as np

import _common  # noqa: F401  (accelerator-or-CPU bootstrap)

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, parallel
from incubator_mxnet_tpu.models import gpt as gpt_mod
from incubator_mxnet_tpu.parallel import mesh as pmesh


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", choices=("mini", "small"), default="mini")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--sharding", choices=("replicated", "fsdp"),
                    default="replicated")
    ap.add_argument("--dp", type=int, default=-1)
    ap.add_argument("--fsdp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--flash", action="store_true")
    ap.add_argument("--remat", action="store_true")
    args = ap.parse_args()

    mx.random.seed(0)
    if args.size == "mini":
        model = gpt_mod.gpt_mini(vocab_size=512,
                                 max_length=max(args.seq_len, 96),
                                 dropout=0.0, flash=args.flash,
                                 remat=args.remat)
    else:
        model = gpt_mod.gpt_small(dtype="bfloat16", flash=args.flash,
                                  remat=args.remat)
    model.initialize()
    vocab = model.vocab_size

    mesh = pmesh.build_mesh(axis_sizes={"dp": args.dp, "fsdp": args.fsdp,
                                        "tp": args.tp})
    trainer = parallel.SPMDTrainer(
        model, forward_loss=gpt_mod.lm_loss, optimizer="adamw",
        optimizer_params={"learning_rate": args.lr,
                          "multi_precision": args.size == "small"},
        mesh=mesh, sharding=args.sharding)

    rng = np.random.RandomState(0)
    B, T = args.batch_size, args.seq_len
    # a learnable synthetic stream: next token = (token + 1) % vocab
    base = rng.randint(0, vocab, (B, 1))
    ids = (base + np.arange(T + 1)[None, :]) % vocab
    inputs = nd.array(ids[:, :-1], dtype="int32")
    labels = nd.array(ids[:, 1:], dtype="int32")

    for step in range(args.steps):
        loss = trainer.step(inputs, labels)
        if step % max(1, args.steps // 5) == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {float(loss.asnumpy()):.4f}")

    # KV-cached greedy decode from a short prompt
    prompt = nd.array(ids[:2, :8], dtype="int32")
    out = gpt_mod.cached_generate(model, prompt, max_new_tokens=8)
    print("prompt :", np.asarray(prompt.asnumpy())[0].tolist())
    print("decoded:", np.asarray(out.asnumpy())[0, 8:].tolist(),
          "(expect the +1 (mod vocab) continuation after training)")


if __name__ == "__main__":
    main()
