"""Continuous-batching engine tests (serve/).

The load-bearing claims: (1) paged-cache decode emits EXACTLY the
tokens of the dense-cache ``cached_generate`` path, per request, even
when requests share a batch at mixed occupancy; (2) occupancy churn
(prefill-insert, EOS-eviction, slot reuse) never retraces the decode
step; (3) pages are fully reclaimed; (4) per-slot sampling params are
isolated; (5) tp pool sharding through parallel.mesh preserves
tokens."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.models import gpt as g
from incubator_mxnet_tpu.serve import InferenceEngine, Request
from incubator_mxnet_tpu.serve.paged_kv import NULL_PAGE, PageAllocator


@pytest.fixture(scope="module")
def model():
    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=64, max_length=64)
    m.initialize()
    return m


def _solo_reference(model, prompt, max_new):
    """Per-request oracle: the dense KV-cache decode path."""
    out = g.cached_generate(model, nd.array(prompt[None, :],
                                            dtype="int32"),
                            max_new_tokens=max_new).asnumpy()
    return out[0, prompt.size:]


def test_single_request_matches_cached_generate(model):
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, 64, size=(7,)).astype(np.int32)
    ref = _solo_reference(model, prompt, 12)
    eng = InferenceEngine(model, num_slots=4, page_size=8, max_len=64)
    req = Request(prompt, max_new_tokens=12)
    eng.run([req])
    np.testing.assert_array_equal(np.asarray(req.token_ids, np.int32),
                                  ref)
    assert eng.decode_trace_count == 1


def test_mixed_occupancy_no_cross_contamination_and_slot_reuse(model):
    """5 ragged requests through 3 slots with staggered arrivals: every
    request's tokens must equal its SOLO dense-cache decode (continuous
    batching is invisible to each request), the decode step compiles
    once across all the insert/evict churn, and every page returns to
    the allocator (slot + page reuse)."""
    rng = np.random.RandomState(2)
    lens = (3, 9, 17, 5, 12)
    news = (10, 6, 14, 8, 12)
    prompts = [rng.randint(0, 64, size=(n,)).astype(np.int32)
               for n in lens]
    refs = [_solo_reference(model, p, k) for p, k in zip(prompts, news)]
    eng = InferenceEngine(model, num_slots=3, page_size=8, max_len=64,
                          num_pages=20)
    reqs = [Request(p, max_new_tokens=k) for p, k in zip(prompts, news)]
    eng.run(reqs, arrival_times=[0.0, 0.0, 0.01, 0.02, 0.03])
    for req, ref in zip(reqs, refs):
        np.testing.assert_array_equal(np.asarray(req.token_ids,
                                                 np.int32), ref)
    assert eng.decode_trace_count == 1, \
        "decode step retraced under occupancy churn"
    assert eng._alloc.free_count == eng.num_pages - 1   # all reclaimed
    assert (eng._page_table == NULL_PAGE).all()
    assert (eng._lengths == 0).all()


def test_eos_eviction_truncates_and_frees(model):
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, 64, size=(6,)).astype(np.int32)
    ref = _solo_reference(model, prompt, 14)
    eos = int(ref[3])
    stop = int(np.argmax(ref == eos))       # first occurrence
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    req = Request(prompt, max_new_tokens=14, eos_id=eos)
    eng.run([req])
    np.testing.assert_array_equal(np.asarray(req.token_ids, np.int32),
                                  ref[:stop + 1])
    assert req.finish_time is not None
    assert eng.active_count == 0
    assert eng._alloc.free_count == eng.num_pages - 1


def test_per_slot_sampling_isolation(model):
    """A greedy request and a temperature>0 request share the decode
    batch; the greedy one's tokens must be bit-identical to its solo
    run — per-slot sampling params must not leak across slots."""
    rng = np.random.RandomState(4)
    p_greedy = rng.randint(0, 64, size=(8,)).astype(np.int32)
    p_hot = rng.randint(0, 64, size=(11,)).astype(np.int32)
    ref = _solo_reference(model, p_greedy, 10)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    r1 = Request(p_greedy, max_new_tokens=10, temperature=0.0)
    r2 = Request(p_hot, max_new_tokens=10, temperature=1.3)
    eng.run([r1, r2])
    np.testing.assert_array_equal(np.asarray(r1.token_ids, np.int32),
                                  ref)
    assert len(r2.token_ids) == 10
    assert all(0 <= t < 64 for t in r2.token_ids)


def test_admission_control_waits_for_pages(model):
    """A pool too small for two concurrent requests serializes them
    (second waits for eviction) instead of corrupting the cache; a pool
    too small for ANY request raises."""
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, 64, size=(8,)).astype(np.int32)
               for _ in range(2)]
    refs = [_solo_reference(model, p, 8) for p in prompts]
    # each request needs ceil(16/8)=2 pages; 3 non-null pages admit one
    # at a time only
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          num_pages=4)
    reqs = [Request(p, max_new_tokens=8) for p in prompts]
    eng.run(reqs)
    for req, ref in zip(reqs, refs):
        np.testing.assert_array_equal(np.asarray(req.token_ids,
                                                 np.int32), ref)
    tiny = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                           num_pages=2)
    with pytest.raises(MXNetError):
        tiny.run([Request(prompts[0], max_new_tokens=16)])


def test_decode_shapes_independent_of_occupancy(model):
    """Drain a batch where every step changes occupancy (different
    max_new per request) — still one decode trace, and prefill traces
    are bounded by the bucket family, not the request count."""
    rng = np.random.RandomState(6)
    reqs = [Request(rng.randint(0, 64, size=(1 + 2 * i,)).astype(
        np.int32), max_new_tokens=3 + i) for i in range(6)]
    eng = InferenceEngine(model, num_slots=4, page_size=8, max_len=64)
    eng.run(reqs)
    assert eng.decode_trace_count == 1
    assert eng.prefill_trace_count <= 3     # pow2 page buckets: 1, 2, 4
    assert all(len(r.token_ids) == 3 + i for i, r in enumerate(reqs))


def test_tp_sharded_pools_token_parity(model):
    """Pools sharded over the tp mesh axis (H dim) through
    parallel.mesh must reproduce the unsharded tokens exactly — the
    engine is mesh-agnostic data-flow, sharding is placement only."""
    from incubator_mxnet_tpu.parallel.mesh import build_mesh
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    mesh = build_mesh(axis_sizes={"tp": 2})
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, 64, size=(n,)).astype(np.int32)
               for n in (5, 13)]
    refs = [_solo_reference(model, p, 9) for p in prompts]
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          mesh=mesh)
    reqs = [Request(p, max_new_tokens=9) for p in prompts]
    eng.run(reqs)
    for req, ref in zip(reqs, refs):
        np.testing.assert_array_equal(np.asarray(req.token_ids,
                                                 np.int32), ref)


def test_warm_restart_swaps_weights_without_retrace(model, tmp_path):
    """Elastic-checkpointing serve integration: warm_start pushes NEW
    weights into a LIVE engine — tokens must match a fresh engine built
    on those weights (proof the swap took effect) while the decode step
    keeps its single compile (weights are traced inputs, not closure
    constants)."""
    from incubator_mxnet_tpu import checkpoint as ckpt

    mx.random.seed(1234)
    model_b = g.gpt_mini(vocab_size=64, max_length=64)
    model_b.initialize()
    rng = np.random.RandomState(11)
    prompt = rng.randint(0, 64, size=(7,)).astype(np.int32)
    ref_b = _solo_reference(model_b, prompt, 10)

    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    r0 = Request(prompt.copy(), max_new_tokens=10)
    eng.run([r0])
    assert eng.decode_trace_count == 1
    prefills_before = eng.prefill_trace_count

    # ship model_b's weights through a committed checkpoint, then warm
    # restart the live engine from it
    mgr = ckpt.CheckpointManager(str(tmp_path), keep=1)
    eng_b = InferenceEngine(model_b, num_slots=2, page_size=8,
                            max_len=64)
    eng_b.save_checkpoint(mgr, block=True)
    eng.warm_start(manager=mgr)
    r1 = Request(prompt.copy(), max_new_tokens=10)
    eng.run([r1])
    np.testing.assert_array_equal(np.asarray(r1.token_ids, np.int32),
                                  ref_b)
    assert eng.decode_trace_count == 1, "warm restart retraced decode"
    assert eng.prefill_trace_count == prefills_before, \
        "warm restart retraced prefill"
    assert eng.warm_restarts == 1
    mgr.close()


def test_warm_restart_accepts_full_training_capsule_tree(model):
    """Regression: a TRAINING capsule also carries opt/<i>/<j> and
    rng/key entries; warm_start must use only the param/ entries
    instead of letting the extra keys break positional-key detection
    (the advertised train-to-serve path)."""
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    tree = {f"param/{i}": p.data().asnumpy()
            for i, p in enumerate(eng._eng_params)}
    tree["opt/0/0"] = np.zeros((1,), np.float32)
    tree["rng/key"] = np.zeros((2,), np.uint32)
    eng.warm_start(params=tree)
    assert eng.warm_restarts == 1
    assert eng.decode_trace_count == 0   # still nothing traced


def test_warm_restart_rejects_shape_mismatch(model):
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    bad = {str(i): np.zeros((1, 1), np.float32)
           for i in range(len(eng._eng_params))}
    with pytest.raises(MXNetError, match="shape/dtype"):
        eng.warm_start(params=bad)


def test_page_allocator_invariants():
    a = PageAllocator(5)
    assert a.free_count == 4                 # page 0 reserved
    got = {a.alloc() for _ in range(4)}
    assert NULL_PAGE not in got
    with pytest.raises(MXNetError):
        a.alloc()
    a.free(got)
    assert a.free_count == 4
    with pytest.raises(MXNetError):
        a.free([NULL_PAGE])
    with pytest.raises(MXNetError):
        PageAllocator(1)
