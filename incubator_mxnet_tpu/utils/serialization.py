"""NDArray / parameter serialization.

Re-design of the reference's ``.params`` format (`NDArray::Save/Load`,
`src/ndarray/ndarray.cc`: magic header + name→array dict, device stripped —
file-level citation, SURVEY.md caveat).

Format (v1): little-endian
    8 bytes  magic  b'MXTPU\\x00\\x01\\x00'
    8 bytes  header length N (uint64)
    N bytes  JSON header: {"names": [...], "arrays": [{dtype, shape}, ...]}
    raw buffers, each 64-byte aligned, in header order (C-contiguous)

Arrays are always materialized on host before save (the reference strips
device too); load returns host arrays that callers place onto devices.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Union

import jax
import numpy as np

from ..base import MXNetError

MAGIC = b"MXTPU\x00\x01\x00"
_ALIGN = 64


def _tohost(arr) -> np.ndarray:
    if hasattr(arr, "_data"):
        arr = arr._data
    out = np.asarray(jax.device_get(arr))
    # bfloat16 has no numpy dtype string repr numpy understands natively in
    # all versions; store via uint16 view with a marker.
    return out


def _dtype_str(a: np.ndarray) -> str:
    return str(a.dtype)


def save_ndarrays(fname: str, data) -> None:
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [_tohost(v) for v in data.values()]
    elif isinstance(data, (list, tuple)):
        names = [str(i) for i in range(len(data))]
        arrays = [_tohost(v) for v in data]
    else:
        names = ["0"]
        arrays = [_tohost(data)]

    metas = []
    bufs = []
    for a in arrays:
        if a.dtype.name == "bfloat16":
            buf = a.view(np.uint16).tobytes(order="C")
            metas.append({"dtype": "bfloat16", "shape": list(a.shape)})
        else:
            buf = np.ascontiguousarray(a).tobytes(order="C")
            metas.append({"dtype": _dtype_str(a), "shape": list(a.shape)})
        bufs.append(buf)

    header = json.dumps({"names": names, "arrays": metas}).encode("utf-8")
    with open(fname, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<Q", len(header)))
        f.write(header)
        pos = len(MAGIC) + 8 + len(header)
        for buf in bufs:
            padding = (-pos) % _ALIGN
            f.write(b"\x00" * padding)
            pos += padding
            f.write(buf)
            pos += len(buf)


def load_ndarrays(fname: str):
    """Returns dict name→NDArray (or list if names are all indices)."""
    from ..ndarray import NDArray
    import jax.numpy as jnp
    import ml_dtypes

    with open(fname, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise MXNetError(f"{fname}: not a MXTPU params file")
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode("utf-8"))
        pos = len(MAGIC) + 8 + hlen
        out = {}
        for name, meta in zip(header["names"], header["arrays"]):
            padding = (-pos) % _ALIGN
            f.read(padding)
            pos += padding
            shape = tuple(meta["shape"])
            if meta["dtype"] == "bfloat16":
                count = int(np.prod(shape)) if shape else 1
                raw = f.read(count * 2)
                pos += len(raw)
                arr = np.frombuffer(raw, dtype=np.uint16).reshape(shape) \
                    .view(ml_dtypes.bfloat16)
            else:
                dt = np.dtype(meta["dtype"])
                count = int(np.prod(shape)) if shape else 1
                raw = f.read(count * dt.itemsize)
                pos += len(raw)
                arr = np.frombuffer(raw, dtype=dt).reshape(shape)
            out[name] = NDArray(jnp.asarray(arr))
    if out and all(k.isdigit() for k in out):
        return [out[str(i)] for i in range(len(out))]
    return out
