"""Failure-detection / recovery surface matrix (SURVEY.md §5.3; VERDICT
r2 table row "failure detection: no failure-surface test matrix").

The reference's story is thin (deferred engine exceptions + checkpoint/
resume); this matrix pins down the TPU-native equivalents:
  1. overflow detection (all_finite / LossScaler skip-and-halve),
  2. error surfacing as MXNetError (not raw jax tracebacks) for common
     misuse,
  3. checkpoint → crash → resume producing an identical trajectory
     (trainer states + params round-trip),
  4. non-finite loss is observable at the fused-step boundary."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd, gluon, parallel


def test_loss_scaler_skips_and_halves_on_overflow():
    from incubator_mxnet_tpu.amp.loss_scaler import LossScaler
    scaler = LossScaler(init_scale=1024.0, scale_window=1)
    good = nd.array(np.ones(3, np.float32))
    assert not scaler.has_overflow([good])
    scale0 = scaler.loss_scale
    scaler.update_scale(False)
    assert scaler.loss_scale >= scale0          # clean step grows/holds
    bad = nd.array(np.array([1.0, np.inf, 0.0], np.float32))
    assert scaler.has_overflow([bad])
    grown = scaler.loss_scale
    scaler.update_scale(True)
    assert scaler.loss_scale == pytest.approx(grown / 2)  # halved


def test_non_finite_loss_observable_at_step_boundary():
    """A poisoned batch produces a non-finite loss the driver can detect
    with all_finite — the fused step itself must not crash."""
    mx.random.seed(0)
    net = gluon.nn.Dense(2, in_units=3)
    net.initialize()
    tr = parallel.SPMDTrainer(
        net, loss=gluon.loss.L2Loss(), optimizer="sgd",
        optimizer_params={"learning_rate": 0.1})
    bad = nd.array(np.array([[1.0, np.inf, 0.0]] * 8, np.float32))
    y = nd.array(np.zeros((8, 2), np.float32))
    loss = tr.step(bad, y)
    assert float(nd.all_finite(loss).asnumpy()) == 0.0


def test_error_surfaces_are_mxneterror():
    with pytest.raises(mx.MXNetError):
        nd.array([1.0], dtype="not_a_dtype")
    with pytest.raises(mx.MXNetError):
        x = nd.array([1.0])
        x.backward()  # backward without recording
    with pytest.raises(mx.MXNetError):
        nd.dot(nd.ones((2, 3)), nd.ones((2, 3)))  # shape mismatch


def test_checkpoint_crash_resume_identical_trajectory(tmp_path):
    """Train 3 steps, checkpoint (params + trainer states), train 3 more;
    separately: restore at step 3 in a FRESH trainer and replay — final
    params must match exactly (reference idiom: do_checkpoint callback +
    Trainer.save_states/load_states)."""
    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype(np.float32)
    y = rng.randint(0, 3, (16,))
    lf = gluon.loss.SoftmaxCrossEntropyLoss()

    def make():
        mx.random.seed(21)
        net = gluon.nn.Dense(3, in_units=4)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.05}, kvstore=None)
        return net, tr

    def step(net, tr):
        with autograd.record():
            L = lf(net(nd.array(X)), nd.array(y)).mean()
        L.backward()
        tr.step(1)

    # uninterrupted run
    net_a, tr_a = make()
    for _ in range(6):
        step(net_a, tr_a)

    # interrupted run: checkpoint at 3, "crash", restore, resume
    net_b, tr_b = make()
    for _ in range(3):
        step(net_b, tr_b)
    net_b.save_parameters(str(tmp_path / "ck.params"))
    tr_b.save_states(str(tmp_path / "ck.states"))

    net_c, tr_c = make()  # fresh processes after the crash
    net_c.load_parameters(str(tmp_path / "ck.params"))
    tr_c.load_states(str(tmp_path / "ck.states"))
    for _ in range(3):
        step(net_c, tr_c)

    np.testing.assert_allclose(net_c.weight.data().asnumpy(),
                               net_a.weight.data().asnumpy(),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(net_c.bias.data().asnumpy(),
                               net_a.bias.data().asnumpy(),
                               rtol=1e-6, atol=1e-7)
