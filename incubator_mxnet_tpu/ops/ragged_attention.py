"""Ragged paged-KV decode attention (the serving-side Pallas kernel).

The training kernels in ``ops.pallas_attention`` assume dense
(B, H, T, D) K/V buffers — every query pays DMA + compute over the full
``Tmax`` window regardless of how many tokens its sequence actually
holds. For continuous-batching inference that is exactly backwards: the
batch is a set of SLOTS at wildly different sequence lengths, and cache
memory must scale with live tokens, not ``B × Tmax``. Following the
ragged-paged-attention design (arxiv 2604.15464; the Gemma-on-TPU
serving study 2605.25645 attributes most TPU serving wins to this
batching + cache discipline), K/V live in a shared page pool

    k_pool / v_pool : (num_pages, H, page_size, D)

and each slot owns an ordered list of pages (its PAGE TABLE row). Page 0
is the NULL page: never allocated, dead page-table entries point at it,
and its contents are garbage by construction — every read of it is
masked by the slot's length.

Kernel design (per /opt/skills/guides/pallas_guide.md):
  - grid (S, max_pages) under a ``PrefetchScalarGridSpec``: the page
    table and per-slot lengths are scalar-prefetched, so the K/V
    BlockSpec index_map dereferences ``page_table[s, j]`` to DMA exactly
    the page that grid step needs — the kernel never sees a gather.
  - the last grid dimension is sequential on TPU, so the online-softmax
    state (m, l, acc) carries across pages in VMEM scratch: init at
    j == 0, accumulate per live page, finalize (acc / l, masked rows
    zeroed) at j == max_pages - 1.
  - DEAD PAGES COST NOTHING: ``pl.when(j * page_size < length)`` skips
    the compute, and because every dead entry indexes the null page the
    block index is unchanged between consecutive dead steps — Pallas
    skips the re-DMA. A slot at length L pays for ceil(L / page_size)
    pages, not max_pages.
  - one decode query per slot: scores are (1, page_size) rows per head,
    dot operands stay in the input dtype, accumulation is f32 via
    ``preferred_element_type`` (same dtype discipline as the training
    kernels). Decode attention is a prefix mask — the query IS position
    ``length - 1`` — so no causal triangle is needed.

Falls back to a pure-jnp gather-and-mask reference off-TPU (the CPU
serving path and the test oracle); ``MXTPU_FLASH_INTERPRET=1`` routes
the dispatcher to the real kernel in interpret mode, mirroring
``ops.pallas_attention``. Same masked-row contract as the training
kernels: a slot with length 0 produces EXACTLY zero output.

``ragged_prefill_attention`` is the chunked-prefill sibling: a CHUNK of
C consecutive prompt tokens of ONE slot (absolute positions
``q_start + i``) attends the slot's already-populated paged prefix plus
the causal intra-chunk part — the chunk's own K/V is scattered into the
pages first, so a single per-query prefix mask ``pos_k <= pos_q``
covers both. Same kernel shape as decode (grid over the page axis,
online-softmax scratch carried across pages, dead pages skipped via the
repeated-null-page index trick), with C query rows per head instead of
one; same jnp gather fallback as CPU path and oracle.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from .pallas_attention import _pallas_available, _pallas_runnable

_NEG_INF = -1e30

__all__ = ["ragged_paged_attention", "ragged_attention_reference",
           "ragged_prefill_attention", "ragged_prefill_reference",
           "ragged_verify_attention", "ragged_verify_reference"]


def _ragged_kernel(pt_ref, ln_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, scale, page_size, n_pages,
                   heads, ks_ref=None, vs_ref=None):
    """``ks_ref``/``vs_ref`` (None = unquantized pools, bit-identical
    to the pre-quantization kernel) are (P,) f32 per-page scale arrays
    riding the SAME scalar-prefetch path as the page table: the grid
    step that DMAs page ``pt[s, j]`` reads that page's scale from SMEM
    and dequantizes the int8/fp8 block inline at the DMA boundary —
    the pool never materializes in float anywhere."""
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    j = pl.program_id(1)
    length = ln_ref[s]                          # live tokens this slot

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j * page_size < length)
    def _accumulate():
        valid = (j * page_size + lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0)) < length
        if ks_ref is not None:                  # this page's scales
            sk = ks_ref[pt_ref[s, j]]
            sv = vs_ref[pt_ref[s, j]]
        for h in range(heads):                  # unrolled head loop
            q = q_ref[0, h]                     # (1, D), input dtype
            k = k_ref[0, h]                     # (page_size, D)
            if ks_ref is not None:              # inline dequant
                q = q.astype(jnp.float32)
                k = k.astype(jnp.float32) * sk
            # SELECT masked rows out of V (not just zero-weight them):
            # a freed page can be reused carrying non-finite garbage in
            # positions past the new owner's length, and 0 * NaN = NaN
            # would leak it through the weighted sum — masked reads
            # must never matter, even poisoned ones (a quantized pool's
            # NaN channel is the page SCALE — the select covers it the
            # same way)
            vv = v_ref[0, h] if vs_ref is None \
                else v_ref[0, h].astype(jnp.float32) * sv
            v = jnp.where(valid, vv, 0.0)
            sc = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                         precision=lax.Precision.DEFAULT) * scale
            pos = j * page_size + lax.broadcasted_iota(
                jnp.int32, (1, page_size), 1)
            sc = jnp.where(pos < length, sc, _NEG_INF)
            m_prev = m_ref[h]                   # (1,)
            l_prev = l_ref[h]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1))
            p = jnp.exp(sc - m_new[:, None])    # (1, page_size) f32
            alpha = jnp.exp(m_prev - m_new)
            m_ref[h] = m_new
            l_ref[h] = l_prev * alpha + jnp.sum(p, axis=-1)
            acc_ref[h] = acc_ref[h] * alpha[:, None] + jnp.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32,
                precision=lax.Precision.DEFAULT)

    @pl.when(j == n_pages - 1)
    def _finalize():
        for h in range(heads):
            m = m_ref[h]
            l_safe = jnp.maximum(l_ref[h], 1e-30)
            # fully-masked slot (length 0): m never left _NEG_INF — emit
            # exactly zero, the masked-row contract shared with the
            # training kernels (ops.pallas_attention). Negated-compare
            # form so a NaN running max (poisoned K/V page) fails the
            # dead-row test and PROPAGATES instead of being silently
            # zeroed — the serving engine's non-finite guard depends on
            # corruption staying visible in the output.
            row_ok = ~(m <= _NEG_INF / 2)
            o_ref[0, h] = jnp.where(row_ok[:, None],
                                    acc_ref[h] / l_safe[:, None],
                                    0.0).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _ragged_pallas(q, k_pool, v_pool, page_table, lengths, scale,
                   interpret):
    """q: (S, H, D); pools: (P, H, page_size, D); page_table:
    (S, max_pages) int32; lengths: (S,) int32. Returns (S, H, D)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, H, D = q.shape
    page_size = k_pool.shape[2]
    n_pages = page_table.shape[1]
    q4 = q[:, :, None, :]                       # (S, H, 1, D)

    kernel = functools.partial(
        _ragged_kernel, scale=scale, page_size=page_size,
        n_pages=n_pages, heads=H)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                  # page_table, lengths
        grid=(S, n_pages),
        in_specs=[
            pl.BlockSpec((1, H, 1, D), lambda s, j, pt, ln: (s, 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda s, j, pt, ln: (pt[s, j], 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda s, j, pt, ln: (pt[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, 1, D),
                               lambda s, j, pt, ln: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 1), jnp.float32),        # m
            pltpu.VMEM((H, 1), jnp.float32),        # l
            pltpu.VMEM((H, 1, D), jnp.float32),     # acc
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, 1, D), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      q4, k_pool, v_pool)
    return out[:, :, 0, :]


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _ragged_pallas_q(q, k_pool, v_pool, page_table, lengths, k_scale,
                     v_scale, scale, interpret):
    """Quantized-pool decode kernel: ``k_scale``/``v_scale`` (P,) f32
    per-page scales join the page table and lengths in the
    scalar-prefetch set; the kernel dequantizes each page inline at
    the DMA boundary (see ``_ragged_kernel``)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, H, D = q.shape
    page_size = k_pool.shape[2]
    n_pages = page_table.shape[1]
    q4 = q[:, :, None, :]                       # (S, H, 1, D)

    def kernel(pt_ref, ln_ref, ks_ref, vs_ref, *rest):
        _ragged_kernel(pt_ref, ln_ref, *rest, scale=scale,
                       page_size=page_size, n_pages=n_pages, heads=H,
                       ks_ref=ks_ref, vs_ref=vs_ref)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,      # page_table, lengths, k/v scales
        grid=(S, n_pages),
        in_specs=[
            pl.BlockSpec((1, H, 1, D),
                         lambda s, j, pt, ln, ks, vs: (s, 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda s, j, pt, ln, ks, vs:
                         (pt[s, j], 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda s, j, pt, ln, ks, vs:
                         (pt[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, 1, D),
                               lambda s, j, pt, ln, ks, vs:
                               (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 1), jnp.float32),        # m
            pltpu.VMEM((H, 1), jnp.float32),        # l
            pltpu.VMEM((H, 1, D), jnp.float32),     # acc
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, 1, D), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      k_scale.astype(jnp.float32), v_scale.astype(jnp.float32),
      q4, k_pool, v_pool)
    return out[:, :, 0, :]


def _gather_window(pool, page_table, scale=None):
    """(S, H, K, D) dense window of a slot's pages — the expensive
    gather over the pool's page axis, shared by the reference paths.
    ``scale`` (P,) dequantizes a quantized pool inline with the gather
    (per-page broadcast) — the f32 oracle's quantized arm."""
    S, n_pages = page_table.shape
    _, H, page_size, D = pool.shape
    g = pool[page_table]                        # (S, n_pages, H, ps, D)
    if scale is not None:
        g = g.astype(jnp.float32) * \
            scale[page_table][:, :, None, None, None]
    g = jnp.moveaxis(g, 2, 1)                   # (S, H, n_pages, ps, D)
    return g.reshape(S, H, n_pages * page_size, D)


def _reference_core(q, k, v, lengths, sc):
    """Masked online-softmax attention over a pre-gathered window.
    q: (S, H, D); k/v: (S, H, K, D). Factored out so the verify
    reference can reuse ONE gather across its W query rows while each
    row runs bitwise the same computation as the decode reference."""
    S, H, D = q.shape
    K = k.shape[2]
    s = jnp.einsum("shd,shkd->shk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sc
    pos = lax.broadcasted_iota(jnp.int32, (S, K), 1)
    valid = pos < lengths.astype(jnp.int32)[:, None]
    s = jnp.where(valid[:, None, :], s, _NEG_INF)
    # select masked positions out of V: a reused page may carry
    # non-finite garbage past this slot's length and 0 * NaN = NaN
    # would leak it through the weighted sum (same contract as the
    # Pallas kernel)
    v = jnp.where(valid[:, None, :, None], v, 0.0)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("shk,shkd->shd", p, v.astype(jnp.float32)) / \
        jnp.maximum(l, 1e-30)[..., None]
    # negated compare: length-0 slots → zero, but a NaN max (poisoned
    # page) PROPAGATES so the engine's non-finite guard can see it
    row_ok = ~(m <= _NEG_INF / 2)
    return jnp.where(row_ok[..., None], out, 0.0).astype(q.dtype)


def ragged_attention_reference(q, k_pool, v_pool, page_table, lengths,
                               scale=None, k_scale=None, v_scale=None):
    """Pure-jnp oracle and CPU serving path: gather each slot's pages to
    a dense (S, H, K, D) window, mask positions >= length, softmax with
    f32 accumulation. Jit-friendly (static shapes; the gather is an XLA
    gather over the pool's page axis). ``k_scale``/``v_scale`` (P,)
    dequantize quantized pools at the gather (per-page broadcast) —
    past that point the math is BITWISE the unquantized reference, which
    is what makes this the quantization accuracy oracle's denominator."""
    D = q.shape[-1]
    sc = D ** -0.5 if scale is None else scale
    k = _gather_window(k_pool, page_table, k_scale)
    v = _gather_window(v_pool, page_table, v_scale)
    return _reference_core(q, k, v, lengths, sc)


def ragged_paged_attention(q, k_pool, v_pool, page_table, lengths,
                           scale=None, interpret=None, k_scale=None,
                           v_scale=None):
    """Decode attention for one new token per slot against the paged KV
    pool. q: (S, H, D); k_pool/v_pool: (num_pages, H, page_size, D);
    page_table: (S, max_pages) int32 (dead entries 0 = null page);
    lengths: (S,) int32 — number of live KV tokens INCLUDING the one
    just written for this step. Returns (S, H, D).

    ``k_scale``/``v_scale`` (P,) f32 mark the pools QUANTIZED (int8 /
    fp8 codes with per-page symmetric scales — serve/paged_kv.py): the
    Pallas path prefetches them next to the page table and dequantizes
    inline at the DMA boundary; the jnp path dequantizes at the gather.
    None (the default) is the unquantized path, bit-identical to
    before.

    Dispatch is static (mirrors ``ops.pallas_attention``): the Pallas
    kernel on TPU, or anywhere under ``MXTPU_FLASH_INTERPRET=1`` /
    ``interpret=True``; the jnp gather reference otherwise (the CPU
    serving path). Both paths share the masked-row contract."""
    if interpret is None:
        interpret = os.environ.get("MXTPU_FLASH_INTERPRET") == "1"
    sc = q.shape[-1] ** -0.5 if scale is None else scale
    if _pallas_available() and _pallas_runnable(interpret):
        if k_scale is not None:
            return _ragged_pallas_q(q, k_pool, v_pool, page_table,
                                    lengths, k_scale, v_scale, sc,
                                    interpret)
        return _ragged_pallas(q, k_pool, v_pool, page_table, lengths,
                              sc, interpret)
    return ragged_attention_reference(q, k_pool, v_pool, page_table,
                                      lengths, sc, k_scale, v_scale)


# --------------------------------------------------------------------- #
# prefill over a paged prefix (the chunked-prefill attention variant)
# --------------------------------------------------------------------- #

def _ragged_prefill_kernel(pr_ref, qi_ref, q_ref, k_ref, v_ref, o_ref,
                           m_ref, l_ref, acc_ref, *, scale, page_size,
                           n_pages, heads, chunk, ks_ref=None,
                           vs_ref=None):
    from jax.experimental import pallas as pl

    j = pl.program_id(0)
    start = qi_ref[0]                # first query's absolute position
    n_real = qi_ref[1]               # live queries in the chunk

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # pages whose first key position is past the last real query's
    # position contribute nothing to any live row — skip them, and
    # (dead entries all indexing the null page) skip their re-DMA too
    @pl.when(j * page_size < start + n_real)
    def _accumulate():
        # positions past the last real query's view are masked for
        # EVERY row — select them out of V so reused-page garbage
        # (possibly non-finite) cannot leak through 0-weight terms
        valid = (j * page_size + lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0)) < start + n_real
        if ks_ref is not None:                  # this page's scales
            sk = ks_ref[pr_ref[j]]
            sv = vs_ref[pr_ref[j]]
        for h in range(heads):                  # unrolled head loop
            q = q_ref[0, h]                     # (chunk, D), input dtype
            k = k_ref[0, h]                     # (page_size, D)
            if ks_ref is not None:              # inline dequant
                q = q.astype(jnp.float32)
                k = k.astype(jnp.float32) * sk
            vv = v_ref[0, h] if vs_ref is None \
                else v_ref[0, h].astype(jnp.float32) * sv
            v = jnp.where(valid, vv, 0.0)
            sc = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                         precision=lax.Precision.DEFAULT) * scale
            pos_k = j * page_size + lax.broadcasted_iota(
                jnp.int32, (chunk, page_size), 1)
            pos_q = start + lax.broadcasted_iota(
                jnp.int32, (chunk, page_size), 0)
            # per-query prefix mask: query i (absolute pos start + i)
            # sees keys [0, start + i] — the paged prefix AND the causal
            # intra-chunk part in one predicate (the chunk's own K/V is
            # already scattered into these pages)
            sc = jnp.where(pos_k <= pos_q, sc, _NEG_INF)
            m_prev = m_ref[h]                   # (chunk,)
            l_prev = l_ref[h]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1))
            p = jnp.exp(sc - m_new[:, None])    # (chunk, page_size) f32
            alpha = jnp.exp(m_prev - m_new)
            m_ref[h] = m_new
            l_ref[h] = l_prev * alpha + jnp.sum(p, axis=-1)
            acc_ref[h] = acc_ref[h] * alpha[:, None] + jnp.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32,
                precision=lax.Precision.DEFAULT)

    @pl.when(j == n_pages - 1)
    def _finalize():
        for h in range(heads):
            m = m_ref[h]
            l_safe = jnp.maximum(l_ref[h], 1e-30)
            # every live query attends at least position 0, so only rows
            # that saw no page at all (possible when padded rows extend
            # past every accumulated page) stay at _NEG_INF — emit zero.
            # Negated compare: NaN (poisoned page) propagates, see the
            # decode kernel's finalize
            row_ok = ~(m <= _NEG_INF / 2)
            o_ref[0, h] = jnp.where(row_ok[:, None],
                                    acc_ref[h] / l_safe[:, None],
                                    0.0).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _ragged_prefill_pallas(q, k_pool, v_pool, page_row, qinfo, scale,
                           interpret):
    """q: (C, H, D) chunk queries of ONE slot; pools: (P, H, ps, D);
    page_row: (max_pages,) int32; qinfo: (2,) int32 = [q_start, n_real].
    Returns (C, H, D)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C, H, D = q.shape
    page_size = k_pool.shape[2]
    n_pages = page_row.shape[0]
    q4 = q.transpose(1, 0, 2)[None]             # (1, H, C, D)

    kernel = functools.partial(
        _ragged_prefill_kernel, scale=scale, page_size=page_size,
        n_pages=n_pages, heads=H, chunk=C)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                  # page_row, qinfo
        grid=(n_pages,),
        in_specs=[
            pl.BlockSpec((1, H, C, D), lambda j, pr, qi: (0, 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda j, pr, qi: (pr[j], 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda j, pr, qi: (pr[j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, C, D),
                               lambda j, pr, qi: (0, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, C), jnp.float32),        # m
            pltpu.VMEM((H, C), jnp.float32),        # l
            pltpu.VMEM((H, C, D), jnp.float32),     # acc
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, H, C, D), q.dtype),
        interpret=interpret,
    )(page_row.astype(jnp.int32), qinfo.astype(jnp.int32),
      q4, k_pool, v_pool)
    return out[0].transpose(1, 0, 2)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _ragged_prefill_pallas_q(q, k_pool, v_pool, page_row, qinfo,
                             k_scale, v_scale, scale, interpret):
    """Quantized-pool chunked-prefill kernel: per-page scales in the
    scalar-prefetch set, dequant at the DMA boundary (see
    ``_ragged_prefill_kernel``)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C, H, D = q.shape
    page_size = k_pool.shape[2]
    n_pages = page_row.shape[0]
    q4 = q.transpose(1, 0, 2)[None]             # (1, H, C, D)

    def kernel(pr_ref, qi_ref, ks_ref, vs_ref, *rest):
        _ragged_prefill_kernel(pr_ref, qi_ref, *rest, scale=scale,
                               page_size=page_size, n_pages=n_pages,
                               heads=H, chunk=C, ks_ref=ks_ref,
                               vs_ref=vs_ref)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,      # page_row, qinfo, k/v scales
        grid=(n_pages,),
        in_specs=[
            pl.BlockSpec((1, H, C, D),
                         lambda j, pr, qi, ks, vs: (0, 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda j, pr, qi, ks, vs: (pr[j], 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda j, pr, qi, ks, vs: (pr[j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, C, D),
                               lambda j, pr, qi, ks, vs: (0, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, C), jnp.float32),        # m
            pltpu.VMEM((H, C), jnp.float32),        # l
            pltpu.VMEM((H, C, D), jnp.float32),     # acc
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, H, C, D), q.dtype),
        interpret=interpret,
    )(page_row.astype(jnp.int32), qinfo.astype(jnp.int32),
      k_scale.astype(jnp.float32), v_scale.astype(jnp.float32),
      q4, k_pool, v_pool)
    return out[0].transpose(1, 0, 2)


def ragged_prefill_reference(q, k_pool, v_pool, page_row, q_start,
                             scale=None, n_real=None, k_scale=None,
                             v_scale=None):
    """Pure-jnp oracle and CPU serving path for chunked prefill: gather
    the slot's whole page window dense, apply the per-query prefix mask
    ``pos_k <= q_start + i``, softmax with f32 accumulation. Same
    numerics discipline as ``ragged_attention_reference``; jit-friendly
    (``q_start`` is traced data). ``n_real`` is the count of live
    (non-padded) chunk rows, default C."""
    C, H, D = q.shape
    page_size = k_pool.shape[2]
    n_pages = page_row.shape[0]
    K = n_pages * page_size
    sc = D ** -0.5 if scale is None else scale
    if n_real is None:
        n_real = C

    def window(pool, pscale):
        g = pool[page_row]                      # (n_pages, H, ps, D)
        if pscale is not None:                  # per-page dequant
            g = g.astype(jnp.float32) * \
                pscale[page_row][:, None, None, None]
        g = jnp.moveaxis(g, 1, 0)               # (H, n_pages, ps, D)
        return g.reshape(H, K, D)

    k = window(k_pool, k_scale)
    v = window(v_pool, v_scale)
    s = jnp.einsum("chd,hkd->chk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sc
    pos_k = lax.broadcasted_iota(jnp.int32, (C, K), 1)
    pos_q = q_start + lax.broadcasted_iota(jnp.int32, (C, K), 0)
    s = jnp.where((pos_k <= pos_q)[:, None, :], s, _NEG_INF)
    # select positions no LIVE query may see out of V (reused-page
    # garbage must not leak through 0-weight terms — see the decode
    # reference): a live row i < n_real reads positions
    # <= q_start + i <= q_start + n_real - 1, all freshly written, so
    # zeroing from q_start + n_real changes no live row's math. The
    # bound must be n_real, not C: on a PARTIAL chunk the positions in
    # [q_start + n_real, q_start + C) are UNWRITTEN — a recycled page
    # can carry a quarantined slot's non-finite K/V there, and
    # 0 * NaN = NaN would poison every live row of this chunk (found
    # by the chaos corrupt_page scenario under speculation, whose
    # wide verify writes NaN into more offsets of the victim's pages
    # before quarantine frees them). Same rule as the Pallas kernel's
    # ``pos < start + n_real`` select. Positions a later LIVE query
    # legitimately reads stay as-is: if they are poisoned, that query
    # is poisoned, which is the point; padded rows may now read zeros,
    # but their output was already contractually garbage.
    never_read = lax.broadcasted_iota(jnp.int32, (K,), 0) >= \
        q_start + n_real
    v = jnp.where(never_read[None, :, None], 0.0, v)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("chk,hkd->chd", p, v.astype(jnp.float32)) / \
        jnp.maximum(l, 1e-30)[..., None]
    # negated compare: padded rows → zero, NaN propagates (see decode)
    row_ok = ~(m <= _NEG_INF / 2)
    return jnp.where(row_ok[..., None], out, 0.0).astype(q.dtype)


# --------------------------------------------------------------------- #
# multi-query verify over a paged prefix (the speculative-decoding
# draft-then-verify attention variant)
# --------------------------------------------------------------------- #

def _ragged_verify_kernel(pt_ref, ln_ref, dl_ref, q_ref, k_ref, v_ref,
                          o_ref, m_ref, l_ref, acc_ref, *, scale,
                          page_size, n_pages, heads, window,
                          ks_ref=None, vs_ref=None):
    """Decode kernel generalized to ``window`` queries per slot: query
    row r of slot s sits at absolute position ``lengths[s] - 1 + r``
    (row 0 IS the ordinary decode query) and attends keys
    ``[0, lengths[s] - 1 + r]`` — the slot's paged prefix plus the
    causal intra-window part in one predicate, exactly the
    chunked-prefill masking with a per-SLOT dynamic start. Same
    online-softmax scratch carried across the page axis, same
    dead-page skip via the repeated-null-page index, same NaN
    propagation / masked-V-select contract as the decode kernel."""
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    j = pl.program_id(1)
    length = ln_ref[s]               # keys visible to query row 0
    dl = dl_ref[s]                   # slot's REAL draft count this step

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # the last CONSUMED row (row dl — accepted drafts + the
    # bonus/correction) sees keys up to length + dl - 1, and that is
    # also the last position freshly written this step; pages wholly
    # past it (and every page of a dead slot) contribute nothing —
    # dead entries all index the null page, so skipping also skips the
    # re-DMA
    @pl.when((length > 0) & (j * page_size < length + dl))
    def _accumulate():
        # positions no CONSUMED row may ever see are selected out of V
        # so reused-page garbage (possibly non-finite) cannot leak
        # through 0-weight terms. The bound must be the slot's real
        # written extent length + dl, NOT length + window - 1: when a
        # slot drafts fewer than window - 1 tokens, positions in
        # [length + dl, length + window - 1) are UNWRITTEN — a recycled
        # page can carry a quarantined slot's non-finite K/V there, and
        # 0 * NaN = NaN would poison every consumed row, falsely
        # quarantining a healthy slot (same rule as the chunked-prefill
        # kernel's n_real bound). Rows past dl may now read fewer
        # positions than their nominal visibility; their output is
        # discarded by the engine and never feeds acceptance (the op's
        # documented PRECONDITION).
        valid = (j * page_size + lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0)) < length + dl
        if ks_ref is not None:                  # this page's scales
            sk = ks_ref[pt_ref[s, j]]
            sv = vs_ref[pt_ref[s, j]]
        for h in range(heads):                  # unrolled head loop
            q = q_ref[0, h]                     # (window, D), input dtype
            k = k_ref[0, h]                     # (page_size, D)
            if ks_ref is not None:              # inline dequant
                q = q.astype(jnp.float32)
                k = k.astype(jnp.float32) * sk
            vv = v_ref[0, h] if vs_ref is None \
                else v_ref[0, h].astype(jnp.float32) * sv
            v = jnp.where(valid, vv, 0.0)
            sc = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                         precision=lax.Precision.DEFAULT) * scale
            pos_k = j * page_size + lax.broadcasted_iota(
                jnp.int32, (window, page_size), 1)
            row = lax.broadcasted_iota(
                jnp.int32, (window, page_size), 0)
            # row r (absolute position length - 1 + r) sees keys
            # [0, length - 1 + r]: prefix + causal intra-window in one
            # predicate
            sc = jnp.where(pos_k < length + row, sc, _NEG_INF)
            m_prev = m_ref[h]                   # (window,)
            l_prev = l_ref[h]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1))
            p = jnp.exp(sc - m_new[:, None])    # (window, page_size) f32
            alpha = jnp.exp(m_prev - m_new)
            m_ref[h] = m_new
            l_ref[h] = l_prev * alpha + jnp.sum(p, axis=-1)
            acc_ref[h] = acc_ref[h] * alpha[:, None] + jnp.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32,
                precision=lax.Precision.DEFAULT)

    @pl.when(j == n_pages - 1)
    def _finalize():
        for h in range(heads):
            m = m_ref[h]
            l_safe = jnp.maximum(l_ref[h], 1e-30)
            # dead slots (length 0) never accumulate: every row stays
            # at _NEG_INF — emit exactly zero. Negated compare so a NaN
            # running max (poisoned page) PROPAGATES, see the decode
            # kernel's finalize
            row_ok = ~(m <= _NEG_INF / 2)
            o_ref[0, h] = jnp.where(row_ok[:, None],
                                    acc_ref[h] / l_safe[:, None],
                                    0.0).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _ragged_verify_pallas(q, k_pool, v_pool, page_table, lengths,
                          draft_len, scale, interpret):
    """q: (S, W, H, D) — W verify queries per slot; pools:
    (P, H, page_size, D); page_table: (S, max_pages) int32; lengths:
    (S,) int32 = keys visible to query row 0 (0 = dead slot);
    draft_len: (S,) int32 = the slot's real draft count (index of its
    last consumed row, bounding the freshly-written extent).
    Returns (S, W, H, D)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, W, H, D = q.shape
    page_size = k_pool.shape[2]
    n_pages = page_table.shape[1]
    q4 = q.transpose(0, 2, 1, 3)                # (S, H, W, D)

    kernel = functools.partial(
        _ragged_verify_kernel, scale=scale, page_size=page_size,
        n_pages=n_pages, heads=H, window=W)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,          # page_table, lengths, draft_len
        grid=(S, n_pages),
        in_specs=[
            pl.BlockSpec((1, H, W, D),
                         lambda s, j, pt, ln, dl: (s, 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda s, j, pt, ln, dl: (pt[s, j], 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda s, j, pt, ln, dl: (pt[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, W, D),
                               lambda s, j, pt, ln, dl: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, W), jnp.float32),        # m
            pltpu.VMEM((H, W), jnp.float32),        # l
            pltpu.VMEM((H, W, D), jnp.float32),     # acc
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, W, D), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      draft_len.astype(jnp.int32), q4, k_pool, v_pool)
    return out.transpose(0, 2, 1, 3)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _ragged_verify_pallas_q(q, k_pool, v_pool, page_table, lengths,
                            draft_len, k_scale, v_scale, scale,
                            interpret):
    """Quantized-pool verify kernel: per-page scales in the
    scalar-prefetch set, dequant at the DMA boundary (see
    ``_ragged_verify_kernel``)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, W, H, D = q.shape
    page_size = k_pool.shape[2]
    n_pages = page_table.shape[1]
    q4 = q.transpose(0, 2, 1, 3)                # (S, H, W, D)

    def kernel(pt_ref, ln_ref, dl_ref, ks_ref, vs_ref, *rest):
        _ragged_verify_kernel(pt_ref, ln_ref, dl_ref, *rest,
                              scale=scale, page_size=page_size,
                              n_pages=n_pages, heads=H, window=W,
                              ks_ref=ks_ref, vs_ref=vs_ref)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,  # page_table, lengths, draft_len, scales
        grid=(S, n_pages),
        in_specs=[
            pl.BlockSpec((1, H, W, D),
                         lambda s, j, pt, ln, dl, ks, vs:
                         (s, 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda s, j, pt, ln, dl, ks, vs:
                         (pt[s, j], 0, 0, 0)),
            pl.BlockSpec((1, H, page_size, D),
                         lambda s, j, pt, ln, dl, ks, vs:
                         (pt[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, W, D),
                               lambda s, j, pt, ln, dl, ks, vs:
                               (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, W), jnp.float32),        # m
            pltpu.VMEM((H, W), jnp.float32),        # l
            pltpu.VMEM((H, W, D), jnp.float32),     # acc
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, W, D), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      draft_len.astype(jnp.int32), k_scale.astype(jnp.float32),
      v_scale.astype(jnp.float32), q4, k_pool, v_pool)
    return out.transpose(0, 2, 1, 3)


def ragged_verify_reference(q, k_pool, v_pool, page_table, lengths,
                            scale=None, k_scale=None, v_scale=None):
    """Pure-jnp verify path: one ``ragged_attention_reference`` call
    per query offset — query row r of slot s attends
    ``lengths[s] + r`` keys (0 for dead slots). DELIBERATELY a loop of
    the decode reference at identical per-call shapes rather than a
    wider einsum: on the CPU serving path each verify position then
    reproduces the single-query decode numerics BITWISE, which is what
    the engine's greedy speculative-vs-sequential token parity rests
    on. The expensive part — the pool gather — is row-INDEPENDENT, so
    it runs ONCE and all W rows share the window (the values each row
    sees are identical to a fresh gather, so per-row numerics are
    unchanged); only the cheap mask + softmax + einsums repeat per
    row. This keeps the zero-agreement floor of the W-wide verify
    program near the single-query decode program's cost instead of
    W x it."""
    W = q.shape[1]
    D = q.shape[-1]
    sc = D ** -0.5 if scale is None else scale
    lengths = lengths.astype(jnp.int32)
    k = _gather_window(k_pool, page_table, k_scale)
    v = _gather_window(v_pool, page_table, v_scale)
    outs = []
    for r in range(W):
        lr = jnp.where(lengths > 0, lengths + r, 0)
        outs.append(_reference_core(q[:, r], k, v, lr, sc))
    return jnp.stack(outs, axis=1)


def ragged_verify_attention(q, k_pool, v_pool, page_table, lengths,
                            draft_len=None, scale=None, interpret=None,
                            k_scale=None, v_scale=None):
    """Multi-query decode (speculative verify) attention: W queries per
    slot — row 0 is the ordinary decode query at position
    ``lengths[s] - 1``, row r sits at position ``lengths[s] - 1 + r``
    and attends the slot's paged prefix plus the causal intra-window
    part (keys ``[0, lengths[s] - 1 + r]``). q: (S, W, H, D);
    k_pool/v_pool: (num_pages, H, page_size, D); page_table:
    (S, max_pages) int32 (dead entries 0 = null page); lengths: (S,)
    int32 = keys visible to row 0, i.e. the slot's pre-step KV length
    PLUS ONE for the token written this step (0 = dead slot → exactly
    zero output, the masked-row contract). Returns (S, W, H, D).

    PRECONDITION (the engine's contract): K/V for every position a
    LIVE row may read — [0, lengths[s] - 1 + r] for the rows whose
    output is consumed — are already scattered into the slot's pages.
    Rows past the slot's real draft window may read stale/garbage tail
    positions; their output is discarded by the caller and never
    feeds acceptance (see serve/engine.py).

    ``draft_len`` (S,) int32 gives each slot's real draft count — the
    index of its last consumed row. The Pallas kernel uses it to bound
    the V-select at the slot's freshly-written extent
    ``lengths[s] + draft_len[s]`` so stale non-finite garbage past it
    (a recycled page from a quarantined slot) cannot leak into
    consumed rows through 0-weight terms; the jnp reference is per-row
    exact and needs no bound. Default None = W - 1 for every slot
    (every window position freshly written — callers that fill the
    whole window).

    Dispatch is static (mirrors ``ragged_paged_attention``): the
    Pallas kernel on TPU or under ``MXTPU_FLASH_INTERPRET=1`` /
    ``interpret=True``; the per-position jnp reference loop otherwise
    (the CPU serving path and oracle)."""
    if interpret is None:
        interpret = os.environ.get("MXTPU_FLASH_INTERPRET") == "1"
    sc = q.shape[-1] ** -0.5 if scale is None else scale
    if draft_len is None:
        draft_len = jnp.full((q.shape[0],), q.shape[1] - 1, jnp.int32)
    if _pallas_available() and _pallas_runnable(interpret):
        if k_scale is not None:
            return _ragged_verify_pallas_q(
                q, k_pool, v_pool, page_table, lengths,
                jnp.asarray(draft_len), k_scale, v_scale, sc,
                interpret)
        return _ragged_verify_pallas(q, k_pool, v_pool, page_table,
                                     lengths, jnp.asarray(draft_len),
                                     sc, interpret)
    return ragged_verify_reference(q, k_pool, v_pool, page_table,
                                   lengths, sc, k_scale, v_scale)


def ragged_prefill_attention(q, k_pool, v_pool, page_row, q_start,
                             n_real=None, scale=None, interpret=None,
                             k_scale=None, v_scale=None):
    """Chunked-prefill attention for ONE slot: C chunk queries at
    absolute positions ``q_start + i`` attend the slot's paged prefix
    plus the causal intra-chunk part. q: (C, H, D); k_pool/v_pool:
    (num_pages, H, page_size, D); page_row: (max_pages,) int32 (dead
    entries 0 = null page); q_start: scalar int32; n_real: live queries
    (trailing padded rows emit garbage the caller discards — defaults
    to C). Returns (C, H, D).

    PRECONDITION (the engine's contract): the chunk's own K/V rows are
    already scattered into the slot's pages, and every page covering
    positions [0, q_start + n_real) is live. Dispatch is static
    (mirrors ``ragged_paged_attention``): the Pallas kernel on TPU or
    under ``MXTPU_FLASH_INTERPRET=1`` / ``interpret=True``; the jnp
    gather reference otherwise (the CPU serving path)."""
    if interpret is None:
        interpret = os.environ.get("MXTPU_FLASH_INTERPRET") == "1"
    sc = q.shape[-1] ** -0.5 if scale is None else scale
    if n_real is None:
        n_real = q.shape[0]
    if _pallas_available() and _pallas_runnable(interpret):
        qinfo = jnp.stack([jnp.asarray(q_start, jnp.int32),
                           jnp.asarray(n_real, jnp.int32)])
        if k_scale is not None:
            return _ragged_prefill_pallas_q(q, k_pool, v_pool,
                                            page_row, qinfo, k_scale,
                                            v_scale, sc, interpret)
        return _ragged_prefill_pallas(q, k_pool, v_pool, page_row,
                                      qinfo, sc, interpret)
    return ragged_prefill_reference(q, k_pool, v_pool, page_row,
                                    q_start, sc, n_real=n_real,
                                    k_scale=k_scale, v_scale=v_scale)
