"""Multi-host (2-process) execution test (VERDICT r2 next-round #6).

Launches tests/dist_worker.py through tools/launch.py --launcher local —
the TPU-native mirror of the reference's
tests/nightly/dist_sync_kvstore.py CI idiom: prove the distributed
kvstore and the fused SPMD step on one box with real separate processes
(jax.distributed over a 2x4-virtual-device CPU mesh)."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# jax's CPU backend only grew multiprocess collectives (the cross-process
# device_put/assert_equal these workers hit inside SPMDTrainer) after the
# 0.4 series; on older jax the workers die with "Multiprocess computations
# aren't implemented on the CPU backend" regardless of framework code.
_jax_ver = tuple(int(x) for x in __import__("jax").__version__.split(".")[:2])
_needs_mp_cpu = pytest.mark.skipif(
    _jax_ver < (0, 5),
    reason="jax<0.5 CPU backend lacks multiprocess collectives")


@_needs_mp_cpu
def test_two_process_dist_sync_and_spmd_step():
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the launcher must not inherit the single-process test mesh flags
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    for attempt in range(2):  # coordinator port/races under load: 1 retry
        r = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
             "-n", "2", "--launcher", "local", "--",
             sys.executable, os.path.join(_REPO, "tests",
                                          "dist_worker.py")],
            capture_output=True, text=True, timeout=540, env=env,
            cwd=_REPO)
        if r.returncode == 0:
            break
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    # both workers share the launcher's stdout pipe: concurrent writes can
    # interleave on one line, so count occurrences, not lines
    oks = r.stdout.count("DIST_WORKER_OK")
    assert oks == 2, f"expected 2 worker OK markers, got: {r.stdout}"


@_needs_mp_cpu
def test_four_process_tp_fsdp_mesh_crosses_process_boundaries():
    """P=4 x 2 virtual devices: dp2 x fsdp2 x tp2 mesh whose dp/fsdp
    axes span process boundaries (VERDICT r3 #7). Asserts all ranks
    agree on loss + params AND that the distributed trajectory equals
    the single-process 8-device run of the identical program."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    for attempt in range(2):
        r = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
             "-n", "4", "--launcher", "local", "--",
             sys.executable, os.path.join(_REPO, "tests",
                                          "dist_worker_p4.py")],
            capture_output=True, text=True, timeout=540, env=env,
            cwd=_REPO)
        if r.returncode == 0:
            break
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    oks = r.stdout.count("DIST4_WORKER_OK")
    assert oks == 4, f"expected 4 worker OK markers, got: {r.stdout}"

    import re
    losses = [float(m) for m in re.findall(r"DIST4_LOSS ([0-9.]+)",
                                           r.stdout)]
    assert len(losses) == 4 and max(losses) - min(losses) < 1e-6, losses

    # single-process reference on this process's own 8 virtual devices
    # (conftest set xla_force_host_platform_device_count=8): identical
    # seed/mesh-shape/data must give the same loss. Initialize THIS
    # process's backend first — the worker module re-exports a 2-device
    # XLA_FLAGS at import, which must not win the lazy jax init race.
    import jax
    assert len(jax.devices()) == 8
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "dist_worker_p4_ref", os.path.join(_REPO, "tests",
                                           "dist_worker_p4.py"))
    mod = importlib.util.module_from_spec(spec)
    # the worker module sets 2-device env vars at import for its
    # subprocess role — restore this process's env so later tests that
    # spawn subprocesses inherit the 8-device test configuration
    saved = {k: os.environ.get(k) for k in ("JAX_PLATFORMS", "XLA_FLAGS")}
    try:
        spec.loader.exec_module(mod)
        _, _, ref_loss = mod.build_and_train()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert abs(ref_loss - losses[0]) < 1e-5, (ref_loss, losses[0])
