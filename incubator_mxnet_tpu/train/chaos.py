"""Deterministic fault injection (chaos) for the TRAINING loop.

The serving chaos harness (serve/chaos.py, round 10) made serving
faults injectable, seeded and reproducible; this is the training twin.
Long preemptible-TPU pretraining dies from a different fault family: a
bad batch or diverging weights putting a NaN in one gradient (which an
unguarded fused step bakes into EVERY parameter forever), an fp16
loss-scale overflow storm, a ``kill -9`` preemption mid-step, a wedged
step that hangs the run, and a flaky data pipeline. Each injector
models one of those, fires at a deterministic STEP INDEX (not wall
time), and draws all randomness from its own seeded ``RandomState`` —
so ``tools/train_chaos_bench.py`` (ci/run.sh ``trainchaos`` stage) can
assert the training resilience contract instead of hoping:

  - every step ends in exactly one recorded ``StepOutcome``;
  - a skipped step leaves params/optimizer state BIT-IDENTICAL;
  - the loss scale halves under overflow and regrows when clean;
  - the fused step compiles exactly once across fault transitions;
  - a killed run resumes to a bit-exact loss sequence (supervisor).

Hooks (drive them from any loop; ``run_train_chaos`` is the canonical
eager-Trainer loop both the bench and tests use):

  ``on_step_begin(step_idx, trainer)``   before forward
  ``on_batch(step_idx, arrays) -> arrays``  corrupt the input batch
  ``on_grads(step_idx, trainer)``        after backward, before step()
"""

from __future__ import annotations

import os
import signal as _signal
import time
from typing import List, Optional, Sequence

import numpy as np

from ..base import MXNetError

__all__ = ["TrainChaosInjector", "NaNGrad", "OverflowStorm", "NaNBatch",
           "SlowStep", "KillSelf", "run_train_chaos"]


class TrainChaosInjector:
    """Base: a seeded training fault with an injection log."""

    name = "train_chaos"

    def __init__(self, seed: int = 0):
        self.rng = np.random.RandomState(seed)
        self.log: List[str] = []
        self.fired = False

    def on_step_begin(self, step_idx: int, trainer) -> None:
        pass

    def on_batch(self, step_idx: int, arrays):
        return arrays

    def on_grads(self, step_idx: int, trainer) -> None:
        pass


def _poison_grad(param, n_entries: int, rng, value=np.nan) -> int:
    """Overwrite ``n_entries`` random entries of ``param``'s gradient
    with ``value`` (host round-trip — chaos is off the hot path)."""
    import jax.numpy as jnp
    g = param.grad()
    arr = np.asarray(g._data).copy()
    flat = arr.reshape(-1)
    idx = rng.choice(flat.size, size=min(n_entries, flat.size),
                     replace=False)
    flat[idx] = value
    g._data = jnp.asarray(arr)
    return len(idx)


class NaNGrad(TrainChaosInjector):
    """Poison one parameter's gradient with NaN at step ``at_step`` —
    the 'bad batch / numerics bug produced a NaN gradient' fault. The
    guard must skip exactly that step with every parameter and
    optimizer-state leaf bit-identical to before it."""

    name = "nan_grad"

    def __init__(self, at_step: int, n_entries: int = 2,
                 param_idx: int = 0, seed: int = 0):
        super().__init__(seed)
        self.at_step = at_step
        self.n_entries = n_entries
        self.param_idx = param_idx

    def on_grads(self, step_idx, trainer):
        if self.fired or step_idx < self.at_step:
            return
        self.fired = True
        params = [p for p in trainer._params if p.grad_req != "null"]
        p = params[self.param_idx % len(params)]
        n = _poison_grad(p, self.n_entries, self.rng)
        self.log.append(f"step {step_idx}: NaN-poisoned {n} entries of "
                        f"grad({p.name})")


class OverflowStorm(TrainChaosInjector):
    """Scale-dependent overflow: from ``at_step`` on, gradients go Inf
    WHILE the trainer's loss scale is above ``overflow_above`` — the
    'fp16 dynamic range exceeded' fault. The scaler must halve its way
    below the threshold (each halving costs one skipped step), then the
    run must go clean and, after ``scale_window`` clean steps, regrow."""

    name = "overflow_storm"

    def __init__(self, at_step: int, overflow_above: float,
                 seed: int = 0):
        super().__init__(seed)
        self.at_step = at_step
        self.overflow_above = float(overflow_above)
        self.overflow_steps = 0

    def on_grads(self, step_idx, trainer):
        if step_idx < self.at_step:
            return
        scaler = getattr(trainer, "_amp_loss_scaler", None) or \
            getattr(trainer, "loss_scaler", None)
        if scaler is None:
            raise MXNetError("OverflowStorm needs a trainer with a "
                             "LossScaler attached")
        if scaler.loss_scale > self.overflow_above:
            self.fired = True
            self.overflow_steps += 1
            params = [p for p in trainer._params if p.grad_req != "null"]
            n = _poison_grad(params[0], 1, self.rng, value=np.inf)
            self.log.append(
                f"step {step_idx}: overflow (scale "
                f"{scaler.loss_scale:g} > {self.overflow_above:g}), "
                f"{n} Inf entries")


class NaNBatch(TrainChaosInjector):
    """Corrupt the input batch with NaN at step ``at_step`` — the
    SPMD-path fault (gradients live inside the fused program, so the
    fault enters through the data). The in-program guard must skip the
    step on every rank."""

    name = "nan_batch"

    def __init__(self, at_step: int, n_entries: int = 4, seed: int = 0):
        super().__init__(seed)
        self.at_step = at_step
        self.n_entries = n_entries

    def on_batch(self, step_idx, arrays):
        if self.fired or step_idx < self.at_step:
            return arrays
        self.fired = True
        out = []
        poisoned = False
        for a in arrays:
            arr = np.asarray(a, dtype=None).copy()
            if not poisoned and np.issubdtype(arr.dtype, np.floating):
                flat = arr.reshape(-1)
                idx = self.rng.choice(
                    flat.size, size=min(self.n_entries, flat.size),
                    replace=False)
                flat[idx] = np.nan
                poisoned = True
            out.append(arr)
        if not poisoned:
            raise MXNetError("NaNBatch found no float array to poison")
        self.log.append(f"step {step_idx}: NaN-poisoned the batch")
        return out


class SlowStep(TrainChaosInjector):
    """Host stall: sleep ``sleep_s`` before steps in [start, end) —
    models a preempted host / GC storm. Long enough, it drives the
    supervisor's zero-progress watchdog."""

    name = "slow_step"

    def __init__(self, start: int, end: int, sleep_s: float,
                 seed: int = 0):
        super().__init__(seed)
        self.start = start
        self.end = end
        self.sleep_s = sleep_s

    def on_step_begin(self, step_idx, trainer):
        if self.start <= step_idx < self.end:
            self.fired = True
            time.sleep(self.sleep_s)


class KillSelf(TrainChaosInjector):
    """``kill -9`` the CURRENT process at step ``at_step`` — the
    preemption / OOM-kill fault, for use inside a supervised training
    SUBPROCESS (tools/train_chaos_bench.py kill9 scenario). Guarded by
    a marker file so the fault fires only once across restarts."""

    name = "kill_self"

    def __init__(self, at_step: int, marker: Optional[str] = None,
                 sig: int = _signal.SIGKILL, seed: int = 0):
        super().__init__(seed)
        self.at_step = at_step
        self.marker = marker
        self.sig = sig

    def on_step_begin(self, step_idx, trainer):
        if step_idx < self.at_step:
            return
        if self.marker is not None:
            if os.path.exists(self.marker):
                return                   # already fired in a past life
            with open(self.marker, "w") as f:
                f.write(f"killed at step {step_idx}\n")
        self.fired = True
        os.kill(os.getpid(), self.sig)


# --------------------------------------------------------------------- #
def run_train_chaos(net, trainer, loss_fn, data, steps: int,
                    injectors: Sequence[TrainChaosInjector] = (),
                    batch_size: Optional[int] = None):
    """The canonical eager-Trainer chaos loop: fixed data, ``steps``
    steps, injectors firing at their hooks, exactly-one-outcome-per-step
    asserted after every step. Returns ``(losses, outcomes)`` — the
    per-step UNSCALED loss and recorded ``StepOutcome`` sequences (the
    parity oracle: unfaulted steps must match a fault-free run's
    bit-exactly)."""
    from .. import autograd, nd

    X, y = data
    bs = batch_size if batch_size is not None else int(X.shape[0])
    losses, outcomes = [], []
    for s in range(steps):
        for inj in injectors:
            inj.on_step_begin(s, trainer)
        arrays = [X, y]
        for inj in injectors:
            arrays = inj.on_batch(s, arrays)
        xb = nd.array(np.asarray(arrays[0]))
        yb = nd.array(np.asarray(arrays[1]))
        with autograd.record():
            L = loss_fn(net(xb), yb).mean()
        trainer.backward(L)   # dynamic scale rides the backward seed
        for inj in injectors:
            inj.on_grads(s, trainer)
        before = trainer._recorder.step_count
        trainer.step(bs)
        if trainer._recorder.step_count != before + 1:
            raise MXNetError(
                f"step {s} recorded {trainer._recorder.step_count - before}"
                f" outcomes — exactly-one-outcome-per-step violated")
        losses.append(float(np.asarray(L._data)))
        outcomes.append(trainer.last_outcome)
    return losses, outcomes
