"""Attention primitives.

Parity target: the reference's fused BERT attention kernels
(`src/operator/contrib/transformer.cc` — ``interleaved_matmul_selfatt_qk`` /
``_valatt`` and the masked softmax they feed; file-level citation, SURVEY.md
caveat §5.7). Those are hand-written CUDA GEMM+softmax fusions; here ONE
pure function expresses the whole attention block and XLA fuses it onto the
MXU. ``flash=True`` switches to a blockwise streaming-softmax evaluation
(O(T·block) score memory) — the slot a Pallas kernel plugs into; the same
recurrence is what ring attention (parallel/ring_attention.py) runs per
sequence shard.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from ..base import MXNetError

_NEG_INF = -1e30


def _sdpa_dense(q, k, v, mask, scale):
    """(B,T,H,D) attention, materializing the (B,H,Tq,Tk) score matrix."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    probs = probs.astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _sdpa_blockwise(q, k, v, key_mask, causal, scale, block_k: int = 512):
    """Streaming-softmax over key blocks (the flash-attention recurrence).

    q: (B,Tq,H,D); k/v: (B,Tk,H,D); key_mask: (B,Tk) bool or None.
    Never materializes more than (B,H,Tq,block_k) scores.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    block_k = min(block_k, Tk)
    pk = (-Tk) % block_k
    if key_mask is None:
        key_mask = jnp.ones((B, Tk), bool)
    if pk:
        k = jnp.pad(k, ((0, 0), (0, pk), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pk), (0, 0), (0, 0)))
        key_mask = jnp.pad(key_mask, ((0, 0), (0, pk)))
    nk = (Tk + pk) // block_k

    # operands keep the input dtype (bf16 -> full-rate MXU); scores and
    # the streaming statistics accumulate in f32, and the scale is
    # applied to the f32 scores (scaling a bf16 q would round it)
    qf = q
    k_blocks = jnp.moveaxis(k.reshape(B, nk, block_k, H, D), 1, 0)
    v_blocks = jnp.moveaxis(v.reshape(B, nk, block_k, H, D), 1, 0)
    m_blocks = jnp.moveaxis(key_mask.reshape(B, nk, block_k), 1, 0)

    pos_q = jnp.arange(Tq)

    acc0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    max0 = jnp.full((B, Tq, H), _NEG_INF, jnp.float32)
    sum0 = jnp.zeros((B, Tq, H), jnp.float32)

    def body(carry, inp):
        acc, row_max, row_sum = carry
        blk_idx, k_blk, v_blk, m_blk = inp
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk,
                       preferred_element_type=jnp.float32) * scale
        allow = m_blk[:, None, None, :]                       # (B,1,1,block)
        if causal:
            # bottom-right aligned for Tq != Tk (KV-cache convention):
            # query i sees keys [0, Tk-Tq+i]
            pos_k = blk_idx * block_k + jnp.arange(block_k)
            allow = jnp.logical_and(
                allow,
                (pos_k[None, :] <= pos_q[:, None] + (Tk - Tq))[None, None])
        s = jnp.where(allow, s, _NEG_INF)
        blk_max = jnp.moveaxis(s.max(axis=-1), 1, -1)         # (B,Tq,H)
        new_max = jnp.maximum(row_max, blk_max)
        corr = jnp.exp(row_max - new_max)
        p = jnp.exp(s - jnp.moveaxis(new_max, -1, 1)[..., None])
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        row_sum = row_sum * corr + jnp.moveaxis(p.sum(-1), 1, -1)
        return (acc, new_max, row_sum), None

    (acc, row_max, row_sum), _ = lax.scan(
        body, (acc0, max0, sum0),
        (jnp.arange(nk), k_blocks, v_blocks, m_blocks))
    out = acc / jnp.maximum(row_sum[..., None], 1e-30)
    # fully-masked rows (all-False key mask): row_max never left
    # _NEG_INF, so p was uniformly 1 and out is the mean of V — zero
    # them instead (same contract as the Pallas kernels)
    out = jnp.where((row_max > _NEG_INF / 2)[..., None], out, 0.0)
    return out.astype(q.dtype)


@register("scaled_dot_product_attention", aliases=("sdpa",))
def scaled_dot_product_attention(q, k, v, mask=None, scale=None,
                                 causal=False, flash=False,
                                 valid_length=None, layout="bthd"):
    """Multi-head attention core. q/k/v: (B, T, H, D). ``mask`` is either a
    key-padding mask (B, Tk) or broadcastable to (B, H, Tq, Tk), True =
    attend. Returns (B, Tq, H, D). ``flash=True`` uses the blockwise
    streaming evaluation (key-padding/causal masks only).

    ``layout="bhtd"`` (flash only): q/k/v and the result are
    (B, H, T, D) — the Pallas kernels' native layout. Callers that
    produce a packed (3, B, H, T, D) projection (the transformer cells'
    perf path, mirroring the rationale of the reference's interleaved
    QKV layout in src/operator/contrib/transformer.cc) avoid the
    per-tensor relayout transposes around every kernel call.

    ``valid_length`` (B,) key lengths: the TPU Pallas kernel needs the
    mask in LENGTH form — a (B, Tk) boolean ``mask`` alone sends the
    flash path to the jnp fallback (a boolean mask cannot be converted
    back to lengths under jit), so length-mask callers should pass this
    through for the real kernel to engage. When BOTH ``mask`` and
    ``valid_length`` are given they must describe the same keep-set
    (the kernel uses the lengths, other paths AND the two — this cannot
    be validated under jit, see use_flash_attention)."""
    D = q.shape[-1]
    if scale is None:
        scale = D ** -0.5
    if layout not in ("bthd", "bhtd"):
        raise MXNetError(f"sdpa: unknown layout {layout!r}")
    if layout == "bhtd" and not (flash and (mask is None or
                                            mask.ndim == 2)):
        raise MXNetError(
            "sdpa: layout='bhtd' is the flash-path fast layout; use the "
            "default layout for the dense/attention-weights path")
    if flash and (mask is None or mask.ndim == 2):
        # Pallas kernel on TPU (length-style masks), blockwise jnp
        # otherwise — same streaming-softmax math either way
        from .pallas_attention import use_flash_attention
        return use_flash_attention(q, k, v, key_mask=mask, causal=causal,
                                   scale=scale, valid_length=valid_length,
                                   layout=layout)
    Tq, Tk = q.shape[1], k.shape[1]
    m = mask
    if m is not None and m.ndim == 2:
        m = m[:, None, None, :]                               # key padding
    if valid_length is not None:
        # honor the length form on the dense path too (silently
        # attending padding keys would be wrong whenever the caller
        # passes lengths without a boolean mask)
        vlm = (lax.broadcasted_iota(jnp.int32, (1, 1, 1, Tk), 3) <
               valid_length.astype(jnp.int32)[:, None, None, None])
        m = vlm if m is None else jnp.logical_and(m.astype(bool), vlm)
    if causal:
        # bottom-right aligned when Tq != Tk (queries sit at the END of
        # the key buffer — the KV-cache decode convention; top-left
        # alignment would let early cached queries see future keys)
        cm = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)[None, None]
        m = cm if m is None else jnp.logical_and(m, cm)
    return _sdpa_dense(q, k, v, m, scale)


@register("masked_softmax")
def masked_softmax(scores, mask=None, axis=-1):
    """Softmax with optional boolean mask (True = keep). Parity surface for
    the reference's masked softmax in the transformer contrib ops."""
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG_INF)
    return jax.nn.softmax(scores, axis=axis)


# --------------------------------------------------------------------- #
# interleaved-projection matmul surface (reference:
# src/operator/contrib/transformer.cc interleaved_matmul_selfatt_qk /
# _valatt, interleaved_matmul_encdec_qk / _valatt, div_sqrt_dim —
# file-level citations, SURVEY.md caveat). The reference hand-writes
# strided-batched CUDA GEMMs over an interleaved (seq, batch,
# heads*3*head_dim) QKV buffer; here each op is one reshape+einsum that
# XLA lowers to a single MXU batch-matmul — same user contract, no
# layout gymnastics needed on TPU.
# --------------------------------------------------------------------- #

@register("div_sqrt_dim", aliases=("_contrib_div_sqrt_dim",))
def div_sqrt_dim(data):
    """data / sqrt(last_dim) (reference transformer.cc DivSqrtDim)."""
    return data * (data.shape[-1] ** -0.5)


def _split_interleaved(qkv, heads, parts):
    """(S, B, heads*parts*D) -> ``parts`` tensors of (B*heads, S, D)."""
    S, B = qkv.shape[0], qkv.shape[1]
    x = qkv.reshape(S, B, heads, parts, -1)
    outs = []
    for p in range(parts):
        t = x[:, :, :, p, :]                     # (S, B, H, D)
        t = t.transpose(1, 2, 0, 3).reshape(B * heads, S, -1)
        outs.append(t)
    return outs


@register("interleaved_matmul_selfatt_qk",
          aliases=("_contrib_interleaved_matmul_selfatt_qk",))
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """Scaled Q·Kᵀ over an interleaved (S, B, H*3*D) self-attention
    projection. Returns (B*H, S, S); queries pre-scaled by 1/sqrt(D)
    exactly like the reference kernel."""
    q, k, _ = _split_interleaved(queries_keys_values, heads, 3)
    q = q * (q.shape[-1] ** -0.5)
    return jnp.einsum("bqd,bkd->bqk", q, k)


@register("interleaved_matmul_selfatt_valatt",
          aliases=("_contrib_interleaved_matmul_selfatt_valatt",))
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                      heads=1):
    """attention @ V, restored to the (S, B, H*D) seq-major layout."""
    S, B = queries_keys_values.shape[0], queries_keys_values.shape[1]
    _, _, v = _split_interleaved(queries_keys_values, heads, 3)
    out = jnp.einsum("bqk,bkd->bqd", attention, v)     # (B*H, S, D)
    out = out.reshape(B, heads, S, -1).transpose(2, 0, 1, 3)
    return out.reshape(S, B, -1)


@register("interleaved_matmul_encdec_qk",
          aliases=("_contrib_interleaved_matmul_encdec_qk",))
def interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    """Scaled Q·Kᵀ for cross-attention: queries (Sq, B, H*D), interleaved
    keys/values (Sk, B, H*2*D). Returns (B*H, Sq, Sk)."""
    Sq, B = queries.shape[0], queries.shape[1]
    q = queries.reshape(Sq, B, heads, -1).transpose(1, 2, 0, 3)
    q = q.reshape(B * heads, Sq, -1)
    q = q * (q.shape[-1] ** -0.5)
    k, _ = _split_interleaved(keys_values, heads, 2)
    return jnp.einsum("bqd,bkd->bqk", q, k)


@register("interleaved_matmul_encdec_valatt",
          aliases=("_contrib_interleaved_matmul_encdec_valatt",))
def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    """attention @ V for cross-attention; output (Sq, B, H*D)."""
    B = keys_values.shape[1]
    _, v = _split_interleaved(keys_values, heads, 2)
    out = jnp.einsum("bqk,bkd->bqd", attention, v)     # (B*H, Sq, D)
    Sq = out.shape[1]
    out = out.reshape(B, heads, Sq, -1).transpose(2, 0, 1, 3)
    return out.reshape(Sq, B, -1)


# --------------------------------------------------------------------- #
# sliding-window (banded) attention surface (reference:
# src/operator/contrib/sldwin_atten*.cc — masked-window self-attention
# for Longformer-style long-context models; file-level citations,
# SURVEY.md caveat). The reference stores scores in a compact
# (B, L, H, W_len) band; on TPU a banded gather breaks MXU tiling, so the
# idiomatic mapping keeps the dense (B*H, L, L) score layout masked to
# the band — XLA fuses the mask into the matmul epilogue, and the flash /
# ring-attention path (ops/pallas_attention.py, parallel/ring_attention)
# is the scalable long-context engine. The op CONTRACT (shapes in/out,
# symmetric + dilation semantics) matches the reference.
# --------------------------------------------------------------------- #

def _sldwin_band_mask(L, w, symmetric, dilation, dtype):
    """(L, L) band mask. ``dilation`` may be a Python int OR a traced
    scalar (the reference passes it as a tensor input) — all arithmetic
    is jnp elementwise, so tracing never needs a concrete value."""
    i = lax.broadcasted_iota(jnp.int32, (L, L), 0)
    j = lax.broadcasted_iota(jnp.int32, (L, L), 1)
    d = j - i
    dil = jnp.asarray(dilation, jnp.int32).reshape(-1)[0]
    lo = -w * dil
    hi = w * dil if symmetric else 0
    band = (d >= lo) & (d <= hi) & (d % jnp.maximum(dil, 1) == 0)
    return band.astype(dtype)


@register("sldwin_atten_mask_like",
          aliases=("_contrib_sldwin_atten_mask_like",))
def sldwin_atten_mask_like(score, dilation, valid_length, num_heads=1,
                           w=1, symmetric=True):
    """Mask with ones where the banded score is valid (reference
    sldwin_atten_mask_like). score: (B*H, L, L) dense-band layout."""
    L = score.shape[-1]
    band = _sldwin_band_mask(L, int(w), bool(symmetric), dilation,
                             score.dtype)
    BH = score.shape[0]
    B = BH // num_heads
    vl = valid_length.astype(jnp.int32).reshape(B, 1)
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    keyok = (pos < vl).astype(score.dtype)          # (B, L)
    keyok = jnp.repeat(keyok, num_heads, axis=0)    # (B*H, L)
    return band[None] * keyok[:, None, :] * keyok[:, :, None]


@register("sldwin_atten_score", aliases=("_contrib_sldwin_atten_score",))
def sldwin_atten_score(query, key, dilation, num_heads=1, w=1,
                       symmetric=True):
    """Banded Q·Kᵀ. query/key: (B, L, H*D) → (B*H, L, L) scores with
    out-of-band entries zeroed (reference sldwin_atten_score)."""
    B, L, HD = query.shape
    D = HD // num_heads
    q = query.reshape(B, L, num_heads, D).transpose(0, 2, 1, 3)
    k = key.reshape(B, L, num_heads, D).transpose(0, 2, 1, 3)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).reshape(
        B * num_heads, L, L)
    band = _sldwin_band_mask(L, int(w), bool(symmetric), dilation,
                             scores.dtype)
    return scores * band[None]


@register("sldwin_atten_context",
          aliases=("_contrib_sldwin_atten_context",))
def sldwin_atten_context(score, value, dilation, num_heads=1, w=1,
                         symmetric=True):
    """attention @ V over the band. score: (B*H, L, L); value:
    (B, L, H*D) → (B, L, H*D) (reference sldwin_atten_context)."""
    BH, L, _ = score.shape
    B = BH // num_heads
    D = value.shape[-1] // num_heads
    band = _sldwin_band_mask(L, int(w), bool(symmetric), dilation,
                             score.dtype)
    s = (score * band[None]).reshape(B, num_heads, L, L)
    v = value.reshape(B, L, num_heads, D).transpose(0, 2, 1, 3)
    out = jnp.einsum("bhqk,bhkd->bhqd", s, v)
    return out.transpose(0, 2, 1, 3).reshape(B, L, num_heads * D)
