"""Pass 5 — lock discipline for classes that own background threads.

A class that starts a ``threading.Thread`` has two execution contexts
touching its attributes: the thread target (and everything it calls)
and the ordinary methods. The repo's contract (CheckpointManager is
the template): such a class designates lock attributes
(``self._lock = threading.Lock()/RLock()`` / a ``Condition``), and
every WRITE to instance state from the thread context — and every
main-side write to state the thread context touches — happens inside
``with self.<lock>:`` or carries a waiver saying why it is safe
(happens-before via start/join, a monotonic stat read torn at worst,
…). ``__init__`` writes are exempt (construction happens-before the
thread starts), as are the lock/thread attributes themselves.

A class with a thread and NO lock gets every thread-context attribute
write flagged: that is the PR-8/PR-9 class of bug (stat counters and
completion flags racing between a writer thread and the step loop).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from ..core import Finding, Project, SourceUnit, dotted, parent, \
    qualname_of

RULE = "lock-discipline"

_SCOPE = "incubator_mxnet_tpu/"
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
_EXEMPT_ATTRS = {"_thread", "_threads"}


def _lock_factory_name(call: ast.AST,
                       unit: SourceUnit) -> Optional[str]:
    if not isinstance(call, ast.Call):
        return None
    d = dotted(call.func) or ""
    parts = d.split(".")
    tail = parts[-1]
    if tail not in _LOCK_FACTORIES:
        return None
    if len(parts) == 1:
        # bare Lock()/Condition(): honest if imported from threading,
        # or (function-local `import threading as X` aliases make the
        # import table incomplete) accepted as-is — a false lock attr
        # only ever SUPPRESSES findings on guarded writes
        sym = unit.import_symbols.get(tail)
        return tail if sym is None or sym[0] in ("threading",
                                                 "multiprocessing") \
            else None
    head = parts[0]
    mod = unit.import_modules.get(head, head)
    if mod in ("threading", "multiprocessing") or \
            mod.startswith("threading."):
        return tail
    return None


def _thread_target(call: ast.Call, unit: SourceUnit) -> Optional[ast.AST]:
    """For ``threading.Thread(target=X)`` return the target expr."""
    d = dotted(call.func) or ""
    if not (d == "threading.Thread" or
            (d == "Thread" and
             unit.import_symbols.get("Thread", ("",))[0] == "threading")):
        return None
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    return call.args[0] if call.args else None


class _ClassModel:
    def __init__(self, cls: ast.ClassDef, unit: SourceUnit):
        self.cls = cls
        self.unit = unit
        self.methods: Dict[str, ast.AST] = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.lock_attrs: Set[str] = set()
        self.thread_targets: List[ast.AST] = []   # FunctionDef nodes
        self._scan()

    def _scan(self) -> None:
        for m in self.methods.values():
            local_defs = {n.name: n for n in ast.walk(m)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))
                          and n is not m}
            for node in ast.walk(m):
                if isinstance(node, ast.Assign):
                    lock = _lock_factory_name(node.value, self.unit)
                    if lock:
                        for t in node.targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self":
                                self.lock_attrs.add(t.attr)
                if isinstance(node, ast.Call):
                    tgt = _thread_target(node, self.unit)
                    if tgt is None:
                        continue
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self" and \
                            tgt.attr in self.methods:
                        self.thread_targets.append(
                            self.methods[tgt.attr])
                    elif isinstance(tgt, ast.Name) and \
                            tgt.id in local_defs:
                        self.thread_targets.append(local_defs[tgt.id])

    # -- thread-context closure over self-method calls ----------------- #
    def thread_context(self) -> List[ast.AST]:
        seen: Set[int] = set()
        out: List[ast.AST] = []
        work = list(self.thread_targets)
        while work:
            fn = work.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            out.append(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self" and \
                        node.func.attr in self.methods:
                    work.append(self.methods[node.func.attr])
        return out


def _under_lock(node: ast.AST, lock_attrs: Set[str]) -> bool:
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                expr = item.context_expr
                # with self._lock: / with self._cv: /
                # with self._lock.acquire_timeout(...):
                base = expr
                if isinstance(base, ast.Call):
                    base = base.func
                d = dotted(base) or ""
                parts = d.split(".")
                if len(parts) >= 2 and parts[0] == "self" and \
                        parts[1] in lock_attrs:
                    return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # stay within the function being analyzed: a `with` in a
            # CALLER does not protect the callee textually — but our
            # walk is per-function, so stop at the boundary.
            return False
        cur = parent(cur)
    return False


def _self_attr_writes(fn: ast.AST):
    for node in ast.walk(fn):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            # self.x = / self.x += / self.x[k] =
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self":
                yield node, base.attr


def _self_attr_reads(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and \
                isinstance(node.ctx, ast.Load):
            out.add(node.attr)
    return out


class LockDisciplinePass:
    name = "lock-discipline"
    rules = (RULE,)

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for unit in project.units:
            if unit.tree is None or not unit.path.startswith(_SCOPE):
                continue
            for node in ast.walk(unit.tree):
                if isinstance(node, ast.ClassDef):
                    out.extend(self._check_class(node, unit))
        return out

    def _check_class(self, cls: ast.ClassDef,
                     unit: SourceUnit) -> List[Finding]:
        model = _ClassModel(cls, unit)
        if not model.thread_targets:
            return []
        out: List[Finding] = []
        thread_fns = model.thread_context()
        thread_ids = {id(f) for f in thread_fns}
        locks = model.lock_attrs

        # attributes the thread context touches at all
        thread_attrs: Set[str] = set()
        for fn in thread_fns:
            thread_attrs |= _self_attr_reads(fn)
            thread_attrs |= {a for _, a in _self_attr_writes(fn)}
        thread_attrs -= locks | _EXEMPT_ATTRS

        # 1) writes from the thread context
        for fn in thread_fns:
            for node, attr in _self_attr_writes(fn):
                if attr in locks or attr in _EXEMPT_ATTRS:
                    continue
                if locks and _under_lock(node, locks):
                    continue
                why = ("class starts a thread but designates no lock"
                       if not locks else
                       f"not inside `with self.<{'|'.join(sorted(locks))}>`")
                out.append(Finding(
                    RULE, unit.path, node.lineno,
                    f"`self.{attr}` written from thread context "
                    f"({cls.name}) without holding the class lock — "
                    f"{why}; racing the main path",
                    symbol=qualname_of(node)))

        # 2) main-side writes to attributes the thread context touches
        in_thread_subtree = {id(n) for f in thread_fns
                             for n in ast.walk(f)}
        for name, fn in model.methods.items():
            if id(fn) in thread_ids or name == "__init__":
                continue
            for node, attr in _self_attr_writes(fn):
                if id(node) in in_thread_subtree:
                    continue        # nested thread target, handled above
                if attr not in thread_attrs:
                    continue
                if locks and _under_lock(node, locks):
                    continue
                out.append(Finding(
                    RULE, unit.path, node.lineno,
                    f"`self.{attr}` is shared with {cls.name}'s thread "
                    f"context but written on the main path without the "
                    f"class lock",
                    symbol=qualname_of(node)))
        return out
