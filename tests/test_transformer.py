"""Transformer NMT tests (BASELINE config #4: attention + beam search)."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.models.transformer import (
    TransformerModel, beam_search_translate, transformer_base)


def _tiny(src_vocab=23, tgt_vocab=19):
    return TransformerModel(src_vocab=src_vocab, tgt_vocab=tgt_vocab,
                            units=32, hidden_size=64, num_heads=4,
                            num_layers=2, max_length=64, dropout=0.0)


def test_forward_shapes():
    net = _tiny()
    net.initialize()
    src = nd.array(np.random.randint(0, 23, (2, 7)).astype(np.float32))
    tgt = nd.array(np.random.randint(0, 19, (2, 5)).astype(np.float32))
    out = net(src, tgt)
    assert out.shape == (2, 5, 19)


def test_src_padding_mask_effective():
    """Padding tokens past valid_length must not affect the output."""
    net = _tiny()
    net.initialize()
    rng = np.random.RandomState(0)
    src = rng.randint(1, 23, (1, 8)).astype(np.float32)
    tgt = rng.randint(1, 19, (1, 4)).astype(np.float32)
    vl = nd.array(np.array([5.0], np.float32))
    out1 = net(nd.array(src), nd.array(tgt), vl).asnumpy()
    src2 = src.copy()
    src2[0, 5:] = 7  # scramble padding region
    out2 = net(nd.array(src2), nd.array(tgt), vl).asnumpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-4, atol=1e-5)


def test_causal_decoder():
    """Future target tokens must not influence earlier logits."""
    net = _tiny()
    net.initialize()
    rng = np.random.RandomState(1)
    src = nd.array(rng.randint(1, 23, (1, 6)).astype(np.float32))
    tgt1 = rng.randint(1, 19, (1, 5)).astype(np.float32)
    tgt2 = tgt1.copy()
    tgt2[0, 3:] = 11  # change the future
    o1 = net(src, nd.array(tgt1)).asnumpy()
    o2 = net(src, nd.array(tgt2)).asnumpy()
    np.testing.assert_allclose(o1[:, :3], o2[:, :3], rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_training_overfits_copy_task():
    """Tiny copy task: loss must drop sharply (convergence smoke,
    reference nightly style)."""
    rng = np.random.RandomState(0)
    V = 12
    net = TransformerModel(src_vocab=V, tgt_vocab=V, units=32,
                           hidden_size=64, num_heads=4, num_layers=1,
                           max_length=32, dropout=0.0)
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 5e-3})
    src = rng.randint(2, V, (16, 6)).astype(np.float32)
    # teacher forcing: predict src shifted
    tgt_in = np.concatenate([np.ones((16, 1), np.float32), src[:, :-1]], 1)
    first = last = None
    for i in range(60):
        with autograd.record():
            logits = net(nd.array(src), nd.array(tgt_in))
            l = loss_fn(logits.reshape((-1, V)),
                        nd.array(src.reshape(-1)))
        l.backward()
        tr.step(16)
        v = float(l.mean().asnumpy())
        first = first if first is not None else v
        last = v
    assert last < 0.5 * first, (first, last)


def test_beam_search_shapes_and_order():
    net = _tiny()
    net.initialize()
    src = nd.array(np.random.RandomState(2).randint(
        1, 23, (2, 6)).astype(np.float32))
    tokens, scores = beam_search_translate(net, src, beam_size=3,
                                           max_length=8)
    assert tokens.shape == (2, 3, 8)
    s = scores.asnumpy()
    assert (np.diff(s, axis=1) <= 1e-5).all()  # best-first


def test_beam_search_greedy_consistency():
    """With beam_size=1 the top beam equals greedy argmax decoding."""
    net = _tiny()
    net.initialize()
    rng = np.random.RandomState(3)
    src = nd.array(rng.randint(1, 23, (1, 5)).astype(np.float32))
    T = 6
    tokens, _ = beam_search_translate(net, src, beam_size=1, max_length=T,
                                      bos_id=1, eos_id=2)
    got = tokens.asnumpy()[0, 0]

    # hand-rolled greedy
    memory, _ = net.encode(src)
    cur = np.full((1, T + 1), 2, np.float32)
    cur[0, 0] = 1.0
    for t in range(T):
        logits = net.decoder(nd.array(cur), memory).asnumpy()[0, t]
        nxt = int(np.argmax(logits))
        cur[0, t + 1] = nxt
        if nxt == 2:
            break
    np.testing.assert_array_equal(got[:t + 1], cur[0, 1:t + 2])


def test_transformer_base_config():
    net = transformer_base(src_vocab=100, tgt_vocab=100)
    assert net.units == 512


def test_length_guards():
    import pytest
    net = _tiny()
    net.initialize()
    src = nd.array(np.ones((1, 70), np.float32))  # > max_length 64
    tgt = nd.array(np.ones((1, 4), np.float32))
    with pytest.raises(mx.base.MXNetError):
        net(src, tgt)
    with pytest.raises(mx.base.MXNetError):
        beam_search_translate(net, nd.array(np.ones((1, 4), np.float32)),
                              max_length=64)


def test_odd_units_positional_encoding():
    from incubator_mxnet_tpu.models.transformer import _positional_encoding
    pe = _positional_encoding(10, 33)
    assert pe.shape == (10, 33)


def test_flash_attention_path_matches_dense():
    dense = _tiny()
    dense.initialize()
    flash = TransformerModel(src_vocab=23, tgt_vocab=19, units=32,
                             hidden_size=64, num_heads=4, num_layers=2,
                             max_length=64, dropout=0.0, flash=True)
    flash.initialize()
    # share params
    dp = dense.collect_params()
    fp = flash.collect_params()
    for (_, a), (_, b) in zip(sorted(dp.items()), sorted(fp.items())):
        b.set_data(a.data())
    src = nd.array(np.random.RandomState(5).randint(
        1, 23, (2, 6)).astype(np.float32))
    tgt = nd.array(np.random.RandomState(6).randint(
        1, 19, (2, 4)).astype(np.float32))
    np.testing.assert_allclose(dense(src, tgt).asnumpy(),
                               flash(src, tgt).asnumpy(),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow   # 13-21s (round-10 tier-1 budget repair); ci stage_unit runs it
def test_cached_beam_search_matches_and_rng_survives():
    """KV-cached beam search must emit exactly beam_search_translate's
    tokens/scores (plain + masked source), and the global RNG stream
    must remain usable after a fori_loop-traced decode (regression: a
    tracer used to leak into the global key via inert-dropout key
    draws)."""
    from incubator_mxnet_tpu.models.transformer import (
        TransformerModel, beam_search_translate,
        beam_search_translate_cached)

    mx.random.seed(0)
    m = TransformerModel(src_vocab=32, tgt_vocab=32, units=32,
                         hidden_size=64, num_heads=4, num_layers=2,
                         max_length=20)
    m.initialize()
    rng = np.random.RandomState(0)
    src = nd.array(rng.randint(3, 32, (2, 6)), dtype="int32")
    t1, s1 = beam_search_translate(m, src, beam_size=3, max_length=10)
    _ = nd.random.uniform(0, 1, shape=(2,)).asnumpy()  # stream intact?
    t2, s2 = beam_search_translate_cached(m, src, beam_size=3,
                                          max_length=10)
    np.testing.assert_array_equal(t1.asnumpy(), t2.asnumpy())
    np.testing.assert_allclose(s1.asnumpy(), s2.asnumpy(), rtol=1e-4)

    svl = nd.array(np.array([6, 4], np.int32))
    t3, _ = beam_search_translate(m, src, beam_size=3, max_length=10,
                                  src_valid_length=svl)
    t4, _ = beam_search_translate_cached(m, src, beam_size=3,
                                         max_length=10,
                                         src_valid_length=svl)
    np.testing.assert_array_equal(t3.asnumpy(), t4.asnumpy())
