"""Quantization operators (int8 PTQ).

Parity target: `src/operator/quantization/{quantize_v2,dequantize,
requantize,quantized_fully_connected,quantized_conv}.cc` (file-level
citations — SURVEY.md caveat).

TPU-native design: symmetric per-tensor int8; the quantized matmul runs
``lax.dot_general`` on int8 operands with ``preferred_element_type=int32``
— the MXU has a native int8 path, so this is the idiomatic analogue of
the reference's cuDNN/oneDNN int8 kernels."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register


# --------------------------------------------------------------------- #
# the ONE audited symmetric-quantization codepath, shared between these
# legacy MXNet-compat operators and the serving tier's quantized KV
# pages (serve/paged_kv.py) — the scale convention, the zero-range
# fallback, and the saturation behaviour live HERE and nowhere else.
# --------------------------------------------------------------------- #

def symmetric_scale(amax, qmax=127.0):
    """Symmetric scale from an absolute-max statistic: ``amax / qmax``,
    with the ZERO-RANGE convention ``scale = 1.0`` where ``amax <= 0``
    (an all-zero page/tensor roundtrips to exact zeros and a freshly
    reset page dequantizes its codes verbatim — never a divide-by-zero
    or a NaN). ``amax`` may be any shape (per-tensor scalar, per-page
    vector); non-finite amax propagates into the scale BY DESIGN — a
    poisoned range statistic must stay visible downstream, not be
    silently clamped (the serving guard depends on it). The zero test
    is ``amax != 0``, not ``amax > 0``: ``NaN > 0`` is False, so the
    greater-than form would quietly map a poisoned amax onto the
    benign zero-range fallback — exactly the corruption the serving
    guard exists to catch (found by the corrupt_page_scale chaos
    scenario)."""
    amax = jnp.asarray(amax, jnp.float32)
    return jnp.where(amax != 0, amax / qmax, 1.0)


def quantize_symmetric(x, scale, dtype=jnp.int8, qmax=127.0):
    """``x / scale`` rounded (integer targets) or cast (fp8 targets),
    saturated to ±qmax. ``scale`` broadcasts against ``x`` (per-tensor
    scalar or per-page column). Accepts any float input (f32/bf16 —
    math runs in f32)."""
    y = x.astype(jnp.float32) / scale
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        y = jnp.round(y)
    return jnp.clip(y, -qmax, qmax).astype(dtype)


def dequantize_symmetric(q, scale):
    """Codes → f32: ``q * scale`` (scale broadcasts)."""
    return q.astype(jnp.float32) * scale


def requantize_symmetric(q, ratio, dtype=jnp.int8, qmax=127.0):
    """Rescale existing codes in place of a dequantize→quantize round
    trip: ``round(q * ratio)`` saturated — the page-scale-growth path of
    the quantized KV pool (a page's symmetric scale only ever GROWS, so
    ``ratio = old_scale / new_scale <= 1`` and the rescale never
    saturates live payload)."""
    y = q.astype(jnp.float32) * ratio
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        y = jnp.round(y)
    return jnp.clip(y, -qmax, qmax).astype(dtype)


def _symmetric_scale(min_r, max_r, bits=8):
    qmax = float(2 ** (bits - 1) - 1)  # 127
    return symmetric_scale(jnp.maximum(jnp.abs(min_r), jnp.abs(max_r)),
                           qmax)


@register("quantize", aliases=("_contrib_quantize",), num_outputs=3)
def quantize(data, min_range, max_range, out_type="uint8"):
    """float → int with EXPLICIT input range tensors (reference:
    quantization/quantize.cc — the v1 surface; quantize_v2 below is the
    calibrated form). Returns (quantized, min_range, max_range).

    ``out_type='uint8'`` (the reference default) is AFFINE: zero point at
    round(-min/scale), scale = (max-min)/255. ``'int8'`` is symmetric."""
    min_r = jnp.asarray(min_range, jnp.float32).reshape(())
    max_r = jnp.asarray(max_range, jnp.float32).reshape(())
    if out_type == "int8":
        scale = _symmetric_scale(min_r, max_r)
        q = quantize_symmetric(data, scale)
    elif out_type == "uint8":
        scale = (max_r - min_r) / 255.0
        zero = jnp.round(-min_r / scale)
        q = jnp.clip(jnp.round(data / scale) + zero, 0, 255) \
            .astype(jnp.uint8)
    else:
        raise MXNetError(
            f"quantize: out_type must be 'uint8' or 'int8', got "
            f"{out_type!r}")
    return q, min_r, max_r


@register("quantize_v2", aliases=("_contrib_quantize_v2",), num_outputs=3)
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """float → int8 with symmetric scaling (reference: quantize_v2.cc).
    Returns (quantized, min_range, max_range). Without calib ranges the
    data's own min/max are used (the reference's on-the-fly mode)."""
    if min_calib_range is None:
        min_r = jnp.min(data)
        max_r = jnp.max(data)
    else:
        min_r = jnp.asarray(min_calib_range, jnp.float32)
        max_r = jnp.asarray(max_calib_range, jnp.float32)
    scale = _symmetric_scale(min_r, max_r)
    q = quantize_symmetric(data, scale)
    return q, min_r, max_r


@register("dequantize", aliases=("_contrib_dequantize",))
def dequantize(data, min_range, max_range, out_type="float32"):
    """int8 → float (reference: dequantize.cc)."""
    scale = _symmetric_scale(min_range, max_range)
    return dequantize_symmetric(data, scale)


@register("requantize", aliases=("_contrib_requantize",), num_outputs=3)
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator → int8 with a new scale (reference:
    requantize.cc)."""
    in_scale = _symmetric_scale(min_range, max_range, bits=32)
    if min_calib_range is None:
        real = data.astype(jnp.float32) * in_scale
        min_out, max_out = jnp.min(real), jnp.max(real)
    else:
        min_out = jnp.asarray(min_calib_range, jnp.float32)
        max_out = jnp.asarray(max_calib_range, jnp.float32)
    out_scale = _symmetric_scale(min_out, max_out)
    q = quantize_symmetric(data.astype(jnp.float32) * in_scale, out_scale)
    return q, min_out, max_out


@register("quantized_fully_connected",
          aliases=("_contrib_quantized_fully_connected",), num_outputs=3)
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias=None,
                              max_bias=None, num_hidden=None, no_bias=False):
    """int8 x int8 → int32 matmul + float bias (reference:
    quantized_fully_connected.cc). data (B, K) int8, weight (N, K) int8;
    returns (float32 out, min_out, max_out) — the float output form the
    reference uses after its dequantize fusion."""
    s_d = _symmetric_scale(min_data, max_data)
    s_w = _symmetric_scale(min_weight, max_weight)
    acc = lax.dot_general(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        (((data.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (s_d * s_w)
    if bias is not None and not no_bias:
        out = out + bias
    return out, jnp.min(out), jnp.max(out)


@register("quantized_conv", aliases=("_contrib_quantized_conv",),
          num_outputs=3)
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias=None, max_bias=None, kernel=None,
                   stride=(1, 1), pad=(0, 0), dilate=(1, 1), num_filter=None,
                   num_group=1, no_bias=False, layout="NCHW"):
    """int8 convolution with int32 accumulation (reference:
    quantized_conv.cc). NCHW data, OIHW weight."""
    s_d = _symmetric_scale(min_data, max_data)
    s_w = _symmetric_scale(min_weight, max_weight)
    if data.ndim != 4:
        raise ValueError("quantized_conv supports 2-D (NCHW) data only")
    ndim = 2
    stride = (stride,) * ndim if isinstance(stride, int) else tuple(stride)
    pad = (pad,) * ndim if isinstance(pad, int) else tuple(pad)
    dilate = (dilate,) * ndim if isinstance(dilate, int) else tuple(dilate)
    acc = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, feature_group_count=num_group,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (s_d * s_w)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * ndim)
    return out, jnp.min(out), jnp.max(out)
