"""Sparse NDArray storage types: ``row_sparse`` and ``csr``.

Parity target: the reference's sparse storage (`include/mxnet/ndarray.h`
storage types, `src/operator/tensor/cast_storage-inl.h`, sparse dot in
`src/operator/tensor/dot-inl.h`, python surface
`python/mxnet/ndarray/sparse.py` — file-level citations, SURVEY.md caveat).

TPU-native design (SURVEY.md §7.2 "row_sparse"): XLA has no sparse
tensors — the idiomatic TPU mapping is *dense gather/scatter over the
active-row index set*. These classes therefore keep the reference's
storage contract (indices/data components, ``stype``, ``retain``,
``cast_storage``, sparse ``dot``) as the API, materialize a dense mirror
for compute interop, and guarantee the part that matters for performance:
**optimizer updates and KVStore pulls touch only the active rows**
(optimizer.py lazy updates, kvstore.row_sparse_pull)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _as_jax, _to_jnp_dtype

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "cast_storage", "retain",
           "zeros", "array", "dot"]


class BaseSparseNDArray(NDArray):
    """Common surface for sparse storage types."""

    __slots__ = ()

    @property
    def stype(self):
        raise NotImplementedError

    def tostype(self, stype):
        if stype == self.stype:
            return self
        if stype == "default":
            return NDArray(self._data)
        return cast_storage(NDArray(self._data), stype)

    def __repr__(self):
        return (f"\n<{type(self).__name__} {self.shape} "
                f"nnz={self.nnz}>")


class RowSparseNDArray(BaseSparseNDArray):
    """Rows at ``indices`` hold ``data``; all other rows are zero
    (parity: mx.nd.sparse.RowSparseNDArray)."""

    __slots__ = ("_sp_indices", "_sp_values")

    def __init__(self, data, indices, shape):
        values = _as_jax(data)
        idx = _as_jax(indices).astype(jnp.int32)
        shape = tuple(shape)
        if values.shape[0] != idx.shape[0]:
            raise MXNetError(
                f"row_sparse: {values.shape[0]} value rows vs "
                f"{idx.shape[0]} indices")
        if values.ndim != len(shape) or values.shape[1:] != shape[1:]:
            raise MXNetError(
                f"row_sparse: value row shape {values.shape[1:]} does not "
                f"match array shape {shape}")
        order = jnp.argsort(idx)
        self._sp_indices = idx[order]
        self._sp_values = values[order]
        dense = jnp.zeros(shape, values.dtype).at[self._sp_indices].set(
            self._sp_values)
        super().__init__(dense)

    @classmethod
    def _from_sorted(cls, values, indices, shape, dense=None):
        """Internal fast path: indices already sorted+unique; reuse an
        existing dense mirror instead of re-scattering (hot path for
        dense-grad → row_sparse conversion in Trainer)."""
        obj = object.__new__(cls)
        NDArray.__init__(obj, dense if dense is not None else
                         jnp.zeros(tuple(shape), values.dtype)
                         .at[indices].set(values))
        obj._sp_indices = indices
        obj._sp_values = values
        return obj

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return NDArray(self._sp_indices)

    @property
    def data(self) -> NDArray:
        return NDArray(self._sp_values)

    @property
    def nnz(self):
        return int(self._sp_indices.shape[0])

    def retain(self, indices):
        return retain(self, indices)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (parity: mx.nd.sparse.CSRNDArray)."""

    __slots__ = ("_sp_data", "_sp_indices", "_sp_indptr")

    def __init__(self, data, indices, indptr, shape):
        vals = _as_jax(data)
        idx = _as_jax(indices).astype(jnp.int32)
        ptr = _as_jax(indptr).astype(jnp.int32)
        shape = tuple(shape)
        if len(shape) != 2:
            raise MXNetError("csr arrays must be 2-D")
        if ptr.shape[0] != shape[0] + 1:
            raise MXNetError(
                f"csr: indptr length {ptr.shape[0]} != rows+1 "
                f"{shape[0] + 1}")
        self._sp_data = vals
        self._sp_indices = idx
        self._sp_indptr = ptr
        counts = _np.diff(_np.asarray(ptr))
        rows = _np.repeat(_np.arange(shape[0]), counts)
        dense = jnp.zeros(shape, vals.dtype).at[
            jnp.asarray(rows), idx].add(vals)
        super().__init__(dense)

    @property
    def stype(self):
        return "csr"

    @property
    def data(self) -> NDArray:
        return NDArray(self._sp_data)

    @property
    def indices(self) -> NDArray:
        return NDArray(self._sp_indices)

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._sp_indptr)

    @property
    def nnz(self):
        return int(self._sp_data.shape[0])


# ------------------------------------------------------------------ #
# factories (parity: mx.nd.sparse.*)
# ------------------------------------------------------------------ #
def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray from (data, indices) or a dense source."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        if shape is None:
            raise MXNetError("row_sparse_array((data, indices)) needs shape")
        data = _as_jax(data, dtype=dtype or "float32")
        return RowSparseNDArray(data, indices, shape)
    dense = _as_jax(arg1, dtype=dtype)
    return cast_storage(NDArray(dense), "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray from (data, indices, indptr) or dense/scipy."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXNetError(
                "csr_matrix((data, indices, indptr)) needs shape")
        data = _as_jax(data, dtype=dtype or "float32")
        return CSRNDArray(data, indices, indptr, shape)
    dense = _as_jax(arg1, dtype=dtype)
    return cast_storage(NDArray(dense), "csr")


def zeros(stype, shape, ctx=None, dtype="float32"):
    dt = _to_jnp_dtype(dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]), dt),
                                jnp.zeros((0,), jnp.int32), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dt), jnp.zeros((0,), jnp.int32),
                          jnp.zeros((shape[0] + 1,), jnp.int32), shape)
    if stype == "default":
        return NDArray(jnp.zeros(tuple(shape), dt))
    raise MXNetError(f"unknown storage type {stype!r}")


def array(source, ctx=None, dtype=None):
    """Sparse-aware mx.nd.sparse.array: preserves the input's stype."""
    if isinstance(source, BaseSparseNDArray):
        return source
    try:  # scipy sparse support (reference accepts scipy.sparse.csr)
        import scipy.sparse as sps
        if sps.issparse(source):
            csr = source.tocsr()
            return CSRNDArray(csr.data, csr.indices, csr.indptr, csr.shape)
    except ImportError:
        pass
    return cast_storage(NDArray(_as_jax(source, dtype=dtype)), "csr")


def cast_storage(arr, stype):
    """Convert between storage types (reference: cast_storage op).
    Note: finding the nonzero structure of a dense array is data-dependent
    → this op synchronizes to host (eager-only, like the reference's)."""
    if isinstance(arr, BaseSparseNDArray):
        arr = NDArray(arr._data)
    if stype == "default":
        return NDArray(arr._data)
    if stype == "row_sparse":
        # device-side row mask; only the (rows,) bool vector crosses to
        # host, and the existing dense array IS the mirror — no scatter
        g = arr._data
        mask = _np.asarray(jnp.any(g.reshape(g.shape[0], -1) != 0, axis=1))
        rows = jnp.asarray(_np.nonzero(mask)[0].astype(_np.int32))
        return RowSparseNDArray._from_sorted(g[rows], rows, g.shape,
                                             dense=g)
    dense = _np.asarray(arr._data)
    if stype == "csr":
        if dense.ndim != 2:
            raise MXNetError("csr arrays must be 2-D")
        rows, cols = _np.nonzero(dense)
        data = dense[rows, cols]
        indptr = _np.zeros(dense.shape[0] + 1, _np.int32)
        _np.add.at(indptr, rows + 1, 1)
        indptr = _np.cumsum(indptr)
        return CSRNDArray(data, cols, indptr, dense.shape)
    raise MXNetError(f"unknown storage type {stype!r}")


def retain(rsp: RowSparseNDArray, indices):
    """Keep only the requested rows (reference: _retain op; the KVStore
    row_sparse_pull building block).

    TPU design: fully device-side (sort + searchsorted + masked gather),
    no host round-trip — this sits on the row_sparse_pull hot path.
    ``indices`` is sorted device-side to keep the RowSparseNDArray
    sorted-indices invariant. Documented divergence from the reference
    ``_retain``: requested rows absent from ``rsp`` come back as explicit
    zero rows (so ``nnz`` counts requested rows, not surviving rows) —
    semantically identical as a sparse array, and shape-static for XLA."""
    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    want = jnp.sort(_as_jax(indices).astype(jnp.int32))
    if rsp.nnz == 0 or want.shape[0] == 0:
        row_shape = tuple(rsp.shape[1:])
        return RowSparseNDArray(
            jnp.zeros((int(want.shape[0]),) + row_shape,
                      rsp._sp_values.dtype), want, rsp.shape)
    src_idx = rsp._sp_indices.astype(jnp.int32)
    order = jnp.argsort(src_idx)
    sorted_idx = src_idx[order]
    pos = jnp.clip(jnp.searchsorted(sorted_idx, want), 0,
                   sorted_idx.shape[0] - 1)
    hit = sorted_idx[pos] == want
    vals = jnp.take(rsp._sp_values, jnp.take(order, pos), axis=0)
    hitb = hit.reshape((-1,) + (1,) * (vals.ndim - 1))
    return RowSparseNDArray(jnp.where(hitb, vals, 0), want, rsp.shape)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (reference: sparse dot kernels, dot-inl.h).

    Supported: csr @ dense, csr.T @ dense, rsp @ dense, dense @ dense.
    On TPU these lower to one dense MXU matmul over the materialized
    mirror — the sparse win on TPU is storage/communication (row pulls),
    not FLOPs, so this is the idiomatic lowering."""
    a = lhs._data if isinstance(lhs, NDArray) else _as_jax(lhs)
    b = rhs._data if isinstance(rhs, NDArray) else _as_jax(rhs)
    if transpose_a:
        a = a.T
    if transpose_b:
        b = b.T
    return NDArray(a @ b)
