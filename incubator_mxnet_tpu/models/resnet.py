"""ResNet entry point for the benchmark configs (BASELINE.md config #2).

The canonical implementations live in the Gluon model zoo
(gluon/model_zoo/vision/resnet.py, parity with
`python/mxnet/gluon/model_zoo/vision/resnet.py`); this module re-exports
them under ``mx.models.resnet`` for the driver/bench scripts."""

from ..gluon.model_zoo.vision.resnet import (  # noqa: F401
    BasicBlockV1, BasicBlockV2, BottleneckV1, BottleneckV2, ResNetV1,
    ResNetV2, get_resnet, resnet18_v1, resnet34_v1, resnet50_v1,
    resnet101_v1, resnet152_v1, resnet18_v2, resnet34_v2, resnet50_v2,
    resnet101_v2, resnet152_v2)

__all__ = ["ResNetV1", "ResNetV2", "get_resnet", "resnet18_v1",
           "resnet34_v1", "resnet50_v1", "resnet101_v1", "resnet152_v1",
           "resnet18_v2", "resnet34_v2", "resnet50_v2", "resnet101_v2",
           "resnet152_v2"]
