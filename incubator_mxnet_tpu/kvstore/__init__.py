"""KVStore: parameter synchronization facade (re-design of
`src/kvstore/` + `python/mxnet/kvstore/` — SURVEY.md §2.1/§5.8)."""

from .base import KVStoreBase, register
from .kvstore import KVStore, create
