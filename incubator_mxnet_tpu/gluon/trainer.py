"""Gluon Trainer.

Re-design of `python/mxnet/gluon/trainer.py` (file-level citation —
SURVEY.md caveat). Orchestrates grad reduction (KVStore facade) + optimizer
updates over a Block's parameters; the reference's update_on_kvstore logic
(server-side optimizer) collapses into post-reduction local updates, which
is mathematically identical for sync training (SURVEY.md §3.2).

The eager ``step()`` here is the correctness path; for TPU throughput use
``parallel.SPMDTrainer`` which fuses fwd+bwd+psum+update into one jitted
program (SURVEY.md §3.2: "the whole step becomes ONE jitted SPMD function").
"""

from __future__ import annotations

from typing import Dict, List, Optional, Union

from .. import optimizer as opt_mod
from ..base import MXNetError
from ..kvstore import create as kv_create
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            param_list = [params[k] for k in sorted(params.keys())] \
                if isinstance(params, dict) else list(params.values())
        elif isinstance(params, (list, tuple)):
            param_list = list(params)
        else:
            raise MXNetError("params must be a (Parameter)Dict or list")
        self._params: List[Parameter] = []
        self._param2idx: Dict[str, int] = {}
        for i, p in enumerate(param_list):
            if not isinstance(p, Parameter):
                raise MXNetError(f"expected Parameter, got {type(p)}")
            self._param2idx[p.name] = i
            self._params.append(p)

        optimizer_params = optimizer_params or {}
        param_dict = {p.name: p for p in self._params}
        self._optimizer = opt_mod.create(
            optimizer, param_dict=param_dict,
            param_idx2name={i: p.name for i, p in enumerate(self._params)},
            **optimizer_params)
        self._updaters = [opt_mod.get_updater(self._optimizer)]
        self._scale = self._optimizer.rescale_grad

        self._compression_params = compression_params
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._kvstore_type = kvstore
        self._distributed = isinstance(kvstore, str) and \
            kvstore.startswith("dist")

    # -- kvstore bootstrap ---------------------------------------------- #
    def _init_kvstore(self):
        if self._kv_initialized:
            return
        if self._kvstore_type is None:
            self._kvstore = None
        else:
            kv = self._kvstore_type if not isinstance(self._kvstore_type, str) \
                else kv_create(self._kvstore_type)
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            self._kvstore = kv
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    kv.init(i, p.data())
        self._kv_initialized = True

    @property
    def learning_rate(self) -> float:
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr: float):
        self._optimizer.set_learning_rate(lr)

    # -- the step -------------------------------------------------------- #
    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce grads then update (parity: Trainer.step)."""
        self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            grads = p.list_grad()
            if self._kvstore.num_workers > 1 or len(grads) > 1:
                self._kvstore.pushpull(i, grads, out=grads)

    def allreduce_grads(self):
        self._init_kvstore()
        self._allreduce_grads()

    def _update(self, ignore_stale_grad=False):
        updater = self._updaters[0]
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            grad = p.grad()
            if getattr(p, "_grad_stype", "default") == "row_sparse":
                # sparse-embedding contract (SURVEY.md §2.3 last row):
                # convert to active rows so the optimizer touches only them
                from ..ndarray import sparse as _sparse
                grad = _sparse.cast_storage(grad, "row_sparse")
            updater(i, grad, p.data())

    def update(self, batch_size, ignore_stale_grad=False):
        self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def zero_grad(self):
        for p in self._params:
            p.zero_grad()

    # -- checkpoint ------------------------------------------------------ #
    def save_states(self, fname):
        """(parity: Trainer.save_states — optimizer state incl. momentum
        buffers; SURVEY.md §5.4)."""
        with open(fname, "wb") as f:
            f.write(self._updaters[0].get_states(dump_optimizer=False))

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self._updaters[0].set_states(f.read())
        self._optimizer = self._updaters[0].optimizer
