"""Autograd semantics tests (behavioral port of the reference's
tests/python/unittest/test_autograd.py — SURVEY.md §4)."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, nd
from incubator_mxnet_tpu.test_utils import (assert_almost_equal,
                                            check_numeric_gradient, with_seed)


def test_basic_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain_rule():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(nd.log(x) * 2.0)  # = x^2
        z = y.sum()
    z.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * x.asnumpy(), rtol=1e-3,
                        atol=1e-3)


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3.0
    y.backward(nd.array([10.0, 100.0]))
    assert x.grad.asnumpy().tolist() == [30.0, 300.0]


def test_grad_req_add():
    x = nd.array([1.0, 1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * 2.0).sum()
        y.backward()
    assert x.grad.asnumpy().tolist() == [6.0, 6.0]
    x.attach_grad()  # re-attach resets to write
    with autograd.record():
        (x * 2.0).sum().backward()
    assert x.grad.asnumpy().tolist() == [2.0, 2.0]


def test_grad_req_null():
    x = nd.array([1.0])
    y = nd.array([2.0])
    x.attach_grad()
    y.attach_grad(grad_req="null")
    with autograd.record():
        z = x * y
    z.backward()
    assert x.grad.asnumpy().tolist() == [2.0]
    assert y.grad.asnumpy().tolist() == [0.0]


def test_multiple_uses():
    # same variable used twice: gradients accumulate along both paths
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + x
    y.backward()
    assert x.grad.asnumpy().tolist() == [7.0]  # 2x + 1


def test_detach_and_stop_gradient():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert x.grad.asnumpy().tolist() == [4.0]  # only d(z)/dx via second factor
    with autograd.record():
        w = nd.BlockGrad(x * x) * x
    w.backward()
    assert x.grad.asnumpy().tolist() == [4.0]


def test_pause_and_modes():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
            y = x * 2.0
        assert y._ag_node is None
        with autograd.predict_mode():
            assert autograd.is_recording()
            assert not autograd.is_training()
    assert not autograd.is_recording()


def test_training_aware_dropout():
    x = nd.ones((100, 100))
    with autograd.record(train_mode=True):
        y = nd.Dropout(x, p=0.5)
    dropped = float((y.asnumpy() == 0).mean())
    assert 0.3 < dropped < 0.7
    with autograd.record(train_mode=False):
        y2 = nd.Dropout(x, p=0.5)
    assert (y2.asnumpy() == 1.0).all()
    y3 = nd.Dropout(x, p=0.5)  # outside record: predict mode
    assert (y3.asnumpy() == 1.0).all()


def test_dropout_backward_consistency():
    # the SAME mask must be used in forward and backward (key threading)
    x = nd.ones((50, 50))
    x.attach_grad()
    with autograd.record():
        y = nd.Dropout(x, p=0.5)
        z = y.sum()
    z.backward()
    g = x.grad.asnumpy()
    out = y.asnumpy()
    assert np.array_equal(g != 0, out != 0)


def test_autograd_grad_function():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    gs = autograd.grad([y], [x], retain_graph=False)
    assert_almost_equal(gs[0].asnumpy(), 2 * x.asnumpy())
    # .grad buffer untouched by autograd.grad
    assert x.grad.asnumpy().tolist() == [0.0, 0.0]


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0, -1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad.asnumpy(), s * (1 - s), rtol=1e-4)


def test_mark_variables():
    x = nd.array([2.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * 5.0
    y.backward()
    assert g.asnumpy().tolist() == [5.0]


def test_multi_output_op_backward():
    x = nd.array(np.arange(6).reshape(2, 3).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        a, b, c = nd.split(x, num_outputs=3, axis=1)
        loss = (a * 1.0 + b * 2.0 + c * 3.0).sum()
    loss.backward()
    assert x.grad.asnumpy().tolist() == [[1.0, 2.0, 3.0]] * 2


@with_seed(0)
def test_numeric_gradient_elemwise():
    x = nd.array(np.random.uniform(0.5, 1.5, (3, 4)).astype(np.float32))
    check_numeric_gradient(lambda a: nd.exp(a), [x])
    check_numeric_gradient(lambda a: nd.log(a), [x])
    check_numeric_gradient(lambda a: nd.sqrt(a), [x])
    check_numeric_gradient(lambda a: nd.sigmoid(a), [x])
    check_numeric_gradient(lambda a: nd.tanh(a), [x])


@with_seed(0)
def test_numeric_gradient_matmul():
    a = nd.array(np.random.uniform(-1, 1, (3, 4)).astype(np.float32))
    b = nd.array(np.random.uniform(-1, 1, (4, 2)).astype(np.float32))
    check_numeric_gradient(lambda x, y: nd.dot(x, y), [a, b])


@with_seed(0)
def test_numeric_gradient_softmax():
    x = nd.array(np.random.uniform(-2, 2, (2, 5)).astype(np.float32))
    check_numeric_gradient(lambda a: nd.softmax(a), [x], rtol=2e-2)
    check_numeric_gradient(lambda a: nd.log_softmax(a), [x], rtol=2e-2)


def test_retain_graph():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    assert x.grad.asnumpy().tolist() == [4.0]
    y.backward()  # second backward works because graph retained
    assert x.grad.asnumpy().tolist() == [4.0]


def test_exception_without_record():
    x = nd.array([1.0])
    with pytest.raises(Exception):
        x.backward()


def test_inplace_ops_record_gradient():
    """__iadd__/__imul__ on a recorded array must keep the tape wired to the
    mutated array (regression: tape node pointed at the discarded temp)."""
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        y += 1
        y *= 3          # y = (2x+1)*3, dy/dx = 6
        loss = y.sum()
    loss.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0, 6.0])


def test_grad_wrt_intermediate():
    """autograd.grad w.r.t. a non-leaf (recorded) array (regression:
    returned zeros because the node path shadowed the marked-variable
    path)."""
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = (y * 3).sum()
    gy = autograd.grad(z, [y])[0]
    assert np.allclose(gy.asnumpy(), [3.0, 3.0, 3.0])


def test_single_output_variadic_backward():
    """split with one section returns a 1-tuple; backward must seed the vjp
    with a tuple (regression: ValueError tree-structure mismatch)."""
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        parts = nd.split_v2(x, 1, axis=0)
        part = parts[0] if isinstance(parts, (list, tuple)) else parts
        loss = (part * 2).sum()
    loss.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * np.ones((2, 2)))


def test_higher_order_grad_create_graph():
    """create_graph=True returns differentiable grads (reference:
    autograd.grad CreateGraph path; upstream supported 2nd order for a
    subset of ops — the tape-replay + vjp-of-vjp design gives any order)."""
    # d2/dx2 x^3 = 6x
    x = nd.array([2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x * x).sum()
        g = autograd.grad(y, [x], create_graph=True)[0]
        # first-order values available immediately
        np.testing.assert_allclose(g.asnumpy(), [12.0, 27.0])
        z = g.sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [12.0, 18.0])

    # third order: d3/dx3 x^4 = 24x
    x2 = nd.array([1.5])
    x2.attach_grad()
    with autograd.record():
        y2 = (x2 * x2 * x2 * x2).sum()
        g1 = autograd.grad(y2, [x2], create_graph=True)[0]
        g2 = autograd.grad(g1.sum(), [x2], create_graph=True)[0]
    g2.backward()
    np.testing.assert_allclose(x2.grad.asnumpy(), [36.0])

    # registry op (sigmoid): d2/dx2 sigma = s(1-s)(1-2s)
    x3 = nd.array([0.3])
    x3.attach_grad()
    with autograd.record():
        s = nd.sigmoid(x3).sum()
        gs = autograd.grad(s, [x3], create_graph=True)[0]
    gs.backward()
    sv = 1 / (1 + np.exp(-0.3))
    np.testing.assert_allclose(x3.grad.asnumpy(),
                               [sv * (1 - sv) * (1 - 2 * sv)], rtol=1e-5)

    # custom Function graphs are gated with a clear error
    class MyF(autograd.Function):
        def forward(self, a):
            return a * 2
        def backward(self, dy):
            return dy * 2

    xa = nd.array([1.0])
    xa.attach_grad()
    with autograd.record():
        out = MyF()(xa).sum()
        try:
            autograd.grad(out, [xa], create_graph=True)
            raised = False
        except mx.MXNetError:
            raised = True
    assert raised


def test_create_graph_nonleaf_and_robustness():
    # grad w.r.t. a NON-LEAF intermediate: d/dy (y*y) = 2y with y = 2x
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = (y * y).sum()
        gy = autograd.grad(z, [y], create_graph=True)[0]
    np.testing.assert_allclose(gy.asnumpy(), [8.0])

    # grad node survives a tape-clearing backward on the original head
    x2 = nd.array([3.0])
    x2.attach_grad()
    with autograd.record():
        y2 = (x2 * x2 * x2).sum()
        g = autograd.grad(y2, [x2], create_graph=True)[0]
        h = (g * g).sum()          # (3x^2)^2
    y2b = None
    h.backward()                   # d/dx 9x^4 = 36x^3
    np.testing.assert_allclose(x2.grad.asnumpy(), [972.0])

    # length-mismatch and unrecorded-head errors match first-order path
    a = nd.array([1.0])
    a.attach_grad()
    with autograd.record():
        out = (a * a).sum()
        try:
            autograd.grad([out], [a], head_grads=[None, None],
                          create_graph=True)
            raised = False
        except mx.MXNetError:
            raised = True
        assert raised
    b = nd.array([1.0]) * 2  # never recorded
    try:
        autograd.grad(b, [a], create_graph=True)
        raised = False
    except mx.MXNetError:
        raised = True
    assert raised


def test_create_graph_matches_first_order_semantics():
    # dz/dx must include the path THROUGH a co-requested intermediate y
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = (y * y).sum()
        gx, gy = autograd.grad(z, [x, y], create_graph=True)
    np.testing.assert_allclose(gy.asnumpy(), [8.0])    # dz/dy = 2y
    np.testing.assert_allclose(gx.asnumpy(), [16.0])   # full chain
    # and equals the first-order path
    x2 = nd.array([2.0])
    x2.attach_grad()
    with autograd.record():
        y2 = x2 * 2
        z2 = (y2 * y2).sum()
        g1 = autograd.grad(z2, [x2, y2])
    np.testing.assert_allclose(g1[0].asnumpy(), gx.asnumpy())
    np.testing.assert_allclose(g1[1].asnumpy(), gy.asnumpy())

    # recorded head_grads participate in higher-order differentiation:
    # g = hg * 2x with hg = 3x  ->  d/dx (sum g) = d/dx 6x^2 = 12x
    x3 = nd.array([2.0])
    x3.attach_grad()
    with autograd.record():
        y3 = (x3 * x3).sum()
        hg = (x3 * 3.0).sum()
        g3 = autograd.grad(y3, [x3], head_grads=hg,
                           create_graph=True)[0]
        s3 = g3.sum()
    s3.backward()
    np.testing.assert_allclose(x3.grad.asnumpy(), [24.0])  # 12 * 2


def test_astype_records_cast_on_tape():
    """Regression: NDArray.astype used to build a raw NDArray outside
    the tape, silently severing gradient flow through every
    mixed-precision forward (f32 -> f16 -> f32 trained nothing, with
    only a stale-grad warning as the symptom). Inside record(), astype
    must route through the Cast op so gradients flow end-to-end."""
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        h = x.astype("float16")
        y = ((h * h).astype("float32")).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 4.0, 6.0],
                               rtol=1e-3)
    # f16 leaf parameters get real gradients through the cast chain
    w = nd.array(np.float16([2.0, 3.0]), dtype="float16")
    w.attach_grad()
    with autograd.record():
        z = (w.astype("float32") * nd.array([5.0, 7.0])).sum()
    z.backward()
    np.testing.assert_allclose(w.grad.astype("float32").asnumpy(),
                               [5.0, 7.0])
