"""Autograd semantics tests (behavioral port of the reference's
tests/python/unittest/test_autograd.py — SURVEY.md §4)."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, nd
from incubator_mxnet_tpu.test_utils import (assert_almost_equal,
                                            check_numeric_gradient, with_seed)


def test_basic_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain_rule():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(nd.log(x) * 2.0)  # = x^2
        z = y.sum()
    z.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * x.asnumpy(), rtol=1e-3,
                        atol=1e-3)


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3.0
    y.backward(nd.array([10.0, 100.0]))
    assert x.grad.asnumpy().tolist() == [30.0, 300.0]


def test_grad_req_add():
    x = nd.array([1.0, 1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * 2.0).sum()
        y.backward()
    assert x.grad.asnumpy().tolist() == [6.0, 6.0]
    x.attach_grad()  # re-attach resets to write
    with autograd.record():
        (x * 2.0).sum().backward()
    assert x.grad.asnumpy().tolist() == [2.0, 2.0]


def test_grad_req_null():
    x = nd.array([1.0])
    y = nd.array([2.0])
    x.attach_grad()
    y.attach_grad(grad_req="null")
    with autograd.record():
        z = x * y
    z.backward()
    assert x.grad.asnumpy().tolist() == [2.0]
    assert y.grad.asnumpy().tolist() == [0.0]


def test_multiple_uses():
    # same variable used twice: gradients accumulate along both paths
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + x
    y.backward()
    assert x.grad.asnumpy().tolist() == [7.0]  # 2x + 1


def test_detach_and_stop_gradient():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert x.grad.asnumpy().tolist() == [4.0]  # only d(z)/dx via second factor
    with autograd.record():
        w = nd.BlockGrad(x * x) * x
    w.backward()
    assert x.grad.asnumpy().tolist() == [4.0]


def test_pause_and_modes():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
            y = x * 2.0
        assert y._ag_node is None
        with autograd.predict_mode():
            assert autograd.is_recording()
            assert not autograd.is_training()
    assert not autograd.is_recording()


def test_training_aware_dropout():
    x = nd.ones((100, 100))
    with autograd.record(train_mode=True):
        y = nd.Dropout(x, p=0.5)
    dropped = float((y.asnumpy() == 0).mean())
    assert 0.3 < dropped < 0.7
    with autograd.record(train_mode=False):
        y2 = nd.Dropout(x, p=0.5)
    assert (y2.asnumpy() == 1.0).all()
    y3 = nd.Dropout(x, p=0.5)  # outside record: predict mode
    assert (y3.asnumpy() == 1.0).all()


def test_dropout_backward_consistency():
    # the SAME mask must be used in forward and backward (key threading)
    x = nd.ones((50, 50))
    x.attach_grad()
    with autograd.record():
        y = nd.Dropout(x, p=0.5)
        z = y.sum()
    z.backward()
    g = x.grad.asnumpy()
    out = y.asnumpy()
    assert np.array_equal(g != 0, out != 0)


def test_autograd_grad_function():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    gs = autograd.grad([y], [x], retain_graph=False)
    assert_almost_equal(gs[0].asnumpy(), 2 * x.asnumpy())
    # .grad buffer untouched by autograd.grad
    assert x.grad.asnumpy().tolist() == [0.0, 0.0]


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0, -1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad.asnumpy(), s * (1 - s), rtol=1e-4)


def test_mark_variables():
    x = nd.array([2.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * 5.0
    y.backward()
    assert g.asnumpy().tolist() == [5.0]


def test_multi_output_op_backward():
    x = nd.array(np.arange(6).reshape(2, 3).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        a, b, c = nd.split(x, num_outputs=3, axis=1)
        loss = (a * 1.0 + b * 2.0 + c * 3.0).sum()
    loss.backward()
    assert x.grad.asnumpy().tolist() == [[1.0, 2.0, 3.0]] * 2


@with_seed(0)
def test_numeric_gradient_elemwise():
    x = nd.array(np.random.uniform(0.5, 1.5, (3, 4)).astype(np.float32))
    check_numeric_gradient(lambda a: nd.exp(a), [x])
    check_numeric_gradient(lambda a: nd.log(a), [x])
    check_numeric_gradient(lambda a: nd.sqrt(a), [x])
    check_numeric_gradient(lambda a: nd.sigmoid(a), [x])
    check_numeric_gradient(lambda a: nd.tanh(a), [x])


@with_seed(0)
def test_numeric_gradient_matmul():
    a = nd.array(np.random.uniform(-1, 1, (3, 4)).astype(np.float32))
    b = nd.array(np.random.uniform(-1, 1, (4, 2)).astype(np.float32))
    check_numeric_gradient(lambda x, y: nd.dot(x, y), [a, b])


@with_seed(0)
def test_numeric_gradient_softmax():
    x = nd.array(np.random.uniform(-2, 2, (2, 5)).astype(np.float32))
    check_numeric_gradient(lambda a: nd.softmax(a), [x], rtol=2e-2)
    check_numeric_gradient(lambda a: nd.log_softmax(a), [x], rtol=2e-2)


def test_retain_graph():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    assert x.grad.asnumpy().tolist() == [4.0]
    y.backward()  # second backward works because graph retained
    assert x.grad.asnumpy().tolist() == [4.0]


def test_exception_without_record():
    x = nd.array([1.0])
    with pytest.raises(Exception):
        x.backward()


def test_inplace_ops_record_gradient():
    """__iadd__/__imul__ on a recorded array must keep the tape wired to the
    mutated array (regression: tape node pointed at the discarded temp)."""
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        y += 1
        y *= 3          # y = (2x+1)*3, dy/dx = 6
        loss = y.sum()
    loss.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0, 6.0])


def test_grad_wrt_intermediate():
    """autograd.grad w.r.t. a non-leaf (recorded) array (regression:
    returned zeros because the node path shadowed the marked-variable
    path)."""
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = (y * 3).sum()
    gy = autograd.grad(z, [y])[0]
    assert np.allclose(gy.asnumpy(), [3.0, 3.0, 3.0])


def test_single_output_variadic_backward():
    """split with one section returns a 1-tuple; backward must seed the vjp
    with a tuple (regression: ValueError tree-structure mismatch)."""
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        parts = nd.split_v2(x, 1, axis=0)
        part = parts[0] if isinstance(parts, (list, tuple)) else parts
        loss = (part * 2).sum()
    loss.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * np.ones((2, 2)))
