"""Symbolic graph front end.

Re-design of `python/mxnet/symbol/symbol.py` + the NNVM graph IR
(`3rdparty/tvm/nnvm/include/nnvm/graph.h`, JSON pass
`saveload_json.cc`; file-level citations — SURVEY.md caveat).

The reference's Symbol is a handle to an NNVM node DAG shared with the C++
executor. Here a Symbol is a lightweight Python DAG over the SAME op
registry the imperative front end uses (SURVEY.md §1 pillar b: one
registration serves both front ends); execution compiles the DAG into one
jitted XLA program (`executor.py`) instead of walking an engine queue.

Graph JSON keeps the NNVM shape (`nodes`/`arg_nodes`/`heads`) AND the
reference's all-strings attr convention on write (fromjson parses
literals back), so saved files open in reference tooling and real
reference `-symbol.json` files load here.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError
from ..ops import registry as _registry

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "fromjson"]

_counter_lock = threading.Lock()
_name_counters: Dict[str, int] = {}


def _auto_name(op: str) -> str:
    with _counter_lock:
        idx = _name_counters.get(op, 0)
        _name_counters[op] = idx + 1
    return f"{op.lower()}{idx}"


class _Node:
    """One graph node: a variable (``op='null'``) or an op application.

    ``attrs`` are op parameters (forwarded as kwargs at execution);
    ``annotations`` are graph metadata (AttrScope / _set_attr — e.g.
    ``ctx_group`` placement hints) that execution never sees. The split
    mirrors the reference's param-vs-attr distinction in nnvm nodes."""

    __slots__ = ("op", "name", "inputs", "attrs", "annotations")

    def __init__(self, op: str, name: str,
                 inputs: Sequence[Tuple["_Node", int]] = (),
                 attrs: Optional[dict] = None,
                 annotations: Optional[dict] = None):
        self.op = op
        self.name = name
        self.inputs = list(inputs)
        self.attrs = dict(attrs or {})
        self.annotations = dict(annotations or {})

    @property
    def is_variable(self) -> bool:
        return self.op == "null"

    def num_outputs(self) -> int:
        if self.is_variable:
            return 1
        spec = _registry.get(self.op)
        if callable(spec.num_outputs):
            # attr-dependent arity declared at registration (e.g. RNN's
            # state_outputs) — arity stays next to the op definition
            return spec.num_outputs(self.attrs)
        if spec.num_outputs:
            return spec.num_outputs
        # variadic-output ops: arity from static attrs (single source of
        # truth — symbol/__init__._invoke_symbol uses this method too)
        if "num_outputs" in self.attrs:
            return int(self.attrs["num_outputs"])
        ios = self.attrs.get("indices_or_sections")
        if ios is not None:
            return len(ios) + 1 if isinstance(ios, (list, tuple)) \
                else int(ios)
        return 1


def _topo(heads: Sequence[Tuple[_Node, int]]) -> List[_Node]:
    """Deterministic post-order topological sort of the DAG."""
    seen: Dict[int, bool] = {}
    order: List[_Node] = []

    def visit(node: _Node):
        if id(node) in seen:
            return
        seen[id(node)] = True
        for src, _ in node.inputs:
            visit(src)
        order.append(node)

    for node, _ in heads:
        visit(node)
    return order


class Symbol:
    """A symbolic multi-output expression (parity: ``mx.sym.Symbol``).

    Internally: a list of ``(node, output_index)`` heads. A single-op
    symbol has one head per op output; ``Group`` concatenates heads.
    """

    def __init__(self, heads: Sequence[Tuple[_Node, int]]):
        self._heads: List[Tuple[_Node, int]] = list(heads)

    # -- identity ---------------------------------------------------- #
    @property
    def name(self) -> str:
        if len(self._heads) == 1:
            return self._heads[0][0].name
        return "grouped"

    @property
    def _node(self) -> _Node:
        return self._heads[0][0]

    def attr(self, key: str):
        node = self._node
        if key in node.annotations:
            return node.annotations[key]
        return node.attrs.get(key)

    def list_attr(self) -> dict:
        node = self._node
        merged = dict(node.attrs)
        merged.update(node.annotations)
        return merged

    def attr_dict(self) -> dict:
        """name -> merged attrs for every node (parity: attr_dict)."""
        out = {}
        for node in _topo(self._heads):
            merged = dict(node.attrs)
            merged.update(node.annotations)
            if merged:
                out[node.name] = {k: str(v) for k, v in merged.items()}
        return out

    def _set_attr(self, **kwargs):
        self._node.annotations.update(
            {k: str(v) for k, v in kwargs.items()})

    # -- composition -------------------------------------------------- #
    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            try:
                index = names.index(index)
            except ValueError:
                raise MXNetError(f"no output named {index!r} in {names}")
        outs = self._all_outputs()
        return Symbol([outs[index]])

    def _all_outputs(self) -> List[Tuple[_Node, int]]:
        """Expand heads so each (node, idx) output appears individually."""
        outs = []
        for node, idx in self._heads:
            outs.append((node, idx))
        return outs

    def __len__(self):
        return len(self._heads)

    def __iter__(self):
        for i in range(len(self._heads)):
            yield self[i]

    def get_internals(self) -> "Symbol":
        """Every intermediate output as a group (parity:
        ``sym.get_internals()`` — used to truncate pretrained nets)."""
        heads = []
        for node in _topo(self._heads):
            for k in range(node.num_outputs()):
                heads.append((node, k))
        return Symbol(heads)

    def get_children(self) -> Optional["Symbol"]:
        if self._node.is_variable:
            return None
        return Symbol(list(self._node.inputs))

    # -- introspection ------------------------------------------------ #
    def list_arguments(self) -> List[str]:
        """Input variable names in topological order (aux excluded),
        parity: ``sym.list_arguments()``."""
        return [n.name for n in _topo(self._heads)
                if n.is_variable and not n.attrs.get("__aux__")]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in _topo(self._heads)
                if n.is_variable and n.attrs.get("__aux__")]

    def list_inputs(self) -> List[str]:
        return [n.name for n in _topo(self._heads) if n.is_variable]

    def list_outputs(self) -> List[str]:
        names = []
        for node, idx in self._heads:
            if node.num_outputs() > 1:
                names.append(f"{node.name}_output{idx}")
            else:
                names.append(f"{node.name}_output")
        return names

    @property
    def num_outputs(self) -> int:
        return len(self._heads)

    # -- shape/type inference ----------------------------------------- #
    def infer_shape(self, *args, **kwargs):
        """Infer argument/output/aux shapes from partial inputs via XLA
        abstract evaluation (parity: ``sym.infer_shape`` — reference runs
        the NNVM `InferShape` pass)."""
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known: Dict[str, tuple] = {}
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        known.update({k: tuple(v) for k, v in kwargs.items()})
        from . import executor as _exec

        try:
            shapes = _exec.infer_shapes(self, known)
        except MXNetError:
            # under-determined partial inference → (None, None, None),
            # matching the reference's contract
            return None, None, None
        return ([shapes["args"][n] for n in arg_names],
                shapes["outs"],
                [shapes["args"][n] for n in aux_names])

    def infer_shape_partial(self, *args, **kwargs):
        try:
            return self.infer_shape(*args, **kwargs)
        except Exception:
            return None, None, None

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        known = dict(zip(arg_names, args))
        known.update(kwargs)
        if any(n not in known for n in arg_names):
            return None, None, None
        from . import executor as _exec

        dtypes = _exec.infer_types(
            self, {k: v for k, v in known.items() if v is not None})
        return ([dtypes["args"][n] for n in arg_names], dtypes["outs"],
                [dtypes["args"][n] for n in self.list_auxiliary_states()])

    # -- execution ---------------------------------------------------- #
    def eval(self, ctx=None, **kwargs):
        """Imperative evaluation with NDArray bindings (parity:
        ``sym.eval``). Returns a list of NDArrays."""
        from . import executor as _exec

        out = _exec.evaluate(self, kwargs, training=False)
        return out if isinstance(out, list) else [out]

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from .executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states)

    def simple_bind(self, ctx=None, grad_req="write", **shapes):
        from .executor import Executor

        return Executor.simple_bind(self, ctx, grad_req, **shapes)

    # -- serialization ------------------------------------------------ #
    def grad(self, wrt):
        """Deprecated in the reference too (symbol.py Symbol.grad raises
        for most ops since 1.0): gradients come from autograd or the
        executor's fused backward."""
        raise MXNetError(
            "Symbol.grad is deprecated (as in the reference); bind the "
            "symbol and use Executor.backward, or autograd.record")

    def tojson(self) -> str:
        nodes = _topo(self._heads)
        node_id = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            spec = {
                "op": n.op,
                "name": n.name,
                # the reference's nnvm reads node attrs as a
                # Map<string, string>: stringify values on write so a
                # saved file opens in reference MXNet tooling too;
                # fromjson coerces literals back, so our own round trip
                # is lossless
                "attrs": {k: v if isinstance(v, str) else str(v)
                          for k, v in n.attrs.items()},
                "inputs": [[node_id[id(src)], idx, 0]
                           for src, idx in n.inputs],
            }
            if n.annotations:
                spec["annotations"] = n.annotations
            out_nodes.append(spec)
        payload = {
            "nodes": out_nodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.is_variable],
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": [[node_id[id(n)], idx, 0] for n, idx in self._heads],
            "attrs": {"framework": "incubator_mxnet_tpu",
                      "format_version": 1},
        }
        return json.dumps(payload, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- operator sugar ----------------------------------------------- #
    _SCALAR_OPS = {
        "broadcast_add": ("_plus_scalar", "_plus_scalar"),
        "broadcast_sub": ("_minus_scalar", "_rminus_scalar"),
        "broadcast_mul": ("_mul_scalar", "_mul_scalar"),
        "broadcast_div": ("_div_scalar", "_rdiv_scalar"),
        "broadcast_power": ("_power_scalar", "_rpower_scalar"),
    }

    def _binop(self, op_name, other, reverse=False):
        from . import _invoke_symbol

        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _invoke_symbol(op_name, a, b)
        fwd, rev = self._SCALAR_OPS[op_name]
        return _invoke_symbol(rev if reverse else fwd, self,
                              scalar=float(other))

    def __add__(self, o):
        return self._binop("broadcast_add", o)

    def __radd__(self, o):
        return self._binop("broadcast_add", o)

    def __sub__(self, o):
        return self._binop("broadcast_sub", o)

    def __rsub__(self, o):
        return self._binop("broadcast_sub", o, reverse=True)

    def __mul__(self, o):
        return self._binop("broadcast_mul", o)

    def __rmul__(self, o):
        return self._binop("broadcast_mul", o)

    def __truediv__(self, o):
        return self._binop("broadcast_div", o)

    def __rtruediv__(self, o):
        return self._binop("broadcast_div", o, reverse=True)

    def __pow__(self, o):
        return self._binop("broadcast_power", o)

    def __neg__(self):
        return self * -1.0

    def __repr__(self):
        outs = ", ".join(self.list_outputs())
        return f"<Symbol {self.name} [{outs}]>"


def Variable(name: str, shape=None, dtype=None, init=None, **attrs) -> Symbol:
    """Create an input placeholder (parity: ``mx.sym.Variable``)."""
    node_attrs = dict(attrs)
    if shape is not None:
        node_attrs["__shape__"] = list(shape)
    if dtype is not None:
        node_attrs["__dtype__"] = str(dtype)
    # scope attrs attach to variables too — the reference's primary
    # AttrScope use (group2ctx placement of weights)
    from ..attribute import current_attrs as _scope_attrs
    return Symbol([(_Node("null", name, (), node_attrs,
                          _scope_attrs() or None), 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    """Concatenate symbols' outputs into one multi-output symbol
    (parity: ``mx.sym.Group``)."""
    heads: List[Tuple[_Node, int]] = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


def _coerce_attr(k, v):
    """Reference ``-symbol.json`` files stringify EVERY attr value
    (nnvm json.cc writes "num_hidden": "4", "kernel": "(3, 3)",
    "no_bias": "True"); parse literals back, keep genuine strings
    (act_type="relu", dtype="float32") as-is. Dunder user attrs
    (``__init__``, ``__lr_mult__``, ...) are string-typed BY CONTRACT
    in the reference attr API — never coerce those."""
    if not isinstance(v, str) or k.startswith("__"):
        return v
    import ast
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def fromjson(text: str) -> Symbol:
    payload = json.loads(text)
    nodes: List[_Node] = []
    for spec in payload["nodes"]:
        attrs = spec.get("attrs") or spec.get("param") or {}
        attrs = {k: _coerce_attr(k, v) for k, v in attrs.items()}
        inputs = [(nodes[i], idx) for i, idx, *_ in spec.get("inputs", [])]
        nodes.append(_Node(spec["op"], spec["name"], inputs, attrs,
                           spec.get("annotations")))
    heads = [(nodes[i], idx) for i, idx, *_ in payload["heads"]]
    return Symbol(heads)


load_json = fromjson


def load(fname: str) -> Symbol:
    """Load a saved symbol JSON (parity: ``mx.sym.load``)."""
    with open(fname) as f:
        return fromjson(f.read())
