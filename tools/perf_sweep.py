"""One-shot TPU perf sweep for the BERT flagship (run when the axon
tunnel is up; each config is a fresh subprocess so a wedged compile can't
sink the whole sweep).

Writes one JSON line per config to ``--out`` (default
/root/repo/perf_sweep.jsonl) and prints a ranked table at the end.

Configs swept (beyond the bench default B=48):
  - batch size ladder
  - rbg PRNG (hardware RNG for the 37 dropout masks/step vs threefry)
  - dropout off (isolates RNG + mask cost)
  - flash block sizes via MXTPU_FLASH_BLOCK_Q/K
  - remat on the larger batches (fit vs recompute trade)
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIGS = [
    {"name": "b48-base", "env": {"MXTPU_BENCH_BATCH": "48"}},
    {"name": "b48-rbg", "env": {"MXTPU_BENCH_BATCH": "48",
                                "JAX_DEFAULT_PRNG_IMPL": "rbg"}},
    {"name": "b48-nodrop", "env": {"MXTPU_BENCH_BATCH": "48",
                                   "MXTPU_BENCH_DROPOUT": "0"}},
    {"name": "b48-bq256", "env": {"MXTPU_BENCH_BATCH": "48",
                                  "MXTPU_FLASH_BLOCK_Q": "256"}},
    {"name": "b48-bk256", "env": {"MXTPU_BENCH_BATCH": "48",
                                  "MXTPU_FLASH_BLOCK_K": "256"}},
    {"name": "b48-bq256-bk256", "env": {"MXTPU_BENCH_BATCH": "48",
                                        "MXTPU_FLASH_BLOCK_Q": "256",
                                        "MXTPU_FLASH_BLOCK_K": "256"}},
    {"name": "b56", "env": {"MXTPU_BENCH_BATCH": "56"}},
    {"name": "b64-remat", "env": {"MXTPU_BENCH_BATCH": "64",
                                  "MXTPU_BENCH_REMAT": "1"}},
    {"name": "b64-remat-dots", "env": {"MXTPU_BENCH_BATCH": "64",
                                       "MXTPU_BENCH_REMAT": "dots"}},
    {"name": "b96-remat-dots", "env": {"MXTPU_BENCH_BATCH": "96",
                                       "MXTPU_BENCH_REMAT": "dots"}},
    {"name": "b48-rbg-nodrop", "env": {"MXTPU_BENCH_BATCH": "48",
                                       "JAX_DEFAULT_PRNG_IMPL": "rbg",
                                       "MXTPU_BENCH_DROPOUT": "0"}},
    {"name": "large-b16", "env": {"MXTPU_BENCH_MODEL": "large",
                                  "MXTPU_BENCH_BATCH": "16"}},
    {"name": "large-b16-remat", "env": {"MXTPU_BENCH_MODEL": "large",
                                        "MXTPU_BENCH_BATCH": "16",
                                        "MXTPU_BENCH_REMAT": "1"}},
    {"name": "large-b24-remat-dots", "env": {"MXTPU_BENCH_MODEL": "large",
                                             "MXTPU_BENCH_BATCH": "24",
                                             "MXTPU_BENCH_REMAT": "dots"}},
    {"name": "large-b32-remat-dots", "env": {"MXTPU_BENCH_MODEL": "large",
                                             "MXTPU_BENCH_BATCH": "32",
                                             "MXTPU_BENCH_REMAT": "dots"}},
]


def run_one(cfg, timeout):
    env = dict(os.environ)
    env.update(cfg["env"])
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--run",
             "--workload", "bert"],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=REPO)
    except subprocess.TimeoutExpired:
        return {"name": cfg["name"], "error": f"timeout {timeout}s"}
    for line in reversed(r.stdout.splitlines()):
        if line.startswith("BENCH_RESULT "):
            out = json.loads(line[len("BENCH_RESULT "):])
            out["name"] = cfg["name"]
            out["wall_s"] = round(time.time() - t0, 1)
            return out
    tail = (r.stderr or r.stdout or "").strip().splitlines()[-4:]
    return {"name": cfg["name"], "error": " | ".join(tail)[:300]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "perf_sweep.jsonl"))
    ap.add_argument("--timeout", type=int, default=900)
    ap.add_argument("--only", default=None,
                    help="comma-separated config names")
    args = ap.parse_args()

    picks = CONFIGS
    if args.only:
        names = set(args.only.split(","))
        picks = [c for c in CONFIGS if c["name"] in names]

    results = []
    with open(args.out, "a") as f:
        for cfg in picks:
            res = run_one(cfg, args.timeout)
            results.append(res)
            f.write(json.dumps(res) + "\n")
            f.flush()
            print(json.dumps(res), flush=True)

    ok = [r for r in results if "value" in r]
    # rank by MFU within each metric group: raw tokens/s is apples-to-
    # oranges across model sizes (bert-large does ~3x the FLOPs/token)
    ok.sort(key=lambda r: (r.get("metric", ""), -r.get("mfu", 0)))
    print("\n=== ranked (by MFU within each metric) ===")
    for r in ok:
        print(f"{r['name']:>18}: {r['value']:>10,.0f} {r.get('unit', '')} "
              f"mfu={r.get('mfu', 0):.3f}")


if __name__ == "__main__":
    main()
