// Native batched JPEG decode + bilinear resize for the image input
// pipeline (TPU-native counterpart of the reference's C++ decode threads
// in src/io/iter_image_recordio_2.cc — capability parity, new design).
//
// Exposed via ctypes (io/_native_image.py). The batch entry decodes N
// independent JPEG payloads on a std::thread pool — no GIL, one
// preallocated (N, H, W, 3) uint8 output — which is exactly the stage
// that bottlenecks a Python-side pipeline feeding an accelerator.

#include <cstddef>
#include <cstdio>  // jpeglib.h needs size_t/FILE declared first

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void error_exit_throw(j_common_ptr cinfo) {
  ErrMgr* mgr = reinterpret_cast<ErrMgr*>(cinfo->err);
  longjmp(mgr->jump, 1);  // libjpeg's default handler would exit()
}

// Decode one JPEG into an RGB buffer it allocates; returns true on
// success with (*w, *h) set.
bool DecodeOne(const uint8_t* buf, int64_t len, std::vector<uint8_t>* rgb,
               int* w, int* h) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit_throw;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  const int stride = *w * 3;
  rgb->resize(static_cast<size_t>(stride) * *h);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = rgb->data() +
                   static_cast<size_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Bilinear resize RGB uint8 (src_h, src_w) -> (dst_h, dst_w) into dst.
void ResizeBilinear(const uint8_t* src, int src_h, int src_w, uint8_t* dst,
                    int dst_h, int dst_w) {
  if (src_h == dst_h && src_w == dst_w) {
    std::memcpy(dst, src, static_cast<size_t>(src_h) * src_w * 3);
    return;
  }
  const float sy = static_cast<float>(src_h) / dst_h;
  const float sx = static_cast<float>(src_w) / dst_w;
  for (int y = 0; y < dst_h; ++y) {
    // pixel-center sampling (the cv2.resize INTER_LINEAR convention)
    float fy = (y + 0.5f) * sy - 0.5f;
    fy = std::max(0.0f, std::min(fy, static_cast<float>(src_h - 1)));
    const int y0 = static_cast<int>(fy);
    const int y1 = std::min(y0 + 1, src_h - 1);
    const float wy = fy - y0;
    for (int x = 0; x < dst_w; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      fx = std::max(0.0f, std::min(fx, static_cast<float>(src_w - 1)));
      const int x0 = static_cast<int>(fx);
      const int x1 = std::min(x0 + 1, src_w - 1);
      const float wx = fx - x0;
      for (int c = 0; c < 3; ++c) {
        const float v00 = src[(y0 * src_w + x0) * 3 + c];
        const float v01 = src[(y0 * src_w + x1) * 3 + c];
        const float v10 = src[(y1 * src_w + x0) * 3 + c];
        const float v11 = src[(y1 * src_w + x1) * 3 + c];
        const float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                        v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[(y * dst_w + x) * 3 + c] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

// Dimensions of one JPEG without full decode. Returns 0 on success.
int mxtpu_img_dims(const uint8_t* buf, int64_t len, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit_throw;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *w = static_cast<int>(cinfo.image_width);
  *h = static_cast<int>(cinfo.image_height);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decode n JPEGs (blob + per-item offsets/lengths) to (n, out_h, out_w, 3)
// uint8 RGB with bilinear resize, on `n_threads` workers. Returns 0 on
// success, -(i+1) when item i failed to decode.
int mxtpu_img_decode_batch(const uint8_t* blob, const int64_t* offsets,
                           const int64_t* lengths, int64_t n, int out_h,
                           int out_w, uint8_t* out, int n_threads) {
  std::atomic<int64_t> next(0);
  std::atomic<int> err(0);
  const size_t item = static_cast<size_t>(out_h) * out_w * 3;
  auto worker = [&]() {
    std::vector<uint8_t> rgb;
    int w = 0, h = 0;
    for (;;) {
      const int64_t i = next.fetch_add(1);
      if (i >= n || err.load() != 0) return;
      if (!DecodeOne(blob + offsets[i], lengths[i], &rgb, &w, &h)) {
        int expected = 0;
        err.compare_exchange_strong(expected, static_cast<int>(-(i + 1)));
        return;
      }
      ResizeBilinear(rgb.data(), h, w, out + item * i, out_h, out_w);
    }
  };
  const int nt = std::max(1, std::min<int>(n_threads, n));
  std::vector<std::thread> pool;
  pool.reserve(nt);
  for (int t = 0; t < nt; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
  return err.load();
}

}  // extern "C"
