"""Pipeline parallelism over the ``pp`` mesh axis (GPipe schedule).

The reference has no pipeline parallelism (SURVEY.md §2.3 marks the row
optional); this module is a TPU-native capability that exceeds it, built
the way the scaling-book prescribes: per-stage parameters are STACKED on
a leading axis sharded over ``pp`` (one stage per device), and inside a
``shard_map`` each device runs its stage while activations rotate to the
next stage via ``lax.ppermute`` on ICI. A GPipe schedule with M
microbatches fills/drains the pipe in M + S - 1 ticks; autodiff flows
through the ppermutes, so ``jax.grad`` of a pipelined loss just works —
no hand-written backward schedule (XLA reverses the permutes).

Layout contract:
  - ``stacked_params``: pytree whose leaves have leading dim S (=pp
    size), sharded ``P("pp", ...)`` — stage i's slice lives on device i.
  - ``x``: (M, B_micro, ...) microbatched input, replicated.
  - ``stage_fn(params_slice, x_micro) -> y_micro`` — one stage's
    computation; activations must keep one shape across stages (the
    usual transformer-block contract).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..base import MXNetError, shard_map

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(param_trees):
    """Stack S per-stage pytrees into one tree with leading stage dim
    (shard it with ``P('pp', ...)`` on the mesh)."""
    if not param_trees:
        raise MXNetError("stack_stage_params needs at least one stage")
    return jtu.tree_map(lambda *xs: jnp.stack(xs), *param_trees)


def _pipeline_local(stage_fn, n_stages, n_micro, axis):
    """Per-device GPipe body (runs inside shard_map)."""

    def body(params, x):
        # params: (1, ...) slice of the stacked tree → drop stage dim
        params = jtu.tree_map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        M = n_micro
        S = n_stages
        B = x.shape[1]
        feat = x.shape[2:]
        # `+ 0*stage` brands the carries as pp-varying from tick 0 so the
        # shard_map VMA checker accepts the scan (they genuinely become
        # device-varying after the first ppermute)
        vary0 = stage.astype(x.dtype) * 0
        outs0 = jnp.zeros((M, B) + feat, x.dtype) + vary0
        cur0 = jnp.zeros((B,) + feat, x.dtype) + vary0

        zero_idx = (0,) * (1 + len(feat))

        def tick(t, carry):
            cur, outs = carry
            # stage 0 injects microbatch t (while it exists);
            # other stages consume what arrived from the previous stage
            inject = jnp.where(t < M, t, M - 1)
            x_t = lax.dynamic_slice(x, (inject,) + zero_idx,
                                    (1,) + (B,) + feat)[0]
            cur = jnp.where(stage == 0, x_t, cur)
            y = stage_fn(params, cur)
            # last stage emits microbatch t-(S-1) once the pipe is full
            out_idx = jnp.clip(t - (S - 1), 0, M - 1)
            emit = (stage == S - 1) & (t >= S - 1)
            old = lax.dynamic_slice(outs, (out_idx,) + zero_idx,
                                    (1,) + y.shape)[0]
            outs = lax.dynamic_update_slice(
                outs, jnp.where(emit, y, old)[None],
                (out_idx,) + zero_idx)
            # rotate activations one stage forward on the ring
            perm = [(i, (i + 1) % S) for i in range(S)]
            cur = lax.ppermute(y, axis, perm)
            return cur, outs

        _, outs = lax.fori_loop(0, M + S - 1, tick, (cur0, outs0))
        # every device returns its outs buffer; only the last stage's is
        # real — psum after masking broadcasts it everywhere (cheap: one
        # buffer per device, and it keeps the output replicated like the
        # input)
        mine = jnp.where(stage == S - 1, 1.0, 0.0).astype(x.dtype)
        return lax.psum(outs * mine, axis)

    return body


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: Mesh,
                   axis: str = "pp"):
    """Run ``x`` (microbatched (M, B, ...)) through S pipeline stages.

    Returns (M, B, ...) outputs, replicated over ``axis``. Differentiable
    end-to-end; wrap in ``jax.jit``/``jax.grad`` freely."""
    S = mesh.shape[axis]
    M = x.shape[0]
    if M < S:
        raise MXNetError(
            f"pipeline needs microbatches >= stages ({M} < {S}); more "
            f"microbatches amortize the fill/drain bubble")
    for leaf in jtu.tree_leaves(stacked_params):
        if leaf.shape[0] != S:
            raise MXNetError(
                f"stacked_params leading dim {leaf.shape[0]} != pp mesh "
                f"size {S}: one stage per device (a multiple would be "
                f"silently truncated by the per-device slice)")
    body = _pipeline_local(stage_fn, S, M, axis)

    def spec_of(leaf):
        return P(axis, *([None] * (leaf.ndim - 1)))

    param_specs = jtu.tree_map(spec_of, stacked_params)
    fn = shard_map(body, mesh=mesh,
                   in_specs=(param_specs, P()), out_specs=P())
    return fn(stacked_params, x)
