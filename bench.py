"""Benchmark: BERT pretraining throughput on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The metric is tokens/sec/chip on a fused BERT pretraining step (BASELINE.md
config #3); vs_baseline is achieved MFU divided by the 0.45 north-star MFU.

Resilience contract (BASELINE.md "Measurement protocol" + the round-3
postmortem, VERDICT.md "What's weak" #1): the orchestrator operates under a
hard TOTAL deadline (`MXTPU_BENCH_DEADLINE`, default 660 s) enforced by a
watchdog thread that emits the best-known JSON line and exits 0 before the
deadline expires — a dead TPU tunnel can no longer push wall-clock past the
driver's window and produce rc=124 with no artifact. Order of operations:

  1. bank a placeholder line immediately (carrying the last measured TPU
     result from BENCH_MEASURED_*.json as `last_tpu`),
  2. start a cheap tunnel-liveness probe subprocess (<=120 s) concurrently,
  3. run the CPU smoke and bank its result,
  4. only if the probe saw a TPU: run accelerator attempts, each capped to
     the remaining budget,
  5. with leftover budget: measured extras (ResNet-50 on the TPU path,
     NMT cached-beam-search decode).

Whatever has been banked when time runs out is what gets printed — exactly
one JSON line, always, exit 0.

Workloads (child mode, selected with --workload):
  bert    — BERT-base/large pretraining, bf16 + Pallas flash attention +
            LAMB with f32 master weights (the MFU flagship; default)
  resnet  — ResNet-50 ImageNet-shaped data-parallel training step,
            img/s/chip (BASELINE.md config #2)
  ssd     — SSD-300 detection training step (MultiBox ops), img/s/chip
            (BASELINE.md config #5)
  nmt     — Transformer KV-cached beam-search decode, tokens/s (config #4)
  gpt     — GPT-2-small causal-LM pretraining, tokens/s/chip + MFU (the
            decoder-side complement: causal dense kernels + packed qkv)
"""

import json
import os
import subprocess
import sys
import threading
import time

TPU_ATTEMPTS = int(os.environ.get("MXTPU_BENCH_ATTEMPTS", "3"))
# per-attempt cap; successful TPU runs (compile through the tunnel + 13
# steps) measured ~4-6 min end to end. The TOTAL deadline below dominates:
# attempts are additionally capped to the remaining budget.
TPU_TIMEOUT = int(os.environ.get("MXTPU_BENCH_TPU_TIMEOUT", "900"))
CPU_TIMEOUT = int(os.environ.get("MXTPU_BENCH_CPU_TIMEOUT", "300"))
PROBE_TIMEOUT = int(os.environ.get("MXTPU_BENCH_PROBE_TIMEOUT", "120"))
DEADLINE = int(os.environ.get("MXTPU_BENCH_DEADLINE", "660"))
BACKOFFS = (10, 30)


# --------------------------------------------------------------------- #
# child: actually run one workload and print its JSON line
# --------------------------------------------------------------------- #

def _peak_flops_per_chip() -> float:
    """bf16 peak FLOP/s for the local chip generation (used for MFU)."""
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    table = {
        "v4": 275e12,
        "v5e": 197e12,
        "v5p": 459e12,
        "v6e": 918e12,
    }
    for k, v in table.items():
        if gen.startswith(k):
            return v
    return 197e12  # default: v5e


def _bert_flops_per_step(B, T, M, L, units, hidden, vocab):
    """Honest fwd+bwd FLOP count (6x matmul rule: 2x fwd, 4x bwd):
    encoder matmuls + O(T^2) attention + MLM/NSP heads. Embedding
    gathers are excluded (they are not matmul FLOPs)."""
    enc = 6.0 * B * T * L * (4 * units * units + 2 * units * hidden)
    attn = 12.0 * L * B * T * T * units
    heads = 6.0 * B * M * units * (vocab + units) + 6.0 * B * (
        units * units + 2 * units)
    return enc + attn + heads


def _env_remat_dropout(default_remat="0"):
    """Shared MXTPU_BENCH_REMAT / MXTPU_BENCH_DROPOUT parsing:
    "0" off; "1" whole-layer remat; "dots" selective (save matmul
    outputs, recompute elementwise only)."""
    remat_env = os.environ.get("MXTPU_BENCH_REMAT", default_remat)
    remat = {"0": False, "1": True}.get(remat_env, remat_env)
    dropout = float(os.environ.get("MXTPU_BENCH_DROPOUT", "0.1"))
    return remat, dropout


def _measure_steps(step_fn, warmup, steps):
    """Shared measurement harness for every training workload: warmup,
    an asnumpy fence (the REAL sync point — block_until_ready is a
    no-op on the axon tunnel backend, verified empirically), the
    optional MXTPU_BENCH_TRACE profiler block (BASELINE.md protocol:
    trace evidence for perf claims), then the timed loop. Returns
    (dt_seconds, last_loss)."""
    assert warmup >= 1, "warmup must compile+fence before the timed loop"
    loss = None
    for _ in range(warmup):
        loss = step_fn()
    float(loss.asnumpy())
    trace_dir = os.environ.get("MXTPU_BENCH_TRACE")
    if trace_dir:
        import jax.profiler
        with jax.profiler.trace(trace_dir):
            loss = step_fn()
            float(loss.asnumpy())
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step_fn()
    float(loss.asnumpy())
    return time.perf_counter() - t0, loss


def _resolve_bert_config(size, on_tpu):
    """(B, T, M, dtype, steps, warmup, flash, remat, dropout) for one
    bench run. With no env knobs the accelerator defaults come from
    ops.kernel_policy (the best-measured config per model size); env
    knobs override so the ladder's A/B rungs can pin configs."""
    if on_tpu or os.environ.get("MXTPU_BENCH_TPU_CONFIG") == "1":
        # MXTPU_BENCH_TPU_CONFIG=1 forces the accelerator code paths
        # (bf16 + flash + T=512 + LAMB masters) on CPU — a dress
        # rehearsal that catches trace-time bugs in the exact config a
        # rare tunnel window would otherwise burn a ladder rung on
        from incubator_mxnet_tpu.ops.kernel_policy import training_plan
        T, M = 512, 76
        dims = {"base": (12, 768, 3072), "large": (24, 1024, 4096)}[size]
        plan = training_plan(*dims, vocab=30522, seq_len=T)
        B = int(os.environ.get("MXTPU_BENCH_BATCH", str(plan["batch"])))
        dtype = "bfloat16"
        steps, warmup = (10, 3) if on_tpu else (1, 1)
        flash = True
        remat, dropout = _env_remat_dropout(default_remat=plan["remat"])
    else:  # CPU smoke mode so the bench is runnable anywhere
        B, T, M = 4, 128, 20
        dtype = "float32"
        steps, warmup = 3, 1
        flash = False
        remat, dropout = _env_remat_dropout()
    return B, T, M, dtype, steps, warmup, flash, remat, dropout


def _run_bert(on_tpu):
    import numpy as np
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.models import bert as bert_mod

    size = os.environ.get("MXTPU_BENCH_MODEL", "base")
    if size not in ("base", "large"):
        raise ValueError(f"MXTPU_BENCH_MODEL must be base|large, got {size!r}")
    B, T, M, dtype, steps, warmup, flash, remat, dropout = \
        _resolve_bert_config(size, on_tpu)

    mx.random.seed(0)
    ctor = bert_mod.bert_large if size == "large" else bert_mod.bert_base
    model = ctor(dtype=dtype, max_length=T, flash=flash,
                 remat=remat, dropout=dropout)
    model.initialize()
    pre = bert_mod.BERTForPretraining(model)
    pre.initialize()

    rng = np.random.RandomState(0)
    batch = (
        nd.array(rng.randint(0, 30522, (B, T)), dtype="int32"),
        nd.array(rng.randint(0, 2, (B, T)), dtype="int32"),
        nd.array(np.full((B,), T), dtype="int32"),
        nd.array(rng.randint(0, T, (B, M)), dtype="int32"),
        nd.array(rng.randint(0, 30522, (B, M)), dtype="int32"),
        nd.ones((B, M)),
        nd.array(rng.randint(0, 2, (B,)), dtype="int32"),
    )

    trainer = parallel.SPMDTrainer(
        pre, forward_loss=bert_mod.pretraining_loss, optimizer="lamb",
        optimizer_params={"learning_rate": 1e-4,
                          "multi_precision": dtype != "float32"},
        sharding="replicated")

    dt, loss = _measure_steps(lambda: trainer.step(*batch), warmup, steps)

    n_chips = len(jax.devices())
    tokens_per_sec_chip = B * T * steps / dt / n_chips
    flops_per_step = _bert_flops_per_step(
        B, T, M, model.num_layers, model._units, model.hidden_size,
        model.vocab_size)
    mfu = (flops_per_step * steps / dt) / (_peak_flops_per_chip() * n_chips)

    return {
        "metric": f"bert_{size}_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "batch": B,
        "seq_len": T,
        "dtype": dtype,
        "flash": flash,
    }


def _gpt_flops_per_step(B, T, L, units, hidden, vocab):
    """Honest fwd+bwd FLOP count for causal LM training (6x matmul
    rule): decoder matmuls + causal O(T^2/2) attention + the full-vocab
    LM head (dominant at GPT-2 vocab). Embedding gathers excluded."""
    dec = 6.0 * B * T * L * (4 * units * units + 2 * units * hidden)
    attn = 6.0 * L * B * T * T * units          # causal: half of full
    head = 6.0 * B * T * units * vocab
    return dec + attn + head


def _run_gpt(on_tpu):
    """GPT-2-small causal-LM pretraining throughput (tokens/s/chip +
    MFU). Exercises the CAUSAL dense Pallas kernels + packed-qkv path —
    the decoder-side complement to the BERT (encoder) headline."""
    import numpy as np
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.models import gpt as gpt_mod

    if on_tpu or os.environ.get("MXTPU_BENCH_TPU_CONFIG") == "1":
        B = int(os.environ.get("MXTPU_BENCH_BATCH", "16"))
        T = 512
        dtype = "bfloat16"
        steps, warmup = (10, 3) if on_tpu else (1, 1)
        flash = True
    else:
        B, T = 2, 64
        dtype = "float32"
        steps, warmup = 3, 1
        flash = False
    remat, dropout = _env_remat_dropout()

    mx.random.seed(0)
    # gpt_small pins max_length=1024 (>= the benched T=512)
    model = gpt_mod.gpt_small(dtype=dtype, flash=flash, remat=remat,
                              dropout=dropout)
    model.initialize()

    rng = np.random.RandomState(0)
    V = model.vocab_size
    batch = (
        nd.array(rng.randint(0, V, (B, T)), dtype="int32"),
        nd.array(rng.randint(0, V, (B, T)), dtype="int32"),
    )

    trainer = parallel.SPMDTrainer(
        model, forward_loss=gpt_mod.lm_loss, optimizer="adamw",
        optimizer_params={"learning_rate": 1e-4,
                          "multi_precision": dtype != "float32"},
        sharding="replicated")

    dt, loss = _measure_steps(lambda: trainer.step(*batch), warmup, steps)

    n_chips = len(jax.devices())
    tokens_per_sec_chip = B * T * steps / dt / n_chips
    flops_per_step = _gpt_flops_per_step(
        B, T, model.num_layers, model._units, model.hidden_size, V)
    mfu = (flops_per_step * steps / dt) / (_peak_flops_per_chip() * n_chips)

    return {
        "metric": "gpt2_small_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "batch": B,
        "seq_len": T,
        "dtype": dtype,
        "flash": flash,
    }


def _run_resnet(on_tpu):
    import numpy as np
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.gluon import loss as gloss
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    if on_tpu or os.environ.get("MXTPU_BENCH_TPU_CONFIG") == "1":
        # separate knob from the BERT flagship's MXTPU_BENCH_BATCH: a
        # BERT batch override must not silently change the ResNet
        # config-#2 batch (B=64) the metric is defined against
        B = int(os.environ.get("MXTPU_BENCH_RESNET_BATCH", "64"))
        side = 224
        dtype = "bfloat16"
        steps, warmup = (10, 3) if on_tpu else (1, 1)
    else:
        B, side = 8, 64
        dtype = "float32"
        steps, warmup = 2, 1

    mx.random.seed(0)
    net = resnet50_v1()
    net.initialize()
    if dtype != "float32":
        # cast params too (the reference's net.cast('float16') recipe) —
        # a bf16 input against f32 weights silently promotes every conv
        # back to f32; multi_precision SGD keeps f32 master weights
        rng0 = np.random.RandomState(0)
        net(nd.array(rng0.rand(1, 3, side, side).astype("float32")))
        net.cast(dtype)

    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(B, 3, side, side).astype("float32"))
    if dtype != "float32":
        x = x.astype(dtype)
    y = nd.array(rng.randint(0, 1000, (B,)), dtype="int32")

    trainer = parallel.SPMDTrainer(
        net, loss=gloss.SoftmaxCrossEntropyLoss(), optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                          "multi_precision": dtype != "float32"},
        sharding="replicated")

    dt, _ = _measure_steps(lambda: trainer.step(x, y), warmup, steps)

    n_chips = len(jax.devices())
    img_per_sec_chip = B * steps / dt / n_chips
    # ResNet-50 fwd at 224^2 is the standard ~4.1 GFLOP/img (mul+add
    # counted); training ~= 3x fwd (fwd + dgrad + wgrad). Scale by
    # spatial area for the CPU-smoke side length.
    fwd_flops = 4.1e9 * (side / 224.0) ** 2
    mfu = (img_per_sec_chip * 3.0 * fwd_flops) / _peak_flops_per_chip()
    return {
        "metric": "resnet50_train_img_per_sec_per_chip",
        "value": round(img_per_sec_chip, 2),
        "unit": "img/s/chip",
        "vs_baseline": 0.0,
        "mfu": round(mfu, 4),
        "batch": B,
        "dtype": dtype,
    }


def _run_ssd(on_tpu):
    """SSD-300 detection training step (BASELINE.md config #5 —
    validates the contrib/custom-op path under training: MultiBoxPrior
    anchors, MultiBoxTarget matching, masked CE + smooth-L1; upstream
    GluonCV scripts/detection/ssd/train_ssd.py, file-level citation)."""
    import numpy as np
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.models.ssd import ssd_300

    if on_tpu or os.environ.get("MXTPU_BENCH_TPU_CONFIG") == "1":
        B = int(os.environ.get("MXTPU_BENCH_SSD_BATCH", "32"))
        side = 300
        steps, warmup = (10, 3) if on_tpu else (1, 1)
    else:
        B, side = 4, 96
        steps, warmup = 2, 1

    mx.random.seed(0)
    net = ssd_300(num_classes=20)
    net.initialize()

    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(B, 3, side, side).astype(np.float32))
    labels = np.full((B, 2, 5), -1.0, np.float32)
    for b in range(B):
        for o in range(2):
            x1, y1 = rng.uniform(0.0, 0.6, 2)
            w, h = rng.uniform(0.2, 0.35, 2)
            labels[b, o] = (rng.randint(0, 20), x1, y1,
                            min(x1 + w, 1.0), min(y1 + h, 1.0))
    y = nd.array(labels)

    def fwd_loss(model, xb, yb):
        anchors, cls_preds, box_preds = model(xb)
        box_t, box_m, cls_t = model.training_targets(anchors, cls_preds,
                                                     yb)
        return model.loss(cls_preds, box_preds, box_t, box_m, cls_t)

    trainer = parallel.SPMDTrainer(
        net, forward_loss=fwd_loss, optimizer="sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                          "wd": 5e-4}, sharding="replicated")

    dt, _ = _measure_steps(lambda: trainer.step(x, y), warmup, steps)
    n_chips = len(jax.devices())
    return {
        "metric": "ssd300_train_img_per_sec_per_chip",
        "value": round(B * steps / dt / n_chips, 2),
        "unit": "img/s/chip",
        "vs_baseline": 0.0,
        "batch": B,
        "side": side,
    }


def _run_nmt(on_tpu):
    """Transformer KV-cached beam-search decode throughput (BASELINE.md
    config #4, the inference path — upstream scripts/nmt translation)."""
    import numpy as np
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.models import transformer as tm

    if on_tpu:
        B, Ts, Tgen, K = 16, 64, 48, 4
        model = tm.transformer_base(max_length=256)
    else:
        B, Ts, Tgen, K = 2, 16, 8, 2
        model = tm.TransformerModel(src_vocab=1000, tgt_vocab=1000,
                                    units=64, hidden_size=128, num_heads=4,
                                    num_layers=2, max_length=64)
    mx.random.seed(0)
    model.initialize()

    rng = np.random.RandomState(0)
    src = nd.array(rng.randint(3, 1000, (B, Ts)), dtype="int32")

    def run():
        out, scores = tm.beam_search_translate_cached(
            model, src, beam_size=K, max_length=Tgen)
        return float(scores.asnumpy().sum())

    run()  # compile
    t0 = time.perf_counter()
    reps = 3 if on_tpu else 1
    for _ in range(reps):
        run()
    dt = time.perf_counter() - t0

    # beam search runs on ONE device (no mesh distribution), so per-chip
    # throughput is the single-device rate — do not divide by device count
    return {
        "metric": "nmt_cached_beam_decode_tokens_per_sec_per_chip",
        "value": round(B * Tgen * reps / dt, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,
        "batch": B,
        "beam": K,
        "gen_len": Tgen,
    }


def _child_main(workload):
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    result = {"bert": _run_bert, "resnet": _run_resnet,
              "nmt": _run_nmt, "gpt": _run_gpt,
              "ssd": _run_ssd}[workload](on_tpu)
    result["platform"] = jax.devices()[0].platform
    print("BENCH_RESULT " + json.dumps(result))


# --------------------------------------------------------------------- #
# orchestrator: hard total deadline, banked best-known result, probe-first
# --------------------------------------------------------------------- #

_T0 = time.monotonic()


def _remaining():
    return DEADLINE - (time.monotonic() - _T0)


class _Bank:
    """Holds the best-known result; exactly one emit, watchdog or main."""

    def __init__(self):
        self._lock = threading.Lock()
        self._emitted = False
        self.result = None

    def update(self, result):
        with self._lock:
            if not self._emitted:
                self.result = result

    def merge(self, **fields):
        with self._lock:
            if not self._emitted and self.result is not None:
                self.result.update(fields)

    def emit(self):
        with self._lock:
            if self._emitted:
                return False
            self._emitted = True
            print(json.dumps(self.result), flush=True)
            return True


def _last_measured_tpu():
    """Newest BENCH_MEASURED_r*.json next to this file, as provenance for
    rounds where the tunnel is down at snapshot time."""
    import glob
    here = os.path.dirname(os.path.abspath(__file__))
    hits = sorted(glob.glob(os.path.join(here, "BENCH_MEASURED_r*.json")))
    if not hits:
        return None
    try:
        with open(hits[-1]) as f:
            data = json.load(f)
        data["source"] = os.path.basename(hits[-1])
        return data
    except (OSError, json.JSONDecodeError):
        return None


_ACTIVE_CHILD = None  # the in-flight child Popen, for watchdog cleanup
_SPAWN_LOCK = threading.Lock()
_SHUTTING_DOWN = False  # set by the watchdog before it kills + exits


def _attempt(workload, platform, timeout):
    """Run one child attempt; returns (result dict | None, error string).

    The child Popen is registered in _ACTIVE_CHILD under _SPAWN_LOCK so
    the deadline watchdog can kill a wedged TPU-init child rather than
    orphan it holding the tunnel after os._exit — and no NEW child can
    slip in between the watchdog's kill and its exit (the TOCTOU race)."""
    global _ACTIVE_CHILD
    if timeout <= 0:
        return None, "budget exhausted"
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        # the smoke must stay the fast small config: a dress-rehearsal
        # override exported in the caller's shell would turn it into the
        # heavy T=512 bf16 run and blow the CPU smoke's time budget
        env.pop("MXTPU_BENCH_TPU_CONFIG", None)
    with _SPAWN_LOCK:
        if _SHUTTING_DOWN:
            return None, "deadline expired"
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--run",
             "--workload", workload],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
        _ACTIVE_CHILD = proc
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        return None, f"timeout after {int(timeout)}s"
    finally:
        _ACTIVE_CHILD = None
    for line in reversed(stdout.splitlines()):
        if line.startswith("BENCH_RESULT "):
            try:
                return json.loads(line[len("BENCH_RESULT "):]), ""
            except json.JSONDecodeError as e:
                return None, f"unparseable result line: {e}"
    tail = (stderr or stdout or "").strip().splitlines()[-8:]
    return None, f"rc={proc.returncode}: " + " | ".join(tail)


def _probe_tpu_start():
    """Kick off a tunnel-liveness probe subprocess (non-blocking)."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return None
    return subprocess.Popen(
        [sys.executable, "-c",
         "import jax; "
         "print('PLATFORMS', ','.join(sorted({d.platform "
         "for d in jax.devices()})))"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=dict(os.environ))


def _probe_tpu_wait(proc, timeout):
    """Tri-state probe outcome: "tpu" (saw an accelerator), "cpu"
    (completed and definitively saw cpu-only — no point gambling an
    attempt), or "timeout" (ambiguous: tunnel wedged OR transient flap)."""
    if proc is None:
        return "cpu"
    try:
        out, _ = proc.communicate(timeout=max(timeout, 1))
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        return "timeout"
    for line in out.splitlines():
        if line.startswith("PLATFORMS "):
            plats = line.split(" ", 1)[1]
            if any(p != "cpu" for p in plats.split(",")):
                return "tpu"
            return "cpu"
    return "timeout"  # probe crashed — as ambiguous as a hang


def main():
    if "--run" in sys.argv:
        wl = "bert"
        if "--workload" in sys.argv:
            wl = sys.argv[sys.argv.index("--workload") + 1]
        _child_main(wl)
        return

    size = os.environ.get("MXTPU_BENCH_MODEL", "base")
    bank = _Bank()
    placeholder = {
        "metric": f"bert_{size}_pretrain_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,
        "platform": "none",
    }
    last_tpu = _last_measured_tpu()
    if last_tpu is not None:
        placeholder["last_tpu"] = last_tpu
    bank.update(placeholder)

    # watchdog: whatever is banked gets printed before the deadline, even
    # if a child subprocess is wedged in TPU backend init
    def _watchdog():
        global _SHUTTING_DOWN
        delay = max(_remaining() - 5, 1)
        time.sleep(delay)
        with _SPAWN_LOCK:  # no new child can spawn past this point
            _SHUTTING_DOWN = True
            child = _ACTIVE_CHILD
            if child is not None:  # don't orphan a wedged child
                try:
                    child.kill()
                except OSError:
                    pass
        if bank.emit():
            os._exit(0)

    threading.Thread(target=_watchdog, daemon=True).start()

    errors = []

    # 1. tunnel probe, concurrent with the CPU smoke
    probe = _probe_tpu_start()

    # 2. CPU smoke — banks a real measured line early
    cpu_res, err = _attempt("bert", "cpu",
                            min(CPU_TIMEOUT, _remaining() - 30))
    if cpu_res is not None:
        if last_tpu is not None:
            cpu_res["last_tpu"] = last_tpu
        bank.update(cpu_res)
    else:
        errors.append(f"cpu: {err}")

    # 3. accelerator attempts. A "tpu" probe earns the full retry ladder;
    #    a "timeout" probe (which can be a transient flap caught at the
    #    wrong moment) still gets ONE gamble attempt if the budget allows
    #    — the banked CPU line + watchdog make that safe; a definitive
    #    "cpu" probe gets none (the gamble would just re-run the same CPU
    #    smoke for minutes).
    verdict = _probe_tpu_wait(probe, min(PROBE_TIMEOUT, _remaining() - 20))
    tpu_res = None
    if probe is not None:
        n_attempts = {"tpu": TPU_ATTEMPTS,
                      "timeout": 1 if _remaining() > 240 else 0,
                      "cpu": 0}[verdict]
        if verdict != "tpu":
            errors.append(f"tpu: liveness probe verdict={verdict}")
        kernel_pinned = False
        for i in range(n_attempts):
            if _remaining() < 120:
                errors.append("tpu: budget exhausted before attempt "
                              f"{i + 1}")
                break
            res, err = _attempt("bert", None,
                                min(TPU_TIMEOUT, _remaining() - 20))
            if res is not None and res.get("platform") != "cpu":
                res["attempts"] = i + 1
                if errors:
                    res["retries"] = "; ".join(errors)[:500]
                tpu_res = res
                bank.update(res)
                break
            errors.append(err if res is None
                          else f"attempt {i + 1} landed on cpu")
            if (res is None and err and i < n_attempts - 1
                    and not kernel_pinned
                    and any(m in err for m in ("Mosaic", "mosaic",
                                               "pallas_call", "Pallas"))):
                # kernel-compile regression (not a tunnel flake): FORCE
                # the full hardware-validated kernel configuration for
                # the remaining attempts — hpp=1 assigned outright and
                # every other trace-time kernel knob cleared back to its
                # validated default (an operator-exported override on
                # ANY of them may be the very thing that broke). Local
                # flag = applied exactly once.
                kernel_pinned = True
                os.environ["MXTPU_FLASH_FWD_HPP"] = "1"
                os.environ["MXTPU_FLASH_BWD_HPP"] = "1"
                for var in ("MXTPU_FLASH_DENSE_T", "MXTPU_FLASH_BLOCK_Q",
                            "MXTPU_FLASH_BLOCK_K"):
                    os.environ.pop(var, None)
                errors.append("kernel error -> retrying with the pinned "
                              "validated kernel config")
            if res is not None:
                # child saw no TPU but DID complete the CPU smoke — bank
                # it if step 2's CPU smoke failed, then stop burning budget
                if bank.result.get("platform") == "none":
                    if last_tpu is not None:
                        res["last_tpu"] = last_tpu
                    bank.update(res)
                break
            if i < n_attempts - 1 and _remaining() > 150:
                time.sleep(BACKOFFS[min(i, len(BACKOFFS) - 1)])

    # 4. measured extras with leftover budget (BASELINE configs
    #    #2/#4/#5); on the TPU path they are on by default, CPU opt-in
    extras = {}
    run_extras_cpu = os.environ.get("MXTPU_BENCH_RESNET") == "1"
    platform = None if tpu_res is not None else "cpu"
    if tpu_res is not None or run_extras_cpu:
        if _remaining() > 180:
            rn, err = _attempt("resnet", platform, _remaining() - 60)
            extras["resnet"] = rn if rn is not None else {"error": err[:300]}
        if _remaining() > 150:
            sd, err = _attempt("ssd", platform, _remaining() - 45)
            extras["ssd"] = sd if sd is not None else {"error": err[:300]}
        if _remaining() > 120:
            nm, err = _attempt("nmt", platform, _remaining() - 30)
            extras["nmt"] = nm if nm is not None else {"error": err[:300]}
    if extras:
        bank.merge(extra=extras)

    if errors and tpu_res is None:
        key = ("error" if bank.result.get("platform") == "none"
               else "retries")
        bank.merge(**{key: "; ".join(e for e in errors if e)[:500]})

    bank.emit()


if __name__ == "__main__":
    main()
