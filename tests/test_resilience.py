"""Resilience-layer tests (serve/outcomes.py, serve/chaos.py, the
engine's overload/fault handling — docs/RESILIENCE.md).

The load-bearing claims: (1) EVERY request submitted to the engine ends
in exactly one structured terminal Outcome — overload, deadlines,
poisoned math and page starvation included; (2) the engine's health
counters are consistent with the per-request outcomes; (3) fault
handling is pure data / host bookkeeping — the decode step never
retraces; (4) pages are reclaimed exactly under every failure path
(audit_pages); (5) faults stay confined to the requests they hit —
other slots' tokens are bit-identical to a fault-free run."""

import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.models import gpt as g
from incubator_mxnet_tpu.serve import (InferenceEngine, Outcome,
                                       PageAllocator, Request)
from incubator_mxnet_tpu.serve.chaos import (CorruptPageWrite,
                                             DelayedSteps, NaNWeights,
                                             PagePressure,
                                             assert_health_consistent,
                                             run_chaos)

VOCAB = 64


@pytest.fixture(scope="module")
def model():
    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=VOCAB, max_length=64)
    m.initialize()
    return m


def _fresh_model(seed=0):
    """Function-scoped model for tests that POISON weights — the
    module fixture must never see NaN."""
    mx.random.seed(seed)
    m = g.gpt_mini(vocab_size=VOCAB, max_length=64)
    m.initialize()
    return m


def _prompt(rng, n):
    return rng.randint(0, VOCAB, size=(n,)).astype(np.int32)


def _solo_reference(model, prompt, max_new):
    out = g.cached_generate(model, nd.array(prompt[None, :],
                                            dtype="int32"),
                            max_new_tokens=max_new).asnumpy()
    return out[0, prompt.size:]


def _nan_params(eng, rng, n_entries=4):
    """Engine params with a few NaN entries in the embedding table."""
    params = {str(i): np.asarray(p.data().asnumpy())
              for i, p in enumerate(eng._eng_params)}
    tab = params["0"].copy()
    flat = tab.reshape(-1)
    flat[rng.choice(flat.size, size=n_entries, replace=False)] = np.nan
    params["0"] = tab
    return params


# ------------------------------------------------------------------ #
# outcome taxonomy: every terminal outcome reachable in a unit test
# ------------------------------------------------------------------ #

def test_success_outcomes_eos_and_max_tokens(model):
    rng = np.random.RandomState(1)
    prompt = _prompt(rng, 6)
    ref = _solo_reference(model, prompt, 10)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    r_max = Request(prompt.copy(), max_new_tokens=10)
    r_eos = Request(prompt.copy(), max_new_tokens=10, eos_id=int(ref[2]))
    eng.run([r_max, r_eos])
    assert r_max.outcome == Outcome.MAX_TOKENS and r_max.outcome.ok
    assert r_eos.outcome == Outcome.EOS and r_eos.outcome.ok
    assert eng.completed == 2 and eng.health["EOS"] == 1
    assert_health_consistent(eng, [r_max, r_eos])
    eng.audit_pages()


def test_shed_at_queue_depth_limit(model):
    """Bounded admission queue: the flood beyond ``max_queue`` is shed
    with a retry-after hint, the rest is served normally."""
    rng = np.random.RandomState(2)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          max_queue=2)
    reqs = [Request(_prompt(rng, 5), max_new_tokens=3)
            for _ in range(6)]
    accepted = [eng.submit(r) for r in reqs]
    # 1 admitted... no: submit only queues; 2 fit the queue bound
    assert accepted == [True, True, False, False, False, False]
    shed = [r for r in reqs if r.outcome == Outcome.SHED]
    assert len(shed) == 4 and eng.shed == 4
    assert all(r.retry_after_s is not None and r.retry_after_s > 0
               for r in shed)
    assert all("depth limit" in r.detail for r in shed)
    eng.run([])                              # drain the two queued
    assert all(r.outcome is not None for r in reqs)
    assert eng.completed == 2
    assert_health_consistent(eng, reqs)
    eng.audit_pages()


def test_shed_on_estimated_queue_delay(model):
    """EWMA-based delay shedding: after one completion calibrates the
    slot-residence estimate, a BACKLOG beyond the free slots under a
    tight delay limit sheds — but an idle engine (queue fits free
    slots, estimated delay zero) must keep admitting: a tier that
    sheds 100% of traffic at zero load because its own steady-state
    latency exceeds the limit is the bug, not the feature."""
    rng = np.random.RandomState(3)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          max_queue_delay_s=1e-9)
    first = Request(_prompt(rng, 5), max_new_tokens=3)
    eng.run([first])                         # calibrates the EWMA
    assert first.outcome.ok and eng._ewma_service_s is not None
    # idle engine, empty queue: estimated delay is 0 — NOT shed, and
    # it is served to a success outcome
    idle_ok = Request(_prompt(rng, 5), max_new_tokens=3)
    assert eng.submit(idle_ok)
    # a second submit now has a backlog beyond the free slot count:
    # waves >= 1, estimate > the (tiny) limit -> shed with the hint
    late = Request(_prompt(rng, 5), max_new_tokens=3)
    assert not eng.submit(late)
    assert late.outcome == Outcome.SHED
    assert "estimated queue delay" in late.detail
    assert late.retry_after_s is not None and late.retry_after_s > 0
    eng.run([])                              # drain the admitted one
    assert idle_ok.outcome is not None and idle_ok.outcome.ok


def test_deadline_expired_mid_queue(model):
    """A queued request whose deadline passes before a slot frees is
    dropped terminally — it never occupies a slot."""
    rng = np.random.RandomState(4)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64)
    hog = Request(_prompt(rng, 5), max_new_tokens=40)
    doomed = Request(_prompt(rng, 5), max_new_tokens=4,
                     deadline_s=0.001)
    eng.run([hog, doomed])
    assert hog.outcome is not None and hog.outcome.ok
    assert doomed.outcome == Outcome.DEADLINE_EXPIRED
    assert "queued" in doomed.detail
    assert doomed.token_ids == []            # never served
    assert eng.expired == 1
    assert_health_consistent(eng, [hog, doomed])
    eng.audit_pages()


def test_deadline_expired_mid_decode(model):
    """A decoding slot past its deadline is evicted with its pages
    reclaimed; the partial tokens are kept (they were real)."""
    rng = np.random.RandomState(5)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64)
    # warm the programs so the deadline measures decode, not compile
    eng.run([Request(_prompt(rng, 5), max_new_tokens=2)])
    req = Request(_prompt(rng, 5), max_new_tokens=50, deadline_s=0.02)
    eng.run([req])
    assert req.outcome == Outcome.DEADLINE_EXPIRED
    assert "decode" in req.detail or "prefill" in req.detail
    assert 0 < len(req.token_ids) < 50
    assert eng.expired == 1
    eng.audit_pages()
    assert eng.decode_trace_count == 1


def test_per_slot_wall_cap(model):
    """``max_slot_wall_s`` is an engine-imposed deadline: no request
    may hold a slot longer, whatever its own deadline says."""
    rng = np.random.RandomState(6)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          max_slot_wall_s=0.02)
    r2 = Request(_prompt(rng, 5), max_new_tokens=50)
    eng.run([r2])
    assert r2.outcome == Outcome.DEADLINE_EXPIRED
    assert "wall cap" in r2.detail
    eng.audit_pages()


def test_nonfinite_quarantine_mid_decode():
    """Weights poisoned AFTER a request is decoding: the per-slot guard
    flag fails the slot the very next decode step — no garbage token is
    ever recorded, the decode step does not retrace."""
    model = _fresh_model(101)
    rng = np.random.RandomState(7)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    req = Request(_prompt(rng, 6), max_new_tokens=40)
    eng.submit(req)
    eng.step()                               # admit + prefill + decode
    eng.step()
    tokens_before = len(req.token_ids)
    assert tokens_before >= 2
    eng.warm_start(params=_nan_params(eng, rng))
    while req.outcome is None:
        eng.step()
    assert req.outcome == Outcome.FAILED_NONFINITE
    assert "decode" in req.detail
    # the poisoned step's token was never recorded — the guard fires
    # before _finish_token, so no garbage token reaches the stream
    assert len(req.token_ids) == tokens_before
    assert eng.quarantined == 1
    assert eng.decode_trace_count == 1, "guard flag retraced decode"
    eng.audit_pages()


def test_nonfinite_quarantine_in_prefill():
    """Poisoned weights present at admission: the prefill guard fails
    the request before it ever becomes decode-visible, and its prompt
    pages are NOT published into the prefix index."""
    model = _fresh_model(102)
    rng = np.random.RandomState(8)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    eng.warm_start(params=_nan_params(eng, rng))
    req = Request(_prompt(rng, 20), max_new_tokens=8)
    eng.run([req])
    assert req.outcome == Outcome.FAILED_NONFINITE
    assert "prefill" in req.detail
    assert req.token_ids == []
    assert len(eng._prefix) == 0, \
        "poisoned prompt pages were published to the prefix index"
    eng.audit_pages()
    assert eng._alloc.free_count == eng.num_pages - 1


def test_unservable_fail_fast_at_submit(model):
    """A request that can NEVER fit (positions or worst-case pages)
    fails at submit — no exception, no queue head-of-line wedge."""
    rng = np.random.RandomState(9)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          num_pages=3)
    too_long = Request(_prompt(rng, 30), max_new_tokens=60)
    assert not eng.submit(too_long)
    assert too_long.outcome == Outcome.FAILED_UNSERVABLE
    too_many_pages = Request(_prompt(rng, 8), max_new_tokens=16)
    assert not eng.submit(too_many_pages)
    assert too_many_pages.outcome == Outcome.FAILED_UNSERVABLE
    assert eng.unservable == 2
    assert not eng._queue


def test_watchdog_evicts_page_starved_slot(model):
    """Full allocator starvation mid-decode: the stalled slot sits out
    decode steps (its masked write cannot touch a real page) and the
    watchdog fails it after ``watchdog_steps`` of zero progress —
    engine audit stays exact throughout, with the held pages counted."""
    rng = np.random.RandomState(10)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          watchdog_steps=6, prefix_cache=False)
    req = Request(_prompt(rng, 7), max_new_tokens=40)
    eng.submit(req)
    eng.step()
    assert req.outcome is None
    held = eng._alloc.hold(10 ** 6)          # capped at free_count
    assert eng._alloc.free_count == 0
    steps = 0
    while req.outcome is None and steps < 50:
        eng.step()
        eng.audit_pages()
        steps += 1
    assert req.outcome == Outcome.FAILED_UNSERVABLE
    assert "watchdog" in req.detail
    assert steps <= 8
    eng._alloc.release_held()
    eng.audit_pages()
    assert eng._alloc.free_count == eng.num_pages - 1
    assert len(held) > 0


def test_run_fails_starved_queue_head_and_keeps_serving(model):
    """Queue-head starvation while the engine is idle: after
    ``stall_steps`` idle polls the head goes FAILED_UNSERVABLE and the
    requests behind it are still served (no head-of-line wedge)."""
    rng = np.random.RandomState(11)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          num_pages=6, stall_steps=5, prefix_cache=False)
    held = eng._alloc.hold(3)                # leave 2 free pages
    big = Request(_prompt(rng, 8), max_new_tokens=16)   # needs 3 pages
    small = Request(_prompt(rng, 5), max_new_tokens=8)  # needs 2 pages
    ref = _solo_reference(model, small.prompt_ids, 8)
    eng.run([big, small], poll_sleep=1e-4)
    assert big.outcome == Outcome.FAILED_UNSERVABLE
    assert "page-starved" in big.detail
    assert small.outcome is not None and small.outcome.ok
    np.testing.assert_array_equal(np.asarray(small.token_ids, np.int32),
                                  ref)
    eng._alloc.release_held(held)
    eng.audit_pages()


def test_shutdown_reaches_quiescence(model):
    """shutdown() (the SIGTERM drain path): every active and queued
    request becomes terminal SHED, pages are reclaimed, the engine is
    reusable."""
    rng = np.random.RandomState(12)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    reqs = [Request(_prompt(rng, 5), max_new_tokens=30)
            for _ in range(4)]
    for r in reqs:
        eng.submit(r)
    eng.step()
    eng.step()
    eng.shutdown("preemption drain")
    assert all(r.outcome == Outcome.SHED for r in reqs)
    assert all(r.detail == "preemption drain" for r in reqs)
    assert eng.active_count == 0 and not eng._queue
    assert_health_consistent(eng, reqs)
    eng.audit_pages()
    # the engine itself is still healthy: serve another request
    again = Request(_prompt(rng, 5), max_new_tokens=3)
    eng.run([again])
    assert again.outcome is not None and again.outcome.ok
    assert eng.decode_trace_count == 1


def test_double_finish_is_refused(model):
    rng = np.random.RandomState(13)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64)
    req = Request(_prompt(rng, 5), max_new_tokens=2)
    eng.run([req])
    assert req.outcome is not None
    with pytest.raises(MXNetError, match="already terminal"):
        eng._record_terminal(req, Outcome.SHED)


def test_allocator_hold_release_accounting():
    """The chaos pressure hook goes through the allocator's own
    bookkeeping: held pages have refcount 1, are listed, and release
    restores the free list exactly; over-hold is capped."""
    a = PageAllocator(8)
    held = a.hold(3)
    assert len(held) == 3 and a.free_count == 4
    assert sorted(a.held) == sorted(held)
    assert all(a.refcount(p) == 1 for p in held)
    more = a.hold(100)                       # capped at what's left
    assert len(more) == 4 and a.free_count == 0
    a.release_held(held)
    assert a.free_count == 3 and sorted(a.held) == sorted(more)
    a.release_held()
    assert a.free_count == 7 and a.held == ()


# ------------------------------------------------------------------ #
# chaos injectors (the heavier end-to-end scenarios live in
# tools/chaos_bench.py --smoke, the ci chaossmoke stage)
# ------------------------------------------------------------------ #

@pytest.mark.slow
def test_chaos_corrupt_page_isolation():
    """NaN page corruption: exactly the mapped slot's request is
    quarantined; every other request's tokens are bit-identical to the
    fault-free run; audit passes every step; decode compiled once."""
    rng = np.random.RandomState(20)
    prompts = [_prompt(rng, 4 + 3 * i) for i in range(5)]
    news = [6 + 2 * i for i in range(5)]

    model_a = _fresh_model(103)
    base = [Request(p.copy(), max_new_tokens=k)
            for p, k in zip(prompts, news)]
    eng_a = InferenceEngine(model_a, num_slots=2, page_size=8,
                            max_len=64, prefix_cache=False)
    run_chaos(eng_a, base, [])               # fault-free baseline
    baseline = [list(r.token_ids) for r in base]

    model_b = _fresh_model(103)
    reqs = [Request(p.copy(), max_new_tokens=k)
            for p, k in zip(prompts, news)]
    eng_b = InferenceEngine(model_b, num_slots=2, page_size=8,
                            max_len=64, prefix_cache=False)
    inj = CorruptPageWrite(at_step=3, mode="nan", seed=1)
    run_chaos(eng_b, reqs, [inj])
    assert inj.fired and len(inj.affected) == 1
    hit = inj.affected[0]
    assert hit.outcome == Outcome.FAILED_NONFINITE
    for r, bt in zip(reqs, baseline):
        if r is not hit:
            assert r.outcome.ok and list(r.token_ids) == bt
    assert eng_b.decode_trace_count == 1
    assert_health_consistent(eng_b, reqs)


@pytest.mark.slow
def test_chaos_transient_page_pressure_full_parity():
    """Allocator pressure is pure scheduling: held pages slow things
    down but change NO data — with the pressure released, every request
    completes bit-identical to the fault-free run."""
    rng = np.random.RandomState(21)
    prompts = [_prompt(rng, 4 + 3 * i) for i in range(5)]
    news = [6 + 2 * i for i in range(5)]

    model_a = _fresh_model(104)
    base = [Request(p.copy(), max_new_tokens=k)
            for p, k in zip(prompts, news)]
    eng_a = InferenceEngine(model_a, num_slots=2, page_size=8,
                            max_len=64, prefix_cache=False)
    run_chaos(eng_a, base, [])
    baseline = [list(r.token_ids) for r in base]

    model_b = _fresh_model(104)
    reqs = [Request(p.copy(), max_new_tokens=k)
            for p, k in zip(prompts, news)]
    eng_b = InferenceEngine(model_b, num_slots=2, page_size=8,
                            max_len=64, prefix_cache=False,
                            watchdog_steps=200)
    inj = PagePressure(hold_at=2, release_after=12)
    run_chaos(eng_b, reqs, [inj])
    assert inj.fired
    for r, bt in zip(reqs, baseline):
        assert r.outcome.ok and list(r.token_ids) == bt
    assert eng_b._alloc.held == ()
    assert_health_consistent(eng_b, reqs)


def test_chaos_delayed_steps_expire_deadlines(model):
    """Host stalls (DelayedSteps) blow the requests' deadlines: every
    request still terminates — DEADLINE_EXPIRED or ok — never wedged."""
    rng = np.random.RandomState(22)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    # warm so compile time doesn't eat the deadlines
    eng.run([Request(_prompt(rng, 5), max_new_tokens=2)])
    reqs = [Request(_prompt(rng, 5), max_new_tokens=30,
                    deadline_s=0.25) for _ in range(3)]
    inj = DelayedSteps(start=2, end=10 ** 9, sleep_s=0.1)
    run_chaos(eng, reqs, [inj])
    assert all(r.outcome is not None for r in reqs)
    assert any(r.outcome == Outcome.DEADLINE_EXPIRED for r in reqs)
    eng.audit_pages()
    assert eng.decode_trace_count == 1
