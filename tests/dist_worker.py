"""Worker program for the multi-host test (run via tools/launch.py
--launcher local with 2 processes; mirrors the reference's
tests/nightly/dist_sync_kvstore.py).

Each process gets 4 virtual CPU devices (global mesh: 8 devices over 2
processes). Exercises: jax.distributed bootstrap from the launcher env,
kvstore('dist_sync') push/pull aggregation across ranks, and two fused
SPMDTrainer steps over the GLOBAL mesh, asserting identical parameters on
every rank afterwards."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from incubator_mxnet_tpu.parallel import mesh as pmesh  # noqa: E402

pmesh.initialize()  # reads MXTPU_* env set by tools/launch.py

import numpy as np  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import nd, gluon, parallel  # noqa: E402
from incubator_mxnet_tpu import kvstore as kvs  # noqa: E402


def main():
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())
    rank = jax.process_index()

    # ---- kvstore dist_sync: push sums across ranks ------------------- #
    store = kvs.create("dist_sync")
    assert store.rank == rank and store.num_workers == 2
    store.init("w", nd.array(np.zeros(4, np.float32)))
    store.push("w", nd.array(np.full(4, float(rank + 1), np.float32)))
    out = nd.zeros((4,))
    store.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0)  # 1 + 2

    # bf16-compressed cross-process reduction: real wire savings, values
    # exact here (small integers are bf16-representable)
    store2 = kvs.create("dist_sync")
    store2.set_gradient_compression({"type": "bf16"})
    store2.init("g", nd.array(np.zeros(4, np.float32)))
    store2.push("g", nd.array(np.full(4, float(rank + 1), np.float32)))
    out2 = nd.zeros((4,))
    store2.pull("g", out=out2)
    np.testing.assert_allclose(out2.asnumpy(), 3.0)

    # ---- fused SPMD step over the global 8-device mesh --------------- #
    mx.random.seed(42)  # identical init on every rank (SPMD contract)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, in_units=8, activation="relu"),
            gluon.nn.Dense(4, in_units=16))
    net.initialize()
    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(16,))

    mesh = pmesh.build_mesh(axis_sizes={"dp": 8})
    tr = parallel.SPMDTrainer(
        net, loss=gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        mesh=mesh)
    for _ in range(2):
        loss = tr.step(nd.array(X), nd.array(y))
    loss_val = float(loss.asnumpy())
    assert np.isfinite(loss_val), loss_val

    # ---- identical params across ranks ------------------------------- #
    from jax.experimental import multihost_utils
    for name, p in sorted(net.collect_params().items()):
        local = np.asarray(p.data()._data)  # replicated → addressable
        gathered = multihost_utils.process_allgather(local)
        np.testing.assert_allclose(gathered[0], gathered[1], rtol=0,
                                   atol=0, err_msg=name)

    print(f"DIST_WORKER_OK rank={rank} loss={loss_val:.4f}", flush=True)


if __name__ == "__main__":
    main()
