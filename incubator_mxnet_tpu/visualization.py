"""Network visualization (parity: `python/mxnet/visualization.py` —
``print_summary`` and ``plot_network``; file-level citation, SURVEY.md
caveat).

``print_summary`` walks the Symbol graph and prints a layer table with
output shapes and parameter counts. ``plot_network`` renders a graphviz
digraph when the ``graphviz`` package is importable and raises a clear
gated error otherwise (the image does not ship graphviz)."""

from __future__ import annotations

from typing import Dict, Optional

from .base import MXNetError
from .symbol.symbol import _topo as _topo_heads

__all__ = ["print_summary", "plot_network"]


def _topo(symbol):
    return _topo_heads(symbol._heads)


def print_summary(symbol, shape: Optional[Dict[str, tuple]] = None,
                  line_length: int = 98, positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a Keras-style layer summary of ``symbol``.

    ``shape``: dict of input-name -> shape used to infer per-layer output
    shapes (optional — the Shape column is empty without it).
    """
    shapes_by_name: Dict[str, tuple] = {}
    arg_shape_by_name: Dict[str, tuple] = {}
    if shape is not None:
        arg_shapes, _, _ = symbol.infer_shape(**shape)
        arg_shape_by_name = dict(zip(symbol.list_arguments(),
                                     (tuple(a) for a in arg_shapes)))
        internals = symbol.get_internals()
        # one entry per internal output, keyed by node name
        _, int_shapes, _ = internals.infer_shape(**shape)
        for s, (node, idx) in zip(int_shapes, internals._heads):
            if idx == 0:
                shapes_by_name[node.name] = tuple(s)

    positions = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields):
        line = ""
        for f, pos in zip(fields, positions):
            line += str(f)
            line = line[:pos - 1]
            line += " " * (pos - len(line))
        print(line)

    print("_" * line_length)
    print_row(headers)
    print("=" * line_length)

    # inputs = the user-provided shape keys (fall back to the "data"
    # naming convention when no shapes are given); everything else that
    # is a variable counts as a parameter
    input_names = set(shape.keys()) if shape else {"data"}
    total_params = 0
    for node in _topo(symbol):
        if node.is_variable:
            continue
        out_shape = shapes_by_name.get(node.name, "")
        n_params = 0
        prev = []
        for inp, _ in node.inputs:
            if inp.is_variable and inp.name not in input_names:
                sh = shapes_by_name.get(inp.name) or \
                    arg_shape_by_name.get(inp.name)
                if sh:
                    p = 1
                    for d in sh:
                        p *= int(d)
                    n_params += p
            elif not inp.is_variable:
                prev.append(inp.name)
        total_params += n_params
        print_row([f"{node.name} ({node.op})", out_shape, n_params,
                   ",".join(prev)])
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Render the Symbol graph as a graphviz Digraph (gated on the
    ``graphviz`` package; parity: mx.viz.plot_network)."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError(
            "plot_network requires the 'graphviz' python package, which "
            "is not installed in this image; use "
            "mx.viz.print_summary(sym, shape) for a text summary"
        ) from e

    node_attrs = node_attrs or {}
    dot = Digraph(name=title, format=save_format)
    dot.attr("node", shape="box", fixedsize="false",
             fontsize="10", **node_attrs)
    for node in _topo(symbol):
        if node.is_variable and hide_weights and node.name != "data":
            continue
        color = "#8dd3c7" if node.is_variable else "#fb8072"
        dot.node(str(id(node)), label=f"{node.name}\n{node.op}",
                 style="filled", fillcolor=color)
        for inp, _ in node.inputs:
            if inp.is_variable and hide_weights and inp.name != "data":
                continue
            dot.edge(str(id(inp)), str(id(node)))
    return dot
