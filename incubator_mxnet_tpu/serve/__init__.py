"""Continuous-batching inference engine (the serving half of the
ROADMAP north star — "serves heavy traffic from millions of users").

The reference's inference story is per-request: a bound Module / a
GluonNLP beam-search decoder owns one dense state per call
(`python/mxnet/module/module.py` forward, `gluonnlp` BeamSearchSampler —
file-level citations, SURVEY.md caveat). That shape dies under ragged
traffic: every request pays attention and cache memory over ``Tmax``.
This package replaces it with the TPU-serving discipline (arxiv
2604.15464, 2605.25645):

  - ``paged_kv``   — a shared KV page pool + per-slot page tables, so
                     cache memory scales with LIVE tokens;
  - ``engine``     — a fixed-slot continuous-batching scheduler whose
                     decode step is ONE jitted program whose shapes
                     never depend on occupancy (prefill-insert and
                     EOS-eviction are host-side data edits, never
                     retraces);
  - ``router``     — a fleet front over N engine replicas:
                     cache-affinity admission (read-only
                     ``prefix_probe``), least-delay spill, heartbeat/
                     circuit-breaker health states, and bounded
                     structured failover — replica death becomes a
                     re-queue with emitted tokens preserved, never a
                     lost request (docs/RESILIENCE.md).

The ragged decode-attention kernel itself lives in
``ops.ragged_attention`` next to its training-side siblings.

See docs/SERVING.md for the architecture and invariants.
"""

from .paged_kv import (NULL_PAGE, PageAllocator, PrefixIndex,
                       init_kv_pools, write_block_kv, write_prompt_kv,
                       write_token_kv)
from .events import Event, EventType, FlightRecorder
from .outcomes import Outcome
from .slo import (BrownoutController, Tier, TierPolicy,
                  default_tier_policies)
from .draft import make_ngram_drafter, ngram_propose
from .sampling import (SamplingParams, TokenFsm, TokenGrammar,
                       choice_grammar)
from .engine import InferenceEngine, Request
from .transport import PageCapsule, PageTransport
from .router import (Replica, ReplicaKilled, ReplicaState, Router,
                     build_fleet)
from .fleet_supervisor import FleetSupervisor
from .metrics import render_metrics
from .frontend import (OUTCOME_HTTP_STATUS, ServeFrontend,
                       stream_completion)

__all__ = ["InferenceEngine", "Request", "Outcome", "PageAllocator",
           "PrefixIndex", "NULL_PAGE", "init_kv_pools", "write_token_kv",
           "write_prompt_kv", "write_block_kv", "ngram_propose",
           "make_ngram_drafter", "Router", "Replica", "ReplicaState",
           "ReplicaKilled", "build_fleet", "Tier", "TierPolicy",
           "default_tier_policies", "BrownoutController",
           "render_metrics", "Event", "EventType", "FlightRecorder",
           "SamplingParams", "TokenGrammar", "TokenFsm",
           "choice_grammar", "ServeFrontend", "OUTCOME_HTTP_STATUS",
           "stream_completion", "PageCapsule", "PageTransport",
           "FleetSupervisor"]
