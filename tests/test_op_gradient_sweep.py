"""Registry-wide numeric-gradient sweep (VERDICT r4 item 6).

Reference idiom: ``tests/python/unittest/test_operator.py`` gradient-checks
essentially every differentiable operator (file-level citation, SURVEY.md
caveat). Here one classified table covers the ENTIRE op registry:

  - ``GRAD_CASES``  — differentiable ops, checked against central finite
    differences via ``check_numeric_gradient`` on small shapes (inputs
    chosen away from kinks: offsets for relu/abs, SPD matrices for
    Cholesky, distinct values for max/sort, ...).
  - ``NONDIFF``     — ops whose outputs are integer/boolean/assignment
    results, value-independent, or zero-gradient by definition.
  - ``CUSTOM_GRAD`` — training heads whose forward is a pass-through and
    whose backward injects the loss gradient by design (numeric diff of
    the forward cannot match: SoftmaxOutput & friends).
  - ``SKIP``        — differentiable but excluded here with an explicit
    reason (stochastic samplers, decomposition gradients covered by
    dedicated tests, fused packed-parameter RNN).

``test_registry_fully_classified`` fails when a newly registered op is
not in exactly one bucket, so the sweep can never silently go stale.
"""

import numpy as np
import pytest

from incubator_mxnet_tpu import nd, ops
from incubator_mxnet_tpu.test_utils import check_numeric_gradient

R = np.random.RandomState


def _a(shape, seed=0, lo=-1.0, hi=1.0):
    return nd.array(R(seed).uniform(lo, hi, shape).astype(np.float32))


def _away(shape, seed=0, lo=0.2, hi=1.0):
    """Values in ±[lo, hi] — bounded away from 0 (relu/abs/sign kinks)."""
    r = R(seed)
    mag = r.uniform(lo, hi, shape)
    sgn = np.where(r.rand(*shape) < 0.5, -1.0, 1.0)
    return nd.array((mag * sgn).astype(np.float32))


def _distinct(shape, seed=0, scale=0.1):
    """Distinct values (max/min/sort/pool ties break finite differences)."""
    n = int(np.prod(shape))
    vals = (np.arange(n, dtype=np.float32) - n / 2) * scale
    return nd.array(R(seed).permutation(vals).reshape(shape))


def _spd(n, seed=0):
    m = R(seed).randn(n, n).astype(np.float32)
    return nd.array(m @ m.T + n * np.eye(n, dtype=np.float32))


def _ints(shape, hi, seed=0):
    return nd.array(R(seed).randint(0, hi, shape).astype(np.int32),
                    dtype="int32")


def _sumall(out):
    """Reduce an op output (array or list of arrays) to one scalar."""
    if isinstance(out, (list, tuple)):
        total = out[0].sum()
        for o in out[1:]:
            total = total + o.sum()
        return total
    return out.sum()


# --------------------------------------------------------------------- #
# differentiable ops: name -> thunk() -> (fn, inputs[, options])
# options: grad_nodes, rtol, atol, eps
# --------------------------------------------------------------------- #
GRAD_CASES = {
    # -- unary elementwise (smooth, or checked away from kinks) --------- #
    "abs": lambda: (nd.abs, [_away((2, 3))]),
    "arccos": lambda: (nd.arccos, [_a((2, 3), lo=-0.8, hi=0.8)]),
    "arccosh": lambda: (nd.arccosh, [_a((2, 3), lo=1.2, hi=2.0)]),
    "arcsin": lambda: (nd.arcsin, [_a((2, 3), lo=-0.8, hi=0.8)]),
    "arcsinh": lambda: (nd.arcsinh, [_a((2, 3))]),
    "arctan": lambda: (nd.arctan, [_a((2, 3))]),
    "arctanh": lambda: (nd.arctanh, [_a((2, 3), lo=-0.8, hi=0.8)]),
    "cbrt": lambda: (nd.cbrt, [_away((2, 3))]),
    "cos": lambda: (nd.cos, [_a((2, 3))]),
    "cosh": lambda: (nd.cosh, [_a((2, 3))]),
    "degrees": lambda: (nd.degrees, [_a((2, 3))]),
    "digamma": lambda: (nd.digamma, [_a((2, 3), lo=0.5, hi=2.0)]),
    "erf": lambda: (nd.erf, [_a((2, 3))]),
    "erfinv": lambda: (nd.erfinv, [_a((2, 3), lo=-0.8, hi=0.8)]),
    "exp": lambda: (nd.exp, [_a((2, 3))]),
    "expm1": lambda: (nd.expm1, [_a((2, 3))]),
    "gamma": lambda: (nd.gamma, [_a((2, 3), lo=0.5, hi=2.0)]),
    "gammaln": lambda: (nd.gammaln, [_a((2, 3), lo=0.5, hi=2.0)]),
    "gelu": lambda: (nd.gelu, [_a((2, 3))]),
    "hard_sigmoid": lambda: (nd.hard_sigmoid, [_a((2, 3))]),
    "identity": lambda: (nd.identity, [_a((2, 3))]),
    "log": lambda: (nd.log, [_a((2, 3), lo=0.2, hi=2.0)]),
    "log10": lambda: (nd.log10, [_a((2, 3), lo=0.2, hi=2.0)]),
    "log1p": lambda: (nd.log1p, [_a((2, 3), lo=-0.5, hi=2.0)]),
    "log2": lambda: (nd.log2, [_a((2, 3), lo=0.2, hi=2.0)]),
    "negative": lambda: (nd.negative, [_a((2, 3))]),
    "quadratic": lambda: (
        lambda x: nd.quadratic(x, a=0.3, b=-0.7, c=1.1), [_a((2, 3))]),
    "radians": lambda: (nd.radians, [_a((2, 3))]),
    "rcbrt": lambda: (nd.rcbrt, [_a((2, 3), lo=0.3, hi=1.5)]),
    "reciprocal": lambda: (nd.reciprocal, [_away((2, 3), lo=0.4)]),
    "relu": lambda: (nd.relu, [_away((2, 3))]),
    "rsqrt": lambda: (nd.rsqrt, [_a((2, 3), lo=0.3, hi=2.0)]),
    "sigmoid": lambda: (nd.sigmoid, [_a((2, 3))]),
    "sin": lambda: (nd.sin, [_a((2, 3))]),
    "sinh": lambda: (nd.sinh, [_a((2, 3))]),
    "smooth_l1": lambda: (
        lambda x: nd.smooth_l1(x, scalar=1.0), [_a((2, 3))]),
    "softsign": lambda: (nd.softsign, [_a((2, 3))]),
    "sqrt": lambda: (nd.sqrt, [_a((2, 3), lo=0.3, hi=2.0)]),
    "square": lambda: (nd.square, [_a((2, 3))]),
    "tan": lambda: (nd.tan, [_a((2, 3))]),
    "tanh": lambda: (nd.tanh, [_a((2, 3))]),
    "clip": lambda: (
        lambda x: nd.clip(x, a_min=-2.0, a_max=2.0), [_a((2, 3))]),
    "Cast": lambda: (
        lambda x: nd.Cast(x, dtype="float32"), [_a((2, 3))]),
    "amp_cast": lambda: (
        lambda x: nd.amp_cast(x, dtype="float32"), [_a((2, 3))]),
    "amp_multicast": lambda: (
        lambda a, b: _sumall(nd.amp_multicast(a, b, num_outputs=2)),
        [_a((2, 3)), _a((3,), seed=1)]),
    "Activation": lambda: (
        lambda x: nd.Activation(x, act_type="softrelu"), [_a((2, 3))]),
    "LeakyReLU": lambda: (
        lambda x: nd.LeakyReLU(x, act_type="leaky", slope=0.25),
        [_away((2, 3))]),
    "gradientmultiplier_scale1": None,  # placeholder, see CUSTOM_GRAD
    # -- scalar arith --------------------------------------------------- #
    "_plus_scalar": lambda: (
        lambda x: nd._plus_scalar(x, scalar=0.7), [_a((2, 3))]),
    "_minus_scalar": lambda: (
        lambda x: nd._minus_scalar(x, scalar=0.7), [_a((2, 3))]),
    "_rminus_scalar": lambda: (
        lambda x: nd._rminus_scalar(x, scalar=0.7), [_a((2, 3))]),
    "_mul_scalar": lambda: (
        lambda x: nd._mul_scalar(x, scalar=-1.3), [_a((2, 3))]),
    "_div_scalar": lambda: (
        lambda x: nd._div_scalar(x, scalar=1.7), [_a((2, 3))]),
    "_rdiv_scalar": lambda: (
        lambda x: nd._rdiv_scalar(x, scalar=1.7), [_away((2, 3), lo=0.5)]),
    "_power_scalar": lambda: (
        lambda x: nd._power_scalar(x, scalar=2.5),
        [_a((2, 3), lo=0.3, hi=1.5)]),
    "_rpower_scalar": lambda: (
        lambda x: nd._rpower_scalar(x, scalar=2.0), [_a((2, 3))]),
    "_maximum_scalar": lambda: (
        lambda x: nd._maximum_scalar(x, scalar=0.0), [_away((2, 3))]),
    "_minimum_scalar": lambda: (
        lambda x: nd._minimum_scalar(x, scalar=0.0), [_away((2, 3))]),
    "_mod_scalar": lambda: (
        lambda x: nd._mod_scalar(x, scalar=1.0),
        [_a((2, 3), lo=0.1, hi=0.9)]),
    "_rmod_scalar": lambda: (
        lambda x: nd._rmod_scalar(x, scalar=1.0),
        [_a((2, 3), lo=0.7, hi=0.9)]),
    "_slice_index": lambda: (
        lambda x: nd._slice_index(x, index=1), [_a((3, 4))]),
    # -- binary broadcast ----------------------------------------------- #
    "broadcast_add": lambda: (
        nd.broadcast_add, [_a((2, 3)), _a((1, 3), seed=1)]),
    "broadcast_sub": lambda: (
        nd.broadcast_sub, [_a((2, 3)), _a((1, 3), seed=1)]),
    "broadcast_mul": lambda: (
        nd.broadcast_mul, [_a((2, 3)), _a((1, 3), seed=1)]),
    "broadcast_div": lambda: (
        nd.broadcast_div, [_a((2, 3)), _away((1, 3), seed=1, lo=0.5)]),
    "broadcast_power": lambda: (
        nd.broadcast_power,
        [_a((2, 3), lo=0.3, hi=1.5), _a((1, 3), seed=1)]),
    "broadcast_hypot": lambda: (
        nd.broadcast_hypot, [_away((2, 3)), _away((1, 3), seed=1)]),
    "broadcast_maximum": lambda: (
        nd.broadcast_maximum, [_distinct((2, 3)), _distinct((1, 3), 1)]),
    "broadcast_minimum": lambda: (
        nd.broadcast_minimum, [_distinct((2, 3)), _distinct((1, 3), 1)]),
    "broadcast_mod": lambda: (
        nd.broadcast_mod,
        [_a((2, 3), lo=0.1, hi=0.9), nd.array(np.full((1, 3), 1.0,
                                                      np.float32))],
        {"grad_nodes": [0]}),
    "broadcast_to": lambda: (
        lambda x: nd.broadcast_to(x, shape=(4, 3)), [_a((1, 3))]),
    "broadcast_axis": lambda: (
        lambda x: nd.broadcast_axis(x, axis=0, size=4), [_a((1, 3))]),
    "broadcast_like": lambda: (
        lambda x, y: nd.broadcast_like(x, y),
        [_a((1, 3)), _a((4, 3), seed=1)], {"grad_nodes": [0]}),
    # -- reductions ----------------------------------------------------- #
    "sum": lambda: (lambda x: nd.sum(x, axis=1), [_a((3, 4))]),
    "nansum": lambda: (lambda x: nd.nansum(x, axis=1), [_a((3, 4))]),
    "mean": lambda: (lambda x: nd.mean(x, axis=0), [_a((3, 4))]),
    "prod": lambda: (
        lambda x: nd.prod(x, axis=1), [_away((2, 3), lo=0.5)]),
    "nanprod": lambda: (
        lambda x: nd.nanprod(x, axis=1), [_away((2, 3), lo=0.5)]),
    "max": lambda: (lambda x: nd.max(x, axis=1), [_distinct((3, 4))]),
    "min": lambda: (lambda x: nd.min(x, axis=1), [_distinct((3, 4))]),
    "norm": lambda: (
        lambda x: nd.norm(x, ord=2, axis=1), [_away((2, 3))]),
    "logsumexp": lambda: (
        lambda x: nd.logsumexp(x, axis=-1), [_a((2, 3))]),
    "moments": lambda: (
        lambda x: _sumall(nd.moments(x, axes=(0,))), [_a((3, 4))]),
    "cumsum": lambda: (lambda x: nd.cumsum(x, axis=1), [_a((2, 4))]),
    "cumprod": lambda: (
        lambda x: nd.cumprod(x, axis=1), [_away((2, 3), lo=0.5)]),
    "softmax": lambda: (lambda x: nd.softmax(x, axis=-1), [_a((2, 4))]),
    "softmin": lambda: (lambda x: nd.softmin(x, axis=-1), [_a((2, 4))]),
    "log_softmax": lambda: (
        lambda x: nd.log_softmax(x, axis=-1), [_a((2, 4))]),
    "masked_softmax": lambda: (
        lambda x: nd.masked_softmax(
            x, mask=nd.array(np.array([[1, 1, 0, 1]] * 2, np.float32))),
        [_a((2, 4))]),
    "SoftmaxActivation": lambda: (nd.SoftmaxActivation, [_a((2, 4))]),
    "softmax_cross_entropy": lambda: (
        lambda x: nd.softmax_cross_entropy(x, nd.array([0.0, 2.0])),
        [_a((2, 4))]),
    "div_sqrt_dim": lambda: (nd.div_sqrt_dim, [_a((2, 4))]),
    "logical_not_placeholder": None,
    # -- shape / layout (linear) ---------------------------------------- #
    "reshape": lambda: (
        lambda x: nd.reshape(x, shape=(3, 2)), [_a((2, 3))]),
    "reshape_like": lambda: (
        lambda x, y: nd.reshape_like(x, y),
        [_a((2, 3)), _a((3, 2), seed=1)], {"grad_nodes": [0]}),
    "flatten": lambda: (nd.flatten, [_a((2, 3, 2))]),
    "transpose": lambda: (
        lambda x: nd.transpose(x, axes=(1, 0)), [_a((2, 3))]),
    "swapaxes": lambda: (
        lambda x: nd.swapaxes(x, dim1=0, dim2=2), [_a((2, 3, 2))]),
    "expand_dims": lambda: (
        lambda x: nd.expand_dims(x, axis=1), [_a((2, 3))]),
    "squeeze": lambda: (
        lambda x: nd.squeeze(x, axis=1), [_a((2, 1, 3))]),
    "flip": lambda: (lambda x: nd.flip(x, axis=1), [_a((2, 3))]),
    "tile": lambda: (lambda x: nd.tile(x, reps=(2, 2)), [_a((2, 3))]),
    "repeat": lambda: (
        lambda x: nd.repeat(x, repeats=2, axis=1), [_a((2, 3))]),
    "pad": lambda: (
        lambda x: nd.pad(x, mode="constant",
                         pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
        [_a((1, 1, 3, 3))]),
    "slice": lambda: (
        lambda x: nd.slice(x, begin=(0, 1), end=(2, 3)), [_a((3, 4))]),
    "slice_axis": lambda: (
        lambda x: nd.slice_axis(x, axis=1, begin=1, end=3), [_a((3, 4))]),
    "slice_like": lambda: (
        lambda x, y: nd.slice_like(x, y),
        [_a((3, 4)), _a((2, 3), seed=1)], {"grad_nodes": [0]}),
    "Crop": lambda: (
        lambda x: nd.Crop(x, num_args=1, offset=(1, 1), h_w=(2, 2)),
        [_a((1, 1, 4, 4))]),
    "concat": lambda: (
        lambda a, b: nd.concat(a, b, dim=1),
        [_a((2, 3)), _a((2, 2), seed=1)]),
    "stack": lambda: (
        lambda a, b: nd.stack(a, b, axis=0),
        [_a((2, 3)), _a((2, 3), seed=1)]),
    "split": lambda: (
        lambda x: _sumall(nd.split(x, num_outputs=2, axis=1)),
        [_a((2, 4))]),
    "split_v2": lambda: (
        lambda x: _sumall(nd.split_v2(x, indices_or_sections=2, axis=1)),
        [_a((2, 4))]),
    "meshgrid": lambda: (
        lambda a, b: _sumall(nd.meshgrid(a, b)),
        [_a((3,)), _a((2,), seed=1)]),
    "diag": lambda: (nd.diag, [_a((3, 3))]),
    "tril": lambda: (nd.tril, [_a((3, 3))]),
    "triu": lambda: (nd.triu, [_a((3, 3))]),
    "depth_to_space": lambda: (
        lambda x: nd.depth_to_space(x, block_size=2), [_a((1, 4, 2, 2))]),
    "space_to_depth": lambda: (
        lambda x: nd.space_to_depth(x, block_size=2), [_a((1, 1, 4, 4))]),
    "im2col": lambda: (
        lambda x: nd.im2col(x, kernel=(2, 2), stride=(1, 1),
                            dilate=(1, 1), pad=(0, 0)),
        [_a((1, 2, 4, 4))]),
    "col2im": lambda: (
        lambda x: nd.col2im(x, output_size=(4, 4), kernel=(2, 2),
                            stride=(1, 1), dilate=(1, 1), pad=(0, 0)),
        [_a((1, 8, 9))]),
    "UpSampling": lambda: (
        lambda x: nd.UpSampling(x, scale=2, sample_type="nearest"),
        [_a((1, 1, 3, 3))]),
    # -- indexing / gather (wrt float data) ----------------------------- #
    "take": lambda: (
        lambda x: nd.take(x, _ints((3,), 3, seed=5), axis=0),
        [_a((3, 4))]),
    "batch_take": lambda: (
        lambda x: nd.batch_take(x, _ints((3,), 4, seed=5)), [_a((3, 4))]),
    "pick": lambda: (
        lambda x: nd.pick(x, _ints((3,), 4, seed=5), axis=1),
        [_a((3, 4))]),
    "gather_nd": lambda: (
        lambda x: nd.gather_nd(
            x, nd.array(np.array([[0, 2], [1, 0]], np.int32),
                        dtype="int32")),
        [_a((3, 4))]),
    "scatter_nd": lambda: (
        lambda x: nd.scatter_nd(
            x, nd.array(np.array([[0, 2]], np.int32), dtype="int32"),
            shape=(4,)),
        [_a((2,))]),
    "boolean_mask": lambda: (
        lambda x: nd.boolean_mask(
            x, nd.array(np.array([1, 0, 1], np.int32), dtype="int32")),
        [_a((3, 4))]),
    "one_hot_placeholder": None,
    "where": lambda: (
        lambda x, y: nd.where(
            nd.array(np.array([[1, 0], [0, 1]], np.float32)), x, y),
        [_a((2, 2)), _a((2, 2), seed=1)]),
    "index_add": lambda: (
        lambda old, new: nd.index_add(
            old, _ints((2,), 3, seed=7), new),
        [_a((3, 4)), _a((2, 4), seed=1)]),
    "index_copy": lambda: (
        lambda old, new: nd.index_copy(
            old, nd.array(np.array([0, 2], np.int32), dtype="int32"), new),
        [_a((3, 4)), _a((2, 4), seed=1)]),
    "choose_element_0index": lambda: (
        lambda x: nd.choose_element_0index(x, nd.array([0.0, 2.0, 1.0])),
        [_a((3, 4))]),
    "fill_element_0index": lambda: (
        lambda x, v: nd.fill_element_0index(
            x, v, nd.array([0.0, 2.0, 1.0])),
        [_a((3, 4)), _a((3,), seed=1)]),
    "SequenceLast": lambda: (
        lambda x: nd.SequenceLast(
            x, sequence_length=nd.array([2.0, 3.0]),
            use_sequence_length=True),
        [_a((3, 2, 4))]),
    "SequenceMask": lambda: (
        lambda x: nd.SequenceMask(
            x, sequence_length=nd.array([2.0, 3.0]),
            use_sequence_length=True, value=0.0),
        [_a((3, 2, 4))]),
    "SequenceReverse": lambda: (
        lambda x: nd.SequenceReverse(
            x, sequence_length=nd.array([2.0, 3.0]),
            use_sequence_length=True),
        [_a((3, 2, 4))]),
    "sort": lambda: (
        lambda x: nd.sort(x, axis=-1), [_distinct((2, 4))]),
    "topk": lambda: (
        lambda x: nd.topk(x, k=2, ret_typ="value"), [_distinct((2, 4))]),
    # -- matmul / linalg ------------------------------------------------ #
    "dot": lambda: (nd.dot, [_a((2, 3)), _a((3, 4), seed=1)]),
    "batch_dot": lambda: (
        nd.batch_dot, [_a((2, 2, 3)), _a((2, 3, 2), seed=1)]),
    "khatri_rao": lambda: (
        nd.khatri_rao, [_a((2, 3)), _a((4, 3), seed=1)]),
    "add_n": lambda: (
        nd.add_n, [_a((2, 3)), _a((2, 3), seed=1), _a((2, 3), seed=2)]),
    "linalg_gemm": lambda: (
        lambda a, b, c: nd.linalg_gemm(a, b, c, alpha=1.3, beta=0.7),
        [_a((2, 3)), _a((3, 2), seed=1), _a((2, 2), seed=2)]),
    "linalg_gemm2": lambda: (
        lambda a, b: nd.linalg_gemm2(a, b, alpha=1.3),
        [_a((2, 3)), _a((3, 2), seed=1)]),
    "linalg_syrk": lambda: (
        lambda a: nd.linalg_syrk(a, alpha=1.1), [_a((2, 3))]),
    "linalg_trmm": lambda: (
        lambda a, b: nd.linalg_trmm(a, b),
        [_spd(3), _a((3, 2), seed=1)], {"rtol": 3e-2}),
    "linalg_trsm": lambda: (
        lambda a, b: nd.linalg_trsm(a, b),
        [_spd(3), _a((3, 2), seed=1)], {"rtol": 3e-2}),
    "linalg_potrf": lambda: (
        nd.linalg_potrf, [_spd(3)], {"rtol": 3e-2}),
    "linalg_potri": lambda: (
        nd.linalg_potri, [_spd(3)], {"rtol": 5e-2, "atol": 5e-3}),
    "linalg_det": lambda: (nd.linalg_det, [_spd(3)], {"rtol": 3e-2}),
    "linalg_slogdet": lambda: (
        lambda a: nd.linalg_slogdet(a)[1], [_spd(3)], {"rtol": 3e-2}),
    "linalg_inverse": lambda: (
        nd.linalg_inverse, [_spd(3)], {"rtol": 5e-2, "atol": 5e-3}),
    "linalg_sumlogdiag": lambda: (
        nd.linalg_sumlogdiag, [_spd(3)], {"rtol": 3e-2}),
    "linalg_extractdiag": lambda: (nd.linalg_extractdiag, [_a((3, 3))]),
    "linalg_extracttrian": lambda: (nd.linalg_extracttrian, [_a((3, 3))]),
    "linalg_makediag": lambda: (nd.linalg_makediag, [_a((3,))]),
    "linalg_maketrian": lambda: (nd.linalg_maketrian, [_a((6,))]),
    # decompositions: heads chosen invariant to the sign/ordering
    # conventions (fixed projections; U*U for eigenvectors; singular
    # values alone for SVD) so finite differences are well-defined
    "linalg_gelqf": lambda: (
        lambda a: (lambda LQ: LQ[0].sum()
                   + (LQ[1] * _a((3, 4), seed=9)).sum())(
            nd.linalg_gelqf(a)),
        [_a((3, 4), lo=-0.5, hi=0.5)], {"rtol": 3e-2, "atol": 3e-3}),
    "linalg_syevd": lambda: (
        lambda a: (lambda Ul: Ul[1].sum()
                   + (Ul[0] * Ul[0] * _a((3, 3), seed=9)).sum())(
            nd.linalg_syevd(a)),
        [_spd(3)], {"rtol": 3e-2, "atol": 3e-3}),
    "linalg_gesvd": lambda: (
        lambda a: nd.linalg_gesvd(a)[1].sum(),
        [_a((3, 4), lo=-0.5, hi=0.5)], {"rtol": 3e-2, "atol": 3e-3}),
    # -- neural layers -------------------------------------------------- #
    "FullyConnected": lambda: (
        lambda x, w, b: nd.FullyConnected(x, w, b, num_hidden=3),
        [_a((2, 4)), _a((3, 4), seed=1), _a((3,), seed=2)]),
    "Convolution": lambda: (
        lambda x, w, b: nd.Convolution(x, w, b, kernel=(3, 3),
                                       num_filter=3),
        [_a((1, 2, 5, 5)), _a((3, 2, 3, 3), seed=1), _a((3,), seed=2)],
        {"rtol": 5e-2, "atol": 5e-3}),
    "Deconvolution": lambda: (
        lambda x, w: nd.Deconvolution(x, w, kernel=(3, 3), num_filter=2,
                                      no_bias=True),
        [_a((1, 3, 4, 4)), _a((3, 2, 3, 3), seed=1)], {"rtol": 3e-2}),
    "Pooling": lambda: (
        lambda x: nd.Pooling(x, kernel=(2, 2), pool_type="avg",
                             stride=(1, 1)),
        [_a((1, 2, 4, 4))]),
    "AdaptiveAvgPooling2D": lambda: (
        lambda x: nd.AdaptiveAvgPooling2D(x, output_size=2),
        [_a((1, 2, 4, 4))]),
    "LRN": lambda: (
        lambda x: nd.LRN(x, nsize=3), [_a((1, 4, 3, 3))]),
    "LayerNorm": lambda: (
        lambda x, g, b: nd.LayerNorm(x, g, b),
        [_a((2, 4)), _a((4,), seed=1, lo=0.5, hi=1.5),
         _a((4,), seed=2)]),
    "GroupNorm": lambda: (
        lambda x, g, b: nd.GroupNorm(x, g, b, num_groups=2),
        [_a((2, 4, 3)), _a((4,), seed=1, lo=0.5, hi=1.5),
         _a((4,), seed=2)], {"rtol": 3e-2}),
    "InstanceNorm": lambda: (
        lambda x, g, b: nd.InstanceNorm(x, g, b),
        [_a((2, 3, 4)), _a((3,), seed=1, lo=0.5, hi=1.5),
         _a((3,), seed=2)], {"rtol": 3e-2}),
    # use_global_stats: the harness evaluates numeric differences under
    # autograd.pause(), where a training-aware BatchNorm would switch to
    # the inference path and diverge from the analytic (recorded)
    # forward; global-stats mode is identical in both and still checks
    # the full (x - mean)/sqrt(var+eps)*gamma + beta wiring
    "BatchNorm": lambda: (
        lambda x, g, b, mm, mv: (nd.BatchNorm(
            x, g, b, mm, mv, fix_gamma=False, use_global_stats=True)[0]
            * _a((4, 3), seed=9)).sum(),
        [_a((4, 3)), _a((3,), seed=1, lo=0.5, hi=1.5), _a((3,), seed=2),
         _a((3,), seed=3), _a((3,), seed=4, lo=0.5, hi=1.5)],
        {"grad_nodes": [0, 1, 2], "rtol": 3e-2, "atol": 3e-3}),
    "L2Normalization": lambda: (
        nd.L2Normalization, [_away((2, 4))]),
    "Embedding": lambda: (
        lambda w: nd.Embedding(_ints((3,), 5, seed=5), w, input_dim=5,
                               output_dim=4),
        [_a((5, 4))]),
    "Dropout_placeholder": None,
    # fused packed-parameter RNN: lstm gate packing 4*(I*H + H*H + 2H)
    "RNN": lambda: (
        lambda d, p: (lambda o: o[0] if isinstance(o, list) else o)(
            nd.RNN(d, p, nd.array(np.zeros((1, 2, 4), np.float32)),
                   nd.array(np.zeros((1, 2, 4), np.float32)),
                   state_size=4, num_layers=1, mode="lstm")),
        [_a((3, 2, 3), lo=-0.5, hi=0.5),
         _a((4 * (3 * 4 + 4 * 4 + 2 * 4),), seed=1, lo=-0.3, hi=0.3)],
        {"rtol": 3e-2, "atol": 3e-3}),
    "CTCLoss": lambda: (
        lambda x: nd.CTCLoss(x, nd.array(np.array([[1, 2], [2, 1]],
                                                  np.float32))),
        [_a((4, 2, 4))], {"rtol": 3e-2, "atol": 3e-3}),
    "BilinearResize2D": lambda: (
        lambda x: nd.BilinearResize2D(x, height=4, width=4),
        [_a((1, 1, 3, 3))]),
    "GridGenerator": lambda: (
        lambda x: nd.GridGenerator(x, transform_type="affine",
                                   target_shape=(4, 4)),
        [_a((1, 6))]),
    "BilinearSampler": lambda: (
        lambda x, g: nd.BilinearSampler(x, g),
        [_a((1, 1, 4, 4)),
         _a((1, 2, 3, 3), seed=1, lo=-0.6, hi=0.6)],
        {"rtol": 5e-2, "atol": 5e-3}),
    "SpatialTransformer": lambda: (
        lambda x, loc: nd.SpatialTransformer(
            x, loc, target_shape=(4, 4), transform_type="affine",
            sampler_type="bilinear"),
        [_a((1, 1, 4, 4)),
         # theta chosen so no bilinear sample point sits near an
         # integer source coordinate (finite differences would cross
         # the sampling kink): x_src/y_src land 0.15+ from integers
         nd.array(np.array([[0.61, 0.02, 0.05, -0.03, 0.57, 0.03]],
                           np.float32))],
        {"rtol": 5e-2, "atol": 5e-3}),
    "ROIAlign": lambda: (
        lambda x: nd.ROIAlign(
            x, nd.array(np.array([[0, 0.6, 0.6, 3.3, 3.3]], np.float32)),
            pooled_size=(2, 2), spatial_scale=1.0, sample_ratio=2),
        [_a((1, 1, 6, 6))], {"rtol": 5e-2, "atol": 5e-3}),
    "ROIPooling": lambda: (
        lambda x: nd.ROIPooling(
            x, nd.array(np.array([[0, 0, 0, 3, 3]], np.float32)),
            pooled_size=(2, 2), spatial_scale=1.0),
        [_distinct((1, 1, 6, 6))], {"rtol": 3e-2}),
    "Correlation": lambda: (
        lambda a, b: nd.Correlation(a, b, kernel_size=1,
                                    max_displacement=1),
        [_a((1, 1, 4, 4)), _a((1, 1, 4, 4), seed=1)], {"rtol": 3e-2}),
    "DeformableConvolution": lambda: (
        lambda x, off, w: nd.DeformableConvolution(
            x, off, w, kernel=(3, 3), num_filter=2, no_bias=True),
        [_a((1, 2, 5, 5)),
         _a((1, 18, 3, 3), seed=1, lo=0.1, hi=0.35),
         _a((2, 2, 3, 3), seed=2)],
        {"rtol": 5e-2, "atol": 5e-3}),
    "ModulatedDeformableConvolution": lambda: (
        lambda x, off, m, w: nd.ModulatedDeformableConvolution(
            x, off, m, w, kernel=(3, 3), num_filter=2, no_bias=True),
        [_a((1, 2, 5, 5)),
         _a((1, 18, 3, 3), seed=1, lo=0.1, hi=0.35),
         _a((1, 9, 3, 3), seed=3, lo=0.3, hi=0.9),
         _a((2, 2, 3, 3), seed=2)],
        {"rtol": 5e-2, "atol": 5e-3}),
    # -- attention ------------------------------------------------------ #
    "scaled_dot_product_attention": lambda: (
        lambda q, k, v: nd.scaled_dot_product_attention(q, k, v),
        [_a((1, 3, 2, 4)), _a((1, 3, 2, 4), seed=1),
         _a((1, 3, 2, 4), seed=2)], {"rtol": 3e-2}),
    "interleaved_matmul_selfatt_qk": lambda: (
        lambda qkv: nd.interleaved_matmul_selfatt_qk(qkv, heads=2),
        [_a((3, 2, 24))], {"rtol": 3e-2}),
    "interleaved_matmul_selfatt_valatt": lambda: (
        lambda qkv, att: nd.interleaved_matmul_selfatt_valatt(
            qkv, att, heads=2),
        [_a((3, 2, 24)), _a((4, 3, 3), seed=1)], {"rtol": 3e-2}),
    "interleaved_matmul_encdec_qk": lambda: (
        lambda q, kv: nd.interleaved_matmul_encdec_qk(q, kv, heads=2),
        [_a((3, 2, 8)), _a((3, 2, 16), seed=1)], {"rtol": 3e-2}),
    "interleaved_matmul_encdec_valatt": lambda: (
        lambda kv, att: nd.interleaved_matmul_encdec_valatt(
            kv, att, heads=2),
        [_a((3, 2, 16)), _a((4, 3, 3), seed=1)], {"rtol": 3e-2}),
    "sldwin_atten_score": lambda: (
        lambda q, k: nd.sldwin_atten_score(q, k, 1, num_heads=2, w=2),
        [_a((2, 6, 8)), _a((2, 6, 8), seed=1)], {"rtol": 3e-2}),
    "sldwin_atten_context": lambda: (
        lambda s, v: nd.sldwin_atten_context(s, v, 1, num_heads=2, w=2),
        [_a((4, 6, 6)), _a((2, 6, 8), seed=1)], {"rtol": 3e-2}),
    # -- misc ----------------------------------------------------------- #
    "count_sketch": lambda: (
        lambda x: nd.count_sketch(
            x, nd.array(R(5).randint(0, 4, 8).astype(np.float32)),
            nd.array(R(6).choice([-1.0, 1.0], 8).astype(np.float32)),
            out_dim=4),
        [_a((2, 8))]),
    "fft": lambda: (
        lambda x: nd.fft(x, compute_size=4), [_a((2, 4))]),
    "ifft": lambda: (
        lambda x: nd.ifft(x, compute_size=4), [_a((2, 8))]),
    "box_decode": lambda: (
        lambda x, a: nd.box_decode(x, a),
        [_a((1, 2, 4), lo=-0.2, hi=0.2),
         nd.array(np.array([[[0.1, 0.1, 0.4, 0.4],
                             [0.5, 0.5, 0.9, 0.9]]], np.float32))],
        {"grad_nodes": [0], "rtol": 3e-2}),
    "box_iou": lambda: (
        lambda a, b: nd.box_iou(a, b),
        [nd.array(np.array([[0.1, 0.1, 0.6, 0.6]], np.float32)),
         nd.array(np.array([[0.3, 0.3, 0.9, 0.9]], np.float32))],
        {"rtol": 5e-2, "atol": 5e-3}),
    "hawkes_ll": lambda: (
        lambda lda, alpha, beta: _sumall(nd.hawkes_ll(
            lda, alpha, beta, nd.zeros((1, 1)),
            nd.array(np.array([[1.0, 0.5, 0.8]], np.float32)),
            nd.array(np.zeros((1, 3), np.float32)),
            nd.array(np.array([3], np.int32), dtype="int32"), 4.0)),
        [nd.array([0.5]), nd.array([0.2]), nd.array([1.0])],
        {"rtol": 3e-2}),
}
# drop documentation placeholders (classified in other buckets)
GRAD_CASES = {k: v for k, v in GRAD_CASES.items() if v is not None}

# ops whose outputs are integer/boolean/assignment results,
# value-independent of the float inputs, or zero-gradient by definition
NONDIFF = {
    # comparisons / logical / boolean outputs
    "_equal_scalar": "boolean output", "_not_equal_scalar": "boolean",
    "_greater_scalar": "boolean", "_greater_equal_scalar": "boolean",
    "_lesser_scalar": "boolean", "_lesser_equal_scalar": "boolean",
    "broadcast_equal": "boolean", "broadcast_not_equal": "boolean",
    "broadcast_greater": "boolean", "broadcast_greater_equal": "boolean",
    "broadcast_lesser": "boolean", "broadcast_lesser_equal": "boolean",
    "broadcast_logical_and": "boolean", "broadcast_logical_or": "boolean",
    "broadcast_logical_xor": "boolean", "logical_not": "boolean",
    "isfinite": "boolean", "isinf": "boolean", "isnan": "boolean",
    "allclose": "boolean", "all_finite": "boolean scalar",
    "multi_all_finite": "boolean scalar",
    # integer / index outputs
    "argmax": "index output", "argmin": "index output",
    "argsort": "index output", "argmax_channel": "index output",
    "histogram": "integer counts", "one_hot": "indices input",
    "ravel_multi_index": "integer", "unravel_index": "integer",
    "shape_array": "shape metadata", "size_array": "size metadata",
    "index_array": "value-independent indices",
    # value-independent outputs
    "zeros_like": "constant output", "ones_like": "constant output",
    "full_like": "constant output", "arange_like": "value-independent",
    "MultiBoxPrior": "anchors depend only on shape",
    # piecewise-constant (zero gradient a.e.)
    "ceil": "zero gradient a.e.", "floor": "zero gradient a.e.",
    "fix": "zero gradient a.e.", "rint": "zero gradient a.e.",
    "round": "zero gradient a.e.", "trunc": "zero gradient a.e.",
    "sign": "zero gradient a.e.",
    # assignment / matching / NMS logic
    "box_nms": "NMS selection logic",
    "bipartite_matching": "assignment indices",
    "MultiBoxDetection": "NMS + decode selection",
    "MultiBoxTarget": "target assignment",
    "Proposal": "NMS proposal selection",
    "mrcnn_mask_target": "target assignment",
    "box_encode": "matching-driven gather",
    "sldwin_atten_mask_like": "boolean band mask",
    # quantized integer path
    "quantize": "int8/uint8 output", "quantize_v2": "int8 output",
    "dequantize": "int8 input", "requantize": "int8 path",
    "quantized_conv": "int8 path",
    "quantized_fully_connected": "int8 path",
    # optimizer update kernels: applied outside the differentiated
    # graph; trajectory-tested in tests/test_optimizer.py
    "adadelta_update": "optimizer kernel",
    "adagrad_update": "optimizer kernel", "adam_update": "optimizer",
    "adamw_update": "optimizer", "ftml_update": "optimizer",
    "ftrl_update": "optimizer", "group_adagrad_update": "optimizer",
    "lamb_update_phase1": "optimizer", "lamb_update_phase2": "optimizer",
    "mp_adam_update": "optimizer", "mp_adamw_update": "optimizer",
    "mp_nag_mom_update": "optimizer", "mp_sgd_mom_update": "optimizer",
    "mp_sgd_update": "optimizer", "multi_lars": "optimizer",
    "multi_mp_sgd_mom_update": "optimizer",
    "multi_mp_sgd_update": "optimizer",
    "multi_sgd_mom_update": "optimizer", "multi_sgd_update": "optimizer",
    "multi_sum_sq": "optimizer-infra reduction",
    "nag_mom_update": "optimizer",
    "preloaded_multi_sgd_update": "optimizer",
    "preloaded_multi_sgd_mom_update": "optimizer",
    "preloaded_multi_mp_sgd_update": "optimizer",
    "preloaded_multi_mp_sgd_mom_update": "optimizer",
    "rmsprop_update": "optimizer",
    "rmspropalex_update": "optimizer", "sgd_mom_update": "optimizer",
    "sgd_update": "optimizer", "signsgd_update": "optimizer",
    "signum_update": "optimizer",
}

# training heads: forward is a pass-through, backward injects the loss
# gradient by design — numeric diff of the forward cannot agree
# (reference: src/operator/regression_output*.cc, softmax_output.cc)
CUSTOM_GRAD = {
    "SoftmaxOutput": "backward = (softmax - label)",
    "LinearRegressionOutput": "backward = data - label",
    "LogisticRegressionOutput": "backward = sigmoid(data) - label",
    "MAERegressionOutput": "backward = sign(data - label)",
    "SVMOutput": "backward = hinge subgradient",
    "make_loss": "forward identity, backward grad_scale",
    "BlockGrad": "gradient barrier (zero by definition)",
    "gradientmultiplier": "backward scaled by `scalar` by design",
}

# stochastic samplers checked at the DISTRIBUTION level instead of by
# numeric gradient (reference idiom: tests/python/unittest/test_random.py
# verifies sample moments against analytic ones under a fixed seed):
# name -> (thunk() -> samples NDArray, analytic mean, analytic variance)
_N_SAMPLES = 200_000
DIST_CHECK = {
    "random_normal": (
        lambda: nd.random_normal(loc=1.5, scale=2.0, shape=(_N_SAMPLES,)),
        1.5, 4.0),
    "random_uniform": (
        lambda: nd.random_uniform(low=-1.0, high=3.0, shape=(_N_SAMPLES,)),
        1.0, 16.0 / 12.0),
    "random_gamma": (
        # mean = alpha*beta, var = alpha*beta^2 (MXNet's beta is scale)
        lambda: nd.random_gamma(alpha=3.0, beta=0.5, shape=(_N_SAMPLES,)),
        1.5, 0.75),
}

# differentiable but excluded here, with reasons
SKIP = {
    "Dropout": "stochastic mask; parity-tested in tests/test_nn_ops.py",
    "shuffle": "random permutation",
    "random_bernoulli": "sampler", "random_exponential": "sampler",
    "random_generalized_negative_binomial": "sampler",
    "random_laplace": "sampler", "random_negative_binomial": "sampler",
    "random_poisson": "sampler",
    "random_randint": "sampler", "random_randn": "sampler",
    "sample_multinomial": "sampler",
    "sample_normal": "sampler", "sample_uniform": "sampler",
    "sample_gamma": "sampler", "sample_exponential": "sampler",
    "sample_poisson": "sampler", "sample_negative_binomial": "sampler",
    "sample_generalized_negative_binomial": "sampler",
}


def test_registry_fully_classified():
    """Every registered op is in exactly one bucket; none unclassified."""
    registry = set(ops.list_all_ops())
    buckets = {"GRAD_CASES": set(GRAD_CASES), "NONDIFF": set(NONDIFF),
               "CUSTOM_GRAD": set(CUSTOM_GRAD), "SKIP": set(SKIP),
               "DIST_CHECK": set(DIST_CHECK)}
    classified = set().union(*buckets.values())
    missing = registry - classified
    assert not missing, f"unclassified ops: {sorted(missing)}"
    stale = classified - registry
    assert not stale, f"classified but unregistered: {sorted(stale)}"
    for a in buckets:
        for b in buckets:
            if a < b:
                dup = buckets[a] & buckets[b]
                assert not dup, f"{sorted(dup)} in both {a} and {b}"


@pytest.mark.parametrize("name", [
    # random_gamma's moment check costs 9 s (round-11 tier-1 budget
    # repair) — stage_unit still runs it
    pytest.param(n, marks=pytest.mark.slow) if n == "random_gamma"
    else n
    for n in sorted(DIST_CHECK)])
def test_sampler_distribution(name):
    """Moment check under a fixed seed: sample mean/variance within 5
    standard errors of the analytic moments (so the check is sharp but
    seed-stable), plus a determinism replay of the seeded stream."""
    import incubator_mxnet_tpu as mx

    thunk, mean, var = DIST_CHECK[name]
    mx.random.seed(1234)
    s = thunk().asnumpy().astype(np.float64)
    n = s.size
    se_mean = np.sqrt(var / n)
    assert abs(s.mean() - mean) < 5 * se_mean, \
        f"{name}: sample mean {s.mean():.4f} vs analytic {mean}"
    # SE of the sample variance ~ var * sqrt(2/(n-1)) for light-tailed
    # distributions; gamma's excess kurtosis widens it, folded into 5 SE
    kurt_margin = 5 * var * np.sqrt(2.0 / (n - 1)) * 3.0
    assert abs(s.var() - var) < kurt_margin, \
        f"{name}: sample var {s.var():.4f} vs analytic {var}"
    mx.random.seed(1234)
    np.testing.assert_array_equal(thunk().asnumpy(), s.astype(np.float32))


# multi-input kernels whose finite-difference sweeps take 30s+ each on
# the 8-virtual-device CPU mesh: still covered, but outside the tier-1
# `-m 'not slow'` budget (ci/run.sh stage_unit runs the full suite)
_SLOW_GRAD = {"RNN", "DeformableConvolution",
              "ModulatedDeformableConvolution",
              # 12s on the tier-1 budget box (round-10 --durations
              # profile); ci stage_unit still runs it
              "CTCLoss",
              # 11s (round-11 profile); stage_unit still runs it
              "ROIAlign"}


@pytest.mark.parametrize("name", [
    pytest.param(n, marks=pytest.mark.slow) if n in _SLOW_GRAD else n
    for n in sorted(GRAD_CASES)])
def test_numeric_gradient(name):
    case = GRAD_CASES[name]()
    fn, inputs = case[0], case[1]
    opts = dict(case[2]) if len(case) > 2 else {}
    check_numeric_gradient(fn, inputs,
                           grad_nodes=opts.get("grad_nodes"),
                           eps=opts.get("eps", 1e-3),
                           rtol=opts.get("rtol", 1e-2),
                           atol=opts.get("atol", 1e-3))
