"""Image augmenter tests (reference: tests/python/unittest/test_image.py
strategy — deterministic seeded augmentation, shape/range checks)."""

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import image, nd


def _img(h=32, w=48):
    rng = np.random.RandomState(0)
    return (rng.rand(h, w, 3) * 255).astype(np.float32)


def test_create_augmenter_pipeline():
    mx.random.seed(0)
    augs = image.CreateAugmenter(data_shape=(3, 24, 24), rand_crop=True,
                                 rand_mirror=True, brightness=0.2,
                                 contrast=0.2, saturation=0.2, hue=0.1,
                                 pca_noise=0.05, rand_gray=0.2,
                                 mean=True, std=True)
    x = _img()
    for a in augs:
        x = a(x)
    out = x.asnumpy()
    assert out.shape == (24, 24, 3)
    assert np.isfinite(out).all()
    # normalized: roughly zero-centered
    assert abs(out.mean()) < 3.0


def test_individual_augs_shapes():
    x = _img()
    assert image.CenterCropAug((16, 16))(x).shape == (16, 16, 3)
    assert image.ForceResizeAug((20, 10))(x).shape == (10, 20, 3)
    assert image.ResizeAug(16)(x).shape[0] == 16  # short side
    g = image.RandomGrayAug(p=1.0)(x).asnumpy()
    assert np.allclose(g[..., 0], g[..., 1])
    f = image.HorizontalFlipAug(p=1.0)(x).asnumpy()
    np.testing.assert_allclose(f, np.asarray(x)[:, ::-1])


def test_hue_preserves_luma_roughly():
    x = _img()
    out = image.HueJitterAug(0.3)(x).asnumpy()
    coef = np.array([0.299, 0.587, 0.114], np.float32)
    np.testing.assert_allclose((out * coef).sum(-1), (np.asarray(x) *
                                                      coef).sum(-1),
                               rtol=0.15, atol=10.0)


def test_augmenter_dumps():
    a = image.BrightnessJitterAug(0.3)
    import json
    name, kw = json.loads(a.dumps())
    assert name == "BrightnessJitterAug" and kw["brightness"] == 0.3


def test_round5_image_additions():
    """random_size_crop / copyMakeBorder / imrotate / random_rotate."""
    mx.random.seed(0)
    img = (np.random.RandomState(0).rand(20, 30, 3) * 255).astype(np.uint8)
    out, box = mx.image.random_size_crop(img, (8, 8), area=(0.2, 0.9),
                                         ratio=(0.7, 1.4))
    assert out.shape == (8, 8, 3)
    b = mx.image.copyMakeBorder(img, 2, 3, 4, 5, values=7.0)
    assert b.shape == (25, 39, 3)
    assert (b.asnumpy()[:2] == 7).all() and (b.asnumpy()[:, :4] == 7).all()
    sq = np.zeros((9, 9, 1), np.float32)
    sq[2, 4] = 1.0
    np.testing.assert_allclose(mx.image.imrotate(sq, 0).asnumpy(), sq,
                               atol=1e-5)
    r90 = mx.image.imrotate(sq, 90).asnumpy()
    assert abs(r90.sum() - 1.0) < 1e-4 and r90[2, 4] != 1.0
    assert mx.image.random_rotate(sq, (-30, 30)).shape == sq.shape
