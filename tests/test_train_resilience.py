"""Fault-tolerant training: the in-step non-finite guard, dynamic loss
scaling, structured step outcomes, and the halt-on-poison contract
(docs/RESILIENCE.md "Training resilience").

The invariants mirror the serving ones (round 10), translated to
training: every step ends in exactly one recorded StepOutcome; a
skipped step leaves params AND optimizer state bit-identical; the
guard and scale ride as pure traced data so overflow/clean transitions
and scale growth/decay never retrace; K consecutive non-finite steps
halt loudly instead of skip-looping forever.
"""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd, parallel
from incubator_mxnet_tpu.amp.loss_scaler import LossScaler
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.parallel import mesh as pmesh
from incubator_mxnet_tpu.train import (NaNBatch, NaNGrad, OverflowStorm,
                                       StepOutcome, StepRecorder,
                                       run_train_chaos)


def _build_net(seed=0):
    mx.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"),
            nn.Dense(4, in_units=16))
    net.initialize()
    return net


def _data(seed=1, n=8):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 8).astype(np.float32),
            rng.randn(n, 4).astype(np.float32))


def _mse(out, label):
    return (out - label) ** 2


def _trainer(net, opt="adam", scaler=None, guard=None, max_nf=None,
             **opt_params):
    opt_params = opt_params or {"learning_rate": 0.01}
    return gluon.Trainer(net.collect_params(), opt, opt_params,
                         kvstore=None, loss_scaler=scaler, guard=guard,
                         max_consecutive_nonfinite=max_nf)


def _state_snapshot(tr):
    """Params + every optimizer-state leaf, as host arrays."""
    import jax.tree_util as jtu
    snap = [p.data().asnumpy().copy() for p in tr._params]
    for i, st in sorted(tr._updaters[0].states.items()):
        for leaf in jtu.tree_leaves(
                st, is_leaf=lambda x: hasattr(x, "asnumpy")):
            snap.append(leaf.asnumpy().copy())
    return snap


# --------------------------------------------------------------------- #
# recorder units (host-only)
# --------------------------------------------------------------------- #

def test_recorder_exactly_one_outcome_per_step():
    rec = StepRecorder(max_consecutive_nonfinite=10)
    rec.open_step()
    rec.record(StepOutcome.APPLIED)
    with pytest.raises(MXNetError, match="double-record"):
        rec.record(StepOutcome.APPLIED)
    rec.open_step()
    with pytest.raises(MXNetError, match="never recorded"):
        rec.open_step()
    rec.record(StepOutcome.SKIPPED_STALE)
    assert rec.step_count == 2 == sum(rec.health.values())


def test_recorder_escalates_to_halt():
    rec = StepRecorder(max_consecutive_nonfinite=3)
    outs = []
    for _ in range(3):
        rec.open_step()
        outs.append(rec.record(StepOutcome.SKIPPED_NONFINITE))
    assert outs == [StepOutcome.SKIPPED_NONFINITE,
                    StepOutcome.SKIPPED_NONFINITE,
                    StepOutcome.HALTED_POISONED]
    # an applied step resets the streak
    rec.open_step()
    rec.record(StepOutcome.APPLIED)
    assert rec.consecutive_nonfinite == 0
    snap = rec.snapshot()
    assert snap["health"]["HALTED_POISONED"] == 1
    snap["health"]["APPLIED"] = 99            # detached copy
    assert rec.health["APPLIED"] == 1


# --------------------------------------------------------------------- #
# the guard on the fused Trainer step
# --------------------------------------------------------------------- #

def test_nan_grad_step_skipped_state_bit_identical():
    net = _build_net()
    tr = _trainer(net)
    X, y = _data()
    # two clean steps build optimizer state, then snapshot
    run_train_chaos(net, tr, _mse, (X, y), 2)
    before = _state_snapshot(tr)
    losses, outcomes = run_train_chaos(net, tr, _mse, (X, y), 1,
                                       [NaNGrad(at_step=0)])
    assert outcomes == [StepOutcome.SKIPPED_NONFINITE]
    after = _state_snapshot(tr)
    for b, a in zip(before, after):
        np.testing.assert_array_equal(a, b)
    assert "non-finite grads" in tr._recorder.last_detail
    # and training continues cleanly afterwards
    _, outcomes = run_train_chaos(net, tr, _mse, (X, y), 2)
    assert outcomes == [StepOutcome.APPLIED] * 2
    assert tr.health == {"APPLIED": 4, "SKIPPED_NONFINITE": 1,
                         "SKIPPED_STALE": 0, "HALTED_POISONED": 0}


def test_guard_no_retrace_across_fault_transitions():
    """Skip-step and scale decay/growth are pure data: one trace of the
    fused group and one of the guard reduction across clean -> nan ->
    clean -> nan transitions."""
    net = _build_net()
    tr = _trainer(net, scaler=LossScaler(init_scale=16.0, scale_window=2))
    X, y = _data()
    run_train_chaos(net, tr, _mse, (X, y), 8,
                    [NaNGrad(at_step=2, seed=1), NaNGrad(at_step=5, seed=2)])
    assert tr._fused.trace_count == 1
    assert tr._fused.guard_trace_count == 1
    assert len(tr._fused._jits) == 1
    assert tr.health["SKIPPED_NONFINITE"] == 2
    assert tr.health["APPLIED"] == 6


def test_guarded_clean_run_matches_unguarded():
    """The guard must be a no-op on healthy steps — same trajectory
    with guard on and off."""
    res = {}
    for guard in (False, True):
        net = _build_net(seed=3)
        tr = _trainer(net, guard=guard)
        X, y = _data(seed=4)
        losses, _ = run_train_chaos(net, tr, _mse, (X, y), 4)
        res[guard] = (losses, [p.data().asnumpy() for p in tr._params])
    assert res[False][0] == res[True][0]
    for a, b in zip(res[False][1], res[True][1]):
        np.testing.assert_array_equal(a, b)


def test_skipped_step_does_not_advance_counters():
    """Adam bias correction must see the same t sequence whether or not
    skipped steps happened in between (skips never happened, as far as
    schedules and bias correction are concerned)."""
    net = _build_net(seed=5)
    tr = _trainer(net)
    X, y = _data(seed=6)
    run_train_chaos(net, tr, _mse, (X, y), 2)
    nu_before = tr.optimizer.num_update
    counts_before = dict(tr.optimizer._index_update_count)
    run_train_chaos(net, tr, _mse, (X, y), 1, [NaNGrad(at_step=0)])
    assert tr.optimizer.num_update == nu_before
    assert dict(tr.optimizer._index_update_count) == counts_before

    # trajectory with an injected skip == trajectory without it
    net_b = _build_net(seed=5)
    tr_b = _trainer(net_b)
    run_train_chaos(net_b, tr_b, _mse, (X, y), 2)
    run_train_chaos(net, tr, _mse, (X, y), 2)      # faulted trainer
    run_train_chaos(net_b, tr_b, _mse, (X, y), 2)  # clean trainer
    for pa, pb in zip(tr._params, tr_b._params):
        np.testing.assert_array_equal(pa.data().asnumpy(),
                                      pb.data().asnumpy())


def test_halt_poisoned_after_k_consecutive():
    net = _build_net()
    tr = _trainer(net, max_nf=3)
    X, y = _data()
    with pytest.raises(MXNetError, match="poisoned"):
        run_train_chaos(net, tr, _mse, (X, y), 5,
                        [_AlwaysNaN()])
    assert tr.health["SKIPPED_NONFINITE"] == 2
    assert tr.health["HALTED_POISONED"] == 1
    assert tr.last_outcome is StepOutcome.HALTED_POISONED
    assert sum(tr.health.values()) == 3


class _AlwaysNaN(NaNGrad):
    """NaN every step (divergence, not a transient)."""

    def __init__(self):
        super().__init__(at_step=0)

    def on_grads(self, step_idx, trainer):
        self.fired = False
        super().on_grads(step_idx, trainer)


def test_skipped_stale_outcome():
    net = _build_net()
    tr = _trainer(net)
    X, y = _data()
    run_train_chaos(net, tr, _mse, (X, y), 1)
    tr.step(8, ignore_stale_grad=True)     # no backward since last step
    assert tr.last_outcome is StepOutcome.SKIPPED_STALE
    assert tr.health["SKIPPED_STALE"] == 1


def test_loss_scaler_halves_on_overflow_and_regrows():
    net = _build_net()
    scaler = LossScaler(init_scale=64.0, scale_window=3)
    tr = _trainer(net, scaler=scaler)
    X, y = _data()
    # persistent storm: any scale above 16 overflows. The scaler must
    # halve its way down (64 -> 32 -> 16, one skip each), run clean,
    # regrow after scale_window=3 clean steps (16 -> 32), hit the
    # ceiling again (one skip back to 16), and keep training — the
    # full decay/recover/probe cycle
    _, outcomes = run_train_chaos(
        net, tr, _mse, (X, y), 8, [OverflowStorm(at_step=0,
                                                 overflow_above=16.0)])
    S, A = StepOutcome.SKIPPED_NONFINITE, StepOutcome.APPLIED
    assert outcomes == [S, S, A, A, A, S, A, A]
    assert scaler.loss_scale == 16.0
    assert tr.health_snapshot()["loss_scale"] == 16.0
    # the traced-scalar path: scale changes never retraced
    assert tr._fused.trace_count == 1
    assert tr._fused.guard_trace_count == 1


def test_scaler_without_guard_warns():
    net = _build_net()
    with pytest.warns(UserWarning, match="guard is off"):
        gluon.Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1}, kvstore=None,
                      fuse_step=False, loss_scaler=LossScaler())


def test_amp_init_trainer_drives_guarded_scaling():
    """The legacy amp surface rides the new machinery: init_trainer's
    scaler adapts automatically through the guard."""
    from incubator_mxnet_tpu import amp
    net = _build_net()
    tr = _trainer(net)
    try:
        amp.init(target_dtype="bfloat16")
        amp.init_trainer(tr)
        tr._amp_loss_scaler = LossScaler(init_scale=8.0, scale_window=100)
        X, y = _data()
        run_train_chaos(net, tr, _mse, (X, y), 1, [NaNGrad(at_step=0)])
        assert tr._amp_loss_scaler.loss_scale == 4.0
    finally:
        amp._deinit_for_tests()


def test_scaler_and_health_ride_the_capsule(tmp_path):
    """Scaler trajectory + step-health counters resume from the capsule
    (a restart must not re-warm the scale — bit-exact loss contract)."""
    from incubator_mxnet_tpu.checkpoint import CheckpointManager
    net = _build_net(seed=9)
    tr = _trainer(net, scaler=LossScaler(init_scale=32.0, scale_window=4))
    X, y = _data(seed=10)
    run_train_chaos(net, tr, _mse, (X, y), 3, [NaNGrad(at_step=1)])
    assert tr._amp_loss_scaler.loss_scale == 16.0
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
    tr.save_checkpoint(mgr, block=True)
    mgr.wait()

    net2 = _build_net(seed=9)
    # a fresh scaler with DIFFERENT settings: the capsule must overwrite
    tr2 = _trainer(net2, scaler=LossScaler(init_scale=2.0,
                                           scale_window=4))
    tr2.restore_checkpoint(mgr)
    assert tr2._amp_loss_scaler.loss_scale == 16.0
    assert tr2._amp_loss_scaler._unskipped == tr._amp_loss_scaler._unskipped
    assert tr2.health == tr.health
    assert tr2._recorder.consecutive_nonfinite == \
        tr._recorder.consecutive_nonfinite

    # the resumed trainer continues the EXACT trajectory
    l_a, _ = run_train_chaos(net, tr, _mse, (X, y), 2)
    l_b, _ = run_train_chaos(net2, tr2, _mse, (X, y), 2)
    assert l_a == l_b

    # restoring into a SCALERLESS trainer must not inject one (a plain
    # loss.backward() loop would then silently divide every update by
    # the saved scale) — it warns and resumes unscaled instead
    net3 = _build_net(seed=9)
    tr3 = _trainer(net3)
    with pytest.warns(RuntimeWarning, match="DROPPED"):
        tr3.restore_checkpoint(mgr)
    assert tr3._amp_loss_scaler is None
    mgr.close()


def test_backward_multi_loss_with_scaler():
    """trainer.backward accepts a list of losses, matching scale_loss's
    contract (seeds each head with the scale)."""
    from incubator_mxnet_tpu import autograd
    net = _build_net(seed=31)
    tr = _trainer(net, scaler=LossScaler(init_scale=4.0,
                                         scale_window=100))
    X, y = _data(seed=32)
    with autograd.record():
        out = net(nd.array(X))
        l1 = ((out - nd.array(y)) ** 2).mean()
        l2 = (out ** 2).mean()
    tr.backward([l1, l2])
    g = list(net.collect_params().values())[0].grad()
    # reference: unscaled sum of both heads, times the scale
    net_b = _build_net(seed=31)
    tr_b = _trainer(net_b)
    with autograd.record():
        out = net_b(nd.array(X))
        L = ((out - nd.array(y)) ** 2).mean() + (out ** 2).mean()
    tr_b.backward(L)
    g_b = list(net_b.collect_params().values())[0].grad()
    np.testing.assert_allclose(g.asnumpy(), 4.0 * g_b.asnumpy(),
                               rtol=1e-5, atol=1e-6)


# --------------------------------------------------------------------- #
# SPMD: the guard inside the one-compile fused step
# --------------------------------------------------------------------- #

def _spmd_setup(sharding="replicated", axis_sizes=None, scaler=None,
                max_nf=None, seed=7):
    mx.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"),
            nn.Dense(4, in_units=16))
    net.initialize()
    mesh = pmesh.build_mesh(axis_sizes=axis_sizes or {"dp": 8})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = parallel.SPMDTrainer(net, loss=loss_fn, optimizer="adam",
                              optimizer_params={"learning_rate": 0.01},
                              mesh=mesh, sharding=sharding,
                              loss_scaler=scaler,
                              max_consecutive_nonfinite=max_nf)
    return net, tr


@pytest.mark.parametrize("sharding,axes", [
    ("replicated", {"dp": 8}),
    ("fsdp", {"dp": 2, "fsdp": 4}),
])
def test_spmd_skip_step_parity(monkeypatch, sharding, axes):
    """A non-finite batch skips the step with params + optimizer state
    bit-identical, on dp AND fsdp meshes — and because the all-finite
    reduction runs INSIDE the SPMD program, the skip decision is global
    (every shard of every param stays untouched — the all-ranks-skip
    contract)."""
    monkeypatch.setenv("MXTPU_FSDP_MIN_SIZE", "0")
    net, tr = _spmd_setup(sharding, axes)
    rng = np.random.RandomState(2)
    X = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(16,))
    for _ in range(2):
        tr.step(nd.array(X), nd.array(y))
    w_before = [p.data().asnumpy().copy() for p in tr._params]
    st_before = [np.asarray(leaf._data).copy()
                 for st in tr._opt_state
                 for leaf in _nd_leaves(st)]
    sc_before = tr.step_count
    inj = NaNBatch(at_step=0)
    arrays = inj.on_batch(0, [X, y])
    tr.step(nd.array(arrays[0]), nd.array(arrays[1]))
    assert tr.last_outcome is StepOutcome.SKIPPED_NONFINITE
    assert tr.step_count == sc_before        # t does not advance
    for b, a in zip(w_before, [p.data().asnumpy() for p in tr._params]):
        np.testing.assert_array_equal(a, b)
    st_after = [np.asarray(leaf._data)
                for st in tr._opt_state for leaf in _nd_leaves(st)]
    for b, a in zip(st_before, st_after):
        np.testing.assert_array_equal(a, b)
    # clean step still applies, through the SAME program
    tr.step(nd.array(X), nd.array(y))
    assert tr.last_outcome is StepOutcome.APPLIED
    assert tr.step_trace_count == 1
    assert sum(tr.health.values()) == 4


def _nd_leaves(st):
    import jax.tree_util as jtu
    return jtu.tree_leaves(st, is_leaf=lambda x: hasattr(x, "asnumpy"))


def test_spmd_scaler_and_halt():
    net, tr = _spmd_setup(scaler=LossScaler(init_scale=8.0,
                                            scale_window=100),
                          max_nf=2)
    rng = np.random.RandomState(3)
    X = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(16,))
    Xbad = X.copy()
    Xbad[0, 0] = np.nan
    tr.step(nd.array(X), nd.array(y))
    tr.step(nd.array(Xbad), nd.array(y))
    assert tr.loss_scaler.loss_scale == 4.0
    with pytest.raises(MXNetError, match="poisoned"):
        tr.step(nd.array(Xbad), nd.array(y))
    assert tr.health["HALTED_POISONED"] == 1
    assert tr.step_trace_count == 1


def test_spmd_guarded_clean_matches_unguarded():
    res = {}
    for guard in (False, True):
        net, tr = _spmd_setup(seed=11)
        tr.guard = guard
        rng = np.random.RandomState(4)
        X = rng.randn(16, 8).astype(np.float32)
        y = rng.randint(0, 4, size=(16,))
        losses = [float(tr.step(nd.array(X), nd.array(y)).asnumpy())
                  for _ in range(3)]
        res[guard] = (losses,
                      [p.data().asnumpy() for p in tr._params])
    assert res[False][0] == res[True][0]
    for a, b in zip(res[False][1], res[True][1]):
        np.testing.assert_array_equal(a, b)


def test_step_exception_does_not_wedge_recorder(monkeypatch):
    """A step that dies before reaching the recorder (dispatch error)
    must not leave it open — the NEXT step would be falsely accused of
    a missing record."""
    net = _build_net()
    tr = _trainer(net)
    X, y = _data()

    def boom(*a, **k):
        raise RuntimeError("dispatch exploded")

    monkeypatch.setattr(tr._fused, "apply", boom)
    with pytest.raises(RuntimeError, match="dispatch exploded"):
        run_train_chaos(net, tr, _mse, (X, y), 1)
    monkeypatch.undo()
    _, outcomes = run_train_chaos(net, tr, _mse, (X, y), 1)
    assert outcomes == [StepOutcome.APPLIED]


def test_spmd_scaler_without_guard_warns_and_freezes_scale():
    """Without the guard overflow can never be observed; the scale must
    not ratchet up forever."""
    mx.random.seed(7)
    net2 = nn.Sequential()
    net2.add(nn.Dense(16, in_units=8, activation="relu"),
             nn.Dense(4, in_units=16))
    net2.initialize()
    with pytest.warns(UserWarning, match="guard is off"):
        tr2 = parallel.SPMDTrainer(
            net2, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            guard=False,
            loss_scaler=LossScaler(init_scale=4.0, scale_window=1))
    rng = np.random.RandomState(1)
    X = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(16,))
    for _ in range(3):
        tr2.step(nd.array(X), nd.array(y))
    assert tr2.loss_scaler.loss_scale == 4.0   # frozen, not ratcheting


def test_row_sparse_grad_joins_guard_verdict():
    """A NaN confined to a row_sparse embedding gradient must veto the
    WHOLE step — sparse rows and fused dense groups alike (the
    all-or-nothing contract; previously invisible to the guard)."""
    mx.random.seed(13)
    net = nn.Sequential()
    net.add(nn.Embedding(20, 4, sparse_grad=True),
            nn.Dense(4, in_units=4))
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.5}, kvstore=None)
    from incubator_mxnet_tpu import autograd
    idx = nd.array(np.array([3.0, 7.0]))
    for _ in range(2):
        with autograd.record():
            L = (net(idx) ** 2).sum()
        L.backward()
        tr.step(1)
    import jax.numpy as jnp
    w_before = [p.data().asnumpy().copy()
                for p in net.collect_params().values()]
    with autograd.record():
        L = (net(idx) ** 2).sum()
    L.backward()
    emb_grad = list(net.collect_params().values())[0].grad()
    arr = np.asarray(emb_grad._data).copy()
    arr[3, 0] = np.nan                       # poison only the embedding
    emb_grad._data = jnp.asarray(arr)
    tr.step(1)
    assert tr.last_outcome is StepOutcome.SKIPPED_NONFINITE
    for b, p in zip(w_before, net.collect_params().values()):
        np.testing.assert_array_equal(b, p.data().asnumpy())
    # clean step afterwards applies again
    with autograd.record():
        L = (net(idx) ** 2).sum()
    L.backward()
    tr.step(1)
    assert tr.last_outcome is StepOutcome.APPLIED


def test_explicit_save_step_survives_guard_skips(tmp_path):
    """save_checkpoint(step=loop_index) must hand that exact index back
    on restore even when guard skips made num_update drift below it —
    resuming from num_update would re-run already-applied batches."""
    from incubator_mxnet_tpu.checkpoint import CheckpointManager
    net = _build_net(seed=21)
    tr = _trainer(net, scaler=LossScaler(init_scale=8.0, scale_window=50))
    X, y = _data(seed=22)
    # 4 loop steps, one skipped -> num_update == 3, loop position == 4
    run_train_chaos(net, tr, _mse, (X, y), 4, [NaNGrad(at_step=1)])
    assert tr.optimizer.num_update == 3
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
    tr.save_checkpoint(mgr, step=4, block=True)

    net2 = _build_net(seed=21)
    tr2 = _trainer(net2)
    assert tr2.restore_checkpoint(mgr) == 4   # the caller's loop index
    assert tr2.optimizer.num_update == 3      # internal counter intact
    mgr.close()
