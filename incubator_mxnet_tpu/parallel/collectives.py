"""Collective communication wrappers.

The reference's comm layer is explicit code paths per transport: CPU reduce
(`CommCPU`), GPU P2P/tree reduce (`CommDevice`/`CommDeviceTree`), NCCL
(`kvstore_nccl.h`), ZMQ parameter server (ps-lite) — SURVEY.md §5.8. Here
every collective is an XLA op on a mesh axis; the compiler schedules it on
ICI within a slice and DCN across slices, and overlap with compute comes
from XLA's latency-hiding scheduler (the reference's P3 priority scheduling
has no manual analogue — SURVEY.md §2.3).

Two API levels:
  - in-step (traced) collectives for use inside `shard_map`-ped functions:
    thin aliases of `jax.lax` collectives, kept here so model code imports
    one namespace;
  - host-level eager helpers (`host_allreduce`) used by the KVStore facade
    for cross-process reduction outside a compiled step.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# ----------------------------------------------------------------------- #
# traced collectives (inside shard_map / pmapped code)
# ----------------------------------------------------------------------- #
psum = lax.psum
pmean = lax.pmean
pmax = lax.pmax
pmin = lax.pmin
ppermute = lax.ppermute
all_gather = lax.all_gather
all_to_all = lax.all_to_all
axis_index = lax.axis_index


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0,
                   tiled: bool = True):
    """Sum across ``axis_name`` and scatter shards along
    ``scatter_dimension`` (reference capability: the reduce half of a
    ring allreduce; used for ZeRO-style grad sharding)."""
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=tiled)


def ring_allreduce_flat(flat, axis_name: str, axis_size: int):
    """Chunked ring all-reduce of a flat f32 buffer over one mesh axis
    via `lax.ppermute` (reduce-scatter pass then all-gather pass) — the
    escape hatch for schedulers that cluster `all-reduce` ops but leave
    `collective-permute` chains alone (ISSUE 19 tentpole; used by the
    pipelined step's ``grad_collective='ring'`` mode). At 2 devices each
    chunk's sum is one commutative add, so the result is bitwise the
    psum value."""
    s = int(axis_size)
    if s == 1:
        return flat
    n = flat.size
    chunk = -(-n // s)
    buf = jnp.pad(flat, (0, chunk * s - n)).reshape(s, chunk)
    r = lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % s) for i in range(s)]

    def row(i):
        return lax.dynamic_slice_in_dim(buf, i % s, 1, axis=0)[0]

    # reduce-scatter: after s-1 hops rank r holds the full sum of
    # chunk (r+1) % s
    partial = row(r)
    for t in range(s - 1):
        partial = lax.ppermute(partial, axis_name, fwd)
        partial = partial + row(r - t - 1)
    # all-gather: circulate the reduced chunks back around the ring
    owned = (r + 1) % s
    out = jnp.zeros_like(buf)
    out = lax.dynamic_update_slice_in_dim(out, partial[None], owned, 0)
    for t in range(s - 1):
        partial = lax.ppermute(partial, axis_name, fwd)
        out = lax.dynamic_update_slice_in_dim(
            out, partial[None], (owned - t - 1) % s, 0)
    return out.reshape(-1)[:n]


def int8_bucket_allreduce(vals, reduce_axes):
    """EQuARX-style traced quantized all-reduce of one gradient bucket:
    ONE symmetric per-bucket scale from the GLOBAL amax (pmax over the
    batch axes), int32 code psum, dequantize. Returns the reduced member
    list in order.

    The scale is shared across every member of the bucket so the whole
    bucket ships as one int32 psum; a non-finite gradient anywhere
    poisons the amax → the scale → every dequantized member, which is
    exactly what lets the PR-8 guard (reading the dequantized grads)
    veto the step without a second reduction."""
    from ..ops.quantization import (dequantize_symmetric,
                                    quantize_symmetric, symmetric_scale)
    amax = jnp.max(jnp.stack(
        [jnp.max(jnp.abs(v.astype(jnp.float32))) for v in vals]))
    amax = lax.pmax(amax, reduce_axes)
    scale = symmetric_scale(amax)
    codes = tuple(
        quantize_symmetric(v.astype(jnp.float32), scale)
        .astype(jnp.int32) for v in vals)
    summed = lax.psum(codes, reduce_axes)
    return [dequantize_symmetric(c, scale).astype(v.dtype)
            for c, v in zip(summed, vals)]


# ----------------------------------------------------------------------- #
# host-level eager collectives (the KVStore facade's transport)
# ----------------------------------------------------------------------- #
def host_allreduce(x: jax.Array, op: str = "sum",
                   compression: Optional[str] = None) -> jax.Array:
    """Eager cross-process allreduce over DCN.

    Replaces the reference's dist_sync push path (worker → ps-lite server
    aggregate → pull, SURVEY.md §3.4): every process contributes its local
    array; all processes get the elementwise reduction. Single-process is
    the identity (the in-process multi-device reduction already happened in
    the caller).

    SCALING NOTE: this is allgather-then-sum — O(P) wire bytes per
    reduction, fine at the P<=4 scale the tests run but the wrong shape
    at P=16+ where the reference's key-sharded server aggregation
    (src/kvstore/kvstore_dist_server.h) is O(1) per worker. Large-P
    training should keep the reduction INSIDE the compiled SPMD step
    (psum over a global mesh — SPMDTrainer does this), where XLA emits
    proper ring/tree collectives; this eager helper is the kvstore
    facade's transport, not the fast path.
    """
    if jax.process_count() == 1:
        return x
    from jax.experimental import multihost_utils

    if op != "sum":
        raise ValueError(f"unsupported host_allreduce op {op!r}")
    if compression == "bf16" and x.dtype == jnp.float32:
        # REAL wire savings (unlike the reference's 2-bit emulation in
        # kvstore): halve the bytes crossing DCN by gathering bf16,
        # accumulate in f32 — the TPU-idiomatic compressed collective
        gathered = multihost_utils.process_allgather(
            x.astype(jnp.bfloat16))
        return jnp.sum(gathered.astype(jnp.float32), axis=0)
    gathered = multihost_utils.process_allgather(x)  # (n_proc, ...)
    return jnp.sum(gathered, axis=0)


# ----------------------------------------------------------------------- #
# 2-bit stochastic-threshold gradient compression (reference:
# src/kvstore/gradient_compression.cc — the dist_sync wire format).
# Codes: 0 → 0, 1 → +threshold, 2 → -threshold; 4 codes packed per uint8
# byte, so the DCN hop carries N/4 bytes instead of 4N (16x). The
# quantization error is kept in a persistent per-key RESIDUAL and added
# back before the next quantization (error feedback) — without it the
# scheme does not converge.
# ----------------------------------------------------------------------- #

def _pack_2bit(codes: jax.Array) -> jax.Array:
    """(N,) uint8 codes in {0,1,2} → (ceil(N/4),) packed uint8. The four
    2-bit fields are disjoint, so a sum of shifted fields IS the bitwise
    or (accumulated in uint32 to dodge integer-promotion surprises)."""
    n = codes.shape[0]
    pad = (-n) % 4
    c = jnp.pad(codes, (0, pad)).reshape(-1, 4).astype(jnp.uint32)
    shifts = jnp.arange(0, 8, 2, dtype=jnp.uint32)
    return jnp.sum(c << shifts[None, :], axis=1).astype(jnp.uint8)


def _unpack_2bit(packed: jax.Array, n: int) -> jax.Array:
    """(ceil(N/4),) packed uint8 → (N,) uint8 codes."""
    shifts = jnp.arange(0, 8, 2, dtype=jnp.uint8)
    c = (packed[:, None] >> shifts[None, :]) & jnp.uint8(3)
    return c.reshape(-1)[:n]


def quantize_2bit(x: jax.Array, residual: Optional[jax.Array],
                  threshold: float):
    """Quantize ``x + residual`` to 2-bit codes.

    Returns (packed_uint8, dequantized, new_residual). The cut points sit
    at ±threshold/2 so the dequantized value is the nearest of
    {-threshold, 0, +threshold}."""
    c = x if residual is None else x + residual
    codes = jnp.where(
        c >= threshold / 2, jnp.uint8(1),
        jnp.where(c <= -threshold / 2, jnp.uint8(2), jnp.uint8(0)))
    deq = (jnp.where(codes == 1, threshold,
                     jnp.where(codes == 2, -threshold, 0.0))
           .astype(x.dtype))
    return _pack_2bit(codes.reshape(-1)), deq, c - deq


def host_allreduce_2bit(x: jax.Array, residual: Optional[jax.Array],
                        threshold: float = 0.5):
    """Cross-process allreduce with REAL 2-bit wire compression.

    Each process quantizes its local contribution (with its own error-
    feedback residual), ships the packed uint8 codes (N/4 bytes) over
    DCN, and every process sums the dequantized contributions — the
    worker→server push format of the reference's dist_sync compression.
    Returns (reduced, new_residual)."""
    packed, deq, new_res = quantize_2bit(x, residual, threshold)
    if jax.process_count() == 1:
        # kvstore-as-local-server: the push still quantizes (numerics
        # contract), there is just no second contribution to sum
        return deq, new_res
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(packed)  # (P, N/4) uint8
    codes = jax.vmap(lambda p: _unpack_2bit(p, x.size))(gathered)
    signs = jnp.where(codes == 1, 1.0, jnp.where(codes == 2, -1.0, 0.0))
    total = jnp.sum(signs, axis=0).reshape(x.shape) * threshold
    return total.astype(x.dtype), new_res


# ----------------------------------------------------------------------- #
# deterministic gradient-bucket schedule (round 16, docs/TRAINING_PERF.md)
#
# The overlapped allreduce fires a bucket's collective DURING backward,
# which makes the issue order a correctness surface: on real hardware a
# collective is a rendezvous, so two processes issuing buckets in
# different orders deadlock (each waits on a collective the other has
# not posted). The plan below is a pure function of (member indices,
# sizes, dtypes, byte limit) — identical on every process — and buckets
# are ISSUED strictly in plan order, gated on readiness: a ready bucket
# behind an unready one waits (issue order == plan order, every run,
# every process; asserted in tests/test_train_perf.py). Members are
# packed in REVERSE parameter order because backward finalizes the last
# layers' gradients first — the plan order approximates readiness order
# so the gate rarely stalls.
# ----------------------------------------------------------------------- #

class GradBucket:
    """One dtype bucket of the overlap plan: a deterministic key plus the
    member parameter indices (in packing order)."""

    __slots__ = ("key", "dtype", "indices", "nbytes")

    def __init__(self, key: str, dtype: str, indices, nbytes: int):
        self.key = key
        self.dtype = dtype
        self.indices = tuple(indices)
        self.nbytes = int(nbytes)

    def __repr__(self):
        return (f"GradBucket({self.key!r}, n={len(self.indices)}, "
                f"{self.nbytes}B)")


def plan_grad_buckets(members, limit_bytes: int,
                      key_prefix: str = "__grad_bucket_",
                      reverse: bool = True):
    """Deterministic bucket plan over ``members`` =
    ``[(param_idx, size_elems, itemsize, dtype_str)]`` — THE one
    audited packing, shared by the serial bucketed pushpull
    (``reverse=False``: forward param order, the PR-1 key format) and
    the overlapped issue plan (``reverse=True``).

    Groups by dtype (sorted), packs each dtype's members in param-index
    order — REVERSE for the overlap plan, because backward finalizes
    the deepest layers first — into <= ``limit_bytes`` buckets, and
    orders the buckets deepest-parameter-first (reverse) or
    shallowest-first (forward). Keys follow the PR-1 bucket-key format
    (dtype + running id + crc of the member composition; the overlap
    plan's ids carry an ``ov`` tag since its compositions differ) so
    dist-mode compression residuals stay coherent per composition."""
    import zlib
    by_dtype = {}
    for idx, size, itemsize, dt in members:
        by_dtype.setdefault(str(dt), []).append((int(idx), int(size),
                                                 int(itemsize)))
    tag = "ov" if reverse else ""
    buckets = []
    for dt in sorted(by_dtype):
        entries = sorted(by_dtype[dt],
                         key=(lambda e: -e[0]) if reverse
                         else (lambda e: e[0]))
        start, bucket_id = 0, 0
        while start < len(entries):
            end, nbytes = start, 0
            while end < len(entries):
                sz = entries[end][1] * entries[end][2]
                if end > start and nbytes + sz > limit_bytes:
                    break
                nbytes += sz
                end += 1
            chunk = entries[start:end]
            comp = zlib.crc32(",".join(
                f"{i}:{n}" for i, n, _ in chunk).encode())
            buckets.append(GradBucket(
                f"{key_prefix}{dt}_{tag}{bucket_id}_{comp:08x}", dt,
                [i for i, _, _ in chunk], nbytes))
            start = end
            bucket_id += 1
    if reverse:
        buckets.sort(key=lambda b: (-max(b.indices), b.dtype))
    else:
        buckets.sort(key=lambda b: (min(b.indices), b.dtype))
    return buckets


class BucketSchedule:
    """Readiness-gated, plan-ordered issue schedule over a bucket plan.

    ``mark_ready(param_idx)`` records one member gradient as final and
    returns the list of buckets now clear to issue: the next bucket in
    plan order issues only when every member is ready AND every earlier
    bucket has issued — so the observed issue order is the plan order by
    construction (the cross-process deadlock-freedom contract above).
    ``drain()`` returns the still-unissued tail (the end-of-backward
    flush). ``issued`` is the per-round ledger of issued bucket keys."""

    def __init__(self, buckets):
        self.buckets = list(buckets)
        self._member_of = {}
        for b in self.buckets:
            for i in b.indices:
                self._member_of[i] = b
        self._pending = {b.key: len(b.indices) for b in self.buckets}
        self._cursor = 0
        self.issued = []

    @property
    def order(self):
        return [b.key for b in self.buckets]

    def reset_round(self):
        self._pending = {b.key: len(b.indices) for b in self.buckets}
        self._cursor = 0
        self.issued = []

    def mark_ready(self, param_idx: int):
        b = self._member_of.get(param_idx)
        if b is None:
            return []
        n = self._pending.get(b.key, 0)
        if n > 0:
            self._pending[b.key] = n - 1
        ready = []
        while self._cursor < len(self.buckets) and \
                self._pending[self.buckets[self._cursor].key] == 0:
            nxt = self.buckets[self._cursor]
            self._cursor += 1
            self.issued.append(nxt.key)
            ready.append(nxt)
        return ready

    def drain(self):
        tail = self.buckets[self._cursor:]
        self._cursor = len(self.buckets)
        for b in tail:
            self.issued.append(b.key)
        return tail


def host_broadcast(x: jax.Array, root: int = 0) -> jax.Array:
    """Broadcast ``x`` from the root process to all processes (the
    reference's init-time weight broadcast via kvstore init/pull)."""
    if jax.process_count() == 1:
        return x
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(
        x, is_source=jax.process_index() == root)


def host_barrier(tag: str = "barrier"):
    """Cross-process barrier (reference: ps-lite ``Barrier``)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)
