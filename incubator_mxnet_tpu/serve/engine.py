"""Continuous-batching inference engine over the paged KV cache.

Design (the jit-once contract):

  - The engine owns ``num_slots`` decode SLOTS. Occupancy (which slots
    are live, at what lengths, with what sampling params) is pure DATA
    — int32/float32 arrays fed to ONE jitted decode-step program whose
    shapes never change. Prefill-insert and EOS-eviction are host-side
    edits of those arrays plus page-allocator bookkeeping; in steady
    state the decode step compiles exactly once — exactly once PER
    decode-family program when speculation is on, see below —
    (asserted by ``tools/serve_bench.py --smoke`` and
    tests/test_serve.py).
  - Prefill is a separate jitted program per PROMPT BUCKET (prompt
    pages rounded up to a power of two), the BucketingModule trade-off:
    a bounded, logarithmic family of prefill shapes instead of one per
    prompt length.
  - The decode step, per layer: project the one new token per slot,
    scatter its K/V into each slot's tail page, then ragged paged
    attention (ops/ragged_attention.py) over exactly the live pages.
    Inactive slots ride along at length 0: they write to the null page,
    attend nothing (zero output by the masked-row contract), and their
    sampled token is discarded on the host — no shape anywhere depends
    on how many slots are live.
  - PREFIX CACHING (copy-on-write page sharing): a host-side radix/hash
    index (``paged_kv.PrefixIndex``) remembers which pages hold which
    page-aligned prompt prefixes. Admission matches a new prompt's
    longest cached prefix and maps those pages into the slot's page
    table READ-ONLY (refcounted — they return to the free list only
    when the last slot and the index let go); the boundary partial page
    is COPIED into a private page; only the un-cached suffix pays
    prefill compute. Shared pages are never written: decode writes land
    at positions >= the prompt length, past every shared page.
    ``warm_start`` flushes the index — cached K/V is weight-dependent.
  - CHUNKED PREFILL (``chunk_pages``): instead of one monolithic
    prompt-sized program between decode steps, the prompt is processed
    in fixed-size page-aligned chunks (one pow2 bucket family)
    interleaved with decode under a per-step TOKEN BUDGET, so a long
    arrival no longer freezes TPOT for every active slot. Chunk queries
    attend the slot's already-populated pages plus the causal
    intra-chunk part (``ops.ragged_attention.ragged_prefill_attention``)
    — chunk position/length/pages are data, so each chunk bucket
    compiles exactly once, same contract as decode. The cache-hit
    suffix path reuses the same chunk programs even in monolithic mode.
  - SPECULATIVE DECODING (``spec_k``): decode is dispatch/bandwidth-
    bound at one token per slot per step, so the engine drafts up to K
    candidate next-tokens per slot HOST-SIDE (n-gram/prompt-lookup over
    the slot's own prompt + emitted history — serve/draft.py, no second
    model) and the decode step VERIFIES all K+1 positions in the same
    single program call: the draft tokens' K/V are written into the
    slot's tail pages up front, every position is scored through a
    multi-query ragged attention variant
    (``ops.ragged_attention.ragged_verify_attention`` — the slot's
    paged prefix plus causal intra-window masking in one predicate),
    and the accepted prefix length is computed ON DEVICE (greedy:
    longest run of drafts matching the argmax chain — bit-identical to
    sequential decode by construction; temperature: rejection-sampled
    acceptance, so the output distribution is provably unchanged). The
    accepted lengths come back as a per-slot data vector feeding the
    SAME ragged lengths/page machinery — drafts, acceptance and the
    per-slot RNG keys are pure data, so the decode family still
    compiles exactly once PER PROGRAM: the W=1 narrow step (bitwise
    the non-speculative decode — it runs whenever no slot drafted,
    via adaptive gating: ``spec_patience`` fully-rejected windows
    stop a slot's drafting, ``spec_probe_every`` re-probes) and the
    K+1-wide verify, two shape-keyed entries in one jit cache
    (``decode_trace_count`` / ``verify_trace_count``). A slot whose
    drafts all miss (or that drafted nothing) advances exactly
    today's 1 token/step. Rejected drafts leave stale K/V above the
    accepted length — harmless by the same masked-read contract that
    covers reused pages, and overwritten by the next step's writes.
  - Per-slot sampling params: a (S,) temperature array is traced data;
    greedy and categorical are both computed and selected per slot.
    Every admitted request carries its own RNG key (``Request.seed``,
    engine-assigned when unset) folded with the TOKEN'S SEQUENCE
    POSITION for every draw — sampling is reproducible per request and
    independent of occupancy, chunking, and speculation depth.
  - tp sharding: pass ``mesh`` — pools are placed with the H axis
    sharded over ``tp`` via the existing ``parallel.mesh`` machinery
    and XLA propagates the layout through the step (attention runs the
    jnp ragged path under tp; wiring the Pallas kernel through
    shard_map is future work, documented in docs/SERVING.md).

The reference's closest surface is the stateful Module/forward loop +
GluonNLP's BeamSearchSampler (file-level citations, SURVEY.md caveat) —
per-request, dense, and retrace-happy; this is its redesign for ragged
multi-tenant decode.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import time
from collections import deque
from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import MXNetError
from ..ndarray import NDArray
from ..ops.attention import scaled_dot_product_attention as _sdpa
from ..ops.ragged_attention import (ragged_attention_reference,
                                    ragged_paged_attention,
                                    ragged_prefill_attention,
                                    ragged_prefill_reference,
                                    ragged_verify_attention,
                                    ragged_verify_reference)
from .draft import make_ngram_drafter
from .events import EventType, resolve_recorder, terminal_fields
from .outcomes import Outcome
from .paged_kv import (NULL_PAGE, KVTierStore, PageAllocator, PrefixIndex,
                       init_kv_pools, kv_quant_spec, page_scales,
                       write_block_kv, write_block_kv_q,
                       write_prompt_kv, write_prompt_kv_q,
                       write_token_kv, write_token_kv_q)
from .sampling import (SamplingParams, constrain_logits, grammar_mask,
                       match_stop)
from .slo import (BrownoutController, Tier, TierPolicy,
                  resolve_tier_policies)

__all__ = ["Request", "InferenceEngine", "Outcome", "Tier",
           "TierPolicy", "SamplingParams"]

_NEG_BIG = -1e30

_REQUEST_IDS = itertools.count(1)    # process-wide: ids never collide
                                     # across engines, so a router can
                                     # address any request it has seen


@dataclasses.dataclass
class Request:
    """One generation request. ``temperature`` 0 = greedy; ``eos_id``
    < 0 disables EOS stopping (generation runs to max_new_tokens).
    ``deadline_s`` (seconds, relative to submit) bounds the request's
    total queue + serve time: past it the request is dropped from the
    queue or evicted mid-decode with outcome DEADLINE_EXPIRED (partial
    tokens are kept). ``seed`` pins the request's own sampling RNG
    stream (temperature draws are then reproducible across engines,
    occupancy mixes, chunking, and speculation depth); None lets the
    engine assign one. Every request submitted to the engine ends with
    ``outcome`` set to exactly one terminal Outcome (serve/outcomes.py);
    ``detail`` carries the human-readable cause for the failure
    outcomes and ``retry_after_s`` the backpressure hint on SHED.
    ``drafted_tokens``/``accepted_tokens`` count this request's
    speculative drafting activity (accepted <= drafted; both 0 when
    the engine does not speculate).

    ``tier`` is the request's SLO priority class (serve/slo.py):
    LATENCY outranks STANDARD outranks BATCH in admission order, shed
    order (BATCH drains first) and slot preemption (a LATENCY
    admission may reclaim a BATCH slot mid-decode — the preempted
    request re-queues and resumes from its emitted suffix,
    bit-identically). ``request_id`` is a process-unique handle for
    client cancellation (``engine.cancel`` / ``router.cancel``);
    auto-assigned unless pinned. ``preemptions`` counts how many times
    a higher tier reclaimed this request's slot."""

    prompt_ids: np.ndarray
    max_new_tokens: int = 32
    temperature: float = 0.0
    eos_id: int = -1
    deadline_s: Optional[float] = None
    seed: Optional[int] = None
    tier: Tier = Tier.STANDARD
    request_id: Optional[int] = None
    # the full sampling menu (serve/sampling.py): top-k/top-p,
    # repetition/presence penalties, logit bias, stop sequences,
    # grammar-constrained decoding — all pure per-slot data through
    # the same compiled programs temperature rides today (None = the
    # plain greedy/temperature path, bit-identical to pre-round-18)
    sampling: Optional[SamplingParams] = None
    # resume split hint: the first ``prompt_len`` prompt ids are the
    # TRUE prompt, the rest previously-emitted tokens folded back in
    # by a failover/preemption replay (serve/router.py). The grammar
    # state and stop-sequence window are derived from the generated
    # part only, so a resumed request samples exactly as the unbroken
    # run would. None = the whole prompt is prompt.
    prompt_len: Optional[int] = None

    # filled in by the engine
    _stop_trim: int = 0          # stop-seq tokens the recording attempt
                                 # could not truncate locally (they were
                                 # emitted by an EARLIER attempt) — the
                                 # router trims them from the client
    preemptions: int = 0
    drafted_tokens: int = 0
    accepted_tokens: int = 0
    token_ids: List[int] = dataclasses.field(default_factory=list)
    token_times: List[float] = dataclasses.field(default_factory=list)
    token_stamps: List[float] = dataclasses.field(default_factory=list)
    submit_time: Optional[float] = None
    finish_time: Optional[float] = None
    outcome: Optional[Outcome] = None
    detail: str = ""
    retry_after_s: Optional[float] = None
    _deadline_abs: Optional[float] = None
    _assigned_key: Optional[np.ndarray] = None   # engine-drawn RNG key,
                                                 # pinned at first
                                                 # admission so a
                                                 # preemption resume
                                                 # replays the SAME
                                                 # sampling stream

    def __post_init__(self):
        self.prompt_ids = np.asarray(self.prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size == 0:
            raise MXNetError("empty prompt")
        if self.max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise MXNetError("deadline_s must be > 0 (or None)")
        if isinstance(self.tier, str):
            self.tier = Tier(self.tier)
        if not isinstance(self.tier, Tier):
            raise MXNetError(f"tier must be a serve.Tier, got "
                             f"{self.tier!r}")
        if self.sampling is not None:
            if not isinstance(self.sampling, SamplingParams):
                raise MXNetError(f"sampling must be a SamplingParams, "
                                 f"got {type(self.sampling).__name__}")
            if self.sampling.grammar is not None and self.eos_id < 0:
                raise MXNetError(
                    "grammar-constrained decoding requires eos_id >= 0 "
                    "(grammar completion is expressed through EOS)")
        if self.prompt_len is not None:
            self.prompt_len = int(self.prompt_len)
            if not (0 < self.prompt_len <= self.prompt_ids.size):
                raise MXNetError(
                    f"prompt_len {self.prompt_len} outside "
                    f"(0, {self.prompt_ids.size}]")
        if self.request_id is None:
            self.request_id = next(_REQUEST_IDS)


@dataclasses.dataclass
class _Slot:
    request: Request
    reserved_pages: int          # worst-case pages (admission guarantee)
    refs: List[int]              # pages this slot holds a refcount on
    row: np.ndarray              # (max_pages,) page row; installed into
                                 # the decode page table when prefill ends
    t0: int                      # attempt prompt length (original
                                 # prompt + tokens emitted before a
                                 # preemption resume)
    attempt_ids: np.ndarray      # the attempt prompt itself — what the
                                 # prefill programs process and the
                                 # prefix index is keyed by
    prefill_pos: int             # prompt tokens whose K/V is populated
    t_admit: float
    key: np.ndarray = None       # (2,) uint32 per-request RNG key
    stall_count: int = 0         # consecutive zero-progress steps (the
                                 # watchdog's evidence; reset on progress)
    spec_streak: int = 0         # consecutive FULLY-REJECTED draft
                                 # windows (adaptive gating's evidence;
                                 # reset on any acceptance)
    grammar_state: object = None  # current DFA state (host data; None
                                  # when the request has no grammar)
    menu_active: bool = False    # request carries LOGIT-touching
                                 # sampling params (stop-only requests
                                 # stay False: stops are host-side) —
                                 # steps serving only neutral slots
                                 # ship the cached device-resident
                                 # neutral operands instead of copying
                                 # the (S, V) tables every step
    stop_tail: list = dataclasses.field(default_factory=list)
                                 # trailing window of the GENERATED
                                 # stream (max_stop_len tokens) — the
                                 # stop-sequence matcher's evidence,
                                 # seeded across resume boundaries

    @property
    def prefilling(self) -> bool:
        return self.prefill_pos < self.t0


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class InferenceEngine:
    """Fixed-slot continuous-batching decode over a GPT-style model
    (models/gpt.py — anything exposing word_embed / position_embed /
    blockN(ln1, attn.{qkv,proj}, ln2, ffn_*) / ln_f and tied LM head).

    ``num_pages`` defaults to the worst case (every slot at max_len) so
    admission never stalls; shrink it to trade admission concurrency
    for cache memory — correctness is preserved by admission control
    (a request is only admitted when its worst-case page count fits,
    counting pages reclaimable from the prefix index).

    ``prefix_cache`` (default on) enables copy-on-write prefix page
    sharing; ``chunk_pages`` (a power of two, default None = the PR 2
    monolithic prefill) enables chunked prefill with at most
    ``token_budget`` prompt tokens processed per engine step (default
    ``chunk_pages * page_size``).

    Resilience knobs (docs/RESILIENCE.md — every request ends in a
    structured terminal ``Outcome`` instead of success-or-exception):

    - ``max_queue``: bounded admission queue depth — a submit beyond it
      is SHED with a ``retry_after_s`` hint instead of growing the
      queue without bound;
    - ``max_queue_delay_s``: estimated-queue-delay admission limit (an
      EWMA of observed slot-residence times scales the queue backlog
      BEYOND today's free slots — zero on an idle engine, which must
      never shed on its own steady-state latency) — load is shed
      BEFORE the queue builds a deadline-busting backlog;
    - ``guard_nonfinite`` (default on): the decode/prefill programs
      compute a cheap per-slot non-finite flag (one logits reduction on
      device) and SIGN-ENCODE it into the sampled tokens (token t on a
      poisoned slot reads -t - 1) — pure DATA riding the existing
      token transfer, so the jit-once contract is untouched and no
      extra program output or host sync is paid; a flagged slot is
      quarantined and failed with FAILED_NONFINITE rather than
      sampling garbage forever;
    - ``watchdog_steps``: a slot making zero progress for this many
      consecutive engine steps (e.g. page-starved for its tail page)
      is evicted with FAILED_UNSERVABLE — a stuck slot never wedges
      the engine;
    - ``max_slot_wall_s``: per-slot wall-clock cap (engine-imposed
      deadline) — exceeded slots are evicted DEADLINE_EXPIRED;
    - ``stall_steps``: consecutive fully-idle scheduler polls (nothing
      decoding, queue head unadmittable) before the head request is
      failed FAILED_UNSERVABLE instead of waiting forever.

    SLO-tier knobs (serve/slo.py, docs/RESILIENCE.md):

    - ``tier_policies``: {Tier: TierPolicy} overrides merged over
      ``default_tier_policies()`` — per-tier ``max_queue`` /
      ``max_queue_delay_s`` / ``default_deadline_s`` scoping of the
      global knobs, plus the preemption contract. Admission is
      priority-ordered (LATENCY > STANDARD > BATCH, FIFO within a
      tier), overload shedding drains the lowest queued tier first,
      and a tier that ``can_preempt`` may reclaim a ``preemptible``
      lower-tier slot mid-decode: the victim keeps its partial tokens
      and re-queues through normal admission as a resume-from-suffix
      replay (continuation bit-identical — same pinned sampling key,
      position-keyed draws), bounded by ``max_preemptions`` before a
      retryable PREEMPTED terminal;
    - ``brownout``: True (default controller) or a
      ``BrownoutController`` — deterministic hysteresis over pressure
      signals stepping through degrade levels (1: speculation off,
      2: chunked-prefill budget clamped to one chunk, 3: BATCH
      admissions clamped to zero) and back out as pressure clears;
    - ``cancel(request_or_id)``: client cancellation from any live
      state to a CANCELLED terminal, pages reclaimed, audit clean.

    All tier/preemption/brownout state is host-side data — none of it
    enters a compiled program, so the jit-once decode contract holds
    (asserted in tests/test_tiers.py, tools/chaos_bench.py --tiers).

    Speculative decoding knobs (docs/SERVING.md):

    - ``spec_k`` (default 0 = off): draft up to K candidate tokens per
      slot per step and verify all K + 1 positions in the one jitted
      decode program; greedy output stays bit-identical to the
      non-speculative path, temperature output keeps its exact
      distribution (rejection-sampled acceptance). A step accepts
      1..K+1 tokens per slot — 1 (exactly today's decode) when the
      drafts miss or none were found;
    - ``draft_fn``: ``(history, k) -> int32[0..k]`` draft proposer;
      default is n-gram/prompt-lookup drafting over the slot's own
      prompt + emitted tokens (``serve.draft.ngram_propose``) with
      max order ``draft_ngram``;
    - ``spec_patience`` / ``spec_probe_every``: adaptive gating — a
      slot whose last ``spec_patience`` draft windows were ALL fully
      rejected stops drafting (0 disables gating); steps where no slot
      drafted run the W=1 program, bitwise the non-speculative decode
      step, so zero-agreement traffic converges to the plain-decode
      floor. Gated slots probe again every ``spec_probe_every``-th
      engine step (shared clock — one wide step per probe, however
      many slots probe); newly admitted requests always draft
      immediately (fresh slot state), so churny traffic re-tests
      agreement without waiting for the clock.

    Quantized KV cache (docs/SERVING.md "Quantized KV cache"):

    - ``kv_quant`` (default None = f32/bf16 pools): ``'int8'`` (or
      ``'fp8_e4m3'`` on a float8-capable jax) stores every KV page as
      narrow codes with ONE symmetric scale per page per pool —
      roughly 4x (f32) / 2x (bf16) more slots-at-context on the same
      pool bytes, and the same factor more prefix-cache working set.
      K/V quantize AT WRITE TIME inside the existing programs (pure
      traced data — decode/verify/prefill trace counts stay 1), all
      three ragged kernels dequantize inline at the DMA boundary with
      the scales riding the scalar-prefetch path next to the page
      table, and the host owns the per-page amax metadata (reset on
      page allocation, copied on COW, shared when the page is
      shared). Accuracy is a measured-tolerance gate against the f32
      jnp oracle (BENCH_QUANT.json), not bit parity; int8 payloads
      cannot carry NaN, so the non-finite channel becomes the page
      SCALE — a poisoned scale makes the attention output non-finite
      and the existing sign-encoded guard quarantines the slot
      (serve/chaos.py ``CorruptPageScale``)."""

    def __init__(self, model, num_slots=8, page_size=16, max_len=None,
                 num_pages=None, dtype=None, mesh=None, interpret=None,
                 prefix_cache=True, chunk_pages=None, token_budget=None,
                 max_queue=None, max_queue_delay_s=None,
                 guard_nonfinite=True, watchdog_steps=1024,
                 max_slot_wall_s=None, stall_steps=500,
                 spec_k=0, draft_fn=None, draft_ngram=3,
                 spec_patience=2, spec_probe_every=64,
                 tier_policies=None, max_preemptions=4,
                 brownout=None, kv_quant=None, kv_tiers=None,
                 recorder=None, component="engine"):
        self.model = model
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.max_len = int(max_len or model.max_length)
        if self.max_len > model.max_length:
            raise MXNetError(f"max_len {self.max_len} exceeds model "
                             f"max_length {model.max_length}")
        self.max_pages = -(-self.max_len // self.page_size)
        if num_pages is None:
            num_pages = 1 + self.num_slots * self.max_pages
        self.num_pages = int(num_pages)
        self._dtype = dtype or model._dtype

        self.chunk_pages = None
        if chunk_pages is not None:
            cp = int(chunk_pages)
            if cp < 1 or (cp & (cp - 1)):
                raise MXNetError(f"chunk_pages must be a power of two, "
                                 f"got {cp}")
            self.chunk_pages = cp
        self.token_budget = int(token_budget) if token_budget is not None \
            else (self.chunk_pages or self.max_pages) * self.page_size
        if self.chunk_pages is not None and \
                self.token_budget < self.chunk_pages * self.page_size:
            raise MXNetError(
                f"token_budget {self.token_budget} below one chunk "
                f"({self.chunk_pages * self.page_size} tokens) — a long "
                f"prompt could never make progress")

        H = model.block0.attn._heads
        D = model._units // H
        self._H, self._D = H, D
        # quantized KV pools (docs/SERVING.md "Quantized KV cache"):
        # int8/fp8 page payload + per-page symmetric scales. The amax
        # arrays are HOST-OWNED page metadata (np, one (P,) f32 per
        # layer per pool): every program that writes pages takes them
        # as traced data and returns them updated — the host pulls the
        # tiny arrays back on its existing per-step sync — and the
        # host resets a page's amax when the allocator hands it out
        # (a recycled page must not inherit its previous owner's
        # range, and a quarantined slot's poisoned scale dies with
        # the page). Everything below is gated on self._kv_spec, so
        # kv_quant=None is byte-for-byte the unquantized engine.
        self._kv_spec = kv_quant_spec(kv_quant)
        self.kv_quant = self._kv_spec.name if self._kv_spec else None
        pools = init_kv_pools(model.num_layers, self.num_pages, H,
                              self.page_size, D, self._dtype,
                              quant=self._kv_spec)
        self._kpools = tuple(k for k, _ in pools)
        self._vpools = tuple(v for _, v in pools)
        if self._kv_spec is not None:
            self._kamax = tuple(np.zeros((self.num_pages,), np.float32)
                                for _ in range(model.num_layers))
            self._vamax = tuple(np.zeros((self.num_pages,), np.float32)
                                for _ in range(model.num_layers))
        else:
            self._kamax = self._vamax = ()

        # model params are TRACED INPUTS of the decode/prefill programs
        # (not closure constants): warm-restarting new weights into a
        # live engine is then pure data — the jitted decode step is
        # reused at compile count 1 (see warm_start / test_serve.py)
        self._eng_params = [p for p in model.collect_params().values()]
        not_ready = [p.name for p in self._eng_params if p._data is None]
        if not_ready:
            raise MXNetError(f"uninitialized model parameters "
                             f"{not_ready}; call model.initialize()")
        self._param_vals = tuple(p.data()._data for p in self._eng_params)

        self._mesh = None
        if mesh is not None and dict(mesh.shape).get("tp", 1) > 1:
            # H-axis tp sharding through parallel.mesh; the step's jnp
            # ragged path partitions cleanly under jit (the Pallas
            # kernel is per-chip — shard_map wiring is future work)
            from ..parallel.mesh import named_sharding
            self._mesh = mesh
            sh = named_sharding(mesh, None, "tp", None, None)
            self._kpools = tuple(jax.device_put(k, sh)
                                 for k in self._kpools)
            self._vpools = tuple(jax.device_put(v, sh)
                                 for v in self._vpools)
        self._interpret = interpret

        self.spec_k = int(spec_k)
        if self.spec_k < 0:
            raise MXNetError(f"spec_k must be >= 0, got {self.spec_k}")
        if self.spec_k >= self.max_len:
            raise MXNetError(f"spec_k {self.spec_k} >= max_len "
                             f"{self.max_len}")
        self._spec_w = self.spec_k + 1       # verify window (queries/slot)
        self._draft_fn = draft_fn if draft_fn is not None \
            else make_ngram_drafter(max_order=int(draft_ngram))
        # adaptive draft gating: a slot whose last ``spec_patience``
        # draft windows were FULLY rejected stops drafting (probing
        # again on every ``spec_probe_every``-th decode step, all gated
        # slots on the SAME step so probes cost one wide step, not
        # many). Zero-draft steps then run the W=1 program — the
        # zero-agreement floor is the non-speculative engine's own
        # step, not a K+1-wide verify of hopeless drafts.
        # spec_patience=0 disables gating (draft every step).
        self.spec_patience = int(spec_patience)
        self.spec_probe_every = max(1, int(spec_probe_every))

        # host-side occupancy state — DATA, never shapes
        S = self.num_slots
        self._page_table = np.zeros((S, self.max_pages), np.int32)
        self._lengths = np.zeros((S,), np.int32)
        self._temps = np.zeros((S,), np.float32)
        self._slot_keys = np.zeros((S, 2), np.uint32)
        # the sampling menu's per-slot state (serve/sampling.py): knob
        # vectors, the logit-bias table, and the token-count table the
        # penalties read — all pure data into the SAME programs
        # temperature rides, reset to exact-identity neutrals on slot
        # free so an unconfigured request costs one where-select
        V = model.vocab_size
        self._vocab = V
        self._top_k = np.zeros((S,), np.int32)
        self._top_p = np.ones((S,), np.float32)
        self._rep_pen = np.ones((S,), np.float32)
        self._pres_pen = np.zeros((S,), np.float32)
        self._logit_bias = np.zeros((S, V), np.float32)
        self._tok_counts = np.zeros((S, V), np.int32)
        self._mask_true: dict = {}   # W -> cached all-True (S, W, V)
        self._neutral_ops: dict = {}  # W -> committed neutral operands
        self._alloc = PageAllocator(self.num_pages)
        self._prefix = PrefixIndex(self.page_size) if prefix_cache \
            else None
        self._slots: List[Optional[_Slot]] = [None] * S
        self._queue: deque = deque()
        self._key = jax.random.PRNGKey(0)
        self._prefill_rr = 0

        # resilience state (docs/RESILIENCE.md)
        self.max_queue = None if max_queue is None else int(max_queue)
        self.max_queue_delay_s = max_queue_delay_s
        self.guard_nonfinite = bool(guard_nonfinite)
        self.watchdog_steps = int(watchdog_steps)
        self.max_slot_wall_s = max_slot_wall_s
        self.stall_steps = int(stall_steps)
        self.health: dict = {o.value: 0 for o in Outcome}
        self.health_by_tier: dict = {
            t.value: {o.value: 0 for o in Outcome} for t in Tier}
        self._ewma_service_s: Optional[float] = None

        # SLO tiers (serve/slo.py): per-tier admission policy, slot
        # preemption and brownout degradation — all host-side DATA
        self._tier_policies = resolve_tier_policies(tier_policies)
        self.max_preemptions = int(max_preemptions)
        self.preemptions = 0                 # slots reclaimed by a
                                             # higher-tier admission
        if brownout is True:
            brownout = BrownoutController(
                delay_ref=max_queue_delay_s or 1.0)
        self._brownout = brownout            # None | BrownoutController

        # flight recorder (serve/events.py, docs/OBSERVABILITY.md):
        # ON by default (overhead banked <2%, BENCH_SERVE.json
        # recorder_overhead); ``recorder=False`` disables, passing an
        # existing FlightRecorder shares a timeline. ``component``
        # names this engine's lane (a Router renames its replicas'
        # default lanes to replica<i> at adoption).
        self.flight = resolve_recorder(recorder)
        self._component = str(component)
        if self._brownout is not None:
            # brownout transitions land on THIS engine's lane (the
            # controller itself is engine-agnostic — serve/slo.py)
            self._brownout.flight = self.flight

        # speculative-decoding observability (docs/SERVING.md): drafted
        # vs accepted counts feed accept_rate; per-request twins live on
        # Request.drafted_tokens / .accepted_tokens
        self.drafted_tokens = 0
        self.accepted_tokens = 0
        self.spec_steps = 0                  # steps run K+1 wide
        self.spec_gated_steps = 0            # steps adaptive gating
                                             # suppressed all drafting

        self.stop_hits = 0                   # stop-sequence terminals
        self.constrained_requests = 0        # admissions with a grammar
        self.decode_trace_count = 0          # W=1 decode program traces
        self.verify_trace_count = 0          # K+1-wide verify traces
        self.prefill_trace_count = 0         # dense + chunk, total
        self.prefill_trace_counts = {}       # ("dense"|"chunk", Tpad) -> n
        self.copy_trace_count = 0
        self.decode_steps = 0
        self.warm_restarts = 0
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        self.prefix_flushes = 0
        self.prefix_reclaimed_pages = 0
        self.max_step_prefill_tokens = 0

        # hierarchical cache tiers beneath the prefix index
        # (docs/SERVING.md "Hierarchical prefix cache"): demote
        # evicted-but-published pages to host DRAM (and DRAM overflow
        # to disk), re-admit by copy instead of recomputing prefill.
        # ``kv_tiers`` is a dict: {"dram_bytes": int, "disk_dir": str?,
        # "disk_bytes": int?} — or None for the untiered engine.
        self._tiers = None
        if kv_tiers is not None:
            if self._prefix is None:
                raise MXNetError("kv_tiers requires prefix_cache=True "
                                 "(tiers hold evicted PREFIX pages)")
            cfg = dict(kv_tiers)
            self._tiers = KVTierStore(
                self.page_size, cfg.pop("dram_bytes"),
                disk_dir=cfg.pop("disk_dir", None),
                disk_bytes=cfg.pop("disk_bytes", None),
                recorder=self.flight, component=self._component)
            if cfg:
                raise MXNetError(f"unknown kv_tiers keys: "
                                 f"{sorted(cfg)}")
        self.tier_demotions = 0          # pages captured HBM → DRAM
        self.tier_promotions = 0         # pages re-admitted by copy
        self.tier_hits = 0               # admissions a tier extended
        self.tier_hit_tokens = 0         # prompt tokens served by tiers
        self.tier_misses = 0             # tier consulted, nothing usable
        self.tier_crc_fallbacks = 0      # integrity check → recompute
        self.promote_trace_count = 0     # the one promotion program
        self.demote_trace_count = 0      # the one page-gather program

        # page transport (serve/transport.py): capsule traffic through
        # this engine — pages/bytes captured off it and installed into
        # it — plus the in-capsule page custody: a detached slot's
        # pages stay refcounted here (owned by the in-flight capsule,
        # keyed by the attempt's request_id) until the transfer lands
        # or falls back, so ``audit_pages`` sees every in-transit page
        self.migrated_out_pages = 0      # pages captured into capsules
        self.migrated_in_pages = 0       # pages installed from capsules
        self.migrated_out_bytes = 0      # capsule wire bytes, outbound
        self.migrated_in_bytes = 0       # capsule wire bytes, inbound
        self._capsule_pages: Dict[int, List[int]] = {}
        # fleet-aware preemption (serve/router.py fleet_preempt): set
        # by the router, called with the victim's request_id BEFORE an
        # engine-internal preemption — True means the fleet moved the
        # slot to a sibling and this engine must not evict/terminal it
        self.preempt_handoff = None

        self._decode_step = jax.jit(self._decode_step_fn,
                                    donate_argnums=(1, 2))
        self._prefill_jits = {}          # bucket_pages -> jitted dense fn
        self._chunk_jits = {}            # bucket_pages -> jitted chunk fn
        self._copy_jit = None
        self._promote_jit = None
        self._gather_jit = None

    # ------------------------------------------------------------- #
    # traced programs
    # ------------------------------------------------------------- #

    def _sample_one(self, logits, temp, pos_key, top_k=None, top_p=None,
                    rep_pen=None, pres_pen=None, counts=None, bias=None,
                    mask=None):
        """Greedy/temperature sample of ONE token from (V,) logits.
        ``pos_key`` is the request's RNG key folded with the sampled
        token's SEQUENCE POSITION (the engine-wide convention: the draw
        for position p uses ``fold_in(fold_in(request_key, p), 0)``),
        so whichever program computes it — dense prefill, chunk tail,
        or a verify emission point — produces the identical draw.

        The sampling-menu knobs (serve/sampling.py) are traced scalars
        / (V,) rows; None (a trace-time constant) means the caller has
        no menu state, which compiles the plain path — the prefill
        programs always pass real values."""
        if top_k is not None:
            logits = constrain_logits(logits, temp, counts, bias, mask,
                                      top_k, top_p, rep_pen, pres_pen)
        cat_key = jax.random.fold_in(pos_key, 0)
        greedy = jnp.argmax(logits, axis=-1)
        samp = jax.random.categorical(
            cat_key, logits.astype(jnp.float32) / jnp.maximum(temp, 1e-6),
            axis=-1)
        return jnp.where(temp > 0, samp, greedy).astype(jnp.int32)

    def _bind_params(self, param_vals):
        """Context manager: point every model Parameter at the traced
        ``param_vals`` for the duration of the model math (the
        SPMDTrainer pure_loss idiom), restoring the eager arrays after.
        This is what makes weights DATA to the compiled programs."""
        import contextlib

        @contextlib.contextmanager
        def scope():
            saved = [p._data for p in self._eng_params]
            for p, v in zip(self._eng_params, param_vals):
                p._data = NDArray(v)
            try:
                yield
            finally:
                for p, s in zip(self._eng_params, saved):
                    p._data = s

        return scope()

    def _ragged_attn(self, q, kp, vp, page_table, lengths, ks=None,
                     vs=None):
        if self._mesh is not None:
            return ragged_attention_reference(q, kp, vp, page_table,
                                              lengths, k_scale=ks,
                                              v_scale=vs)
        return ragged_paged_attention(q, kp, vp, page_table, lengths,
                                      interpret=self._interpret,
                                      k_scale=ks, v_scale=vs)

    def _verify_attn(self, q, kp, vp, page_table, lengths, draft_len,
                     ks=None, vs=None):
        """Multi-query (speculative verify) decode attention: q is
        (S, W, H, D), ``lengths`` counts keys visible to query row 0
        (0 = dead slot), ``draft_len`` the slot's real draft count
        bounding the kernel's V-select at the freshly-written extent.
        The W = 1 narrow program routes through ``_ragged_attn`` — the
        PR 2 single-query decode step, LITERALLY (on CPU the verify
        reference's row 0 is the same ``_reference_core`` call, so
        this changes nothing there; on TPU it keeps the specialized
        decode kernel the narrow path's kernel). Under tp meshes the
        jnp reference partitions cleanly, same as the single-query
        path."""
        if q.shape[1] == 1:
            out = self._ragged_attn(q[:, 0], kp, vp, page_table,
                                    lengths, ks, vs)
            return out[:, None]
        if self._mesh is not None:
            return ragged_verify_reference(q, kp, vp, page_table,
                                           lengths, k_scale=ks,
                                           v_scale=vs)
        return ragged_verify_attention(q, kp, vp, page_table, lengths,
                                       draft_len=draft_len,
                                       interpret=self._interpret,
                                       k_scale=ks, v_scale=vs)

    def _prefill_attn(self, q, kp, vp, page_row, start, n_real,
                      ks=None, vs=None):
        if self._mesh is not None:
            return ragged_prefill_reference(q, kp, vp, page_row, start,
                                            n_real=n_real, k_scale=ks,
                                            v_scale=vs)
        return ragged_prefill_attention(q, kp, vp, page_row, start,
                                        n_real=n_real,
                                        interpret=self._interpret,
                                        k_scale=ks, v_scale=vs)

    def _accept_emit(self, logits, tokens, draft_len, temps, slot_keys,
                     pos, act, top_k=None, top_p=None, rep_pen=None,
                     pres_pen=None, counts=None, bias=None, mask=None):
        """On-device draft acceptance — the speculative-decoding core.

        ``logits`` (S, W, V) scores token positions ``pos + 1``;
        ``tokens[:, 0]`` is the last accepted token, ``tokens[:, 1:]``
        the draft candidates (column j+1 proposed for position
        ``pos[:, j] + 1``). Greedy slots accept the longest prefix of
        drafts matching the argmax chain — BIT-IDENTICAL to running
        that many sequential decode steps, since an accepted draft IS
        the argmax its predecessor produced. Temperature slots use
        rejection sampling against the deterministic draft proposal
        (q = point mass): draft d at position p is accepted with
        probability softmax(logits/T)[d]; on rejection the emission is
        sampled from the residual (softmax with d's mass removed) — so
        the emitted distribution is exactly the non-speculative one.
        Every RNG draw is keyed by ``fold_in(request_key, position)``
        (categorical: sub-fold 0, acceptance uniform: sub-fold 1) —
        reproducible per request, independent of occupancy and K.

        Returns ``(emitted (S, W) int32, n_emit (S,) int32)``: columns
        ``[0, n_emit)`` of ``emitted`` are real tokens (accepted drafts
        then the correction/bonus sample), later columns are dead.

        Round 18: the acceptance tests and the residual both run over
        the CONSTRAINED target distribution (serve/sampling.py — bias,
        penalties with in-window count updates, top-k/top-p
        truncation, grammar mask), so speculation stays
        distribution-correct under truncated/masked proposals: a
        drafted token the constraint forbids has p̃(d) = 0 and is
        rejected; the correction resamples from the masked residual.
        Column j's penalty counts include the drafts at columns <= j —
        exactly the history a sequential decode would have seen —
        computed in-program from the (known) draft block. The
        degenerate single-allowed-token case (empty residual) force-
        accepts: p̃ is that point mass."""
        S, W = tokens.shape
        V = logits.shape[-1]
        jj = lax.broadcasted_iota(jnp.int32, (S, W), 1)
        jpos = pos + 1                   # position of column j's token
        pos_keys = jax.vmap(
            lambda key, row: jax.vmap(
                lambda p: jax.random.fold_in(key, p))(row)
        )(slot_keys, jpos)                               # (S, W, 2)
        cat_keys = jax.vmap(jax.vmap(
            lambda k: jax.random.fold_in(k, 0)))(pos_keys)
        acc_keys = jax.vmap(jax.vmap(
            lambda k: jax.random.fold_in(k, 1)))(pos_keys)
        u = jax.vmap(jax.vmap(jax.random.uniform))(acc_keys)   # (S, W)

        if top_k is not None:
            # in-window history: column j scores the token AFTER
            # tokens[:, 0..j], so its penalty counts are the base
            # (prompt + emitted, incl. tokens[:, 0]) plus the one-hot
            # sum of draft columns 1..j
            oh = jax.nn.one_hot(tokens, V, dtype=jnp.int32)
            win_counts = counts[:, None, :] + \
                jnp.cumsum(oh, axis=1) - oh[:, :1]
            logits = constrain_logits(
                logits, temps[:, None], win_counts, bias[:, None, :],
                mask, top_k[:, None], top_p[:, None],
                rep_pen[:, None], pres_pen[:, None])
        greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits.astype(jnp.float32) / \
            jnp.maximum(temps, 1e-6)[:, None, None]
        logp = jax.nn.log_softmax(scaled, axis=-1)       # (S, W, V)
        # column j tests/replaces the token at position jpos[:, j] —
        # the draft in tokens column j + 1 (the wrapped last column is
        # never valid: draft_len <= W - 1)
        d_next = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
        p_next = jnp.take_along_axis(logp, d_next[..., None],
                                     axis=-1)[..., 0]    # log p_j(d)
        # residual for a REJECTED draft at column j: q was a point mass
        # at d, so max(p - q, 0) is p with d's mass removed — mask d's
        # logit out and renormalize via the categorical itself. Columns
        # with no draft (j >= draft_len) sample plain p — the bonus
        # token when every draft was accepted.
        valid = jj < draft_len[:, None]
        res_logits = scaled + jax.nn.one_hot(
            d_next, V, dtype=jnp.float32) * \
            jnp.where(valid, _NEG_BIG, 0.0)[..., None]
        # an empty residual (every unit of mass sits on the draft —
        # e.g. a grammar state with ONE legal token) means p̃(d) = 1:
        # force acceptance instead of resampling from nothing. Tested
        # on the UNSCALED constrained logits: a temperature divide
        # could float a masked -1e30 back over the threshold
        res_empty = ~jnp.any(
            (logits + jax.nn.one_hot(d_next, V, dtype=jnp.float32) *
             _NEG_BIG) > _NEG_BIG / 2, axis=-1)
        accept = jnp.where((temps > 0)[:, None],
                           (jnp.log(u) < p_next) | res_empty,
                           d_next == greedy_tok)
        chain = jnp.cumprod((accept & valid).astype(jnp.int32), axis=1)
        n_acc = jnp.sum(chain, axis=1).astype(jnp.int32)
        samp = jax.vmap(jax.vmap(jax.random.categorical))(
            cat_keys, res_logits).astype(jnp.int32)
        final = jnp.where((temps > 0)[:, None], samp, greedy_tok)
        emitted = jnp.where(jj < n_acc[:, None], d_next, final)
        n_emit = jnp.where(act, n_acc + 1, 0).astype(jnp.int32)
        return emitted, n_emit

    def _decode_step_fn(self, param_vals, kpools, vpools, kamax, vamax,
                        tokens, draft_len, page_table, lengths, temps,
                        slot_keys, top_k, top_p, rep_pen, pres_pen,
                        counts, bias, mask):
        """ONE decode/verify step for every slot: W token positions per
        slot — the last accepted token plus up to W - 1 draft
        candidates — embedded, written into the tail pages, and scored
        in this single program call. W is taken from ``tokens``'
        (S, W) shape, so the SAME function yields the engine's two
        decode-family programs: the W=1 decode step (bitwise the PR 2
        single-token step — it runs whenever no slot drafted, so the
        zero-agreement floor pays no verify width) and the
        W = spec_k + 1 verify step. Each traces exactly once
        (``decode_trace_count`` / ``verify_trace_count``); within a
        width, occupancy, drafts, acceptance, sampling keys AND
        weights are data."""
        if tokens.shape[1] == 1:             # trace-time only
            self.decode_trace_count += 1
        else:
            self.verify_trace_count += 1
        from ..gluon.block import _hybrid_trace_scope
        from .. import autograd
        from ..models.gpt import _lm_head, _mlp, _qkv_heads

        model = self.model
        S, ps = self.num_slots, self.page_size
        W = tokens.shape[1]
        act = lengths > 0
        jj = lax.broadcasted_iota(jnp.int32, (S, W), 1)
        pos = lengths[:, None] + jj          # column j's token position
        used = jj <= draft_len[:, None]      # real token columns
        # K/V writes: real columns land at their position's page (the
        # host pre-mapped the whole draft window); padded columns and
        # dead slots write to the null page, harmless and never read
        # unmasked
        page_idx = jnp.clip(pos // ps, 0, self.max_pages - 1)
        write_page = jnp.where(act[:, None] & used,
                               jnp.take_along_axis(page_table, page_idx,
                                                   axis=1),
                               NULL_PAGE)
        write_off = pos % ps
        # padded columns of a nearly-finished slot can index past the
        # table — clamp for the (masked, discarded) embedding lookup
        emb_pos = jnp.minimum(pos, model.max_length - 1)
        eff_len = jnp.where(act, lengths + 1, 0)

        with self._bind_params(param_vals), _hybrid_trace_scope(), \
                autograd._ModeScope(recording=False, training=False):
            x = model.word_embed(NDArray(tokens)) + \
                model.position_embed(NDArray(emb_pos))
            if model._dtype != "float32":
                x = x.astype(model._dtype)
            new_k, new_v = [], []
            new_ka, new_va = [], []
            spec = self._kv_spec
            for i in range(model.num_layers):
                blk = getattr(model, f"block{i}")
                q, k, v = _qkv_heads(blk.attn, blk.ln1(x))  # (S,W,H,D)
                if spec is None:
                    kp = write_block_kv(kpools[i], k, write_page,
                                        write_off)
                    vp = write_block_kv(vpools[i], v, write_page,
                                        write_off)
                    ks = vs = None
                    adt = kp.dtype
                else:
                    # quantize-at-write: the page's scale grows with
                    # the window's amax and existing codes requantize
                    # in the same scatter — pure traced data, no new
                    # programs (trace counts stay asserted at 1)
                    kp, ka = write_block_kv_q(kpools[i], kamax[i], k,
                                              write_page, write_off,
                                              spec)
                    vp, va = write_block_kv_q(vpools[i], vamax[i], v,
                                              write_page, write_off,
                                              spec)
                    new_ka.append(ka)
                    new_va.append(va)
                    ks = page_scales(ka, spec)
                    vs = page_scales(va, spec)
                    adt = self._dtype
                new_k.append(kp)
                new_v.append(vp)
                out = self._verify_attn(q.astype(adt), kp, vp,
                                        page_table, eff_len, draft_len,
                                        ks, vs)
                out = NDArray(out.astype(q.dtype).reshape(
                    S, W, model._units))
                x = x + blk.attn.proj(out)
                x = x + _mlp(blk, x)
            # shared head: f32 cast BEFORE ln_f + tied vocab projection
            # (models/gpt.py::_lm_head — token parity with
            # decode_forward / the training path)
            logits = _lm_head(model, x)._data        # (S, W, V)
        emitted, n_emit = self._accept_emit(
            logits, tokens, draft_len, temps, slot_keys, pos, act,
            top_k=top_k, top_p=top_p, rep_pen=rep_pen,
            pres_pen=pres_pen, counts=counts, bias=bias, mask=mask)
        new_lengths = jnp.where(act, lengths + n_emit, 0)
        # per-slot non-finite guard: one logits reduction over the USED
        # verify columns (later columns may legitimately read stale
        # draft K/V — their logits are dead data), SIGN-ENCODED into
        # the emitted tokens (column 0 reads -t - 1 on a poisoned slot)
        # — pure data riding the existing token transfer, so the
        # jit-once contract is untouched (asserted), a poisoned slot is
        # visible the step it poisons, and NOTHING from a poisoned
        # verify step is ever recorded (accepted drafts included; see
        # step()). Cost banked in BENCH_SERVE.json guard_overhead.
        if self.guard_nonfinite:
            bad = jnp.any(jnp.any(~jnp.isfinite(logits), axis=-1) &
                          used, axis=-1) & act
            emitted = jnp.where(bad[:, None], -emitted - 1, emitted)
        return (tuple(new_k), tuple(new_v), tuple(new_ka),
                tuple(new_va), emitted, n_emit, new_lengths)

    def _prefill_fn(self, param_vals, kpools, vpools, kamax, vamax,
                    ids, t0, pages, temp, key, top_k, top_p, rep_pen,
                    pres_pen, counts, bias, vocab_mask):
        """Prompt forward for ONE request (ids (1, Tpad) padded): dense
        causal attention inside the prompt (the prompt attends only
        itself), K/V scattered into the slot's pages, and the FIRST
        generated token sampled from the last real position's logits.
        Tpad is the bucket shape — one compile per bucket, counted in
        ``prefill_trace_count``."""
        self.prefill_trace_count += 1        # trace-time only
        key_tc = ("dense", ids.shape[1])
        self.prefill_trace_counts[key_tc] = \
            self.prefill_trace_counts.get(key_tc, 0) + 1
        from jax import lax
        from ..gluon.block import _hybrid_trace_scope
        from .. import autograd
        from ..models.gpt import _mlp, _qkv_heads

        model = self.model
        Tpad = ids.shape[1]
        with self._bind_params(param_vals), _hybrid_trace_scope(), \
                autograd._ModeScope(recording=False, training=False):
            pos = NDArray(lax.broadcasted_iota(jnp.int32, (1, Tpad), 1))
            x = model.word_embed(NDArray(ids)) + model.position_embed(pos)
            if model._dtype != "float32":
                x = x.astype(model._dtype)
            pos_q = lax.broadcasted_iota(jnp.int32, (Tpad, Tpad), 0)
            pos_k = lax.broadcasted_iota(jnp.int32, (Tpad, Tpad), 1)
            mask = ((pos_k <= pos_q) & (pos_k < t0))[None, None]
            new_k, new_v = list(kpools), list(vpools)
            new_ka, new_va = list(kamax), list(vamax)
            spec = self._kv_spec
            for i in range(model.num_layers):
                blk = getattr(model, f"block{i}")
                q, k, v = _qkv_heads(blk.attn, blk.ln1(x))  # (1,Tpad,H,D)
                if spec is None:
                    new_k[i] = write_prompt_kv(new_k[i], k[0], pages)
                    new_v[i] = write_prompt_kv(new_v[i], v[0], pages)
                else:
                    # quantize the prompt's pages at a FRESH per-page
                    # scale; the prompt's own attention below runs on
                    # the exact pre-quantization K/V (only future
                    # paged reads pay the quantization error)
                    new_k[i], new_ka[i] = write_prompt_kv_q(
                        new_k[i], new_ka[i], k[0], pages, spec)
                    new_v[i], new_va[i] = write_prompt_kv_q(
                        new_v[i], new_va[i], v[0], pages, spec)
                out = _sdpa(q, k, v, mask=mask)
                x = x + blk.attn.proj(NDArray(out.reshape(
                    1, Tpad, model._units)))
                x = x + _mlp(blk, x)
            last = lax.dynamic_slice(
                x._data, (0, t0 - 1, 0), (1, 1, model._units))
            from ..models.gpt import _lm_head
            logits = _lm_head(model, NDArray(last))._data[:, 0]
        # the first generated token occupies position t0: its draw is
        # keyed by fold_in(request_key, t0), the engine-wide convention
        tok = self._sample_one(logits[0], temp,
                               jax.random.fold_in(key, t0),
                               top_k, top_p, rep_pen, pres_pen,
                               counts, bias, vocab_mask)
        if self.guard_nonfinite:             # sign-encoded, see decode
            tok = jnp.where(jnp.any(~jnp.isfinite(logits)),
                            -tok - 1, tok)
        return tuple(new_k), tuple(new_v), tuple(new_ka), \
            tuple(new_va), tok

    def _chunk_prefill_fn(self, param_vals, kpools, vpools, kamax,
                          vamax, ids, start, n_real, page_row, temp,
                          key, top_k, top_p, rep_pen, pres_pen, counts,
                          bias, vocab_mask):
        """ONE prefill chunk of ONE slot's prompt: ids (1, Cpad) holds
        ``n_real`` prompt tokens at absolute positions ``start + i``.
        Their K/V is scattered into the slot's pages (padded tokens land
        in the null page), then each chunk query attends the slot's
        already-populated paged prefix plus the causal intra-chunk part
        (``ragged_prefill_attention``). The last real row's logits are
        always computed and sampled — the host uses the token only when
        this is the final chunk. Cpad is the bucket shape; start /
        lengths / pages / weights are data, so each chunk bucket
        compiles exactly once (same contract as decode)."""
        self.prefill_trace_count += 1        # trace-time only
        key_tc = ("chunk", ids.shape[1])
        self.prefill_trace_counts[key_tc] = \
            self.prefill_trace_counts.get(key_tc, 0) + 1
        from jax import lax
        from ..gluon.block import _hybrid_trace_scope
        from .. import autograd
        from ..models.gpt import _mlp, _qkv_heads

        model = self.model
        ps = self.page_size
        Cpad = ids.shape[1]
        with self._bind_params(param_vals), _hybrid_trace_scope(), \
                autograd._ModeScope(recording=False, training=False):
            pos = start + lax.broadcasted_iota(jnp.int32, (1, Cpad), 1)
            x = model.word_embed(NDArray(ids)) + \
                model.position_embed(NDArray(pos))
            if model._dtype != "float32":
                x = x.astype(model._dtype)
            live = lax.broadcasted_iota(jnp.int32, (Cpad,), 0) < n_real
            page_idx = jnp.clip(pos[0] // ps, 0, self.max_pages - 1)
            tok_pages = jnp.where(live, page_row[page_idx], NULL_PAGE)
            tok_off = pos[0] % ps
            new_k, new_v = list(kpools), list(vpools)
            new_ka, new_va = list(kamax), list(vamax)
            spec = self._kv_spec
            for i in range(model.num_layers):
                blk = getattr(model, f"block{i}")
                q, k, v = _qkv_heads(blk.attn, blk.ln1(x))  # (1,Cpad,H,D)
                if spec is None:
                    new_k[i] = write_token_kv(new_k[i], k[0], tok_pages,
                                              tok_off)
                    new_v[i] = write_token_kv(new_v[i], v[0], tok_pages,
                                              tok_off)
                    ks = vs = None
                    adt = new_k[i].dtype
                else:
                    new_k[i], new_ka[i] = write_token_kv_q(
                        new_k[i], new_ka[i], k[0], tok_pages, tok_off,
                        spec)
                    new_v[i], new_va[i] = write_token_kv_q(
                        new_v[i], new_va[i], v[0], tok_pages, tok_off,
                        spec)
                    ks = page_scales(new_ka[i], spec)
                    vs = page_scales(new_va[i], spec)
                    adt = self._dtype
                out = self._prefill_attn(q[0].astype(adt),
                                         new_k[i], new_v[i], page_row,
                                         start, n_real, ks, vs)
                x = x + blk.attn.proj(NDArray(out.astype(q.dtype).reshape(
                    1, Cpad, model._units)))
                x = x + _mlp(blk, x)
            last = lax.dynamic_slice(
                x._data, (0, n_real - 1, 0), (1, 1, model._units))
            from ..models.gpt import _lm_head
            logits = _lm_head(model, NDArray(last))._data[:, 0]
        # on the FINAL chunk start + n_real == t0, so the draw key
        # matches the dense prefill's exactly — chunked vs monolithic
        # prefill emit the identical first token even at temperature
        tok = self._sample_one(logits[0], temp,
                               jax.random.fold_in(key, start + n_real),
                               top_k, top_p, rep_pen, pres_pen,
                               counts, bias, vocab_mask)
        if self.guard_nonfinite:             # sign-encoded, see decode
            tok = jnp.where(jnp.any(~jnp.isfinite(logits)),
                            -tok - 1, tok)
        return tuple(new_k), tuple(new_v), tuple(new_ka), \
            tuple(new_va), tok

    def _copy_page_fn(self, kpools, vpools, src, dst):
        """COW boundary copy: duplicate one page's K/V across every
        layer, so the cached partial page becomes this slot's private
        page (the cached original stays read-only for its sharers).
        src/dst are traced scalars — one compile, ever."""
        self.copy_trace_count += 1           # trace-time only
        new_k = tuple(p.at[dst].set(p[src]) for p in kpools)
        new_v = tuple(p.at[dst].set(p[src]) for p in vpools)
        return new_k, new_v

    def _copy_page(self, src: int, dst: int):
        if self._copy_jit is None:
            self._copy_jit = jax.jit(self._copy_page_fn,
                                     donate_argnums=(0, 1))
        self._kpools, self._vpools = self._copy_jit(
            self._kpools, self._vpools, np.int32(src), np.int32(dst))
        if self._kv_spec is not None:
            # the scale is page metadata: a COW copy carries its
            # source's scale (the codes were copied verbatim), and the
            # suffix writes grow it from there
            for a in self._kamax:
                a[dst] = a[src]
            for a in self._vamax:
                a[dst] = a[src]

    def _promote_page_fn(self, kpools, vpools, kpage, vpage, dst):
        """Write one demoted page's payload (per-layer (H, ps, D)
        host arrays, traced as data) into page ``dst`` of every pool —
        the tier PROMOTION program. Like the COW copy it is jitted
        once with donated pools and traced operands: re-admitting a
        page from DRAM or disk is data movement, never a new program
        and never a prefill recompute."""
        self.promote_trace_count += 1        # trace-time only
        new_k = tuple(p.at[dst].set(pg.astype(p.dtype))
                      for p, pg in zip(kpools, kpage))
        new_v = tuple(p.at[dst].set(pg.astype(p.dtype))
                      for p, pg in zip(vpools, vpage))
        return new_k, new_v

    def _promote_page(self, k_payload, v_payload, kamax, vamax,
                      dst: int):
        if self._promote_jit is None:
            self._promote_jit = jax.jit(self._promote_page_fn,
                                        donate_argnums=(0, 1))
        self._kpools, self._vpools = self._promote_jit(
            self._kpools, self._vpools, tuple(k_payload),
            tuple(v_payload), np.int32(dst))
        if self._kv_spec is not None:
            # scale metadata rides back with the codes: the payload
            # was captured at demotion with exactly these amaxes
            for l, a in enumerate(self._kamax):
                a[dst] = kamax[l]
            for l, a in enumerate(self._vamax):
                a[dst] = vamax[l]

    def _gather_page_fn(self, kpools, vpools, page):
        """Demotion capture: slice one page out of EVERY pool in one
        program call. Naively ``np.asarray(pool[page])`` per layer
        costs 2L separate dispatches per demoted page — on a small
        host that overhead alone made re-admission-by-copy slower
        than the recompute it replaces. One program, traced once
        (``page`` is a traced scalar), then a single device_get."""
        self.demote_trace_count += 1         # trace-time only
        return (tuple(p[page] for p in kpools),
                tuple(p[page] for p in vpools))

    def gather_page(self, page: int) -> tuple:
        """One page's wire/at-rest payload: per-layer (H, ps, D)
        host arrays plus the per-layer amax pair on quantized pools
        (int8/fp8 codes + one f32 scale — the 4x-denser form), None
        otherwise. One jitted gather program (traced once) and ONE
        device_get — shared by tier demotion and page transport, so
        a capture never compiles a second program."""
        if self._gather_jit is None:
            self._gather_jit = jax.jit(self._gather_page_fn)
        k_payload, v_payload = jax.device_get(
            self._gather_jit(self._kpools, self._vpools,
                             np.int32(page)))
        kamax = vamax = None
        if self._kv_spec is not None:
            kamax = np.asarray([a[page] for a in self._kamax],
                               np.float32)
            vamax = np.asarray([a[page] for a in self._vamax],
                               np.float32)
        return k_payload, v_payload, kamax, vamax

    def _demote_entry(self, key: bytes, ent) -> None:
        """Capture an evicted-but-published page's payload into the
        cache tiers BEFORE its page returns to the free list (the
        ``demote`` callback threaded through PrefixIndex.reclaim).
        For quantized pools the payload is the page's int8/fp8 codes
        plus its per-layer amax — the 4x-denser at-rest form; for
        unquantized pools the raw-dtype page."""
        k_payload, v_payload, kamax, vamax = self.gather_page(ent.page)
        if self._tiers.put(key, ent.tokens, ent.depth, k_payload,
                           v_payload, kamax, vamax):
            self.tier_demotions += 1
            self.flight.emit(self._component, EventType.CACHE_DEMOTE,
                             entity=f"tier:{key.hex()[:16]}",
                             tier="dram", depth=ent.depth)

    def _reclaim_prefix(self, n: int) -> int:
        """Reclaim ``n`` pages from the prefix index, demoting every
        victim's payload into the cache tiers when they are on."""
        demote = self._demote_entry if self._tiers is not None else None
        return self._prefix.reclaim(n, self._alloc, demote)

    def _reset_page_amax(self, pages):
        """Zero the scale metadata of freshly-allocated pages (host-
        side np — the arrays are host-owned between program calls).
        Pages are identity-free and never cleared on reuse; their
        SCALE must be, or a recycled page would quantize its new
        owner's rows against the previous owner's range (including a
        quarantined slot's poisoned scale)."""
        if self._kv_spec is None or not pages:
            return
        idx = np.asarray(list(pages), np.int64)
        for a in self._kamax:
            a[idx] = 0.0
        for a in self._vamax:
            a[idx] = 0.0

    def _pull_amax(self, ka, va):
        """Re-take host ownership of the scale metadata a program just
        updated (``np.array`` — a mutable COPY, never a read-only view
        of the device buffer; the host resets entries in place)."""
        if self._kv_spec is None:
            return
        self._kamax = tuple(np.array(a, np.float32) for a in ka)
        self._vamax = tuple(np.array(a, np.float32) for a in va)

    # ------------------------------------------------------------- #
    # host-side scheduler
    # ------------------------------------------------------------- #

    @property
    def active_count(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def _lazy_debt(self) -> int:
        """Pages promised at admission but not yet physically held."""
        return sum(s.reserved_pages - len(s.refs)
                   for s in self._slots if s is not None)

    # health counters (asserted consistent with per-request outcomes in
    # tests/test_resilience.py)
    @property
    def completed(self) -> int:
        return self.health[Outcome.EOS.value] + \
            self.health[Outcome.MAX_TOKENS.value] + \
            self.health[Outcome.STOP.value]

    @property
    def shed(self) -> int:
        return self.health[Outcome.SHED.value]

    @property
    def expired(self) -> int:
        return self.health[Outcome.DEADLINE_EXPIRED.value]

    @property
    def quarantined(self) -> int:
        return self.health[Outcome.FAILED_NONFINITE.value]

    @property
    def unservable(self) -> int:
        return self.health[Outcome.FAILED_UNSERVABLE.value]

    def _retry_hint(self) -> float:
        """The machine-readable backoff hint attached to every
        retryable terminal: the EWMA of observed slot-residence times
        (how long until capacity realistically frees), or a small
        default before a first completion calibrates it."""
        return self._ewma_service_s if self._ewma_service_s else 0.05

    def _record_terminal(self, request: Request, outcome: Outcome,
                         detail: str = "",
                         retry_after: Optional[float] = None):
        """The single point where a request becomes terminal — exactly
        once, with the health counter kept consistent. Every
        shed/deadline-class (``Outcome.retryable``) terminal carries a
        ``retry_after_s`` hint — callers may pass a sharper estimate,
        but no retryable outcome ever leaves without one (the single
        backoff contract clients and the fleet router consume)."""
        if request.outcome is not None:
            raise MXNetError(
                f"request already terminal ({request.outcome}) — "
                f"double-finish is an engine bug")
        if retry_after is None and outcome.retryable:
            retry_after = self._retry_hint()
        request.outcome = outcome
        request.detail = detail
        request.retry_after_s = retry_after
        request.finish_time = time.perf_counter()
        self.health[outcome.value] += 1
        self.health_by_tier[request.tier.value][outcome.value] += 1
        # the TERMINAL event (and the latency histograms it feeds) are
        # emitted HERE and only here — exactly-once by the same
        # construction as the outcome itself (serve/events.py). The
        # enabled gate keeps the O(tokens) gap derivation off the
        # recorder=False path entirely.
        if self.flight.enabled:
            self.flight.emit(self._component, EventType.TERMINAL,
                             request_id=request.request_id,
                             **terminal_fields(request))

    def _tier_policy(self, tier: Tier) -> TierPolicy:
        return self._tier_policies[tier]

    @property
    def brownout_level(self) -> int:
        return self._brownout.level if self._brownout is not None else 0

    def _observe_service(self, t_admit: float):
        """EWMA of SLOT-RESIDENCE time (admit -> finish) for completed
        requests — the unit the queue-delay estimate multiplies. NOT
        submit -> finish: that would fold past queue wait back into the
        estimate and double-count delay under load."""
        served = time.perf_counter() - t_admit
        self._ewma_service_s = served if self._ewma_service_s is None \
            else 0.2 * served + 0.8 * self._ewma_service_s

    def _estimated_queue_delay(self, tier: Optional[Tier] = None) \
            -> Optional[float]:
        """Rough admission-delay estimate for a NEWLY submitted
        request: how many service generations must complete before it
        gets a slot, scaled by the EWMA of observed slot-residence
        times. Zero when the queue fits today's free slots — an idle
        engine must never shed on its own steady-state latency. None
        until a first completion calibrates the EWMA.

        ``tier`` scopes the backlog to the requests that will actually
        be admitted ahead of (or with) that tier — priority admission
        means a queue full of BATCH work does not delay a LATENCY
        arrival, so it must not shed one either. None counts
        everything (the tierless view health_snapshot exports)."""
        if self._ewma_service_s is None:
            return None
        if tier is None:
            ahead = len(self._queue)
        else:
            ahead = sum(1 for q in self._queue
                        if q.tier.order <= tier.order)
        free = self.num_slots - self.active_count
        if ahead < free:
            return 0.0
        waves = (ahead - free) // self.num_slots + 1
        return waves * self._ewma_service_s

    def health_snapshot(self) -> dict:
        """A CONSISTENT, detached copy of the engine's health state.

        ``engine.health`` is a live-mutated dict — a scraper (or the
        fleet router's scheduling read) iterating it while the
        scheduler records terminals can see torn state, and anything
        that stores the reference sees values silently change under
        it. This returns a snapshot taken in one pass — outcome
        counters plus the scheduling signals the router routes on
        (queue depth, free slots, EWMA service time, estimated
        admission delay) — that never mutates after return. All
        ``serve_bench``/``chaos_bench`` reporting and the router's
        least-delay spill read through here, never through the live
        dict."""
        bo = self._brownout
        return {
            "outcomes": dict(self.health),
            "outcomes_by_tier": {t: dict(d) for t, d in
                                 self.health_by_tier.items()},
            "queue_depth": len(self._queue),
            "queue_depth_by_tier": {
                t.value: sum(1 for q in self._queue if q.tier is t)
                for t in Tier},
            "active_slots": self.active_count,
            "free_slots": self.num_slots - self.active_count,
            "num_slots": self.num_slots,
            "ewma_service_s": self._ewma_service_s,
            "estimated_queue_delay_s": self._estimated_queue_delay(),
            # the PRIORITY tiers' delay (LATENCY+STANDARD backlog
            # only): the brownout controller's delay signal — BATCH
            # queue depth must not drive it, or the level-3 clamp
            # would sustain the very signal that raised it (the
            # clamped queue never drains → the estimate never falls
            # → the clamp never lifts; deadlock found end-to-end)
            "estimated_queue_delay_priority_s":
                self._estimated_queue_delay(Tier.STANDARD),
            "free_pages": self._alloc.free_count,
            # KV-pool capacity surface (docs/SERVING.md "Quantized KV
            # cache"): the bytes the cache actually pins — scale
            # metadata included — and the payload dtype, so a capacity
            # dashboard can see the quantized working set. At a fixed
            # HBM budget slots × context ≤ pool bytes, so kv_pool_bytes
            # IS the serving-capacity denominator.
            "kv_dtype": str(self._kpools[0].dtype),
            "kv_quant": self.kv_quant or "off",
            "kv_pool_bytes": int(
                sum(k.nbytes + v.nbytes
                    for k, v in zip(self._kpools, self._vpools)) +
                sum(a.nbytes for a in self._kamax) +
                sum(a.nbytes for a in self._vamax)),
            "kv_quantized_pages": (
                self.num_pages - 1 - self._alloc.free_count
                if self._kv_spec is not None else 0),
            "decode_steps": self.decode_steps,
            "drafted_tokens": self.drafted_tokens,
            "accepted_tokens": self.accepted_tokens,
            "accept_rate": self.accept_rate,
            "prefix_hits": self.prefix_hits,
            "prefix_lookups": self.prefix_lookups,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            # hierarchical cache tiers (docs/SERVING.md "Hierarchical
            # prefix cache"): per-tier resident bytes plus the
            # demotion/promotion/fallback counters — all zeros when
            # tiers are off, so scrapers need no feature probe
            "kv_tier_bytes": (self._tiers.tier_bytes()
                              if self._tiers is not None
                              else {"dram": 0, "disk": 0}),
            "tier_demotions": self.tier_demotions,
            "tier_disk_demotions": (self._tiers.disk_demotions
                                    if self._tiers is not None else 0),
            "tier_promotions": self.tier_promotions,
            "tier_hits": self.tier_hits,
            "tier_hit_tokens": self.tier_hit_tokens,
            "tier_misses": self.tier_misses,
            "tier_crc_fallbacks": self.tier_crc_fallbacks,
            "tier_disk_errors": (self._tiers.disk_errors
                                 if self._tiers is not None else 0),
            "tier_dropped": (self._tiers.dropped
                             if self._tiers is not None else 0),
            # page transport (serve/transport.py): capsule traffic
            # through this engine, plus live in-custody state — pages
            # a detached slot parked here while its transfer is in
            # flight (gauge, normally 0 between router steps)
            "migrated_out_pages": self.migrated_out_pages,
            "migrated_in_pages": self.migrated_in_pages,
            "migrated_out_bytes": self.migrated_out_bytes,
            "migrated_in_bytes": self.migrated_in_bytes,
            "capsule_pages": sum(len(p) for p in
                                 self._capsule_pages.values()),
            "stop_hits": self.stop_hits,
            "constrained_requests": self.constrained_requests,
            "preemptions": self.preemptions,
            "brownout_level": self.brownout_level,
            "brownout_escalations": bo.escalations if bo else 0,
            "brownout_deescalations": bo.deescalations if bo else 0,
            # tier-labeled TTFT/TPOT/queue-delay/e2e histograms,
            # ingested from the SAME event stream as every counter
            # above (serve/events.py) — rendered by serve/metrics.py;
            # None when the recorder is disabled
            "latency_hists": self.flight.hist_snapshot(),
        }

    def prefix_probe(self, prompt_ids) -> int:
        """READ-ONLY cache-affinity query: how many leading tokens of
        ``prompt_ids`` this engine's prefix index has cached right now.
        No refcounts move, no LRU clock ticks, nothing compiles — a
        router may probe every replica per admission for free. 0 when
        the prefix cache is off (an affinity-blind replica)."""
        if self._prefix is None:
            return 0
        return int(self._prefix.probe(prompt_ids))

    def tier_probe(self, prompt_ids) -> int:
        """READ-ONLY twin of ``prefix_probe`` for the cache tiers: how
        many leading tokens the engine could serve counting HBM PLUS
        the pages its lower tiers would re-admit by copy. Side-effect
        free like ``prefix_probe`` (no LRU ticks in any tier) — the
        router's SECOND affinity axis. Equals ``prefix_probe`` when
        tiers are off."""
        if self._prefix is None:
            return 0
        shared, _, cached_len = self._prefix.match(prompt_ids,
                                                   mutate=False)
        if self._tiers is None:
            return int(cached_len)
        n = self._tiers.probe(prompt_ids, len(shared))
        if n == 0:
            return int(cached_len)
        return (len(shared) + n) * self.page_size

    def can_serve(self, total_positions: int) -> bool:
        """Could a request spanning ``total_positions`` (prompt +
        max_new_tokens) EVER be served by this engine? The single
        definition of the servability bound — ``submit``'s fail-fast,
        the fleet router's fleet-wide admission check, and its
        per-replica routing filter all call this, so the bound can
        never drift between the engine and the router."""
        need = -(-total_positions // self.page_size)
        return total_positions <= self.max_len and \
            need <= self.num_pages - 1

    def withdraw(self, request: Request) -> bool:
        """Remove a still-QUEUED request from the admission queue
        without recording a terminal (the caller owns the outcome) —
        the fleet router's starved-attempt give-up. Returns False when
        the request is not in the queue (already admitted or
        terminal). Queued requests hold no pages, so nothing else
        needs releasing. Removal is by IDENTITY: Request's generated
        __eq__ compares ndarray fields, so deque.remove would raise
        mid-scan on a same-shape neighbour instead of finding the
        target."""
        for i, q in enumerate(self._queue):
            if q is request:
                del self._queue[i]
                return True
        return False

    def _shed_one_below(self, tier: Tier) -> bool:
        """Overload drains the LOWEST tier first: shed the most
        recently queued request of the lowest-priority tier strictly
        below ``tier`` (it waited least — FIFO fairness within its
        tier is preserved for the rest). Returns True when a queued
        request was shed to make room."""
        victim = None
        for q in self._queue:
            if q.tier.order <= tier.order:
                continue
            if victim is None or q.tier.order >= victim.tier.order:
                victim = q               # rightmost of the worst tier
        if victim is None:
            return False
        self.withdraw(victim)
        self._record_terminal(
            victim, Outcome.SHED,
            f"displaced from the admission queue by a {tier.value} "
            f"submission under overload")
        return True

    def cancel(self, request: Union[Request, int],
               detail: str = "cancelled by client") -> bool:
        """Client cancellation — a first-class transition from ANY
        live state to the CANCELLED terminal: a QUEUED request leaves
        the queue, a slotted one (prefilling, mid-decode, or
        mid-spec-verify — all host-visible as a live slot between
        steps) is evicted with its pages reclaimed; partial tokens are
        kept either way. Accepts the ``Request`` itself or its
        ``request_id``. Returns False — the refusal the double-finish
        guard implies — when the request is already terminal (or not
        known to this engine): exactly one terminal, ever, even when
        a cancel races a completion."""
        if isinstance(request, Request) and request.outcome is not None:
            return False                     # already terminal: refuse
        for i, q in enumerate(self._queue):
            if q is request or q.request_id == request:
                del self._queue[i]
                self._record_terminal(q, Outcome.CANCELLED, detail)
                return True
        for s in range(self.num_slots):
            slot = self._slots[s]
            if slot is not None and (slot.request is request or
                                     slot.request.request_id == request):
                self._evict(s, Outcome.CANCELLED, detail)
                return True
        return False

    def submit(self, request: Request) -> bool:
        """Admission-queue entry with load shedding. Returns True when
        the request was queued; False when it was refused — already
        terminal with SHED (queue bounds exceeded, ``retry_after_s``
        set) or FAILED_UNSERVABLE (it could NEVER be served: more
        positions than ``max_len`` or more worst-case pages than the
        whole pool — failing fast beats wedging the queue head).

        Tier scoping (serve/slo.py): the request's ``TierPolicy`` may
        supply a default deadline, a per-tier queue depth bound, and a
        per-tier estimated-delay limit (each falling back to the
        engine-global knob). When the GLOBAL queue bound is hit by a
        higher-tier submission, shedding drains the lowest queued tier
        first (``_shed_one_below``) — BATCH absorbs overload before
        STANDARD before LATENCY."""
        request.submit_time = time.perf_counter()
        self.flight.emit(self._component, EventType.SUBMIT,
                         request_id=request.request_id,
                         tier=request.tier.value,
                         queue_depth=len(self._queue))
        pol = self._tier_policy(request.tier)
        if request.deadline_s is None and \
                pol.default_deadline_s is not None:
            request.deadline_s = float(pol.default_deadline_s)
        if request.deadline_s is not None:
            request._deadline_abs = request.submit_time + request.deadline_s
        total = int(request.prompt_ids.size) + request.max_new_tokens
        need = -(-total // self.page_size)
        if not self.can_serve(total):
            self._record_terminal(
                request, Outcome.FAILED_UNSERVABLE,
                f"request needs {total} positions / {need} pages but the "
                f"engine caps at max_len {self.max_len} / "
                f"{self.num_pages - 1} usable pages")
            return False
        if request.sampling is not None:
            # fail-fast like the size bound: a grammar over the wrong
            # vocab (or a bias on a token the model has no logit for)
            # could NEVER be served — it must not wedge the queue head
            err = request.sampling.validate_for(self.model.vocab_size,
                                                request.eos_id)
            if err is not None:
                self._record_terminal(request,
                                      Outcome.FAILED_UNSERVABLE, err)
                return False
        est = self._estimated_queue_delay(request.tier)
        # the newcomer's OWN refusals come first: a request its tier
        # bound or delay limit is about to refuse anyway must not
        # displace an innocent lower-tier victim on the way out
        if pol.max_queue is not None and \
                sum(1 for q in self._queue
                    if q.tier is request.tier) >= pol.max_queue:
            self._record_terminal(
                request, Outcome.SHED,
                f"{request.tier.value} queue at its tier depth limit "
                f"{pol.max_queue}",
                retry_after=est if est else 0.05)
            return False
        delay_limit = pol.max_queue_delay_s \
            if pol.max_queue_delay_s is not None else self.max_queue_delay_s
        if delay_limit is not None and est is not None \
                and est > delay_limit:
            self._record_terminal(
                request, Outcome.SHED,
                f"estimated queue delay {est:.3f}s exceeds "
                f"{delay_limit}s for tier {request.tier.value}",
                retry_after=est)
            return False
        if self.max_queue is not None and \
                len(self._queue) >= self.max_queue and \
                not self._shed_one_below(request.tier):
            self._record_terminal(
                request, Outcome.SHED,
                f"admission queue at depth limit {self.max_queue}",
                retry_after=est if est else 0.05)
            return False
        self._queue.append(request)
        return True

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    @property
    def accept_rate(self) -> float:
        """Fraction of drafted tokens the verify step accepted (0.0
        when the engine never drafted)."""
        return self.accepted_tokens / self.drafted_tokens \
            if self.drafted_tokens else 0.0

    def _finish_token(self, slot_idx: int, token: int,
                      dt: float) -> Optional[Outcome]:
        """Record one generated token; returns the success outcome when
        the request's own stopping condition hit (EOS / max_new_tokens),
        else None."""
        slot = self._slots[slot_idx]
        req = slot.request
        tok = int(token)
        req.token_ids.append(tok)
        req.token_times.append(dt)
        req.token_stamps.append(time.perf_counter())
        self._tok_counts[slot_idx, tok] += 1     # penalty history
        if req.eos_id >= 0 and tok == req.eos_id:
            return Outcome.EOS
        sp = req.sampling
        if sp is not None:
            if sp.grammar is not None:
                nxt = sp.grammar.advance(slot.grammar_state, tok)
                if nxt is not None:
                    slot.grammar_state = nxt
            if sp.stop_sequences:
                slot.stop_tail.append(tok)
                if len(slot.stop_tail) > sp.max_stop_len:
                    del slot.stop_tail[:-sp.max_stop_len]
                hit = match_stop(slot.stop_tail, sp.stop_sequences)
                if hit:
                    # the matched sequence is NOT part of the output:
                    # truncate what this attempt recorded; anything the
                    # match reaches back into an EARLIER attempt's
                    # stream is reported via _stop_trim for the router
                    # to trim off the client (docs/SERVING.md)
                    trim = min(hit, len(req.token_ids))
                    if trim:
                        del req.token_ids[-trim:]
                        del req.token_times[-trim:]
                        del req.token_stamps[-trim:]
                    req._stop_trim = hit - trim
                    self.stop_hits += 1
                    return Outcome.STOP
        if len(req.token_ids) >= req.max_new_tokens:
            return Outcome.MAX_TOKENS
        return None

    def _evict(self, slot_idx: int, outcome: Outcome, detail: str = ""):
        slot = self._slots[slot_idx]
        self._free_slot_state(slot_idx)
        if outcome.ok:
            self._observe_service(slot.t_admit)
        self._record_terminal(slot.request, outcome, detail)

    def _quarantine(self, slot_idx: int, detail: str):
        """Fail a poisoned slot (non-finite logits): evict it — pages
        reclaimed, its output never published — and flush the prefix
        index, since a corrupt SHARED page would otherwise keep
        poisoning every future cache hit (the index cannot tell which
        cached page went bad; dropping retention is cheap and safe —
        live slots keep their own page references)."""
        self._evict(slot_idx, Outcome.FAILED_NONFINITE, detail)
        if self._prefix is not None and len(self._prefix):
            self._prefix.flush(self._alloc)
            self.prefix_flushes += 1
        if self._tiers is not None and len(self._tiers):
            # demoted payloads were captured from the same poisoned
            # cache lineage — quarantine drops them with the index
            self._tiers.flush()

    def _expire_queue(self):
        """Host-side deadline enforcement for QUEUED requests: a
        request whose deadline passes before admission is dropped
        terminally (mid-queue expiry) instead of being admitted into
        work it can no longer use."""
        if not any(r._deadline_abs is not None for r in self._queue):
            return
        now = time.perf_counter()
        keep = deque()
        for req in self._queue:
            if req._deadline_abs is not None and now > req._deadline_abs:
                self._record_terminal(
                    req, Outcome.DEADLINE_EXPIRED,
                    f"deadline ({req.deadline_s}s) passed while queued")
            else:
                keep.append(req)
        self._queue = keep

    def _expire_slots(self):
        """Host-side deadline enforcement for DECODING slots: evict
        (pages reclaimed) any slot past its request deadline or the
        engine's per-slot wall cap — before spending another decode
        step on it. Partial tokens are kept."""
        now = time.perf_counter()
        for s in range(self.num_slots):
            slot = self._slots[s]
            if slot is None:
                continue
            dl = slot.request._deadline_abs
            if dl is not None and now > dl:
                phase = "prefill" if slot.prefilling else "decode"
                self._evict(s, Outcome.DEADLINE_EXPIRED,
                            f"deadline ({slot.request.deadline_s}s) "
                            f"passed mid-{phase}")
                continue
            if self.max_slot_wall_s is not None and \
                    now - slot.t_admit > self.max_slot_wall_s:
                self._evict(s, Outcome.DEADLINE_EXPIRED,
                            f"per-slot wall cap {self.max_slot_wall_s}s "
                            f"exceeded")

    def _attempt_ids(self, req: Request) -> np.ndarray:
        """The sequence a (re)admission actually prefills: the
        original prompt plus every token already emitted — the
        resume-from-suffix replay (PR 7's router pattern, here used by
        slot preemption). Fresh requests return the prompt itself."""
        if not req.token_ids:
            return req.prompt_ids
        return np.concatenate([req.prompt_ids,
                               np.asarray(req.token_ids, np.int32)])

    def _queue_head(self, clamped_ok: bool = True) -> Optional[Request]:
        """The queue's PRIORITY head: the earliest-submitted request of
        the highest-priority tier present (FIFO within a tier —
        deque order is submit order). ``clamped_ok=False`` skips tiers
        the brownout controller has clamped (level 3: BATCH admissions
        held at zero — they stay queued, they do not block others)."""
        best = None
        for q in self._queue:
            if not clamped_ok and self.brownout_level >= 3 and \
                    q.tier is Tier.BATCH:
                continue
            if best is None or q.tier.order < best.tier.order:
                best = q
        return best

    def _preempt_candidate(self, tier: Tier) -> Optional[int]:
        """The slot a ``tier`` admission may reclaim: a live slot of a
        PREEMPTIBLE, strictly lower-priority tier — the lowest tier
        first, the fewest emitted tokens within it (cheapest replay),
        smallest index as the deterministic tie-break. None when
        ``tier`` cannot preempt or no victim qualifies."""
        if not self._tier_policy(tier).can_preempt:
            return None
        best, best_key = None, None
        for s, slot in enumerate(self._slots):
            if slot is None:
                continue
            vt = slot.request.tier
            if vt.order <= tier.order or \
                    not self._tier_policy(vt).preemptible:
                continue
            key = (-vt.order, len(slot.request.token_ids), s)
            if best_key is None or key < best_key:
                best, best_key = s, key
        return best

    def _free_slot_state(self, slot_idx: int):
        """Release a slot's pages and scrub its device-facing arrays —
        shared by eviction (terminal) and preemption (re-queue)."""
        slot = self._slots[slot_idx]
        self._alloc.free(slot.refs)          # refcounted: shared pages
        self._scrub_slot_arrays(slot_idx)

    def _scrub_slot_arrays(self, slot_idx: int):
        """Scrub a slot's device-facing arrays WITHOUT touching its
        page references — the shared tail of ``_free_slot_state``
        (pages freed) and ``detach_slot`` (pages move to in-capsule
        custody instead)."""
        self._page_table[slot_idx, :] = NULL_PAGE  # survive via sharers
        self._lengths[slot_idx] = 0
        self._temps[slot_idx] = 0.0
        self._slot_keys[slot_idx] = 0
        # sampling-menu state back to exact-identity neutrals
        self._top_k[slot_idx] = 0
        self._top_p[slot_idx] = 1.0
        self._rep_pen[slot_idx] = 1.0
        self._pres_pen[slot_idx] = 0.0
        self._logit_bias[slot_idx, :] = 0.0
        self._tok_counts[slot_idx, :] = 0
        self._slots[slot_idx] = None

    def _preempt(self, slot_idx: int, detail: str = ""):
        """Reclaim a slot for a higher-tier admission: pages released,
        partial tokens KEPT, and — within ``max_preemptions`` — the
        request re-queued through normal admission (original
        ``submit_time`` / ``_deadline_abs`` untouched: deadlines stay
        anchored to the original admission). The resume replays
        prompt + emitted as the next attempt's prompt under the SAME
        pinned sampling key, so the continuation is bit-identical to
        an unpreempted run. Past the budget the request terminates
        PREEMPTED — bounded, retryable, hinted."""
        slot = self._slots[slot_idx]
        req = slot.request
        if self.preempt_handoff is not None and not slot.prefilling:
            # fleet-aware preemption: offer the victim to a sibling
            # FIRST — a successful handoff MOVES the slot's pages
            # (zero redone prefill, no queue bounce); the fallback
            # below keeps the engine-internal requeue semantics when
            # nobody can take it. The handoff may also end with the
            # router re-queueing the request itself (replay fallback
            # after a failed transfer) — the slot is gone from this
            # engine either way, so the re-check guards the eviction,
            # not the return value alone.
            try:
                handed = bool(self.preempt_handoff(req.request_id))
            except Exception:
                handed = False
            if handed or self._slots[slot_idx] is not slot:
                self.preemptions += 1
                self.flight.emit(self._component, EventType.PREEMPT,
                                 request_id=req.request_id,
                                 tier=req.tier.value, slot=slot_idx,
                                 preemptions=req.preemptions,
                                 handoff=True, detail=detail)
                return
        req.preemptions += 1
        self.preemptions += 1
        self._free_slot_state(slot_idx)
        self.flight.emit(self._component, EventType.PREEMPT,
                         request_id=req.request_id,
                         tier=req.tier.value, slot=slot_idx,
                         preemptions=req.preemptions, detail=detail)
        if req.preemptions > self.max_preemptions:
            self._record_terminal(
                req, Outcome.PREEMPTED,
                f"preempted {req.preemptions} times "
                f"(max_preemptions={self.max_preemptions}): {detail}")
        else:
            self.flight.emit(self._component, EventType.REQUEUE,
                             request_id=req.request_id,
                             cause="preemption",
                             preemptions=req.preemptions)
            self._queue.append(req)

    def _admit(self):
        """Priority admission into free slots, gated on worst-case
        pages: the highest-priority queued request first (FIFO within
        a tier), with slot PREEMPTION — when no slot (or not enough
        pages) is free for a tier that ``can_preempt``, a preemptible
        lower-tier slot is reclaimed (``_preempt``: partial tokens
        kept, bounded re-queue). The blocked priority head blocks the
        tiers at and below it (no priority inversion: BATCH never
        slips past a page-starved LATENCY head).

        With the prefix cache on, admission first matches the attempt
        prompt's longest cached page-aligned prefix: matched full
        pages are mapped copy-on-write (incref'd, read-only), the
        boundary partial page is copied, and only the remaining suffix
        pays prefill compute — a preempted request's resume typically
        re-lands on its own published prompt pages. Pages held only by
        the index count as reclaimable budget — they are evicted (LRU)
        when the free list alone cannot cover a request."""
        while self._queue:
            req = self._queue_head(clamped_ok=False)
            if req is None:
                return
            slot_idx = next((i for i in range(self.num_slots)
                             if self._slots[i] is None), None)
            if slot_idx is None:
                slot_idx = self._preempt_candidate(req.tier)
                if slot_idx is None:
                    return
                self._preempt(slot_idx,
                              f"slot reclaimed for a {req.tier.value} "
                              f"admission")
            if not self._try_admit(slot_idx, req):
                return

    def _try_admit(self, slot_idx: int, req: Request) -> bool:
        """Admit ``req`` into the free ``slot_idx`` if its worst-case
        pages fit (preempting lower-tier slots for pages when the
        request's tier may); returns False — request left queued,
        nothing pinned — when the pool cannot cover it yet."""
        ids = self._attempt_ids(req)
        t0 = int(ids.size)
        # submit() fail-fasts requests that can never fit, so here
        # ``need`` is always <= the usable pool (resume attempts span
        # the same total positions: prompt + max_new_tokens)
        total = t0 + (req.max_new_tokens - len(req.token_ids))
        need = -(-total // self.page_size)
        prompt_pages = -(-t0 // self.page_size)

        shared: List[int] = []
        partial = None
        cached_len = 0
        if self._prefix is not None:
            self.prefix_lookups += 1
            shared, partial, cached_len = self._prefix.match(ids)
            # pin matches NOW so reclaim below can't free them
            for p in shared:
                self._alloc.incref(p)
            if partial is not None:
                self._alloc.incref(partial[0])

        tier_chain = []
        if self._tiers is not None:
            # continue the radix walk through the lower tiers from the
            # page where HBM stopped. A chain supersedes a boundary
            # partial hit: the tiers hold the FULL page the partial is
            # a prefix of, and promotion is cheaper than COW + suffix
            # recompute of the same tokens.
            tier_chain = self._tiers.match_chain(ids, len(shared))
            if tier_chain:
                if partial is not None:
                    self._alloc.decref(partial[0])
                    partial = None
                    cached_len = len(shared) * self.page_size
                # pin the chain: THIS admission's reclaim demotes pages
                # into the same store and must not spill or drop what
                # it is about to promote
                self._tiers.pin(tier_chain)

        def _budget():
            n_new = need - len(shared)   # pages the free list owes
            avail = self._alloc.free_count - self._lazy_debt
            recl = self._prefix.reclaimable(self._alloc) \
                if self._prefix is not None else 0
            return n_new, avail, recl

        n_new, avail, recl = _budget()
        if avail + recl < n_new:
            # not enough pages even reclaiming cache retention: a tier
            # that can preempt reclaims lower-tier slots' pages — but
            # only when the OPTIMISTIC bound (every preemptible
            # victim's refs freed in full) can actually cover the
            # deficit. Bouncing every BATCH slot (each bounce burning
            # its preemption budget and redoing its prefill) only to
            # fail the admission anyway would be pure loss.
            victim_pages = sum(
                len(s.refs) for s in self._slots
                if s is not None
                and s.request.tier.order > req.tier.order
                and self._tier_policy(s.request.tier).preemptible)
            if self._tier_policy(req.tier).can_preempt and \
                    avail + recl + victim_pages >= n_new:
                while avail + recl < n_new:
                    victim = self._preempt_candidate(req.tier)
                    if victim is None:
                        break
                    self._preempt(victim, f"pages reclaimed for a "
                                          f"{req.tier.value} admission")
                    n_new, avail, recl = _budget()
        if avail + recl < n_new:
            # no cache budget yet — unpin and wait for evictions
            for p in shared:
                self._alloc.decref(p)
            if partial is not None:
                self._alloc.decref(partial[0])
            if tier_chain:
                self._tiers.unpin(tier_chain)
            return False
        if avail < n_new:
            self.prefix_reclaimed_pages += \
                self._reclaim_prefix(n_new - avail)
        if cached_len:
            self.prefix_hits += 1
            self.prefix_hit_tokens += cached_len

        self.withdraw(req)
        priv = [self._alloc.alloc()
                for _ in range(prompt_pages - len(shared))]
        self._reset_page_amax(priv)          # fresh pages, fresh scales
        row = np.zeros((self.max_pages,), np.int32)
        row[:len(shared)] = shared
        row[len(shared):prompt_pages] = priv

        promoted = 0
        if tier_chain:
            # re-admit the chain BY COPY into the freshly allocated
            # pages: host-side data movement through the one jitted
            # promotion program — never a prefill recompute. A failed
            # integrity check truncates the chain there and falls back
            # to recomputing the rest, loudly.
            for key, ent in tier_chain:
                dst = int(priv[promoted])
                src_tier = ent.tier
                payload = self._tiers.load(key, ent)
                if payload is None:
                    self.tier_crc_fallbacks += 1
                    self.flight.emit(
                        self._component, EventType.CACHE_TIER_MISS,
                        request_id=req.request_id, reason="integrity",
                        tier=src_tier, depth=ent.depth)
                    break
                self._promote_page(*payload, dst)
                self._tiers.remove(key, ent)
                promoted += 1
                self.tier_promotions += 1
                self.flight.emit(
                    self._component, EventType.CACHE_PROMOTE,
                    request_id=req.request_id, tier=src_tier,
                    depth=ent.depth, page=dst)
            self._tiers.unpin(tier_chain)
            if promoted:
                cached_len = (len(shared) + promoted) * self.page_size
                self.tier_hits += 1
                self.tier_hit_tokens += promoted * self.page_size
                # promoted pages are published back into the HBM index
                # IMMEDIATELY (refcount slot + index, exactly as if
                # never evicted) so sibling requests share them without
                # waiting for this slot's prefill to finish
                self._prefix.insert(ids[:cached_len], row, self._alloc)
        elif self._tiers is not None \
                and (t0 - 1) // self.page_size > len(shared):
            # tiers consulted, nothing usable, and at least one full
            # page of this prompt was demotable — a true tier miss
            self.tier_misses += 1
            self.flight.emit(self._component, EventType.CACHE_TIER_MISS,
                             request_id=req.request_id, reason="absent")
        # per-request RNG key: pinned by Request.seed (reproducible
        # across engines/occupancy), engine-split otherwise — and
        # REMEMBERED on the request, so a preemption resume keeps the
        # same stream and the continuation stays bit-identical
        if req.seed is not None:
            # mxlint: allow-host-sync(once per request at admission, not per decode step)
            skey = np.asarray(jax.random.PRNGKey(int(req.seed)),
                              np.uint32)
        elif req._assigned_key is not None:
            skey = req._assigned_key
        else:
            # mxlint: allow-host-sync(once per request at admission, not per decode step)
            skey = np.asarray(self._next_key(), np.uint32)
            req._assigned_key = skey
        slot = _Slot(req, reserved_pages=need,
                     refs=list(shared) + priv, row=row, t0=t0,
                     attempt_ids=ids, prefill_pos=cached_len,
                     t_admit=time.perf_counter(), key=skey)
        self._slots[slot_idx] = slot
        self._slot_keys[slot_idx] = skey
        # decode-invisible until prefill completes: the decode step
        # must neither attend a half-built prompt nor scatter its
        # (dead-slot) write into a mapped — possibly SHARED — page
        self._page_table[slot_idx, :] = NULL_PAGE
        self._lengths[slot_idx] = 0
        self._temps[slot_idx] = 0.0
        self._restore_stream_state(slot_idx, slot)
        if partial is not None:
            # COW: the boundary page becomes a private copy; drop
            # the temporary pin on the cached source
            self._copy_page(partial[0], int(row[len(shared)]))
            self._alloc.decref(partial[0])
        self.flight.emit(
            self._component, EventType.ADMIT,
            request_id=req.request_id, tier=req.tier.value,
            slot=slot_idx, t0=t0, cached_len=cached_len,
            queue_delay_s=(slot.t_admit - req.submit_time
                           if req.submit_time is not None else None))

        if self.chunk_pages is None:
            # monolithic mode: prefill to completion inside _admit.
            # A cache hit still runs the (chunk-program) suffix path
            # — the dense program cannot start mid-prompt.
            if cached_len == 0:
                self._dense_prefill(slot_idx)
            else:
                while (self._slots[slot_idx] is slot and
                       slot.prefilling):
                    self._run_chunk(slot_idx)
        # chunked mode: the slot prefills across subsequent step()
        # calls under the token budget
        return True

    def _restore_stream_state(self, slot_idx: int, slot: "_Slot"):
        """Re-derive a slot's resumable-as-data stream state from its
        attempt ids — sampling-menu slot state (serve/sampling.py):
        knob vectors, bias row, and the token-count table (full attempt
        history — prompt + carried tokens) the penalties read. Grammar
        state and the stop-sequence window are re-derived from the
        GENERATED part only (``prompt_len`` marks the resume split), so
        a preemption/failover resume — and a migration install, which
        goes through exactly this path on the destination — samples as
        the unbroken run would: bit-identical continuations under every
        knob (tests/test_sampling.py, tests/test_transport.py)."""
        req = slot.request
        ids = slot.attempt_ids
        self._tok_counts[slot_idx] = np.bincount(
            ids, minlength=self._vocab)[:self._vocab]
        sp = req.sampling
        slot.menu_active = sp is not None and not sp.logits_neutral
        if sp is not None:
            self._top_k[slot_idx] = sp.top_k
            self._top_p[slot_idx] = sp.top_p
            self._rep_pen[slot_idx] = sp.repetition_penalty
            self._pres_pen[slot_idx] = sp.presence_penalty
            if sp.logit_bias:
                for t, b in sp.logit_bias.items():
                    self._logit_bias[slot_idx, t] = b
            base = req.prompt_len if req.prompt_len is not None \
                else int(req.prompt_ids.size)
            gen = [int(t) for t in ids[base:]]
            if sp.grammar is not None:
                self.constrained_requests += 1
                st = sp.grammar.start()
                for t in gen:
                    nxt = sp.grammar.advance(st, t)
                    if nxt is None:
                        break        # off-grammar history: hold state
                    st = nxt
                slot.grammar_state = st
            if sp.stop_sequences and sp.max_stop_len > 1:
                slot.stop_tail = gen[-(sp.max_stop_len - 1):]

    # ------------------------------------------------------------- #
    # page-transport hooks (serve/transport.py owns the capsule)
    # ------------------------------------------------------------- #

    def kv_wire_sig(self) -> tuple:
        """The pool layout a page payload is only meaningful under:
        quant mode, page size, layer count, per-page shape, and code
        dtype. A capsule captured under one signature must never be
        installed under another — the transport refuses the transfer
        and the replay fallback recomputes instead."""
        return (self.kv_quant or "off", self.page_size,
                len(self._kpools), tuple(self._kpools[0].shape[1:]),
                str(self._kpools[0].dtype))

    def decode_ready(self, request_id: int) -> bool:
        """True when ``request_id`` holds a slot past prefill — the
        only state a slot is page-capturable from (a prefilling slot's
        pages are half-built; migrating it is a replay, not a
        transfer). The router's role-split streaming poll."""
        for slot in self._slots:
            if slot is not None and \
                    slot.request.request_id == request_id:
                return not slot.prefilling
        return False

    def capture_slot(self, request_id: int) -> Optional[dict]:
        """READ-ONLY capture probe for the page transport: the decode-
        ready slot's populated page row (positions ``[0, n_pos)`` —
        the one position beyond it is recomputed on the destination,
        its logits must seed the next sample there), its pinned RNG
        key, and the attempt request. Nothing moves: refcounts, the
        slot, and the pools are untouched, so an aborted capture
        (source death mid-transfer) leaves the slot exactly as it was.
        None when the request holds no slot here or is still
        prefilling."""
        for i, slot in enumerate(self._slots):
            if slot is not None and \
                    slot.request.request_id == request_id:
                if slot.prefilling:
                    return None
                n_pos = int(self._lengths[i])
                if n_pos <= 0:
                    return None
                n_pages = -(-n_pos // self.page_size)
                return {
                    "request": slot.request,
                    "key": np.array(slot.key, np.uint32),
                    "pages": [int(p) for p in
                              self._page_table[i, :n_pages]],
                    "n_pos": n_pos,
                }
        return None

    def detach_slot(self, request_id: int) -> Optional[Request]:
        """Move a captured slot's page references into in-capsule
        custody (``_capsule_pages``) and release the slot — WITHOUT a
        terminal (the transport owns the outcome: install on the
        destination, or the replay fallback). The pages stay
        refcounted by the custody entry, so ``audit_pages`` balances
        at every step of an in-flight transfer; ``release_capsule``
        returns them to the pool once the transfer lands or falls
        back. Returns the detached attempt request, or None when the
        request holds no decode-ready slot here."""
        for i, slot in enumerate(self._slots):
            if slot is not None and \
                    slot.request.request_id == request_id:
                if slot.prefilling:
                    return None
                self._capsule_pages[int(request_id)] = list(slot.refs)
                self._scrub_slot_arrays(i)
                return slot.request
        return None

    def release_capsule(self, request_id: int) -> int:
        """Drop an in-flight capsule's page custody — the source-side
        end of every transfer, success or fallback. Returns the number
        of page references released."""
        pages = self._capsule_pages.pop(int(request_id), None)
        if pages is None:
            return 0
        self._alloc.free(pages)
        return len(pages)

    def install_slot(self, request: Request, payloads, n_pos: int,
                     key, wire_bytes: int = 0, page_hook=None,
                     abort=None) -> bool:
        """Install a transported slot: allocate private pages, write
        every capsule payload through the ONE jitted promotion program
        (the tier re-admission program — nothing new compiles), pin
        the capsule's RNG key, and re-derive the stream state exactly
        as a preemption resume would. The slot resumes with
        ``prefill_pos = n_pos``: the only recomputed position is the
        boundary token the wire cannot carry (its logits seed the next
        sample), so redone prefill is zero.

        Refuses — False, engine untouched — when no slot or not
        enough pages are free, the request is already terminal, or the
        capsule does not line up with the resume attempt
        (``n_pos != len(attempt) - 1``). A mid-install abort (chaos:
        destination death) frees the allocated pages and refuses —
        ``audit_pages`` stays clean on the destination too."""
        if request.outcome is not None:
            return False
        slot_idx = next((i for i in range(self.num_slots)
                         if self._slots[i] is None), None)
        if slot_idx is None:
            return False
        ids = self._attempt_ids(request)
        t0 = int(ids.size)
        if n_pos != t0 - 1 or n_pos <= 0:
            return False                 # capsule/attempt mismatch
        n_install = -(-n_pos // self.page_size)
        if n_install != len(payloads):
            return False
        total = t0 + (request.max_new_tokens - len(request.token_ids))
        need = -(-total // self.page_size)
        prompt_pages = -(-t0 // self.page_size)
        avail = self._alloc.free_count - self._lazy_debt
        recl = self._prefix.reclaimable(self._alloc) \
            if self._prefix is not None else 0
        if avail + recl < need:
            return False
        if avail < prompt_pages:
            self.prefix_reclaimed_pages += \
                self._reclaim_prefix(prompt_pages - avail)
        priv = [self._alloc.alloc() for _ in range(prompt_pages)]
        self._reset_page_amax(priv)      # fresh pages, fresh scales
        aborted = False
        for j, payload in enumerate(payloads):
            if page_hook is not None:
                page_hook(j, len(payloads))
            if abort is not None and abort():
                aborted = True
                break
            self._promote_page(*payload, int(priv[j]))
        if aborted:
            # pages are identity-free: a half-written payload needs no
            # scrub, only its references back on the free list
            self._alloc.free(priv)
            return False
        row = np.zeros((self.max_pages,), np.int32)
        row[:prompt_pages] = priv
        skey = np.asarray(key, np.uint32)
        # the capsule's pinned key IS the live stream's key: remember
        # it on the request so a later preemption resume on THIS
        # replica keeps the same stream (the cross-replica seed gap —
        # an engine-drawn key must travel, never be re-drawn)
        request._assigned_key = skey
        if request.submit_time is None:
            request.submit_time = time.perf_counter()
        if request._deadline_abs is None and \
                request.deadline_s is not None:
            request._deadline_abs = \
                request.submit_time + request.deadline_s
        slot = _Slot(request, reserved_pages=need, refs=priv, row=row,
                     t0=t0, attempt_ids=ids, prefill_pos=n_pos,
                     t_admit=time.perf_counter(), key=skey)
        self._slots[slot_idx] = slot
        self._slot_keys[slot_idx] = skey
        # decode-invisible until the boundary token lands — exactly
        # the cache-hit suffix admission contract
        self._page_table[slot_idx, :] = NULL_PAGE
        self._lengths[slot_idx] = 0
        self._temps[slot_idx] = 0.0
        self._restore_stream_state(slot_idx, slot)
        self.migrated_in_pages += len(payloads)
        self.migrated_in_bytes += int(wire_bytes)
        self.flight.emit(
            self._component, EventType.ADMIT,
            request_id=request.request_id, tier=request.tier.value,
            slot=slot_idx, t0=t0, cached_len=n_pos, migrated=True,
            queue_delay_s=None)
        # recompute ONLY the boundary position, through the same chunk
        # program family a cache-hit suffix uses (bucket 1 — already
        # compiled on any engine that admitted a cache hit)
        while self._slots[slot_idx] is slot and slot.prefilling:
            self._run_chunk(slot_idx)
        return True

    def _slot_sampling_args(self, slot_idx: int) -> tuple:
        """The per-request sampling-row operands a prefill/chunk
        program takes: knob scalars, the count/bias rows, and the
        grammar mask for the FIRST generated token — all traced data
        (same bucket, same compile; trace counts asserted). A slot
        with neutral (or no) params reuses one cached device-resident
        row set — a long chunked prompt re-ships zero sampling bytes
        per chunk."""
        slot = self._slots[slot_idx]
        req = slot.request
        sp = req.sampling
        if not slot.menu_active:
            ops = self._neutral_ops.get("row")
            if ops is None:
                V = self._vocab
                ops = (jnp.int32(0), jnp.float32(1.0),
                       jnp.float32(1.0), jnp.float32(0.0),
                       jnp.zeros((V,), jnp.int32),
                       jnp.zeros((V,), jnp.float32),
                       jnp.ones((V,), bool))
                self._neutral_ops["row"] = ops
            return ops
        if sp is not None and sp.grammar is not None:
            mask = grammar_mask(sp.grammar, slot.grammar_state,
                                req.eos_id)
        else:
            mask = np.ones((self._vocab,), bool)
        return (np.int32(self._top_k[slot_idx]),
                np.float32(self._top_p[slot_idx]),
                np.float32(self._rep_pen[slot_idx]),
                np.float32(self._pres_pen[slot_idx]),
                self._tok_counts[slot_idx].copy(),
                self._logit_bias[slot_idx].copy(), mask)

    def _dense_prefill(self, slot_idx: int):
        """The PR 2 monolithic prompt program (one pow2-page bucket)."""
        slot = self._slots[slot_idx]
        req = slot.request
        t_start = time.perf_counter()
        t0 = slot.t0
        prompt_pages = -(-t0 // self.page_size)
        bucket = min(_next_pow2(prompt_pages), self.max_pages)
        Tpad = bucket * self.page_size
        ids = np.zeros((1, Tpad), np.int32)
        ids[0, :t0] = slot.attempt_ids
        pages_arr = np.zeros((bucket,), np.int32)
        pages_arr[:prompt_pages] = slot.row[:prompt_pages]
        fn = self._prefill_jits.get(bucket)
        if fn is None:
            fn = jax.jit(self._prefill_fn, donate_argnums=(1, 2))
            self._prefill_jits[bucket] = fn
        self._kpools, self._vpools, ka, va, tok = fn(
            self._param_vals, self._kpools, self._vpools, self._kamax,
            self._vamax, ids, np.int32(t0), pages_arr,
            np.float32(req.temperature), slot.key,
            *self._slot_sampling_args(slot_idx))
        self._pull_amax(ka, va)
        slot.prefill_pos = t0
        # mxlint: allow-host-sync(prefill-boundary readback, once per prompt: the sampled first token must reach token_ids)
        tok = int(np.asarray(tok))
        self.flight.emit(self._component, EventType.PREFILL_CHUNK,
                         request_id=req.request_id, ts=t_start,
                         slot=slot_idx, start=0, n=t0,
                         dur_s=time.perf_counter() - t_start)
        if tok < 0:                          # sign-encoded guard flag
            self._quarantine(slot_idx, "non-finite logits in prefill")
            return
        self._finish_prefill(slot_idx, tok)

    def _run_chunk(self, slot_idx: int) -> int:
        """Process ONE prefill chunk for a prefilling slot; returns the
        number of real prompt tokens processed. The chunk size is
        ``chunk_pages * page_size`` (the tail, and the monolithic-mode
        cache-hit suffix, bucket to the same pow2-page family)."""
        slot = self._slots[slot_idx]
        req = slot.request
        t_start = time.perf_counter()
        start = slot.prefill_pos
        remaining = slot.t0 - start
        if self.chunk_pages is not None:
            n = min(remaining, self.chunk_pages * self.page_size)
        else:
            n = remaining
        bucket = min(_next_pow2(-(-n // self.page_size)), self.max_pages)
        Cpad = bucket * self.page_size
        ids = np.zeros((1, Cpad), np.int32)
        ids[0, :n] = slot.attempt_ids[start:start + n]
        fn = self._chunk_jits.get(bucket)
        if fn is None:
            fn = jax.jit(self._chunk_prefill_fn, donate_argnums=(1, 2))
            self._chunk_jits[bucket] = fn
        self._kpools, self._vpools, ka, va, tok = fn(
            self._param_vals, self._kpools, self._vpools, self._kamax,
            self._vamax, ids, np.int32(start), np.int32(n),
            slot.row.copy(), np.float32(req.temperature), slot.key,
            *self._slot_sampling_args(slot_idx))
        self._pull_amax(ka, va)
        slot.prefill_pos = start + n
        # mxlint: allow-host-sync(chunk-boundary readback, once per chunk: the guard flag and tail token gate the next chunk)
        tok = int(np.asarray(tok))
        self.flight.emit(self._component, EventType.PREFILL_CHUNK,
                         request_id=req.request_id, ts=t_start,
                         slot=slot_idx, start=start, n=n,
                         dur_s=time.perf_counter() - t_start)
        if tok < 0:                          # sign-encoded guard flag
            # poisoned mid-prompt: fail NOW — later chunks would only
            # propagate the contamination (and the prompt's pages must
            # never reach the prefix index)
            self._quarantine(slot_idx, "non-finite logits in prefill "
                                       f"chunk at {start}")
            return n
        if not slot.prefilling:
            self._finish_prefill(slot_idx, tok)
        return n

    def _finish_prefill(self, slot_idx: int, tok: int):
        """Prompt fully populated: make the slot decode-visible, publish
        its full prompt pages into the prefix index, and record the
        first generated token."""
        slot = self._slots[slot_idx]
        self._page_table[slot_idx, :] = slot.row
        self._lengths[slot_idx] = slot.t0
        self._temps[slot_idx] = slot.request.temperature
        if self._prefix is not None:
            self._prefix.insert(slot.attempt_ids, slot.row,
                                self._alloc)
        done = self._finish_token(slot_idx, tok,
                                  time.perf_counter() - slot.t_admit)
        if done is not None:
            self._evict(slot_idx, done)

    def _advance_prefill(self) -> int:
        """Chunked-prefill scheduler: round-robin one chunk at a time
        over prefilling slots, never exceeding ``token_budget`` real
        prompt tokens per engine step (brownout level 2+ clamps the
        budget to ONE chunk — same bucket shapes, so nothing
        retraces). Returns tokens processed."""
        budget = self.token_budget
        if self.brownout_level >= 2 and self.chunk_pages is not None:
            budget = min(budget, self.chunk_pages * self.page_size)
        spent = 0
        progressed = True
        while budget > 0 and progressed:
            progressed = False
            pf = [s for s in range(self.num_slots)
                  if self._slots[s] is not None
                  and self._slots[s].prefilling]
            if not pf:
                break
            for k in range(len(pf)):
                s = pf[(self._prefill_rr + k) % len(pf)]
                slot = self._slots[s]
                if slot is None or not slot.prefilling:
                    continue
                nxt = min(slot.t0 - slot.prefill_pos,
                          self.chunk_pages * self.page_size)
                if nxt > budget:
                    continue
                n = self._run_chunk(s)
                budget -= n
                spent += n
                progressed = True
            self._prefill_rr += 1
        self.max_step_prefill_tokens = max(self.max_step_prefill_tokens,
                                           spent)
        return spent

    def _propose_drafts(self) -> dict:
        """Host-side drafting (pure data): up to ``spec_k`` candidate
        tokens per decode-ready slot from its OWN prompt + emitted
        history, capped at ``max_new_tokens - emitted - 1`` so the
        accepted output can never exceed the request's token budget —
        which also keeps every write of the draft window inside the
        admission-time worst-case page reservation. Out-of-vocab
        proposals from a custom ``draft_fn`` are truncated at the first
        invalid token rather than fed to the embedding.

        Adaptive gating: a slot whose last ``spec_patience`` draft
        windows were ALL fully rejected is skipped (its drafts are
        hopeless — randomish text the n-gram drafter cannot predict),
        probing again on every ``spec_probe_every``-th engine decode
        step. All gated slots share the probe clock, so a probe costs
        ONE wide step. Returns ``(drafts, gated)`` where ``gated``
        records whether gating suppressed at least one slot — when it
        suppressed them ALL, step() runs the W=1 program and the
        zero-agreement workload pays the plain decode price."""
        drafts: dict = {}
        gated = False
        if self.spec_k == 0 or self.brownout_level >= 1:
            # brownout level 1+ disables speculation: the engine
            # narrow-steps (W=1 — already compiled) until pressure
            # clears, trading peak tokens/s for per-step latency
            return drafts, gated
        vocab = self.model.vocab_size
        probe = self.spec_patience == 0 or \
            self.decode_steps % self.spec_probe_every == 0
        for s in range(self.num_slots):
            slot = self._slots[s]
            if slot is None or slot.prefilling:
                continue
            req = slot.request
            kmax = min(self.spec_k,
                       req.max_new_tokens - len(req.token_ids) - 1)
            if kmax <= 0:
                continue
            if not probe and slot.spec_streak >= self.spec_patience > 0:
                gated = True
                continue
            hist = np.concatenate([req.prompt_ids,
                                   np.asarray(req.token_ids, np.int32)])
            d = np.asarray(self._draft_fn(hist, kmax),
                           np.int32).reshape(-1)[:kmax]
            oob = np.nonzero((d < 0) | (d >= vocab))[0]
            if oob.size:
                d = d[:oob[0]]
            sp = req.sampling
            if d.size and sp is not None and sp.grammar is not None:
                # truncate at the first grammar-forbidden draft: a
                # masked token has probability 0 under the constrained
                # target, so verifying it (and everything after it)
                # would be a guaranteed rejection — pure waste
                st = slot.grammar_state
                keep = 0
                for t in d:
                    t = int(t)
                    if not grammar_mask(sp.grammar, st, req.eos_id)[t]:
                        break
                    keep += 1
                    if t == req.eos_id:
                        break            # drafting past EOS is waste
                    nxt = sp.grammar.advance(st, t)
                    if nxt is None:
                        break
                    st = nxt
                d = d[:keep]
            if d.size:
                drafts[s] = d
        return drafts, gated

    def _ensure_tail_pages(self, drafts=None) -> List[int]:
        """Lazily allocate the pages the NEXT write positions need —
        this is where cache memory tracks live tokens. Prefilling slots
        are skipped: they are decode-invisible and their pages are
        already mapped.

        With speculation, a slot drafting d tokens writes positions
        ``[L, L + d]`` this step, so every page covering that WINDOW
        must be mapped up front. The FIRST page (position L) keeps the
        watchdog/stall semantics — without it the slot cannot advance
        at all; failing to map a LATER window page merely TRUNCATES the
        slot's drafts in ``drafts`` (speculation is best-effort: under
        page pressure it degrades to fewer — or zero — drafts, never
        to a stall the non-speculative engine would not have had).

        A slot whose tail page cannot be allocated (pool starved even
        after reclaiming prefix-index retention) is STALLED, not
        crashed: it sits out this decode step (returned here, masked to
        length 0 with a NULL page row so its dead write cannot touch a
        real — possibly shared — page) and the watchdog evicts it
        FAILED_UNSERVABLE after ``watchdog_steps`` of zero progress."""
        drafts = {} if drafts is None else drafts
        ps = self.page_size
        stalled: List[int] = []
        for s in range(self.num_slots):
            slot = self._slots[s]
            if slot is None or slot.prefilling:
                continue
            L = int(self._lengths[s])
            d = drafts.get(s)
            dlen = 0 if d is None else int(d.size)
            first_pi = L // ps
            mapped_through = first_pi - 1
            starved = False
            for pi in range(first_pi, (L + dlen) // ps + 1):
                if self._page_table[s, pi] != NULL_PAGE:
                    mapped_through = pi
                    continue
                if self._alloc.free_count == 0 and \
                        self._prefix is not None:
                    self.prefix_reclaimed_pages += \
                        self._reclaim_prefix(1)
                if self._alloc.free_count == 0:
                    if pi == first_pi:
                        slot.stall_count += 1
                        if slot.stall_count > self.watchdog_steps:
                            self._evict(
                                s, Outcome.FAILED_UNSERVABLE,
                                f"watchdog: tail page starved for "
                                f"{slot.stall_count} steps")
                        else:
                            stalled.append(s)
                        starved = True
                    break
                page = self._alloc.alloc()
                self._reset_page_amax((page,))   # fresh page, fresh scale
                self._page_table[s, pi] = page
                slot.row[pi] = page
                slot.refs.append(page)
                mapped_through = pi
            if starved:
                drafts.pop(s, None)
                continue
            slot.stall_count = 0
            if dlen:                         # clip drafts to the window
                cap = (mapped_through + 1) * ps - 1 - L
                if cap < dlen:
                    if cap <= 0:
                        drafts.pop(s, None)
                    else:
                        drafts[s] = d[:cap]
        return stalled

    def _mask_block(self, drafts: dict, W: int, live) -> np.ndarray:
        """The (S, W, V) vocabulary-mask block this step's decode
        program takes: column j of a grammar-constrained slot is
        masked at the grammar state AFTER consuming its drafts at
        columns <= j (the host walks the known draft chain), so every
        verify column is constrained exactly as the sequential decode
        at that position would be. Grammar-free steps reuse one cached
        all-True block per width — no per-step allocation on the
        unconstrained hot path."""
        gslots = [s for s in live
                  if self._slots[s].request.sampling is not None and
                  self._slots[s].request.sampling.grammar is not None]
        if not gslots:
            # cached all-True block per width (host np: this branch
            # only runs on the menu-ACTIVE path — fully-neutral steps
            # take _neutral_step_ops' device-resident operands and
            # never reach here)
            m = self._mask_true.get(W)
            if m is None:
                m = self._mask_true[W] = np.ones(
                    (self.num_slots, W, self._vocab), bool)
            return m
        m = np.ones((self.num_slots, W, self._vocab), bool)
        for s in gslots:
            slot = self._slots[s]
            sp = slot.request.sampling
            eos = slot.request.eos_id
            st = slot.grammar_state
            m[s, 0] = grammar_mask(sp.grammar, st, eos)
            d = drafts.get(s)
            if d is None:
                continue
            for j, t in enumerate(d):
                t = int(t)
                if t == eos:
                    break                # later columns are dead
                nxt = sp.grammar.advance(st, t)
                if nxt is not None:
                    st = nxt
                if j + 1 < W:
                    m[s, j + 1] = grammar_mask(sp.grammar, st, eos)
        return m

    def _neutral_step_ops(self, W: int) -> tuple:
        """Committed device-resident NEUTRAL sampling operands for a
        step whose live slots all carry neutral (or no) sampling
        params — built once per width and reused, so the menu-free hot
        path ships zero per-step sampling bytes (the operands are
        value-identical to the real tables when every knob is neutral:
        the penalties never read the counts, the bias adds zero, the
        mask allows everything)."""
        ops = self._neutral_ops.get(W)
        if ops is None:
            S, V = self.num_slots, self._vocab
            ops = (jnp.zeros((S,), jnp.int32),        # top_k (off)
                   jnp.ones((S,), jnp.float32),       # top_p
                   jnp.ones((S,), jnp.float32),       # rep_pen
                   jnp.zeros((S,), jnp.float32),      # pres_pen
                   jnp.zeros((S, V), jnp.int32),      # counts (unread)
                   jnp.zeros((S, V), jnp.float32),    # bias
                   jnp.ones((S, W, V), bool))         # mask
            self._neutral_ops[W] = ops
        return ops

    def step(self) -> int:
        """Enforce deadlines, admit, advance chunked prefill under the
        token budget, then run ONE decode/verify step for all
        decode-ready slots: each live slot advances 1..spec_k+1 tokens
        (exactly 1 when speculation is off, found no draft, or every
        draft missed). Returns the number of live slots that advanced."""
        self._expire_queue()
        self._expire_slots()
        if self._brownout is not None:
            # one deterministic evaluation per scheduler step, BEFORE
            # admission so a clamp decision applies to this step's
            # admissions; level effects are pure host policy
            self._brownout.update(self)
        self._admit()
        if self.chunk_pages is not None:
            self._advance_prefill()
        drafts, gated = self._propose_drafts()
        stalled = self._ensure_tail_pages(drafts)
        live = [s for s in range(self.num_slots)
                if self._slots[s] is not None
                and not self._slots[s].prefilling and s not in stalled]
        if not live:
            return 0
        # adaptive width routing: a step where NO slot drafted runs the
        # W=1 program — bitwise the non-speculative decode step — so
        # gated/zero-draft workloads pay no verify width. Either width
        # traces exactly once (shape-keyed jit cache).
        W = self._spec_w if drafts else 1
        if W > 1:
            self.spec_steps += 1
        elif gated:
            self.spec_gated_steps += 1
        tokens = np.zeros((self.num_slots, W), np.int32)
        draft_len = np.zeros((self.num_slots,), np.int32)
        for s in live:
            tokens[s, 0] = self._slots[s].request.token_ids[-1]
            d = drafts.get(s)
            if d is not None and d.size:
                tokens[s, 1:1 + d.size] = d
                draft_len[s] = d.size
        lengths_dev = self._lengths.copy()
        table_dev = self._page_table.copy()
        for s in stalled:                    # decode-invisible this step
            lengths_dev[s] = 0
            table_dev[s, :] = NULL_PAGE
        if any(self._slots[s].menu_active for s in live):
            samp_ops = (self._top_k.copy(), self._top_p.copy(),
                        self._rep_pen.copy(), self._pres_pen.copy(),
                        self._tok_counts.copy(),
                        self._logit_bias.copy(),
                        self._mask_block(drafts, W, live))
        else:
            samp_ops = self._neutral_step_ops(W)
        t_start = time.perf_counter()
        self._kpools, self._vpools, ka, va, emitted, n_emit, lengths = \
            self._decode_step(self._param_vals, self._kpools,
                              self._vpools, self._kamax, self._vamax,
                              tokens, draft_len,
                              table_dev, lengths_dev,
                              self._temps.copy(),
                              self._slot_keys.copy(), *samp_ops)
        self._pull_amax(ka, va)
        # THE designed per-step host sync: the scheduler needs the
        # emitted tokens/acceptance counts to advance slots; everything
        # above this line is enqueued without blocking
        # mxlint: allow-host-sync(THE one designed readback per decode step)
        emitted = np.asarray(emitted)
        # mxlint: allow-host-sync(same readback: device already synced by the emitted pull)
        n_emit = np.asarray(n_emit)
        # mxlint: allow-host-sync(same readback: device already synced by the emitted pull)
        new_lengths = np.asarray(lengths).copy()
        for s in stalled:                    # their true length is kept
            new_lengths[s] = self._lengths[s]
        self._lengths = new_lengths
        dt = time.perf_counter() - t_start
        self.decode_steps += 1
        self.flight.emit(self._component, EventType.DECODE_STEP,
                         ts=t_start, step=self.decode_steps, width=W,
                         live=len(live), dur_s=dt)
        for s in live:
            if emitted[s, 0] < 0:            # sign-encoded guard flag
                # poisoned verify: NOTHING from this step is recorded —
                # accepted drafts included (they were scored by
                # non-finite math)
                self._quarantine(s, "non-finite logits in decode")
                continue
            slot = self._slots[s]
            req = slot.request
            d = int(draft_len[s])
            n = int(n_emit[s])
            if d:
                self.drafted_tokens += d
                req.drafted_tokens += d
                # adaptive gating signal: a fully-rejected window grows
                # the streak; ANY acceptance resets it
                slot.spec_streak = 0 if n > 1 else slot.spec_streak + 1
            per_tok = dt / max(n, 1)
            recorded = 0
            for i in range(n):
                done = self._finish_token(s, int(emitted[s, i]), per_tok)
                recorded += 1
                if done is not None:
                    # EOS inside the accepted window: later accepted
                    # tokens are discarded — sequential decode would
                    # never have generated them
                    self._evict(s, done)
                    break
            if d:
                # count only accepted drafts actually RECORDED —
                # columns [0, n - 1) are drafts, n - 1 the
                # bonus/correction, and an in-window EOS discards the
                # tail, which must not inflate accept_rate
                kept = min(recorded, n - 1)
                self.accepted_tokens += kept
                req.accepted_tokens += kept
        return len(live)

    # ------------------------------------------------------------- #
    # page accounting audit (tests / debugging)
    # ------------------------------------------------------------- #

    def audit_pages(self):
        """Assert the global page invariant: every page 1..P-1 is EITHER
        on the free list (refcount 0) OR live — and a live page's
        refcount equals exactly the number of slot mappings plus index
        entries that hold it. Raises MXNetError on any leak (page
        unreachable but not free) or double grant (page free AND
        referenced, or granted twice). With cache tiers on, the third
        state — demoted — is audited too: a demoted entry is payload
        WITHOUT a page id (structurally disjoint from free and live),
        and the tier store's own byte/shape accounting must balance.
        With page transport in play there is a fourth state — IN
        CAPSULE: a detached slot's pages sit in ``_capsule_pages``
        custody (refcounted here, owned by the in-flight transfer) so
        the invariant is free XOR live XOR demoted XOR in-capsule, and
        a request id must never be both slotted and in custody."""
        for rid in self._capsule_pages:
            for slot in self._slots:
                if slot is not None and \
                        slot.request.request_id == rid:
                    raise MXNetError(
                        f"page audit: request {rid} holds a slot AND "
                        f"an in-flight capsule (double identity)")
        expect = [0] * self.num_pages
        for slot in self._slots:
            if slot is None:
                continue
            for p in slot.refs:
                expect[p] += 1
        if self._prefix is not None:
            for p in self._prefix.held_pages():
                expect[p] += 1
        for p in self._alloc.held:           # chaos-harness page holds
            expect[p] += 1
        for pages in self._capsule_pages.values():   # in-capsule custody
            for p in pages:
                expect[p] += 1
        free = self._alloc._free
        free_set = set(free)
        if len(free_set) != len(free):
            raise MXNetError("page audit: duplicate pages on the free "
                             "list (double grant)")
        if NULL_PAGE in free_set:
            raise MXNetError("page audit: the null page is on the free "
                             "list")
        for p in range(1, self.num_pages):
            rc = self._alloc.refcount(p)
            if rc != expect[p]:
                raise MXNetError(
                    f"page audit: page {p} refcount {rc} != "
                    f"{expect[p]} references held (slots + index)")
            if (p in free_set) == (rc > 0):
                state = "free AND referenced (double grant)" if rc > 0 \
                    else "neither free nor referenced (leak)"
                raise MXNetError(f"page audit: page {p} is {state}")
        if self._tiers is not None:
            # demoted entries hold PAYLOADS, never page ids, so the
            # page-level invariant above cannot see them; the tier
            # store audits its own accounting (free XOR live XOR
            # demoted — "demoted" lives entirely below this line)
            self._tiers.audit()

    # ------------------------------------------------------------- #
    # elastic checkpointing / warm restart (checkpoint/ subsystem)
    # ------------------------------------------------------------- #

    def warm_start(self, params=None, manager=None, step=None) -> None:
        """Swap new model weights into the LIVE engine without
        retracing: weights are traced inputs of the decode/prefill
        programs, so as long as shapes and dtypes match, the compiled
        steps are reused as-is (``decode_trace_count`` stays put —
        asserted in tests/test_serve.py).

        The prefix index is FLUSHED: cached K/V was computed under the
        old weights, and serving it against new weights would silently
        mix models (``prefix_flushes`` counts, asserted in tests).

        ``params``: dict keyed by Parameter name (a training capsule's
        ``param/`` entries also accepted), or pass ``manager`` (+
        optional ``step``) to pull the latest committed training
        capsule straight from a CheckpointManager.
        """
        import jax.numpy as jnp
        if params is None:
            if manager is None:
                raise MXNetError("warm_start needs params or a "
                                 "CheckpointManager")
            params, _meta = manager.restore(step)
        # a training capsule also carries opt/<i>/<j> and rng/key
        # entries — when param/ keys exist, ONLY they are weights;
        # otherwise the dict itself is the name→array mapping
        items = {k[len("param/"):]: v for k, v in params.items()
                 if k.startswith("param/")} or params
        flat = {}
        for name, v in items.items():
            flat[name] = v._data if isinstance(v, NDArray) else np.asarray(v)
        positional = all(n.isdigit() for n in flat)
        for i, p in enumerate(self._eng_params):
            # capsules key params positionally ("param/<i>", construction
            # order); plain dicts may key by Parameter name
            lookup = str(i) if positional else p.name
            if lookup not in flat:
                raise MXNetError(f"warm_start: no value for parameter "
                                 f"{i} ('{p.name}')")
            new = jnp.asarray(flat[lookup])    # one conversion, reused
            cur = p.data()._data
            if new.shape != cur.shape or new.dtype != cur.dtype:
                raise MXNetError(
                    f"warm_start: parameter '{p.name}' is "
                    f"{str(cur.dtype)}{tuple(cur.shape)} but new value "
                    f"is {str(new.dtype)}{tuple(new.shape)}"
                    f" — shape/dtype changes require a new engine")
            p.data()._data = new
        self._param_vals = tuple(p.data()._data
                                 for p in self._eng_params)
        if self._prefix is not None:
            # cached K/V is weight-dependent — a prefix computed under
            # the old weights must never be matched again
            self._prefix.flush(self._alloc)
            self.prefix_flushes += 1
        if self._tiers is not None:
            # same contract one level down: DRAM/disk payloads were
            # captured under the old weights — ALL tiers flush
            self._tiers.flush()
        self.warm_restarts += 1

    def save_checkpoint(self, manager, step=None, block=False) -> int:
        """Snapshot the serving weights into ``manager`` (async) so a
        replacement process can ``warm_start(manager=...)``."""
        tree = {f"param/{i}": p.data()
                for i, p in enumerate(self._eng_params)}
        meta = {"kind": "serve",
                "param_names": [p.name for p in self._eng_params],
                "step": int(step if step is not None
                            else self.decode_steps)}
        manager.save(int(meta["step"]), tree, meta=meta, block=block)
        return int(meta["step"])

    def install_preemption(self, manager, exit_after=True):
        """SIGTERM → drain in-flight snapshot + final sync weight save
        (the serving tier's preemption contract)."""

        def _state():
            tree = {f"param/{i}": p.data()
                    for i, p in enumerate(self._eng_params)}
            return self.decode_steps, tree, {"kind": "serve",
                                             "step": self.decode_steps}

        return manager.install_preemption_hook(_state,
                                               exit_after=exit_after)

    def shutdown(self, detail: str = "engine shutdown"):
        """Graceful stop (SIGTERM / replica drain): every in-flight and
        queued request becomes terminal — active slots are evicted
        (pages reclaimed, partial tokens kept) and the queue is failed —
        all with SHED, the 'retry me on another replica' signal. The
        engine stays structurally valid (``audit_pages`` passes) and
        idle afterwards."""
        for s in range(self.num_slots):
            if self._slots[s] is not None:
                self._evict(s, Outcome.SHED, detail)
        while self._queue:
            self._record_terminal(self._queue.popleft(), Outcome.SHED,
                                  detail)

    def _fail_starved_head(self, polls: int):
        """Bounded give-up on an unadmittable queue head while the
        engine is otherwise idle — shared by ``run()`` and the HTTP
        front end's driver loop (serve/frontend.py), so both speak the
        same outcome semantics. The PRIORITY head is what admission is
        blocked on — failing a lower tier behind it would not unwedge
        anything. A head that is only queued because the brownout
        clamp holds its tier is NOT page-starved: it gets a retryable
        SHED (the honest 'come back when pressure clears'), not a
        FAILED_UNSERVABLE — still bounded, the engine never wedges on
        a pinned controller."""
        head = self._queue_head(clamped_ok=False)
        if head is not None:
            self.withdraw(head)
            self._record_terminal(
                head, Outcome.FAILED_UNSERVABLE,
                f"page-starved: head of an idle engine "
                f"for {polls} polls "
                f"(free={self._alloc.free_count})")
        else:
            head = self._queue_head()
            self.withdraw(head)
            self._record_terminal(
                head, Outcome.SHED,
                f"brownout level {self.brownout_level} "
                f"held {head.tier.value} admissions "
                f"clamped for {polls} idle polls")

    def run(self, requests, arrival_times=None, poll_sleep=1e-3,
            before_step=None, after_step=None):
        """Drive ``requests`` until EVERY one is terminal (structured
        ``Outcome`` — never an exception for per-request conditions).
        ``arrival_times`` (seconds, relative to call time) gates
        submission — the Poisson-arrival harness of
        tools/serve_bench.py; None submits everything up front (pure
        batch drain).

        ``before_step(engine, i)`` / ``after_step(engine, i)`` bracket
        every scheduler iteration ``i`` — the chaos harness's injection
        and per-step audit hooks (serve/chaos.py).

        A queue head that cannot be admitted while the engine is
        otherwise idle (page starvation — e.g. the pool is chaos-held
        or fragmented by retention) is failed FAILED_UNSERVABLE after
        ``stall_steps`` consecutive idle polls; requests too large to
        EVER fit were already failed at submit. The engine keeps
        serving everything else — one doomed request no longer raises
        out of the serving loop."""
        if arrival_times is None:
            for r in requests:
                self.submit(r)
            pending = []
        else:
            pending = sorted(zip(arrival_times, requests),
                             key=lambda p: p[0])
        t0 = time.perf_counter()
        stall = 0
        it = 0
        while pending or self._queue or self.active_count:
            now = time.perf_counter() - t0
            while pending and pending[0][0] <= now:
                self.submit(pending.pop(0)[1])
            if before_step is not None:
                before_step(self, it)
            n = self.step()
            if after_step is not None:
                after_step(self, it)
            it += 1
            if n > 0 or self.active_count:
                stall = 0
                continue
            if self._queue:
                # nothing decoding, nothing prefilling, head unadmitted
                stall += 1
                if stall > self.stall_steps:
                    self._fail_starved_head(stall)
                    stall = 0
                else:
                    time.sleep(poll_sleep)   # let deadlines/holds move
            elif pending:
                stall = 0
                time.sleep(min(poll_sleep,
                               max(0.0, pending[0][0] - now)))
        return requests
