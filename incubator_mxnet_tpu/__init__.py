"""incubator_mxnet_tpu — a TPU-native deep-learning framework with the
capabilities of Apache MXNet 1.x (the reference: sudhirshahu51/incubator-mxnet).

Not a port: the reference's layered C++ core (dependency engine, NNVM graph
IR, mshadow, KVStore/ps-lite — SURVEY.md §1) is re-designed around JAX/XLA:

  - the async dependency engine  → XLA async dispatch (SURVEY.md §7.3)
  - NNVM + CachedOp              → trace-to-XLA compilation (``hybridize()``)
  - mshadow/cuDNN kernels        → jnp/lax + Pallas TPU kernels
  - KVStore/ps-lite/NCCL         → jax.sharding + ICI/DCN collectives

Usage mirrors the reference::

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, autograd, gluon

    x = nd.ones((2, 3), ctx=mx.tpu(0))
    with autograd.record():
        y = (x * 2).sum()
    y.backward()
"""

__version__ = "0.1.0"

import os as _os

import jax as _jax

# Reference parity: float32 ops compute in true float32 (the reference's
# cuBLAS/oneDNN fp32 paths). The TPU perf path uses bfloat16 *dtypes* (AMP),
# which this default does not affect. Override via MXTPU_MATMUL_PRECISION.
_jax.config.update(
    "jax_default_matmul_precision",
    _os.environ.get("MXTPU_MATMUL_PRECISION", "float32"))

from . import base
from .base import MXNetError
from . import context
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, \
    num_gpus, num_tpus, num_devices, gpu_memory_info
from . import random
from . import autograd
from . import ops
from . import ndarray
from . import ndarray as nd
from .ndarray.ndarray import NDArray

_LAZY_SUBMODULES = (
    "gluon", "symbol", "sym", "optimizer", "kvstore", "metric", "io", "image",
    "initializer", "init", "lr_scheduler", "profiler", "amp", "parallel",
    "models", "checkpoint", "train", "serve",
    "runtime", "test_utils", "callback", "util", "engine", "recordio",
    "numpy", "np", "npx", "module", "mod", "model", "executor", "kv",
    "contrib", "operator", "rtc", "monitor", "mon",
    "name", "attribute", "viz", "visualization",
)


def __getattr__(name):
    """Lazy submodule loading (keeps import light and cycle-free)."""
    if name == "AttrScope":
        from .attribute import AttrScope
        globals()["AttrScope"] = AttrScope
        return AttrScope
    if name in _LAZY_SUBMODULES:
        import importlib

        alias = {"sym": ".symbol", "kv": ".kvstore", "mon": ".monitor",
                 "init": ".initializer",
                 "npx": ".numpy_extension",
                 "numpy": ".numpy_shim", "np": ".numpy_shim",
                 "recordio": ".io.recordio",
                 "lr_scheduler": ".optimizer.lr_scheduler",
                 "mod": ".module", "executor": ".symbol.executor",
                 "viz": ".visualization"}
        modpath = alias.get(name, "." + name)
        mod = importlib.import_module(modpath, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
