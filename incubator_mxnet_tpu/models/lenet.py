"""LeNet-5 (BASELINE.md config #1 — `example/gluon/mnist/mnist.py` in the
reference; file-level citation, SURVEY.md caveat). The minimum end-to-end
slice: conv/pool/dense on a single chip."""

from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["LeNet"]


class LeNet(HybridBlock):
    """Classic LeNet: 2×(conv+pool) → 2×dense → logits."""

    def __init__(self, classes=10, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.conv1 = nn.Conv2D(20, kernel_size=5, activation="relu")
            self.pool1 = nn.MaxPool2D(pool_size=2, strides=2)
            self.conv2 = nn.Conv2D(50, kernel_size=5, activation="relu")
            self.pool2 = nn.MaxPool2D(pool_size=2, strides=2)
            self.fc1 = nn.Dense(500, activation="relu")
            self.fc2 = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.pool1(self.conv1(x))
        x = self.pool2(self.conv2(x))
        x = self.fc1(x)
        return self.fc2(x)
