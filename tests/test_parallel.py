"""Parallel package tests on the virtual 8-device CPU mesh
(SURVEY.md §4 idiom 4: multi-device simulation on one box)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd, gluon
from incubator_mxnet_tpu import parallel
from incubator_mxnet_tpu.parallel import mesh as pmesh


def test_build_mesh_axes():
    m = pmesh.build_mesh(axis_sizes={"dp": 4, "tp": 2})
    assert m.shape["dp"] == 4 and m.shape["tp"] == 2
    assert m.shape["sp"] == 1
    # wildcard absorbs remaining devices
    m2 = pmesh.build_mesh()
    assert m2.shape["dp"] == len(jax.devices())


def test_build_mesh_bad_product():
    with pytest.raises(mx.MXNetError):
        pmesh.build_mesh(axis_sizes={"dp": 3})  # 8 % 3 != 0


def _make_mlp(in_units=8):
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu", in_units=in_units))
        net.add(gluon.nn.Dense(4, in_units=16))
    net.initialize()
    return net


def test_spmd_trainer_matches_eager():
    """The fused SPMD step must produce the same training trajectory as the
    eager Trainer path (data-parallel sum ≡ single-device batch)."""
    mx.random.seed(7)
    rng = np.random.RandomState(3)
    X = rng.randn(32, 8).astype("float32")
    y = rng.randint(0, 4, size=(32,))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # eager reference
    mx.random.seed(11)
    net_a = _make_mlp()
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9},
                         kvstore=None)
    for _ in range(5):
        with autograd.record():
            L = loss_fn(net_a(nd.array(X)), nd.array(y)).mean()
        L.backward()
        tr_a.step(batch_size=1)

    # fused SPMD over an 8-way dp mesh
    mx.random.seed(11)
    net_b = _make_mlp()
    mesh = pmesh.build_mesh(axis_sizes={"dp": 8})
    tr_b = parallel.SPMDTrainer(net_b, loss=loss_fn, optimizer="sgd",
                                optimizer_params={"learning_rate": 0.1,
                                                  "momentum": 0.9},
                                mesh=mesh)
    for _ in range(5):
        loss_b = tr_b.step(nd.array(X), nd.array(y))

    for (na, pa), (nb, pb) in zip(
            sorted(net_a.collect_params().items()),
            sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"{na} vs {nb}")


def test_spmd_trainer_adam_bias_correction_advances():
    """Adam's t must advance across jitted steps (traced-t regression)."""
    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype("float32")
    y = rng.randint(0, 4, size=(16,))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    mx.random.seed(5)
    net_a = _make_mlp()
    tr_a = gluon.Trainer(net_a.collect_params(), "adam",
                         {"learning_rate": 0.01}, kvstore=None)
    mx.random.seed(5)
    net_b = _make_mlp()
    tr_b = parallel.SPMDTrainer(net_b, loss=loss_fn, optimizer="adam",
                                optimizer_params={"learning_rate": 0.01})
    for _ in range(4):
        with autograd.record():
            L = loss_fn(net_a(nd.array(X)), nd.array(y)).mean()
        L.backward()
        tr_a.step(batch_size=1)
        tr_b.step(nd.array(X), nd.array(y))

    for (na, pa), (nb, pb) in zip(
            sorted(net_a.collect_params().items()),
            sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"{na} vs {nb}")


def test_spmd_trainer_fsdp_sharding(monkeypatch):
    """FSDP mode shards parameters over the fsdp axis and still trains."""
    # tiny test params are below the default replicate-small-params floor
    monkeypatch.setenv("MXTPU_FSDP_MIN_SIZE", "0")
    rng = np.random.RandomState(1)
    X = rng.randn(16, 8).astype("float32")
    y = rng.randint(0, 4, size=(16,))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net = _make_mlp()
    mesh = pmesh.build_mesh(axis_sizes={"dp": 2, "fsdp": 4})
    tr = parallel.SPMDTrainer(net, loss=loss_fn, optimizer="sgd",
                              optimizer_params={"learning_rate": 0.1},
                              mesh=mesh, sharding="fsdp")
    l0 = float(tr.step(nd.array(X), nd.array(y)).asnumpy())
    for _ in range(10):
        l_last = float(tr.step(nd.array(X), nd.array(y)).asnumpy())
    assert l_last < l0
    # weight really sharded: 16x8 weight should shard dim0=16 over fsdp=4
    w = net.collect_params()
    first_w = [p for _, p in sorted(w.items()) if p.shape == (16, 8)][0]
    shard_shape = list(first_w.data()._data.addressable_shards)[0].data.shape
    assert shard_shape[0] == 4  # 16 / fsdp(4)


def test_ring_attention_matches_dense():
    """Ring attention over the sp axis must equal dense softmax attention."""
    mesh = pmesh.build_mesh(axis_sizes={"sp": 8})
    B, T, H, D = 2, 32, 2, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)

    def dense(q, k, v, causal):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = np.triu(np.ones((T, T)), 1) * -1e30
            s = s + mask[None, None]
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    for causal in (False, True):
        out_ring = parallel.ring_self_attention(
            q, k, v, mesh=mesh, causal=causal, batch_axis=None)
        out_dense = dense(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_dense),
                                   rtol=2e-5, atol=2e-5)


def test_ring_attention_grad_flows():
    mesh = pmesh.build_mesh(axis_sizes={"sp": 4})
    B, T, H, D = 1, 16, 1, 4
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)

    def f(q):
        return parallel.ring_self_attention(
            q, q, q, mesh=mesh, causal=True, batch_axis=None).sum()

    g = jax.grad(f)(q)
    assert np.isfinite(np.asarray(g)).all()
    assert np.abs(np.asarray(g)).sum() > 0


def test_host_allreduce_single_process_identity():
    x = jnp.ones((4,))
    out = parallel.host_allreduce(x)
    np.testing.assert_allclose(np.asarray(out), np.ones((4,)))


def test_kvstore_dist_type_works_single_process():
    """dist_sync kvstore must not crash in a single-process run
    (regression: ModuleNotFoundError on parallel.collectives)."""
    kv = mx.kvstore.create("dist_sync")
    a = nd.ones((3,))
    kv.init(0, a)
    kv.push(0, nd.ones((3,)) * 2)
    out = nd.zeros((3,))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), 2 * np.ones(3))


def test_spmd_trainer_multi_precision_bf16():
    """bf16 params + multi_precision: the optimizer keeps f32 master
    weights (VERDICT r2 next-round #3 — the reference's multi-precision
    optimizer path, src/operator/optimizer_op.cc)."""
    mx.random.seed(5)
    net = gluon.nn.Dense(4, in_units=8, dtype="bfloat16")
    net.initialize()
    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype("float32")
    y = rng.randint(0, 4, size=(16,))
    tr = parallel.SPMDTrainer(
        net, loss=gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="lamb",
        optimizer_params={"learning_rate": 1e-2, "multi_precision": True})
    w0 = net.weight.data().asnumpy().astype(np.float32).copy()
    for _ in range(3):
        L = tr.step(nd.array(X).astype("bfloat16"), nd.array(y))
    assert np.isfinite(float(L.asnumpy()))
    assert str(net.weight.data().dtype) == "bfloat16"
    assert not np.allclose(
        w0, net.weight.data().asnumpy().astype(np.float32))
    # master copy exists in optimizer state as f32
    masters = [st for st in tr._opt_state
               if isinstance(st, tuple) and len(st) == 2]
    assert masters, "expected (master, inner) multi-precision state"
    assert str(masters[0][0].dtype) == "float32"


def test_sharded_embedding_vocab_split_matches_replicated():
    """nn.Embedding(sharded=True): the table is vocab-sharded over
    tp x fsdp on the mesh, and the training trajectory matches the
    replicated run (VERDICT r2 missing #6 / next-round #9)."""
    rng = np.random.RandomState(0)
    V, U, B, T = 64, 8, 8, 4
    ids = rng.randint(0, V, (B, T))
    y = rng.randint(0, 4, (B,))

    class Tiny(gluon.HybridBlock):
        def __init__(self, sharded, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.emb = gluon.nn.Embedding(V, U, sharded=sharded)
                self.out = gluon.nn.Dense(4, in_units=U)

        def hybrid_forward(self, F, x):
            h = self.emb(x).mean(axis=1)
            return self.out(h)

    losses = {}
    params = {}
    for sharded in (False, True):
        mx.random.seed(3)
        net = Tiny(sharded)
        net.initialize()
        mesh = pmesh.build_mesh(axis_sizes={"dp": 2, "fsdp": 2, "tp": 2})
        tr = parallel.SPMDTrainer(
            net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
            optimizer="sgd", optimizer_params={"learning_rate": 0.5},
            mesh=mesh, sharding="fsdp")
        for _ in range(3):
            L = tr.step(nd.array(ids, dtype="int32"), nd.array(y))
        losses[sharded] = float(L.asnumpy())
        params[sharded] = net.emb.weight.data()
        if sharded:
            # vocab dim really split 4-ways (tp=2 x fsdp=2): each shard
            # holds V/4 rows and all U columns
            shards = list(params[True]._data.addressable_shards)
            assert shards[0].data.shape == (V // 4, U), \
                shards[0].data.shape
    assert abs(losses[True] - losses[False]) < 1e-5
    np.testing.assert_allclose(params[True].asnumpy(),
                               params[False].asnumpy(), rtol=1e-5,
                               atol=1e-6)


@pytest.mark.slow   # 8s (round-11 tier-1 budget repair); ci stage_unit
                    # runs it
def test_pipeline_apply_matches_sequential():
    """GPipe over pp=4: pipelined forward equals sequential stage
    application, and gradients flow through the ppermute schedule."""
    from incubator_mxnet_tpu.parallel import pipeline as pl

    S, M, B, F = 4, 8, 2, 6
    rng = np.random.RandomState(0)
    stage_params = [
        {"w": jnp.asarray(rng.randn(F, F).astype(np.float32) * 0.4),
         "b": jnp.asarray(rng.randn(F).astype(np.float32) * 0.1)}
        for _ in range(S)]
    stacked = pl.stack_stage_params(stage_params)
    x = jnp.asarray(rng.randn(M, B, F).astype(np.float32))

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    mesh = pmesh.build_mesh(axis_sizes={"pp": 4, "dp": 2})

    got = jax.jit(lambda sp, xx: pl.pipeline_apply(
        stage_fn, sp, xx, mesh))(stacked, x)

    want = x
    for p in stage_params:
        want = jnp.tanh(want @ p["w"] + p["b"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    # differentiable end-to-end
    def loss(sp):
        return pl.pipeline_apply(stage_fn, sp, x, mesh).sum()

    g = jax.grad(loss)(stacked)
    gsum = sum(float(np.abs(np.asarray(v)).sum())
               for v in jax.tree_util.tree_leaves(g))
    assert np.isfinite(gsum) and gsum > 0

    def seq_loss(plist):
        h = x
        for p in plist:
            h = jnp.tanh(h @ p["w"] + p["b"])
        return h.sum()

    g_seq = jax.grad(seq_loss)(stage_params)
    for i in range(S):
        np.testing.assert_allclose(np.asarray(g["w"][i]),
                                   np.asarray(g_seq[i]["w"]),
                                   rtol=5e-4, atol=5e-5)
        np.testing.assert_allclose(np.asarray(g["b"][i]),
                                   np.asarray(g_seq[i]["b"]),
                                   rtol=5e-4, atol=5e-5)


def test_pipeline_needs_enough_microbatches():
    from incubator_mxnet_tpu.parallel import pipeline as pl
    mesh = pmesh.build_mesh(axis_sizes={"pp": 8})
    stacked = {"w": jnp.zeros((8, 2, 2))}
    with pytest.raises(mx.MXNetError, match="microbatches"):
        pl.pipeline_apply(lambda p, h: h, stacked,
                          jnp.zeros((4, 1, 2)), mesh)


def test_switch_moe_matches_direct_routing():
    """Top-1 MoE with ample capacity: every token goes to its argmax
    expert, so the output equals gate * expert(token) computed directly;
    the expert dim is ep-sharded on the mesh."""
    from incubator_mxnet_tpu.parallel import moe

    rng = np.random.RandomState(0)
    N, D, E = 32, 8, 8
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    logits = jnp.asarray(rng.randn(N, E).astype(np.float32))
    params = [{"w": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.3)}
              for _ in range(E)]
    stacked = moe.stack_expert_params(params)
    mesh = pmesh.build_mesh(axis_sizes={"ep": 8})

    def expert_fn(p, h):
        return jnp.tanh(h @ p["w"])

    out, aux = jax.jit(lambda xx, ll, sp: moe.switch_moe(
        xx, ll, expert_fn, sp, capacity_factor=8.0, mesh=mesh))(
            x, logits, stacked)

    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    eidx = probs.argmax(-1)
    want = np.stack([
        probs[i, eidx[i]] * np.tanh(np.asarray(x)[i] @
                                    np.asarray(params[eidx[i]]["w"]))
        for i in range(N)])
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-5,
                               atol=2e-5)
    assert float(aux) > 0

    # differentiable (experts + router both get gradient)
    def loss(sp, ll):
        o, a = moe.switch_moe(x, ll, expert_fn, sp, capacity_factor=8.0,
                              mesh=mesh)
        return o.sum() + 0.01 * a

    gw, gl = jax.grad(loss, argnums=(0, 1))(stacked, logits)
    assert np.abs(np.asarray(gw["w"])).sum() > 0
    assert np.isfinite(np.asarray(gl)).all()


def test_switch_moe_capacity_drops_tokens():
    """With capacity 1 and all tokens preferring one expert, overflow
    tokens come back as zeros (Switch drop contract)."""
    from incubator_mxnet_tpu.parallel import moe

    N, D, E = 8, 4, 4
    x = jnp.ones((N, D), jnp.float32)
    logits = jnp.zeros((N, E), jnp.float32).at[:, 2].set(10.0)
    params = moe.stack_expert_params(
        [{"w": jnp.eye(D)} for _ in range(E)])
    out, _ = moe.switch_moe(x, logits, lambda p, h: h @ p["w"], params,
                            capacity_factor=0.5)  # C = 1
    nonzero_rows = (np.abs(np.asarray(out)).sum(-1) > 0).sum()
    assert nonzero_rows == 1  # only the first routed token fits


@pytest.mark.slow   # 13-21s (round-10 tier-1 budget repair); ci stage_unit runs it
def test_ring_flash_attention_matches_dense():
    """Ring attention with the (out, lse) flash-block engine must equal
    dense attention — jnp fallback path on the CPU mesh, both causal
    and bidirectional, with gradients flowing."""
    mesh = pmesh.build_mesh(axis_sizes={"sp": 4})
    B, T, H, D = 2, 32, 2, 8
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)

    def dense(q, k, v, causal):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = np.triu(np.ones((T, T)), 1) * -1e30
            s = s + mask[None, None]
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    for causal in (False, True):
        got = parallel.ring_flash_attention(
            q, k, v, mesh=mesh, causal=causal, batch_axis=None)
        want = dense(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-5, atol=3e-5, err_msg=str(causal))

    def loss(q):
        return parallel.ring_flash_attention(
            q, k, v, mesh=mesh, causal=True, batch_axis=None).sum()

    g = jax.grad(loss)(q)
    g_ref = jax.grad(lambda q: dense(q, k, v, True).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=5e-4, atol=5e-5)


def test_sync_batch_norm_global_stats_under_spmd():
    """SyncBatchNorm's TPU contract: with the batch sharded over an 8-way
    dp mesh, batch statistics must equal the FULL-batch oracle (the
    reference's cross-worker all-reduce of stats), not per-shard stats -
    i.e. the SPMD trajectory matches the single-device trajectory even
    though each device only sees 1/8 of the batch."""
    from incubator_mxnet_tpu.gluon.contrib import nn as gcn

    def make_net():
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(4, kernel_size=3, padding=1, in_channels=2),
                gcn.SyncBatchNorm(in_channels=4, num_devices=8),
                gluon.nn.Activation("relu"),
                gluon.nn.GlobalAvgPool2D(),
                gluon.nn.Dense(3, in_units=4))
        net.initialize()
        return net

    rng = np.random.RandomState(0)
    # per-sample values vary wildly so per-shard stats differ sharply
    # from global stats - a per-shard BN would diverge immediately
    X = (rng.randn(32, 2, 6, 6) * np.linspace(
        0.1, 10, 32).reshape(32, 1, 1, 1)).astype("float32")
    y = rng.randint(0, 3, size=(32,))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    mx.random.seed(5)
    net_a = make_net()
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.05}, kvstore=None)
    for _ in range(3):
        with autograd.record():
            L = loss_fn(net_a(nd.array(X)), nd.array(y)).mean()
        L.backward()
        tr_a.step(batch_size=1)

    mx.random.seed(5)
    net_b = make_net()
    mesh = pmesh.build_mesh(axis_sizes={"dp": 8})
    tr_b = parallel.SPMDTrainer(net_b, loss=loss_fn, optimizer="sgd",
                                optimizer_params={"learning_rate": 0.05},
                                mesh=mesh)
    for _ in range(3):
        tr_b.step(nd.array(X), nd.array(y))

    for (na, pa), (nb, pb) in zip(
            sorted(net_a.collect_params().items()),
            sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=5e-4, atol=5e-5,
                                   err_msg=f"{na} vs {nb}")


def test_ring_attention_training_composes_with_dp():
    """Long-context composition: a tiny attention 'model' trained with
    ring attention over sp x dp matches the same training run with dense
    attention on one device - optimizer + ring backward + mesh all in
    one jitted step."""
    import jax
    import jax.numpy as jnp

    mesh = pmesh.build_mesh(axis_sizes={"dp": 2, "sp": 4})
    rng = np.random.RandomState(0)
    B, T, H, D = 4, 32, 2, 8
    x = jnp.asarray(rng.randn(B, T, H * D), jnp.float32)
    w0 = jnp.asarray(rng.randn(H * D, H * D) * 0.2, jnp.float32)

    def fwd(w, xx, ring):
        qkv = xx @ w
        q = qkv.reshape(B, T, H, D)
        if ring:
            o = parallel.ring_self_attention(q, q, q, mesh=mesh,
                                             causal=True, batch_axis="dp")
        else:
            from incubator_mxnet_tpu.ops.attention import (
                scaled_dot_product_attention)
            o = scaled_dot_product_attention(q, q, q, causal=True)
        return jnp.mean(o ** 2)

    def train(ring, steps=4, lr=0.1):
        w = w0
        lossf = jax.jit(jax.value_and_grad(
            lambda ww: fwd(ww, x, ring)))
        losses = []
        for _ in range(steps):
            L, g = lossf(w)
            w = w - lr * g
            losses.append(float(L))
        return w, losses

    w_ring, l_ring = train(True)
    w_dense, l_dense = train(False)
    np.testing.assert_allclose(l_ring, l_dense, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(w_ring), np.asarray(w_dense),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow   # 13s (round-11 tier-1 budget repair); sp-ring
                    # tier-1 coverage stays via
                    # test_ring_attention_grad_flows; stage_unit runs it
def test_gpt_seq_parallel_training_matches_dense():
    """Flagship long-context integration: a GPT trained through
    SPMDTrainer on a dp2 x sp4 mesh with seq_parallel=True (attention
    rides the sp ring inside the fused step) matches the plain dp-mesh
    dense-attention trajectory."""
    from incubator_mxnet_tpu.models import gpt as gpt_mod

    rng = np.random.RandomState(0)
    B, T, V = 8, 32, 64
    ids = rng.randint(0, V, (B, T)).astype(np.int32)
    labels = np.concatenate([ids[:, 1:], ids[:, :1]], axis=1).astype(
        np.int32)

    def train(seq_parallel, axis_sizes, steps=3):
        mx.random.seed(3)
        model = gpt_mod.gpt_mini(vocab_size=V, max_length=T,
                                 seq_parallel=seq_parallel)
        model.initialize()
        mesh = pmesh.build_mesh(axis_sizes=axis_sizes)
        tr = parallel.SPMDTrainer(
            model, forward_loss=gpt_mod.lm_loss, optimizer="adam",
            optimizer_params={"learning_rate": 1e-3}, mesh=mesh)
        losses = []
        for _ in range(steps):
            L = tr.step(nd.array(ids), nd.array(labels))
            losses.append(float(L.asnumpy()))
        return model, losses

    m_ring, l_ring = train(True, {"dp": 2, "sp": 4})
    m_dense, l_dense = train(False, {"dp": 8})
    np.testing.assert_allclose(l_ring, l_dense, rtol=1e-4)
    for (na, pa), (nb, pb) in zip(
            sorted(m_ring.collect_params().items()),
            sorted(m_dense.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(),
                                   rtol=5e-4, atol=5e-5,
                                   err_msg=f"{na} vs {nb}")


@pytest.mark.slow   # 13-21s (round-10 tier-1 budget repair); ci stage_unit runs it
def test_bert_seq_parallel_training_matches_dense():
    """Encoder long-context: BERT trained on a dp2 x sp4 mesh with
    seq_parallel=True (key-padding masks ride the ring as global valid
    lengths) matches the dp8 dense-attention trajectory."""
    from incubator_mxnet_tpu.models import bert as bert_mod

    rng = np.random.RandomState(0)
    B, T, M, V = 8, 32, 4, 64

    def make_batch():
        # ragged valid lengths exercise the masked-ring path
        vls = np.array([T, 24, 16, T, 28, T, 20, T], np.int32)
        return (
            nd.array(rng.randint(0, V, (B, T)), dtype="int32"),
            nd.array(rng.randint(0, 2, (B, T)), dtype="int32"),
            nd.array(vls, dtype="int32"),
            nd.array(rng.randint(0, T, (B, M)), dtype="int32"),
            nd.array(rng.randint(0, V, (B, M)), dtype="int32"),
            nd.ones((B, M)),
            nd.array(rng.randint(0, 2, (B,)), dtype="int32"),
        )

    state = rng.get_state()

    def train(seq_parallel, axis_sizes, steps=2):
        rng.set_state(state)
        mx.random.seed(4)
        model = bert_mod.bert_tiny(vocab_size=V, max_length=T,
                                   seq_parallel=seq_parallel)
        model.initialize()
        pre = bert_mod.BERTForPretraining(model)
        pre.initialize()
        mesh = pmesh.build_mesh(axis_sizes=axis_sizes)
        tr = parallel.SPMDTrainer(
            pre, forward_loss=bert_mod.pretraining_loss, optimizer="adam",
            optimizer_params={"learning_rate": 1e-3}, mesh=mesh)
        losses = []
        for _ in range(steps):
            L = tr.step(*make_batch())
            losses.append(float(L.asnumpy()))
        return losses

    l_ring = train(True, {"dp": 2, "sp": 4})
    l_dense = train(False, {"dp": 8})
    np.testing.assert_allclose(l_ring, l_dense, rtol=2e-4)


def test_2bit_pack_unpack_roundtrip():
    """4 codes per uint8 byte, exact for any length (incl. non-multiples
    of 4) — the wire format of the dist_sync gradient compression."""
    import jax.numpy as jnp
    from incubator_mxnet_tpu.parallel.collectives import (_pack_2bit,
                                                          _unpack_2bit)
    rng = np.random.RandomState(0)
    for n in (1, 4, 7, 64, 103):
        codes = jnp.asarray(rng.randint(0, 3, (n,)).astype(np.uint8))
        packed = _pack_2bit(codes)
        assert packed.shape == ((n + 3) // 4,)
        assert packed.dtype == jnp.uint8
        np.testing.assert_array_equal(np.asarray(_unpack_2bit(packed, n)),
                                      np.asarray(codes))


def test_2bit_error_feedback_tracks_true_sum():
    """The residual carries quantization error forward, so the running
    dequantized sum tracks the running true sum within one threshold."""
    import jax.numpy as jnp
    from incubator_mxnet_tpu.parallel.collectives import quantize_2bit

    rng = np.random.RandomState(1)
    x = rng.uniform(-0.4, 0.4, (257,)).astype(np.float32)
    threshold = 0.5
    res = None
    deq_sum = np.zeros_like(x)
    for step in range(30):
        packed, deq, res = quantize_2bit(jnp.asarray(x), res, threshold)
        assert packed.size == (x.size + 3) // 4
        deq_sum += np.asarray(deq)
        np.testing.assert_allclose(deq_sum, x * (step + 1),
                                   atol=threshold + 1e-6)


def test_kvstore_2bit_compression_single_process():
    """kvstore 2-bit path: quantized push with per-key error feedback
    (single process = the local-server case; the same code ships packed
    uint8 codes across DCN when process_count > 1)."""
    kv = mx.kvstore.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", nd.zeros((4,)))
    g = nd.array(np.full(4, 0.3, np.float32))
    out = nd.zeros((4,))
    kv.push("w", g)            # 0.3 rounds up to 0.5, residual -0.2
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.5)
    kv.push("w", g)            # 0.3 - 0.2 = 0.1 -> 0, residual 0.1
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.0)
    # two keys keep independent residuals
    kv.init("v", nd.zeros((4,)))
    kv.push("v", g)
    kv.pull("v", out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.5)


def test_ring_attention_fully_masked_row_is_zero():
    """vl==0 rows: every ring chunk reports lse=_NEG_INF for the row;
    the merge must weight it out to an exact zero (not NaN, not the
    mean of V) — the r5 masked-row contract across the ring."""
    mesh = pmesh.build_mesh(axis_sizes={"sp": 4})
    B, T, H, D = 2, 32, 2, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    vl = jnp.asarray([0, 20], jnp.int32)      # batch 0 fully masked

    out = parallel.ring_self_attention(q, k, v, mesh=mesh, causal=False,
                                       batch_axis=None, valid_length=vl)
    out_np = np.asarray(out)
    assert np.isfinite(out_np).all()
    np.testing.assert_array_equal(out_np[0], 0.0)
    # batch 1 matches dense attention over the 20-key prefix
    s = np.einsum("qhd,khd->hqk", np.asarray(q)[1],
                  np.asarray(k)[1][:20]) / np.sqrt(D)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("hqk,khd->qhd", p, np.asarray(v)[1][:20])
    np.testing.assert_allclose(out_np[1], want, rtol=2e-5, atol=2e-5)
