"""mxlint framework: source model, findings, waivers, baseline, runner.

The moving parts (docs/STATIC_ANALYSIS.md):

  - ``SourceUnit``   one parsed file: AST with parent links, the
                     module's dotted name, and its inline waivers.
  - ``Project``      every unit plus cross-file lookup (module name →
                     unit, function tables) so passes can walk call
                     graphs project-wide.
  - ``Finding``      one violation. Its ``key`` deliberately excludes
                     the line number — baselines must survive unrelated
                     edits above the finding — and disambiguates
                     repeats within one (path, symbol, rule, message)
                     cell with a ``#n`` suffix ordered by line.
  - waivers          ``# mxlint: allow-<rule>(reason)`` on the flagged
                     line, the line above, or the ``def``/``class``
                     line of an enclosing scope (scope-wide waiver).
                     A waiver is a CONTRACT: the reason is mandatory
                     and an empty or unknown-rule waiver is itself a
                     finding (rule ``waiver-syntax``).
  - baseline         checked-in JSON debt ledger: pre-existing findings
                     that are acknowledged but not yet fixed. Every
                     entry carries a human-readable reason; stale
                     entries are dropped on ``--update-baseline`` and
                     reported otherwise.
"""

from __future__ import annotations

import ast
import builtins
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# rules that exist only as annotation vocabulary (no detection pass):
# waivers under these names document an invariant at the site that an
# external tool (or a human reader) would otherwise question.
ANNOTATION_RULES = {
    "import-effect":   "import kept for its side effect (op registration,"
                       " availability probe)",
    "pinned-name":     "name bound only to pin an object's lifetime or"
                       " identity",
}

_BUILTIN_NAMES = frozenset(dir(builtins))

_WAIVER_ITEM_RE = re.compile(r"allow-([A-Za-z0-9_-]+)\(([^()]*)\)")
_WAIVER_MARK_RE = re.compile(r"#\s*mxlint:")


# --------------------------------------------------------------------- #
# findings
# --------------------------------------------------------------------- #

@dataclasses.dataclass
class Finding:
    rule: str
    path: str                   # repo-relative, '/' separated
    line: int
    message: str                # stable text: never embeds line numbers
    symbol: str = "<module>"    # enclosing def/class qualname
    severity: str = "error"     # "error" gates CI; "warn" is advisory
    # filled by the runner:
    status: str = "active"      # active | waived | baselined
    reason: str = ""            # waiver/baseline justification
    occurrence: int = 1         # disambiguates identical keys
    note: str = ""              # attribution caveats (aliased groups)

    @property
    def key(self) -> str:
        base = f"{self.path}::{self.symbol}::{self.rule}::{self.message}"
        return base if self.occurrence == 1 else \
            f"{base}#{self.occurrence}"

    def render(self) -> str:
        sev = self.severity.upper()
        tag = "" if self.status == "active" else f" [{self.status}]"
        note = f" [{self.note}]" if self.note else ""
        return (f"{self.path}:{self.line}: {sev} {self.rule}{tag} "
                f"({self.symbol}): {self.message}{note}")


# --------------------------------------------------------------------- #
# source model
# --------------------------------------------------------------------- #

def _attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._mxparent = node          # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_mxparent", None)


def enclosing_scopes(node: ast.AST) -> List[ast.AST]:
    """Innermost-first chain of enclosing FunctionDef/ClassDef nodes."""
    out = []
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            out.append(cur)
        cur = parent(cur)
    return out


def qualname_of(node: ast.AST) -> str:
    names = [s.name for s in reversed(enclosing_scopes(node))]
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        names.append(node.name)
    return ".".join(names) if names else "<module>"


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class SourceUnit:
    def __init__(self, path: str, text: str, module: str):
        self.path = path
        self.module = module
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text, filename=path)
            _attach_parents(self.tree)
        except SyntaxError as e:
            self.parse_error = e
        # line -> [(rule, reason)] waiver table from inline comments
        self.waivers: Dict[int, List[Tuple[str, str]]] = {}
        self.bad_waivers: List[Tuple[int, str]] = []
        self._scan_waivers()
        # import table: local alias -> dotted module / imported symbol
        self.import_modules: Dict[str, str] = {}
        self.import_symbols: Dict[str, Tuple[str, str]] = {}
        if self.tree is not None:
            self._scan_imports()

    # -- waivers -------------------------------------------------------- #
    def _comment_lines(self) -> Dict[int, str]:
        """line -> comment text, via tokenize so a docstring MENTIONING
        the waiver syntax is not a waiver."""
        import io
        import tokenize
        out: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass
        return out

    def _scan_waivers(self) -> None:
        for i, line in sorted(self._comment_lines().items()):
            m = _WAIVER_MARK_RE.search(line)
            if not m:
                continue
            tail = line[m.end():]
            items = _WAIVER_ITEM_RE.findall(tail)
            if not items:
                self.bad_waivers.append(
                    (i, "mxlint marker without a parseable "
                        "'allow-<rule>(reason)' clause"))
                continue
            for rule, reason in items:
                reason = reason.strip()
                if not reason:
                    self.bad_waivers.append(
                        (i, f"waiver allow-{rule} carries no reason — "
                            f"a waiver is a contract, state why"))
                    continue
                self.waivers.setdefault(i, []).append((rule, reason))

    def waiver_reason(self, rule: str, line: int) -> Optional[str]:
        """Waiver lookup for a finding at ``line``: same line, the line
        above, or the def/class line of any enclosing scope."""
        for cand in (line, line - 1):
            for r, reason in self.waivers.get(cand, ()):
                if r == rule:
                    return reason
        if self.tree is not None:
            for node in ast.walk(self.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                    continue
                if not (node.lineno <= line <= (node.end_lineno or 0)):
                    continue
                # scope-wide waivers live ON the def/class line or the
                # line directly above it — NEVER on body lines: a
                # line-level waiver on the first statement must not be
                # silently promoted to cover the whole function
                # (fail-closed; found by review)
                for cand in (node.lineno, node.lineno - 1):
                    for r, reason in self.waivers.get(cand, ()):
                        if r == rule:
                            return reason
        return None

    # -- imports -------------------------------------------------------- #
    def _resolve_relative(self, level: int, name: str) -> str:
        base = self.module.split(".")
        if level:
            base = base[:-level] if level <= len(base) else []
        return ".".join(base + ([name] if name else [])).strip(".")

    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_modules[a.asname or
                                        a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                mod = self._resolve_relative(node.level, node.module or "")
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.import_symbols[a.asname or a.name] = (mod, a.name)


class Project:
    def __init__(self, root: str, units: Sequence[SourceUnit]):
        self.root = root
        self.units = list(units)
        self.by_module: Dict[str, SourceUnit] = {
            u.module: u for u in units}
        self.by_path: Dict[str, SourceUnit] = {u.path: u for u in units}

    def functions(self, unit: SourceUnit) \
            -> Dict[str, List[ast.FunctionDef]]:
        out: Dict[str, List[ast.FunctionDef]] = {}
        if unit.tree is None:
            return out
        for node in ast.walk(unit.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.setdefault(node.name, []).append(node)
        return out


# --------------------------------------------------------------------- #
# pass interface
# --------------------------------------------------------------------- #

class LintPass:
    """One invariant. ``rules`` names every rule the pass may emit (the
    waiver vocabulary is validated against the union of these)."""

    name = "base"
    rules: Tuple[str, ...] = ()

    def run(self, project: Project) -> Iterable[Finding]:
        raise NotImplementedError


# --------------------------------------------------------------------- #
# baseline
# --------------------------------------------------------------------- #

UNREVIEWED = ("UNREVIEWED: added by --update-baseline — replace with a "
              "real justification")


def load_baseline(path: Optional[str]) -> Dict[str, str]:
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {e["key"]: e.get("reason", "") for e in data.get("entries", [])}


def save_baseline(path: str, entries: Dict[str, str]) -> None:
    data = {
        "_comment": ("mxlint baseline: acknowledged pre-existing findings."
                     " An entry here is DEBT (a waiver in the source is a"
                     " contract) — every entry needs a reason, and the"
                     " lintcore CI stage reports the total so growth is"
                     " visible. Regenerate with --update-baseline."),
        "version": 1,
        "entries": [{"key": k, "reason": entries[k]}
                    for k in sorted(entries)],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1)
        f.write("\n")


# --------------------------------------------------------------------- #
# runner
# --------------------------------------------------------------------- #

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}


def iter_py_files(paths: Sequence[str], root: str) -> List[str]:
    out: List[str] = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full) and full.endswith(".py"):
            out.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def module_name_for(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".").replace("\\", ".")
    if mod.endswith(".__init__"):
        mod = mod[:-len(".__init__")]
    return mod


def build_project(paths: Sequence[str], root: str) -> Project:
    units = []
    for full in iter_py_files(paths, root):
        rel = os.path.relpath(full, root).replace(os.sep, "/")
        try:
            with open(full, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        units.append(SourceUnit(rel, text, module_name_for(rel)))
    return Project(root, units)


def _known_rules(passes: Sequence[LintPass]) -> set:
    known = set(ANNOTATION_RULES)
    for p in passes:
        known.update(p.rules)
    return known


def analyze_project(project: Project, passes: Sequence[LintPass],
                    baseline: Optional[Dict[str, str]] = None
                    ) -> List[Finding]:
    """Run every pass, then classify findings against waivers and the
    baseline. Returns ALL findings (status marks the triage)."""
    baseline = dict(baseline or {})
    known = _known_rules(passes)
    findings: List[Finding] = []

    for unit in project.units:
        if unit.parse_error is not None:
            findings.append(Finding(
                "parse-error", unit.path,
                unit.parse_error.lineno or 1,
                f"file does not parse: {unit.parse_error.msg}"))
        for line, msg in unit.bad_waivers:
            findings.append(Finding("waiver-syntax", unit.path, line, msg))
        for line, items in unit.waivers.items():
            for rule, _ in items:
                if rule not in known:
                    findings.append(Finding(
                        "waiver-syntax", unit.path, line,
                        f"waiver names unknown rule '{rule}' — see "
                        f"--list-rules for the vocabulary"))

    for p in passes:
        for f in p.run(project):
            findings.append(f)

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

    # occurrence disambiguation for identical (path,symbol,rule,message)
    seen: Dict[str, int] = {}
    for f in findings:
        base = f"{f.path}::{f.symbol}::{f.rule}::{f.message}"
        seen[base] = seen.get(base, 0) + 1
        f.occurrence = seen[base]

    for f in findings:
        unit = project.by_path.get(f.path)
        reason = unit.waiver_reason(f.rule, f.line) if unit else None
        if reason is not None:
            f.status, f.reason = "waived", reason
        elif f.key in baseline:
            f.status, f.reason = "baselined", baseline[f.key]

    # Aliased groups: identical findings are keyed by ORDER (#n), so
    # when a group holds both baselined and active members, which line
    # inherited the baseline entry is arbitrary — a NEW identical
    # violation above an acknowledged one swaps identities with it.
    # The count stays fail-closed (one new finding => one active), but
    # the line attribution must say it is approximate (found by review).
    groups: Dict[str, List[Finding]] = {}
    for f in findings:
        base = f"{f.path}::{f.symbol}::{f.rule}::{f.message}"
        groups.setdefault(base, []).append(f)
    for members in groups.values():
        statuses = {m.status for m in members}
        if len(members) > 1 and "active" in statuses \
                and "baselined" in statuses:
            n_base = sum(1 for m in members if m.status == "baselined")
            for m in members:
                if m.status == "active":
                    m.note = (f"{n_base} identical sibling(s) "
                              f"baselined — line attribution within "
                              f"this group is by order; re-triage the "
                              f"whole group")
    return findings


def run_paths(paths: Sequence[str], root: str, passes: Sequence[LintPass],
              baseline_path: Optional[str] = None) -> List[Finding]:
    project = build_project(paths, root)
    return analyze_project(project, passes,
                           load_baseline(baseline_path))
