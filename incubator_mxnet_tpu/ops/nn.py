"""Neural-network operators: conv, pooling, dense, norm, softmax, dropout.

TPU-native re-design of the reference's `src/operator/nn/` family
(`convolution.cc`, `fully_connected.cc`, `batch_norm.cc`, `layer_norm.cc`,
`pooling.cc`, `activation.cc`, `softmax.cc`, `dropout.cc`, `indexing_op.cc`
Embedding — file-level citations, SURVEY.md caveat).

Design notes (TPU-first):
  - Convolutions lower to ONE ``lax.conv_general_dilated`` in NCHW/OIHW —
    XLA tiles it onto the MXU; there is no algorithm-selection layer (the
    reference's cuDNN autotune, `nn/cudnn/`) because XLA owns that choice.
  - BatchNorm returns ``(out, batch_mean, batch_var)``; running-stat update
    is the caller's (Gluon layer's) responsibility — functional style keeps
    the op pure so it composes with jit/vjp/vmap.
  - Dropout takes an explicit PRNG ``key`` argument (counter-based RNG —
    SURVEY.md §7.2 RNG parity); the imperative front end threads the global
    stream automatically.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register

# --------------------------------------------------------------------- #
# dense / linear
# --------------------------------------------------------------------- #


@register("FullyConnected", aliases=("fully_connected",))
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    """y = x W^T + b (reference: src/operator/nn/fully_connected.cc).
    Weight layout (num_hidden, in_units) matches the reference."""
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = jnp.matmul(data, weight.T)
    if not no_bias and bias is not None:
        out = out + bias
    return out


# --------------------------------------------------------------------- #
# convolution
# --------------------------------------------------------------------- #
def _tup(v, n):
    if v is None:
        return (0,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


@register("Convolution", aliases=("convolution",))
def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                layout=None):
    """N-d convolution, NC(D)HW layout, OIHW kernel
    (reference: src/operator/nn/convolution.cc). Lowers to one
    ``lax.conv_general_dilated`` so XLA maps it onto the MXU."""
    nsp = len(kernel)  # spatial dims
    stride = _tup(stride, nsp) or (1,) * nsp
    stride = tuple(s or 1 for s in stride)
    dilate = tuple(d or 1 for d in (_tup(dilate, nsp) or (1,) * nsp))
    pad = _tup(pad, nsp)
    spatial = "DHW"[-nsp:] if nsp <= 3 else None
    if spatial is None:
        raise MXNetError("convolution supports 1-3 spatial dims")
    lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    (lhs_spec, rhs_spec, lhs_spec))
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nsp)
    return out


@register("Deconvolution", aliases=("deconvolution",))
def deconvolution(data, weight, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, num_filter=None,
                  num_group=1, no_bias=True, target_shape=None, layout=None):
    """Transposed convolution (reference: src/operator/nn/deconvolution.cc).
    Weight layout (in_channels, out_channels/groups, kh, kw) as in the
    reference."""
    nsp = len(kernel)
    stride = tuple(s or 1 for s in (_tup(stride, nsp) or (1,) * nsp))
    dilate = tuple(d or 1 for d in (_tup(dilate, nsp) or (1,) * nsp))
    pad = _tup(pad, nsp)
    adj = _tup(adj, nsp)
    spatial = "DHW"[-nsp:]
    lhs_spec = "NC" + spatial
    # gradient-of-conv implementation: lhs-dilate the input
    rhs_spec = "IO" + spatial
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    (lhs_spec, rhs_spec, lhs_spec))
    k_eff = [(kernel[i] - 1) * dilate[i] + 1 for i in range(nsp)]
    padding = [(k_eff[i] - 1 - pad[i], k_eff[i] - 1 - pad[i] + adj[i])
               for i in range(nsp)]
    out = lax.conv_general_dilated(
        data, jnp.flip(weight, axis=tuple(range(2, 2 + nsp))),
        window_strides=(1,) * nsp,
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nsp)
    return out


# --------------------------------------------------------------------- #
# pooling
# --------------------------------------------------------------------- #
@register("Pooling", aliases=("pooling",))
def pooling(data, kernel=None, pool_type="max", global_pool=False,
            stride=None, pad=None, pooling_convention="valid",
            count_include_pad=True, layout=None):
    """Max/avg/sum/lp pooling over NC(D)HW (reference: src/operator/nn/pooling.cc)."""
    nsp = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            out = jnp.max(data, axis=axes, keepdims=True)
        elif pool_type in ("avg", "sum"):
            out = (jnp.mean if pool_type == "avg" else jnp.sum)(
                data, axis=axes, keepdims=True)
        else:
            raise MXNetError(f"pool_type {pool_type}")
        return out
    kernel = _tup(kernel, nsp)
    stride = tuple(s or 1 for s in (_tup(stride, nsp) or (1,) * nsp))
    pad = _tup(pad, nsp)

    window = (1, 1) + kernel
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode: pad on the high side enough to cover the last window
        hi_pad = []
        for i in range(nsp):
            in_sz = data.shape[2 + i]
            out_sz = -(-(in_sz + 2 * pad[i] - kernel[i]) // stride[i]) + 1
            need = (out_sz - 1) * stride[i] + kernel[i] - in_sz - pad[i]
            hi_pad.append(max(need, pad[i]))
        pads = ((0, 0), (0, 0)) + tuple((pad[i], hi_pad[i]) for i in range(nsp))
    else:
        pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)

    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = 1.0
            for k in kernel:
                denom *= k
            return summed / denom
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return summed / counts
    raise MXNetError(f"pool_type {pool_type}")


@register("AdaptiveAvgPooling2D", aliases=("contrib_AdaptiveAvgPooling2D",
                                           "_contrib_AdaptiveAvgPooling2D"))
def adaptive_avg_pooling2d(data, output_size=None):
    """(reference: src/operator/contrib/adaptive_avg_pooling.cc)"""
    if output_size is None:
        output_size = (1, 1)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    n, c, h, w = data.shape
    oh, ow = output_size
    if h % oh == 0 and w % ow == 0:
        x = data.reshape(n, c, oh, h // oh, ow, w // ow)
        return x.mean(axis=(3, 5))
    # general case: exact per-bin averages via the integral-image trick —
    # one cumsum + four gathers, static shapes for XLA. The cumsum runs in
    # f32: its magnitude reaches H*W, far past bf16's 8-bit mantissa, and
    # the a-b-c+d window difference would cancel catastrophically
    ii = jnp.cumsum(jnp.cumsum(data.astype(jnp.float32), axis=2), axis=3)
    ii = jnp.pad(ii, ((0, 0), (0, 0), (1, 0), (1, 0)))
    hs = (jnp.arange(oh) * h) // oh
    he = ((jnp.arange(oh) + 1) * h + oh - 1) // oh
    ws = (jnp.arange(ow) * w) // ow
    we = ((jnp.arange(ow) + 1) * w + ow - 1) // ow
    a = ii[:, :, he[:, None], we[None, :]]
    b = ii[:, :, hs[:, None], we[None, :]]
    c_ = ii[:, :, he[:, None], ws[None, :]]
    d = ii[:, :, hs[:, None], ws[None, :]]
    area = (he - hs)[:, None] * (we - ws)[None, :]
    return ((a - b - c_ + d) / area).astype(data.dtype)


# --------------------------------------------------------------------- #
# normalization
# --------------------------------------------------------------------- #
@register("BatchNorm", aliases=("batch_norm",), num_outputs=3,
          training_aware=True)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               axis=1, training=None):
    """Batch normalization (reference: src/operator/nn/batch_norm.cc).

    Returns ``(out, batch_mean, batch_var)``; running stats are updated by
    the Gluon layer (functional purity — see module docstring).
    """
    dt = data.dtype
    x = data.astype(jnp.float32)
    axes = tuple(i for i in range(data.ndim) if i != axis % data.ndim)
    bshape = tuple(data.shape[i] if i == axis % data.ndim else 1
                   for i in range(data.ndim))
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if training and not use_global_stats:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
    else:
        mean, var = moving_mean, moving_var
    inv = lax.rsqrt(var + eps)
    # f32 stats/affine, output cast back to the input dtype (keep a bf16
    # conv stream bf16 — see layer_norm below for why this matters)
    out = (x - mean.reshape(bshape)) * (inv * g).reshape(bshape) \
        + beta.reshape(bshape)
    out = out.astype(dt)
    if training and not use_global_stats:
        # batch stats go back in the RUNNING-stat dtype: a bf16-cast net
        # must not have its aux params drift to f32 after one step (that
        # would force a recompile and break checkpoint dtype round-trips)
        return (out, mean.astype(moving_mean.dtype),
                var.astype(moving_var.dtype))
    return out, moving_mean, moving_var


@register("LayerNorm", aliases=("layer_norm",))
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    """(reference: src/operator/nn/layer_norm.cc)

    Statistics and the affine transform run in f32, but the OUTPUT is cast
    back to the input dtype: fp32 gamma/beta must not promote a bf16
    activation stream to f32, or every downstream matmul silently runs at
    the MXU's f32 rate (~4x slower on v5e) — the mixed-precision contract
    of the reference's LayerNorm-with-AMP path."""
    dt = data.dtype
    x = data.astype(jnp.float32)
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps)
    bshape = tuple(data.shape[a] if a == axis % data.ndim else 1
                   for a in range(data.ndim))
    out = out * gamma.reshape(bshape).astype(jnp.float32) + \
        beta.reshape(bshape).astype(jnp.float32)
    return out.astype(dt)


@register("InstanceNorm", aliases=("instance_norm",))
def instance_norm(data, gamma, beta, eps=1e-3):
    """(reference: src/operator/instance_norm.cc); data NC+spatial.
    f32 stats, output in input dtype (see layer_norm)."""
    dt = data.dtype
    x = data.astype(jnp.float32)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (out * gamma.reshape(bshape) + beta.reshape(bshape)).astype(dt)


@register("GroupNorm", aliases=("group_norm",))
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    """(reference: src/operator/nn/group_norm.cc); data NCHW."""
    n, c = data.shape[:2]
    spatial = data.shape[2:]
    dt = data.dtype
    x = data.astype(jnp.float32).reshape(
        (n, num_groups, c // num_groups) + spatial)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (x * gamma.reshape(bshape) + beta.reshape(bshape)).astype(dt)


@register("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance"):
    """(reference: src/operator/l2_normalization.cc)"""
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, data.ndim))
    else:
        raise MXNetError(f"mode {mode}")
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / norm


@register("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response norm across channels (reference: src/operator/nn/lrn.cc)."""
    sq = jnp.square(data)
    pad = nsize // 2
    sq_pad = jnp.pad(sq, ((0, 0), (pad, pad)) + ((0, 0),) * (data.ndim - 2))
    windows = sum(sq_pad[:, i:i + data.shape[1]] for i in range(nsize))
    return data / jnp.power(knorm + alpha * windows / nsize, beta)


# --------------------------------------------------------------------- #
# activations
# --------------------------------------------------------------------- #
@register("Activation", aliases=("activation",))
def activation(data, act_type="relu"):
    """(reference: src/operator/nn/activation.cc)"""
    fns = {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
    }
    if act_type not in fns:
        raise MXNetError(f"unknown act_type {act_type!r}")
    return fns[act_type](data)


@register("LeakyReLU", needs_key=True, training_aware=True)
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, key=None, training=None):
    """leaky / prelu / elu / selu / gelu / rrelu
    (reference: src/operator/leaky_relu.cc)."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) \
            if gamma.ndim == 1 and data.ndim > 2 else gamma
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        return 1.0507009873554805 * jnp.where(
            data > 0, data, 1.6732632423543772 * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        if training:
            s = jax.random.uniform(key, data.shape, minval=lower_bound,
                                   maxval=upper_bound, dtype=data.dtype)
        else:
            s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, s * data)
    raise MXNetError(f"unknown act_type {act_type!r}")


@register("softmax", aliases=("Softmax",))
def softmax(data, axis=-1, temperature=None, length=None):
    """(reference: src/operator/nn/softmax.cc); optional masking by length."""
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    if length is not None:
        T = data.shape[axis]
        pos = jnp.arange(T)
        mask = pos[None, :] < length[:, None].astype(pos.dtype)
        shape = [1] * data.ndim
        shape[0] = data.shape[0]
        shape[axis % data.ndim] = T
        mask = mask.reshape(shape)
        data = jnp.where(mask, data, -jnp.inf)
        out = jax.nn.softmax(data, axis=axis)
        return jnp.where(mask, out, 0.0)
    return jax.nn.softmax(data, axis=axis)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.log_softmax(data, axis=axis)


@register("softmin")
def softmin(data, axis=-1):
    return jax.nn.softmax(-data, axis=axis)


@register("SoftmaxActivation")
def softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


@register("SoftmaxOutput", aliases=("softmax_output",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1,
                   use_ignore=False, multi_output=False, normalization="null",
                   smooth_alpha=0.0, out_grad=False, preserve_shape=False):
    """Softmax with cross-entropy gradient fused in backward
    (reference: src/operator/softmax_output.cc). Forward returns softmax;
    backward is (p - onehot(label)) * grad_scale via custom VJP."""
    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def _so(d, l):
        return jax.nn.softmax(d, axis=axis)

    def _fwd(d, l):
        p = jax.nn.softmax(d, axis=axis)
        return p, (p, l)

    def _bwd(res, g):
        p, l = res
        depth = p.shape[axis]
        l_primal = l      # cotangent must keep the ORIGINAL label shape
        # the reference accepts labels with a trailing singleton class
        # axis ((B, 1) from row-shaped iterators); squeeze it so the
        # one_hot gradient keeps the data's shape instead of
        # broadcasting (B,1,C) against (B,C)
        if l.ndim == p.ndim and l.shape[axis] == 1:
            l = jnp.squeeze(l, axis=axis)
        lab = l.astype(jnp.int32)
        oh = jax.nn.one_hot(lab, depth, dtype=p.dtype)
        if multi_output:
            oh = jnp.moveaxis(oh, -1, 1)
        grad = p - oh
        if smooth_alpha:
            grad = grad + smooth_alpha * (oh - 1.0 / depth)
        if use_ignore:
            keep = (l != ignore_label).astype(p.dtype)
            keep = jnp.expand_dims(keep, axis % p.ndim)
            grad = grad * keep
        scale = grad_scale
        if normalization == "batch":
            scale = scale / p.shape[0]
        elif normalization == "valid" and use_ignore:
            valid = jnp.maximum(jnp.sum(l != ignore_label), 1)
            scale = scale / valid
        return (grad * scale, jnp.zeros_like(l_primal))

    _so.defvjp(_fwd, _bwd)
    return _so(data, label)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    """(reference: src/operator/loss_binary_op.cc)"""
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return -jnp.sum(picked)


# --------------------------------------------------------------------- #
# dropout / embedding
# --------------------------------------------------------------------- #
@register("Dropout", aliases=("dropout",), needs_key=True, training_aware=True)
def dropout_op(data, p=0.5, mode="training", axes=(), key=None, training=None):
    """Inverted dropout with counter-based RNG
    (reference: src/operator/nn/dropout.cc; RNG parity — SURVEY.md §7.2)."""
    if (not training and mode != "always") or p == 0.0:
        return data
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1  # broadcast dropout (reference `axes` param)
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape)).astype(data.dtype)
    return data * mask / keep


@register("Embedding", aliases=("embedding",))
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    """Lookup table (reference: src/operator/tensor/indexing_op.cc Embedding).
    Lowers to one gather; on a sharded mesh the table shards row-wise and the
    gather rides XLA collectives (row_sparse_pull parity — SURVEY.md §2.3)."""
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0, mode="clip")


@register("CTCLoss", aliases=("ctc_loss",))
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """Connectionist temporal classification loss
    (reference: src/operator/nn/ctc_loss.cc). Layout: data (T, B, C) raw
    activations (softmax applied internally, matching the reference);
    label (B, L) padded with -1 (or 0 when blank is 'first' and labels are
    1-indexed... we follow the reference: padding value 0 with blank='first'
    means "shift labels by 1"; here padding is -1 unless label_lengths given).
    """
    T, B, C = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)  # (T,B,C)
    blank = 0 if blank_label == "first" else C - 1
    lab = label.astype(jnp.int32)
    if blank_label == "first" and not use_label_lengths:
        pass
    L = lab.shape[1]
    if use_label_lengths and label_lengths is not None:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        lab_len = jnp.sum((lab >= 0).astype(jnp.int32), axis=1)
        lab = jnp.where(lab >= 0, lab, 0)
    if use_data_lengths and data_lengths is not None:
        in_len = data_lengths.astype(jnp.int32)
    else:
        in_len = jnp.full((B,), T, dtype=jnp.int32)

    # extended label seq: blank, l1, blank, l2, ... blank  → length 2L+1
    S = 2 * L + 1
    ext = jnp.full((B, S), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    ext_valid = jnp.arange(S)[None, :] < (2 * lab_len + 1)[:, None]

    neg_inf = jnp.asarray(-1e30, dtype=logp.dtype)

    def emit(t_logp, s_idx):  # (B,C),(B,S)->(B,S)
        return jnp.take_along_axis(t_logp, s_idx, axis=1)

    # alpha recursion (forward algorithm) via lax.scan over time
    same_as_prev2 = jnp.concatenate(
        [jnp.zeros((B, 2), dtype=bool), ext[:, 2:] == ext[:, :-2]], axis=1)
    can_skip = jnp.logical_and(ext != blank, jnp.logical_not(same_as_prev2))

    init = jnp.full((B, S), neg_inf)
    init = init.at[:, 0].set(emit(logp[0], ext[:, :1])[:, 0])
    first_lab = jnp.where(lab_len > 0, emit(logp[0], ext[:, 1:2])[:, 0], neg_inf)
    init = init.at[:, 1].set(first_lab)

    def step(alpha, t):
        shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(can_skip, shift2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
        new_alpha = merged + emit(logp[t], ext)
        new_alpha = jnp.where(ext_valid, new_alpha, neg_inf)
        # positions beyond in_len keep previous alpha (sequence ended)
        active = (t < in_len)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        return new_alpha, None

    alpha, _ = lax.scan(step, init, jnp.arange(1, T))
    # final: sum of alpha at S-1 and S-2 positions (per true label length)
    sl = 2 * lab_len  # index of final blank
    a_last = jnp.take_along_axis(alpha, sl[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, jnp.maximum(sl - 1, 0)[:, None], axis=1)[:, 0]
    a_prev = jnp.where(lab_len > 0, a_prev, neg_inf)
    ll = jnp.logaddexp(a_last, a_prev)
    return -ll


@register("gelu")
def gelu(data, approximate=False):
    """Gaussian error linear unit (reference: leaky_relu.cc act_type='gelu';
    surfaced as a first-class op for transformer FFNs)."""
    return jax.nn.gelu(data, approximate=approximate)
