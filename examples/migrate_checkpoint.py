"""Migrate a reference MXNet 1.x checkpoint in and out.

Demonstrates the binary-compat path (SURVEY §5.4 "keep .params
read/write compat as a migration tool"; ref layouts:
`src/ndarray/ndarray.cc` NDArray::Save, nnvm json — file-level
citations, SURVEY.md caveat):

  1. writes a checkpoint PAIR in the reference layout
     (-symbol.json + -NNNN.params with arg:/aux: prefixes),
  2. loads it back through the auto-detecting loaders,
  3. verifies byte-level format + prediction identity,
  4. re-saves in the native MXTPU format.

With a real reference-written checkpoint, replace step 1 with your
files — the load path is identical.

    python examples/migrate_checkpoint.py
"""

import os
import struct
import tempfile

import numpy as np

# force CPU before any jax work so the example runs anywhere
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import nd  # noqa: E402


def main():
    # a small symbolic net, as a reference user would have built it
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    out = mx.sym.FullyConnected(act, name="fc2", num_hidden=4)

    rng = np.random.RandomState(0)
    arg_params = {
        "fc1_weight": nd.array(rng.randn(16, 8).astype(np.float32) * 0.1),
        "fc1_bias": nd.array(np.zeros(16, np.float32)),
        "fc2_weight": nd.array(rng.randn(4, 16).astype(np.float32) * 0.1),
        "fc2_bias": nd.array(np.zeros(4, np.float32)),
    }

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "net")
        # 1. write the REFERENCE layout
        mx.model.save_checkpoint(prefix, 0, out, arg_params, {},
                                 format="mxnet")
        raw = open(f"{prefix}-0000.params", "rb").read()
        assert struct.unpack("<Q", raw[:8])[0] == 0x112
        print(f"wrote reference-layout pair: {prefix}-symbol.json + "
              f"{prefix}-0000.params ({len(raw)} bytes, magic 0x112)")

        # 2. load back (format auto-detected)
        sym, arg, aux = mx.model.load_checkpoint(prefix, 0)
        x = nd.array(rng.randn(2, 8).astype(np.float32))
        ex = sym.bind(None, dict(arg, data=x))
        pred = ex.forward()[0].asnumpy()

        # 3. identity vs the original parameters
        ex0 = out.bind(None, dict(arg_params, data=x))
        np.testing.assert_allclose(pred, ex0.forward()[0].asnumpy(),
                                   rtol=1e-6)
        print(f"reloaded and verified: predictions identical, "
              f"shape {pred.shape}")

        # 4. re-save native
        nd.save(os.path.join(d, "native.params"), arg)
        print("re-saved in the native MXTPU format — migration done")


if __name__ == "__main__":
    main()
