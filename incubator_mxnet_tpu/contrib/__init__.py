"""``mx.contrib`` (parity: python/mxnet/contrib/). Quantization is the
main subsystem; ONNX import/export is gated (no onnx package in this
build — SURVEY.md §7.3 documented substitutions)."""

from . import quantization
from .quantization import quantize_net

__all__ = ["quantization", "quantize_net"]


def __getattr__(name):
    if name == "onnx":
        from ..base import MXNetError
        raise MXNetError(
            "contrib.onnx is not available: the onnx package is not part "
            "of this build. Use HybridBlock.export / SymbolBlock for "
            "native serialization.")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
