"""Tests for the infra shims: runtime features, engine, util, profiler, AMP,
mx.np / mx.npx (SURVEY.md §5.1/5.2/5.6 + §2.2 AMP/numpy rows)."""

import json
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd


def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("XLA")
    assert feats.is_enabled("CPU")
    assert "DIST_KVSTORE" in feats
    names = {f.name for f in mx.runtime.feature_list()}
    assert {"TPU", "PALLAS", "PROFILER", "AMP"} <= names


def test_engine_sync_mode_and_waitall():
    prev = mx.engine.set_sync(True)
    try:
        x = nd.ones((4, 4))
        y = (x * 2).sum()
        assert float(y.asnumpy()) == 32.0
    finally:
        mx.engine.set_sync(prev)
    mx.engine.wait_all()
    with mx.engine.bulk(16):
        assert nd.ones((2,)).shape == (2,)


def test_util_environment():
    assert os.environ.get("MXTPU_TEST_KNOB") is None
    with mx.util.environment("MXTPU_TEST_KNOB", "7"):
        assert os.environ["MXTPU_TEST_KNOB"] == "7"
        with mx.util.environment({"MXTPU_TEST_KNOB": None}):
            assert os.environ.get("MXTPU_TEST_KNOB") is None
    assert os.environ.get("MXTPU_TEST_KNOB") is None


def test_util_np_semantics():
    assert not mx.util.is_np_array()
    with mx.util.np_array(True):
        assert mx.util.is_np_array()
    mx.npx.set_np()
    assert mx.util.is_np_array() and mx.util.is_np_shape()
    mx.npx.reset_np()
    assert not mx.util.is_np_array()


def test_profiler_events_and_dump(tmp_path):
    prof = mx.profiler
    prof.set_config(filename=str(tmp_path / "trace.json"),
                    aggregate_stats=True)
    prof.start()
    with prof.scope("fwd"):
        nd.ones((8, 8)).sum().asnumpy()
    ev = prof.ProfileEvent("manual")
    ev.start()
    ev.stop()
    c = prof.Counter("batches")
    c.increment(3)
    prof.Marker("epoch_end").mark()
    prof.stop()
    path = prof.dump()
    with open(path) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"fwd", "manual", "batches", "epoch_end"} <= names
    table = prof.dumps(reset=True)
    assert "fwd" in table and "Calls" in table


def test_profiler_mfu():
    val = mx.profiler.mfu(1e12, 1.0, n_chips=1, peak_flops_per_chip=2e12)
    assert val == pytest.approx(0.5)


def test_amp_autocast_and_loss_scaler():
    from incubator_mxnet_tpu import amp

    amp.init("bfloat16")
    try:
        a = nd.ones((4, 8))
        b = nd.ones((8, 4))
        out = nd.dot(a, b)
        # autocast computes in bf16 but returns the widest input dtype
        assert out.dtype == np.float32
        assert np.allclose(out.asnumpy(), 8.0)
        # fp32-pinned op keeps behaviour on low-precision input
        sm = nd.softmax(nd.ones((2, 3), dtype="bfloat16"))
        assert str(sm.dtype) == "bfloat16"

        from incubator_mxnet_tpu import gluon
        net = gluon.nn.Dense(4, in_units=8)
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        amp.init_trainer(trainer)
        with autograd.record():
            loss = net(nd.ones((2, 8))).sum()
            with amp.scale_loss(loss, trainer) as scaled:
                scaled.backward()
        assert not amp.unscale(trainer)
        trainer.step(2)
    finally:
        amp._deinit_for_tests()


def test_loss_scaler_policy():
    from incubator_mxnet_tpu.amp import LossScaler

    s = LossScaler(init_scale=1024., scale_window=2)
    s.update_scale(overflow=True)
    assert s.loss_scale == 512.
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 1024.


def test_mx_np_forwarding_and_autograd():
    x = mx.np.array([[1., 2.], [3., 4.]])
    y = mx.np.exp(x)
    assert isinstance(y, nd.NDArray)
    assert np.allclose(y.asnumpy(), np.exp(x.asnumpy()))
    # tape integration: grad of sum(x**2) = 2x
    x.attach_grad()
    with autograd.record():
        z = mx.np.sum(mx.np.square(x))
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy())
    # non-array leading args fall back cleanly
    r = mx.np.arange(5)
    assert np.allclose(r.asnumpy(), np.arange(5))
    assert mx.np.pi == np.pi


def test_mx_npx_forwarding():
    x = nd.array(np.array([[-1., 2.]]))
    out = mx.npx.relu(x)
    assert np.allclose(out.asnumpy(), [[0., 2.]])
    sm = mx.npx.softmax(x)
    assert sm.shape == (1, 2)
    mx.npx.waitall()


def test_mx_np_random_surface():
    """mx.np.random — numpy.random-style API over the seeded stream."""
    mx.random.seed(0)
    r = mx.np.random
    assert r.rand(3, 4).shape == (3, 4)
    u = r.uniform(2.0, 4.0, size=(4000,)).asnumpy()
    assert 2.9 < u.mean() < 3.1 and u.min() >= 2.0
    b = r.beta(2.0, 5.0, size=(4000,)).asnumpy()
    assert 0.24 < b.mean() < 0.33 and 0.0 <= b.min() <= b.max() <= 1.0
    p = r.permutation(6).asnumpy()
    assert sorted(p.tolist()) == [0, 1, 2, 3, 4, 5]
    arr = mx.np.array([10.0, 20.0, 30.0, 40.0])
    cs = r.choice(arr, size=(3,), replace=False).asnumpy()
    assert len(set(cs.tolist())) == 3
    # weighted sampling without replacement: distinct draws (Gumbel top-k)
    cw = r.choice(5, size=(4,), replace=False,
                  p=[0.92, 0.02, 0.02, 0.02, 0.02]).asnumpy()
    assert len(set(cw.tolist())) == 4
    # numpy contracts: shuffle is in place and returns None; p must
    # match a; replace=False caps at the population size
    x = mx.np.array([0.0, 1.0, 2.0, 3.0, 4.0, 5.0])
    assert r.shuffle(x) is None
    assert sorted(x.asnumpy().tolist()) == [0, 1, 2, 3, 4, 5]
    import pytest as _pytest
    from incubator_mxnet_tpu.base import MXNetError as _E
    with _pytest.raises(_E):
        r.choice(10, size=(3,), p=[0.5, 0.5])
    with _pytest.raises(_E):
        r.choice(3, size=(5,), replace=False)
    r.seed(7)
    a1 = r.rand(4).asnumpy()
    r.seed(7)
    a2 = r.rand(4).asnumpy()
    assert (a1 == a2).all()


@pytest.mark.slow   # 8s (round-11 tier-1 budget repair); optimizer
                    # tier-1 coverage stays via test_fused_step;
                    # ci stage_unit runs it
def test_round5_optimizer_and_initializer_fills():
    """Adamax/Nadam/DCASGD/SGLD converge (SGLD stays finite — it's a
    sampler); Mixed/InitDesc/Load initializers behave per reference."""
    import numpy as np
    from incubator_mxnet_tpu import autograd, gluon, nd
    from incubator_mxnet_tpu.base import MXNetError

    mx.random.seed(0)
    for name in ("adamax", "nadam", "dcasgd", "sgld"):
        net = gluon.nn.Dense(4)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), name,
                           {"learning_rate": 0.05})
        X = nd.array(np.random.RandomState(0).randn(16, 6)
                     .astype(np.float32))
        Y = nd.array(np.random.RandomState(1).randn(16, 4)
                     .astype(np.float32))
        l2 = gluon.loss.L2Loss()
        first = last = None
        for _ in range(30):
            with autograd.record():
                l = l2(net(X), Y).mean()
            l.backward()
            tr.step(1)
            last = float(l.asnumpy())
            if first is None:
                first = last
        assert np.isfinite(last), name
        if name != "sgld":
            assert last < first, (name, first, last)

    ini = mx.initializer
    m = ini.Mixed([".*bias", ".*"], [ini.Zero(), ini.Constant(2.0)])
    a, b = nd.zeros((3,)), nd.zeros((2, 2))
    m("fc_bias", a)
    m("fc_weight", b)
    assert (a.asnumpy() == 0).all() and (b.asnumpy() == 2.0).all()
    saved = {"arg:w": nd.array(np.arange(4, dtype=np.float32)
                               .reshape(2, 2))}
    ld = ini.Load(saved, default_init=ini.Zero())
    w = nd.zeros((2, 2))
    ld("w", w)
    assert (w.asnumpy() == np.arange(4).reshape(2, 2)).all()
    import pytest as _pytest
    with _pytest.raises(MXNetError):
        ld("w", nd.zeros((3, 3)))


def test_check_consistency_reference_form():
    """check_consistency accepts the reference calling form (symbol +
    ctx-dict list, the fp16-vs-fp32 test_operator idiom) comparing
    forward outputs AND gradients at dtype-scaled tolerance."""
    import numpy as np
    from incubator_mxnet_tpu import test_utils as tu

    sym = mx.sym.FullyConnected(mx.sym.Variable("data"), name="fc",
                                num_hidden=4)
    tu.check_consistency(sym, [
        {"ctx": mx.cpu(), "data": (2, 3),
         "type_dict": {"data": np.float32}},
        {"ctx": mx.cpu(), "data": (2, 3),
         "type_dict": {"data": np.float16}},
    ])
    with pytest.raises(mx.MXNetError, match="must agree on shapes"):
        tu.check_consistency(sym, [
            {"ctx": mx.cpu(), "data": (2, 3)},
            {"ctx": mx.cpu(), "data": (2, 4)},
        ])


def test_check_consistency_multi_output_and_int_inputs():
    """Reference-form check_consistency handles multi-output symbols
    (synthesized unit head grads) and integer-typed inputs (valid
    indices synthesized; float0 tangents excluded from comparison)."""
    import numpy as np
    from incubator_mxnet_tpu import test_utils as tu

    ms = mx.sym.split(mx.sym.Variable("data"), num_outputs=2, axis=1)
    tu.check_consistency(ms, [
        {"ctx": mx.cpu(), "data": (2, 4)},
        {"ctx": mx.cpu(), "data": (2, 4),
         "type_dict": {"data": np.float16}},
    ])
    es = mx.sym.Embedding(mx.sym.Variable("data"), name="emb",
                          input_dim=4, output_dim=3)
    tu.check_consistency(es, [
        {"ctx": mx.cpu(), "data": (5,), "type_dict": {"data": np.int32}},
        {"ctx": mx.cpu(), "data": (5,), "type_dict": {"data": np.int32}},
    ])
