"""``mx.npx`` — numpy-extension namespace (re-design of
`python/mxnet/numpy_extension/`; file-level citation — SURVEY.md caveat).

The reference's ``npx`` holds the neural-network ops that have no NumPy
equivalent (relu, softmax, batch_norm, convolution, …) plus the
``set_np``/``reset_np`` semantics switches. Here every registry op is
already numpy-friendly, so ``npx`` forwards by name to the ``mx.nd``
namespace and re-exports the semantics toggles from ``mx.util``.
"""

from __future__ import annotations

from . import ndarray as _nd
from .util import (is_np_array, is_np_shape, np_array, np_shape, reset_np,
                   set_np, set_np_shape, use_np)

__all__ = ["set_np", "reset_np", "set_np_shape", "is_np_array",
           "is_np_shape", "use_np", "np_array", "np_shape", "waitall",
           "cpu", "gpu", "tpu", "num_gpus", "current_context"]

from .context import cpu, gpu, tpu, num_gpus, current_context  # noqa: E402


def waitall():
    """Parity: ``mx.npx.waitall``."""
    from .engine import wait_all

    wait_all()


# snake_case names whose registry spelling has irregular capitalization
_IRREGULAR_CAMEL = {
    "leaky_relu": "LeakyReLU", "lrn": "LRN", "rnn": "RNN",
    "roi_pooling": "ROIPooling", "roi_align": "ROIAlign",
    "ctc_loss": "CTCLoss", "l2_normalization": "L2Normalization",
    "svm_output": "SVMOutput",
}


def __getattr__(name: str):
    # registry-backed nn ops: npx.relu, npx.softmax, npx.batch_norm …
    attr = getattr(_nd, name, None)
    if attr is not None:
        return attr
    # snake_case → CamelCase registry aliases (npx.batch_norm → BatchNorm)
    camel = _IRREGULAR_CAMEL.get(
        name, "".join(p.capitalize() for p in name.split("_")))
    attr = getattr(_nd, camel, None)
    if attr is not None:
        return attr
    raise AttributeError(f"mx.npx has no attribute {name!r}")
