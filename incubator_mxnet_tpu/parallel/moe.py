"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

The reference has no MoE (SURVEY.md §2.3 reserves the axis); this is a
TPU-native capability beyond it, in the Mesh-TensorFlow/Switch
formulation the scaling-book prescribes: routing is expressed as dense
one-hot einsums (MXU-friendly, fully differentiable, static shapes) and
the expert dimension is sharded over ``ep`` with
``lax.with_sharding_constraint`` — XLA inserts the token all_to_all on
ICI between the batch-sharded token layout and the expert-sharded
expert layout. No per-token control flow anywhere.

Top-1 (Switch) routing with capacity: tokens over an expert's capacity
are DROPPED (output zero — the caller's residual connection carries
them), the Switch-Transformer contract. The auxiliary load-balancing
loss (E * Σ_e fraction_e * mean_prob_e) is returned for the caller to
add to the objective.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = ["switch_moe", "stack_expert_params"]


def stack_expert_params(param_trees):
    """Stack E per-expert pytrees on a leading expert dim (shard it
    ``P('ep', ...)``)."""
    if not param_trees:
        raise MXNetError("stack_expert_params needs at least one expert")
    return jtu.tree_map(lambda *xs: jnp.stack(xs), *param_trees)


def _constrain(x, mesh, *spec):
    from .spmd import constrain
    return constrain(x, *spec, mesh=mesh)


def switch_moe(x, gate_logits, expert_fn: Callable, expert_params,
               capacity_factor: float = 1.25, mesh: Optional[Mesh] = None,
               axis: str = "ep", token_axis: str = "dp"):
    """Top-1 sparse MoE layer.

    x: (N, D) tokens (flatten batch×seq first); gate_logits: (N, E);
    expert_fn(params_slice, h (C, D)) -> (C, D) — one expert's FFN;
    expert_params: pytree with leading expert dim E.

    Returns (out (N, D), aux_loss scalar). Dropped tokens come back as
    zeros — add the layer's residual around it."""
    N, D = x.shape
    E = gate_logits.shape[-1]
    C = max(1, math.ceil(N / E * capacity_factor))

    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                    # (N,)
    gate = jnp.take_along_axis(probs, expert_idx[:, None],
                               axis=-1)[:, 0]                  # (N,)

    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # (N, E)
    # position of each token in its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0            # (N, E)
    keep = (pos < C) * onehot                                  # (N, E)
    dispatch = keep[..., None] * jax.nn.one_hot(
        jnp.clip(pos, 0, C - 1).astype(jnp.int32), C,
        dtype=jnp.float32)                                     # (N, E, C)

    # tokens (batch-sharded) → expert-major layout (ep-sharded): XLA
    # lowers the layout change to an all_to_all on ICI
    expert_in = jnp.einsum("nec,nd->ecd", dispatch,
                           x.astype(jnp.float32))              # (E, C, D)
    expert_in = _constrain(expert_in, mesh, axis, None, None)
    expert_params = jtu.tree_map(
        lambda p: _constrain(p, mesh, axis,
                             *([None] * (p.ndim - 1))), expert_params)
    expert_out = jax.vmap(expert_fn)(expert_params,
                                     expert_in.astype(x.dtype))
    expert_out = _constrain(expert_out.astype(jnp.float32), mesh, axis,
                            None, None)
    out = jnp.einsum("nec,ecd->nd", dispatch, expert_out)      # (N, D)
    out = _constrain(out, mesh, token_axis, None)
    out = out * gate[:, None]

    # Switch load-balancing auxiliary loss
    frac_tokens = onehot.mean(axis=0)                          # (E,)
    mean_prob = probs.mean(axis=0)                             # (E,)
    aux = E * jnp.sum(frac_tokens * mean_prob)
    return out.astype(x.dtype), aux
