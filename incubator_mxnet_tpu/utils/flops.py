"""Analytic FLOPs and MFU accounting (round 16, docs/TRAINING_PERF.md).

MFU (model FLOPs utilization) is the honest throughput number: the
analytic FLOPs a training step MUST perform (matmuls of the model's
math, nothing the implementation happens to add — recompute under
remat, the optimizer, casts and copies all count as ZERO) divided by
what the hardware could have done in the same wall time. The SNIPPETS
north-star is BERT-large pretraining at >= 45% MFU; this module is how
every training PR banks its number next to tokens/s.

FLOPs formulas (the PaLM-appendix convention, counting a multiply-add
as 2 FLOPs):

  forward  per token ≈ 2·P  +  4·L·T·d       (params + attention scores)
  backward ≈ 2× forward
  train    per token ≈ 6·P  + 12·L·T·d

where P counts the MATMUL-VISIBLE parameters: embedding tables are
excluded from the 2·P term (a lookup is a gather, not a matmul) but a
tied LM head re-enters as a full d×V matmul. Both model helpers below
build the terms from the model's own dims, so step_bench computes MFU
from the same run that banks tokens/s.

Peak FLOPs honesty (the CPU caveat, docs/TRAINING_PERF.md): on TPU the
per-chip peak is a datasheet constant and MFU is absolute. On the CPU
backend there is no meaningful datasheet peak, so ``peak_flops_per_
device`` measures a sustained large-matmul rate once per process and
uses it as a PROXY ceiling — CPU MFU is a relative regression number
(comparable across arms of one bench run on one box), never a
hardware-utilization claim. ``MXTPU_PEAK_FLOPS`` overrides both paths.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["transformer_train_flops", "gpt_train_flops",
           "bert_train_flops", "model_train_flops", "count_params",
           "peak_flops_per_device", "mfu"]

# bf16 peak FLOPs per chip by TPU generation (datasheet numbers; the
# device_kind strings match jax.devices()[0].device_kind). Runtimes have
# reported the same chip under several spellings across libtpu releases
# ("TPU v5 lite" vs "TPU v5e", "TPU v6 lite" vs "TPU v6e", "TPU v5" for
# v5p pods), so each generation lists its known variants — matching is
# longest-prefix so "TPU v5 lite" never falls into the bare "TPU v5" row.
_TPU_PEAK_BF16 = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5litepod": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU v6": 918e12,
}

_CPU_PEAK_CACHE: Optional[float] = None


def transformer_train_flops(n_matmul_params: int, n_layers: int,
                            units: int, seq_len: int,
                            tokens: int) -> float:
    """Forward+backward FLOPs for ``tokens`` tokens of a transformer
    with ``n_matmul_params`` matmul-visible parameters: ``6·P·tokens``
    for the parameter matmuls plus ``12·L·T·d·tokens`` for attention
    score/value products (fwd 2 + bwd 4 of each (T,d)x(d,T) pair)."""
    return float(tokens) * (6.0 * n_matmul_params
                            + 12.0 * n_layers * seq_len * units)


def count_params(block, trainable_only: bool = True) -> int:
    """Total parameter count of an initialized block."""
    total = 0
    for p in block.collect_params().values():
        if trainable_only and p.grad_req == "null":
            continue
        n = 1
        for s in p.shape:
            n *= s
        total += n
    return total


def _matmul_params(model, embed_names=("word_embed", "position_embed",
                                       "token_type_embed")) -> int:
    """Parameter count entering matmuls: everything except embedding
    lookups (the tied LM head is added back by the caller)."""
    embeds = 0
    for name in embed_names:
        child = getattr(model, name, None)
        if child is None:
            continue
        for p in child.collect_params().values():
            n = 1
            for s in p.shape:
                n *= s
            embeds += n
    return count_params(model, trainable_only=False) - embeds


def gpt_train_flops(model, batch: int, seq_len: int) -> float:
    """Analytic fwd+bwd FLOPs for one ``GPTModel`` training step over a
    ``(batch, seq_len)`` token grid. The tied LM head (logits = x @ Eᵀ)
    is a real d×V matmul, so the word-embedding table re-enters P."""
    p_mm = _matmul_params(model)
    p_mm += model.vocab_size * model._units          # tied LM head
    return transformer_train_flops(p_mm, model.num_layers,
                                   model._units, seq_len,
                                   batch * seq_len)


def bert_train_flops(model, batch: int, seq_len: int,
                     mlm_head: bool = True) -> float:
    """Analytic fwd+bwd FLOPs for one BERT pretraining step
    (``BERTModel`` or ``BERTForPretraining``). The MLM head's decode
    matmul (d×V, tied) dominates the heads; the NSP/pooler terms ride
    in the generic param count."""
    bert = getattr(model, "bert", model)
    p_mm = _matmul_params(bert)
    extra = count_params(model, trainable_only=False) - \
        count_params(bert, trainable_only=False)
    p_mm += max(extra, 0)
    if mlm_head:
        p_mm += bert.vocab_size * bert._units        # tied MLM decode
    return transformer_train_flops(p_mm, bert.num_layers, bert._units,
                                   seq_len, batch * seq_len)


def model_train_flops(model, batch: int, seq_len: int) -> float:
    """Dispatch on the model family (gpt/bert) — the per-model analytic
    FLOPs hook step_bench and trace_summary share."""
    name = type(model).__name__
    if "GPT" in name:
        return gpt_train_flops(model, batch, seq_len)
    if "BERT" in name:
        return bert_train_flops(model, batch, seq_len)
    raise ValueError(
        f"no analytic FLOPs formula for {name}; supported: GPTModel, "
        f"BERTModel/BERTForPretraining (add one in utils/flops.py)")


def _measure_cpu_peak() -> float:
    """Sustained large-matmul f32 rate on the current backend — the CPU
    MFU proxy ceiling (see module docstring). One-time cost ~0.5 s."""
    import time

    import jax
    import jax.numpy as jnp

    n = 1024
    a = jnp.ones((n, n), jnp.float32)
    b = jnp.ones((n, n), jnp.float32)
    f = jax.jit(lambda x, y: x @ y)
    jax.block_until_ready(f(a, b))                   # compile + warm
    reps = 8
    t0 = time.perf_counter()
    out = a
    for _ in range(reps):
        out = f(out, b)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    return reps * 2.0 * n ** 3 / max(dt, 1e-9)


def peak_flops_per_device() -> dict:
    """Per-device peak FLOPs and its provenance:
    ``{"flops": float, "source": "env"|"tpu-datasheet"|"cpu-proxy",
    "device_kind": str}``. ``MXTPU_PEAK_FLOPS`` overrides."""
    import jax

    dev = jax.devices()[0]
    kind = dev.device_kind
    env = os.environ.get("MXTPU_PEAK_FLOPS")
    if env:
        return {"flops": float(env), "source": "env",
                "device_kind": kind}
    # longest-prefix match so variant spellings ("TPU v5 lite") never
    # fall into a shorter generation row ("TPU v5")
    for k in sorted(_TPU_PEAK_BF16, key=len, reverse=True):
        if kind.lower().startswith(k.lower()):
            return {"flops": _TPU_PEAK_BF16[k], "source": "tpu-datasheet",
                    "device_kind": kind}
    if dev.platform != "cpu":
        # an accelerator we have no datasheet row for: the cpu-proxy
        # ceiling below would silently bank nonsense MFU, so say so
        # loudly and name the escape hatch
        import warnings
        warnings.warn(
            f"no peak-FLOPs datasheet entry for device_kind={kind!r} "
            f"(platform={dev.platform!r}); falling back to a measured "
            f"matmul-rate proxy ceiling, so MFU numbers are NOT a "
            f"hardware-utilization claim. Set MXTPU_PEAK_FLOPS to the "
            f"chip's bf16 peak (FLOPs/s) or add a row to "
            f"utils/flops.py:_TPU_PEAK_BF16.",
            RuntimeWarning, stacklevel=2)
    global _CPU_PEAK_CACHE
    if _CPU_PEAK_CACHE is None:
        _CPU_PEAK_CACHE = _measure_cpu_peak()
    return {"flops": _CPU_PEAK_CACHE, "source": "cpu-proxy",
            "device_kind": kind}


def mfu(step_flops: float, step_seconds: float, n_devices: int,
        peak: Optional[dict] = None) -> dict:
    """Achieved-FLOPs/peak-FLOPs per device for one step: the fields
    every BENCH_MFU arm banks (docs/TRAINING_PERF.md)."""
    peak = peak or peak_flops_per_device()
    achieved = step_flops / max(step_seconds, 1e-12) / max(n_devices, 1)
    return {
        "model_flops_per_step": step_flops,
        "achieved_flops_per_device": achieved,
        "peak_flops_per_device": peak["flops"],
        "peak_source": peak["source"],
        "mfu": achieved / peak["flops"],
    }
