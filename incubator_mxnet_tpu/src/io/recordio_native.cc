// Native RecordIO engine: index scan + multi-threaded batch reads off the
// Python thread.
//
// TPU-native replacement for the reference's native IO layer
// (dmlc recordio `3rdparty/dmlc-core/src/recordio.cc` + the reader/parser
// thread pool of `src/io/iter_image_recordio_2.cc`; file-level citations —
// SURVEY.md caveat §3.5). Same on-disk format as io/recordio.py:
//   record := magic(u32)=0xced7230a | cflag_len(u32) | payload | pad to 4B
//
// Exposed as a minimal C ABI consumed via ctypes (no pybind11 in the
// image). All reads use pread so one handle serves many threads; the batch
// call fans out across a small thread pool, which is where the win over
// the pure-Python path comes from (GIL-free file IO + splitting).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Handle {
  int fd = -1;
  int64_t file_size = 0;
  std::vector<int64_t> offsets;  // record start offsets (header position)
  std::vector<int64_t> lengths;  // payload lengths
};

int64_t PayloadAt(const Handle* h, int64_t offset, int64_t* length_out) {
  uint32_t header[2];
  if (pread(h->fd, header, 8, offset) != 8) return -1;
  if (header[0] != kMagic) return -2;
  *length_out = static_cast<int64_t>(header[1] & kLenMask);
  return offset + 8;
}

}  // namespace

extern "C" {

void* mxtpu_rio_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  Handle* h = new Handle();
  h->fd = fd;
  h->file_size = st.st_size;
  return h;
}

void mxtpu_rio_close(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  if (h == nullptr) return;
  if (h->fd >= 0) close(h->fd);
  delete h;
}

// Scan the whole file once, recording every record's offset+length.
// Returns the record count, or a negative errno-style code.
int64_t mxtpu_rio_scan(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  if (h == nullptr) return -1;
  h->offsets.clear();
  h->lengths.clear();
  // buffered sequential scan
  constexpr int64_t kChunk = 8 << 20;
  std::vector<char> buf(kChunk);
  int64_t pos = 0;
  while (pos + 8 <= h->file_size) {
    uint32_t header[2];
    if (pread(h->fd, header, 8, pos) != 8) return -2;
    if (header[0] != kMagic) return -3;
    int64_t len = static_cast<int64_t>(header[1] & kLenMask);
    h->offsets.push_back(pos);
    h->lengths.push_back(len);
    int64_t padded = (len + 3) & ~int64_t{3};
    pos += 8 + padded;
  }
  (void)buf;
  return static_cast<int64_t>(h->offsets.size());
}

int64_t mxtpu_rio_count(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  return h ? static_cast<int64_t>(h->offsets.size()) : -1;
}

// Copy scan results out (cap = capacity of each array).
int64_t mxtpu_rio_index(void* handle, int64_t* offsets, int64_t* lengths,
                        int64_t cap) {
  Handle* h = static_cast<Handle*>(handle);
  if (h == nullptr) return -1;
  int64_t n = std::min<int64_t>(cap, h->offsets.size());
  std::memcpy(offsets, h->offsets.data(), n * sizeof(int64_t));
  std::memcpy(lengths, h->lengths.data(), n * sizeof(int64_t));
  return n;
}

// Read one payload at a header offset. Returns payload length or negative.
int64_t mxtpu_rio_read_at(void* handle, int64_t offset, char* out,
                          int64_t cap) {
  Handle* h = static_cast<Handle*>(handle);
  if (h == nullptr) return -1;
  int64_t len = 0;
  int64_t payload_off = PayloadAt(h, offset, &len);
  if (payload_off < 0) return payload_off;
  if (len > cap) return -4;
  int64_t got = pread(h->fd, out, len, payload_off);
  return got == len ? len : -5;
}

// Read n records (by header offsets) into one contiguous buffer using a
// thread pool. out_lens[i] receives each payload length; payloads are
// packed back-to-back in request order. Returns total bytes or negative.
int64_t mxtpu_rio_read_batch(void* handle, const int64_t* offsets, int64_t n,
                             char* out, int64_t cap, int64_t* out_lens,
                             int64_t n_threads) {
  Handle* h = static_cast<Handle*>(handle);
  if (h == nullptr) return -1;
  std::vector<int64_t> lens(n), payload_offs(n), starts(n);
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t len = 0;
    int64_t poff = PayloadAt(h, offsets[i], &len);
    if (poff < 0) return poff;
    lens[i] = len;
    payload_offs[i] = poff;
    starts[i] = total;
    total += len;
  }
  if (total > cap) return -4;

  n_threads = std::max<int64_t>(1, std::min<int64_t>(n_threads, n));
  std::atomic<int64_t> next{0};
  std::atomic<bool> ok{true};
  auto worker = [&]() {
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= n || !ok.load()) break;
      int64_t got = pread(h->fd, out + starts[i], lens[i], payload_offs[i]);
      if (got != lens[i]) ok.store(false);
    }
  };
  std::vector<std::thread> pool;
  for (int64_t t = 1; t < n_threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& t : pool) t.join();
  if (!ok.load()) return -5;
  std::memcpy(out_lens, lens.data(), n * sizeof(int64_t));
  return total;
}

}  // extern "C"
