"""Data pipeline tests: RecordIO (pure + native), datasets, DataLoader,
iterators (SURVEY.md §4: synthetic fixtures, no network)."""

import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.gluon.data import (ArrayDataset, DataLoader,
                                            SimpleDataset)
from incubator_mxnet_tpu.gluon.data.vision import MNIST, transforms
from incubator_mxnet_tpu.io import (DataBatch, ImageRecordIter, MNISTIter,
                                    NDArrayIter, recordio)


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [bytes([i]) * (i * 7 + 1) for i in range(20)]
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for expected in payloads:
        assert r.read() == expected
    assert r.read() is None


def test_indexed_recordio_and_native_reader(tmp_path):
    path = str(tmp_path / "idx.rec")
    idx_path = str(tmp_path / "idx.idx")
    w = recordio.IndexedRecordIO(idx_path, path, "w")
    for i in range(50):
        w.write_idx(i, f"record-{i}".encode() * (i % 5 + 1))
    w.close()

    r = recordio.IndexedRecordIO(idx_path, path, "r")
    assert r.read_idx(17) == b"record-17" * 3

    # native reader agrees with the python reader
    try:
        from incubator_mxnet_tpu.io._native import NativeRecordReader
        native = NativeRecordReader(path)
    except (RuntimeError, OSError):
        pytest.skip("native IO lib unavailable")
    assert len(native) == 50
    assert native.read(17) == b"record-17" * 3
    batch = native.read_batch([3, 17, 42])
    assert batch[1] == b"record-17" * 3
    assert batch[0] == b"record-3" * 4
    assert batch[2] == b"record-42" * 3


def test_pack_unpack_with_label():
    header = recordio.IRHeader(0, [1.0, 2.0, 3.0], 7, 0)
    s = recordio.pack(header, b"payload")
    h2, payload = recordio.unpack(s)
    np.testing.assert_allclose(h2.label, [1.0, 2.0, 3.0])
    assert payload == b"payload"


def test_image_record_iter(tmp_path):
    from incubator_mxnet_tpu.io.recordio import (IRHeader, IndexedRecordIO,
                                                 pack_img)
    prefix = str(tmp_path / "imgs")
    rng = np.random.RandomState(0)
    w = IndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(12):
        img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
        w.write_idx(i, pack_img(IRHeader(0, float(i % 3), i, 0), img))
    w.close()

    it = ImageRecordIter(path_imgrec=prefix + ".rec", data_shape=(3, 8, 8),
                         batch_size=4, shuffle=True)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 3, 8, 8)
    assert batches[0].label[0].shape == (4,)
    it.reset()
    assert len(list(it)) == 3


def test_ndarray_iter_pad_and_discard():
    data = np.arange(10 * 3, dtype=np.float32).reshape(10, 3)
    it = NDArrayIter(data, np.arange(10), batch_size=4,
                     last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    it2 = NDArrayIter(data, np.arange(10), batch_size=4,
                      last_batch_handle="discard")
    assert len(list(it2)) == 2


def test_mnist_synthetic_and_iter():
    ds = MNIST(root="/nonexistent", train=True, synthetic=True,
               synthetic_size=64)
    img, label = ds[0]
    assert img.shape == (28, 28, 1) and 0 <= int(label) < 10
    it = MNISTIter(image="/nonexistent/train-images", batch_size=16)
    b = next(iter(it))
    assert b.data[0].shape == (16, 1, 28, 28)


def test_dataset_transform_and_loader():
    xs = np.arange(20, dtype=np.float32).reshape(20, 1)
    ys = (np.arange(20) % 2).astype(np.int32)
    ds = ArrayDataset(xs, ys)
    tds = ds.transform_first(lambda x: x * 2)
    x0, y0 = tds[1]
    assert float(np.asarray(x0).reshape(())) == 2.0

    loader = DataLoader(tds, batch_size=5, shuffle=True)
    seen = 0
    for data, label in loader:
        assert data.shape == (5, 1)
        seen += data.shape[0]
    assert seen == 20


def test_dataloader_workers():
    xs = np.arange(16, dtype=np.float32)
    ds = SimpleDataset(xs.tolist())
    loader = DataLoader(ds, batch_size=4, num_workers=2, thread_pool=True)
    total = sum(float(b.asnumpy().sum()) for b in loader)
    assert total == xs.sum()


def test_transforms_compose():
    img = (np.random.RandomState(0).rand(10, 12, 3) * 255).astype(np.uint8)
    t = transforms.Compose([
        transforms.Resize(8),
        transforms.ToTensor(),
        transforms.Normalize([0.5, 0.5, 0.5], [0.5, 0.5, 0.5]),
    ])
    out = t(img)
    assert out.shape == (3, 8, 8)
    assert out.dtype == np.float32


def test_im2rec_tool(tmp_path):
    import subprocess
    import sys
    root = tmp_path / "imgs"
    (root / "cat").mkdir(parents=True)
    (root / "dog").mkdir()
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        for i in range(3):
            np.save(root / cls / f"{i}.npy",
                    (rng.rand(6, 6, 3) * 255).astype(np.uint8))
    prefix = str(tmp_path / "pack")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "im2rec.py"),
         prefix, str(root), "--recursive"],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stderr
    it = ImageRecordIter(path_imgrec=prefix + ".rec", data_shape=(3, 6, 6),
                         batch_size=2)
    b = next(iter(it))
    assert b.data[0].shape == (2, 3, 6, 6)


def test_ndarray_iter_roll_over():
    # 10 samples, bs=4: epoch1 emits 2 full batches, 2 samples roll over;
    # epoch2's first batch = 2 rolled + 2 new
    data = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = mx.io.NDArrayIter(data, batch_size=4, last_batch_handle="roll_over")
    e1 = [b.data[0].asnumpy() for b in it]
    assert len(e1) == 2 and all(b.shape == (4, 1) for b in e1)
    it.reset()
    e2 = [b.data[0].asnumpy() for b in it]
    assert len(e2) == 3 and all(b.shape == (4, 1) for b in e2)
    assert set(e2[0].ravel()) == {8., 9., 0., 1.}


def test_prefetching_iter_mid_epoch_reset_and_exhaustion():
    from incubator_mxnet_tpu.io import PrefetchingIter

    data = np.arange(32, dtype=np.float32).reshape(32, 1)
    it = PrefetchingIter(mx.io.NDArrayIter(data, batch_size=4))
    first = it.next()
    assert first.data[0].shape == (4, 1)
    it.reset()  # mid-epoch: must not deadlock or duplicate producers
    batches = list(it)
    assert len(batches) == 8
    # exhausted iterator raises StopIteration repeatedly, never blocks
    with pytest.raises(StopIteration):
        it.next()
    with pytest.raises(StopIteration):
        it.next()
    it.reset()
    assert len(list(it)) == 8


def test_dataloader_workers_prefetch_zero():
    ds = ArrayDataset(np.arange(12, dtype=np.float32).reshape(12, 1))
    loader = DataLoader(ds, batch_size=4, num_workers=2, prefetch=0)
    assert len(list(loader)) == 3


def test_dataloader_process_workers_never_fork():
    # forking the JAX-threaded parent risks a worker deadlocking in a
    # copied lock; the default process context must be fork-free (the
    # reference needed fork handlers in src/initialize.cc for this)
    import warnings

    ds = ArrayDataset(np.arange(48, dtype=np.float32).reshape(24, 2))
    with warnings.catch_warnings():
        # CPython emits fork-in-multithreaded-process as
        # DeprecationWarning (3.12) / RuntimeWarning (earlier)
        warnings.simplefilter("error", RuntimeWarning)
        warnings.simplefilter("error", DeprecationWarning)
        loader = DataLoader(ds, batch_size=4, num_workers=2)
        assert loader._pool._ctx.get_start_method() in ("forkserver",
                                                        "spawn")
        batches = list(loader)
    assert len(batches) == 6
    np.testing.assert_allclose(
        np.concatenate([b.asnumpy() for b in batches]),
        np.arange(48, dtype=np.float32).reshape(24, 2))


def test_image_record_iter_small_images(tmp_path):
    # images smaller than data_shape must be upsized, not crash np.stack
    from incubator_mxnet_tpu.io.recordio import IRHeader, IndexedRecordIO, \
        pack_img

    path = str(tmp_path / "small.rec")
    idx = str(tmp_path / "small.idx")
    w = IndexedRecordIO(idx, path, "w")
    rng = np.random.RandomState(0)
    for i in range(4):
        img = rng.randint(0, 255, (20, 20, 3), np.uint8)
        w.write_idx(i, pack_img(IRHeader(0, float(i % 2), i, 0), img,
                                quality=90))
    w.close()
    it = ImageRecordIter(path_imgrec=path, path_imgidx=idx,
                         data_shape=(3, 28, 28), batch_size=2)
    batch = next(iter(it))
    assert batch.data[0].shape == (2, 3, 28, 28)


def test_recordio_pickle_closed_reader(tmp_path):
    import pickle

    from incubator_mxnet_tpu.io.recordio import MXRecordIO

    path = str(tmp_path / "p.rec")
    w = MXRecordIO(path, "w")
    w.write(b"hello")
    w.close()
    r = MXRecordIO(path, "r")
    r.close()
    r2 = pickle.loads(pickle.dumps(r))
    assert r2.read() == b"hello"


def test_cifar100_and_image_record_dataset(tmp_path):
    from incubator_mxnet_tpu.gluon.data import vision
    from incubator_mxnet_tpu.io import recordio

    d = vision.CIFAR100(synthetic=True, synthetic_size=64)
    x, y = d[3]
    assert x.shape == (32, 32, 3) and 0 <= int(y) < 100 and len(d) == 64

    # build a tiny im2rec-style .rec/.idx with NPY0-raw images
    rec = str(tmp_path / "toy.rec")
    idx = str(tmp_path / "toy.idx")
    w = recordio.IndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    imgs = []
    for i in range(5):
        img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
        imgs.append(img)
        # NPY0 codec (image.decode_to_numpy): magic + np.save payload
        import io as _io
        bio = _io.BytesIO()
        np.save(bio, img)
        payload = b"NPY0" + bio.getvalue()
        hdr = recordio.IRHeader(0, float(i), i, 0)
        w.write_idx(i, recordio.pack(hdr, payload))
    w.close()

    ds = vision.ImageRecordDataset(rec)
    assert len(ds) == 5
    img, label = ds[2]
    assert int(label) == 2
    np.testing.assert_array_equal(np.asarray(img), imgs[2])


def test_native_jpeg_batch_decode_matches_cv2():
    """Native C++ thread-pool JPEG decode+resize (mx.image.
    imdecode_resize_batch) must match the cv2 decode+INTER_LINEAR path
    within JPEG-codec tolerance, and reject malformed payloads."""
    cv2 = pytest.importorskip("cv2")
    from incubator_mxnet_tpu import image as mximg
    from incubator_mxnet_tpu.io import _native_image as ni
    if ni.lib() is None:
        pytest.skip("native image lib unavailable")

    rng = np.random.RandomState(0)
    payloads = []
    refs = []
    for h, w in [(40, 56), (72, 72), (33, 49)]:
        img = (rng.rand(h, w, 3) * 255).astype(np.uint8)
        ok, buf = cv2.imencode(".jpg", img[:, :, ::-1])
        assert ok
        payloads.append(buf.tobytes())
        dec = cv2.imdecode(buf, cv2.IMREAD_COLOR)[:, :, ::-1]
        refs.append(cv2.resize(dec, (24, 24),
                               interpolation=cv2.INTER_LINEAR))
    out = mximg.imdecode_resize_batch(payloads, 24, 24)
    assert out.shape == (3, 24, 24, 3) and out.dtype == np.uint8
    for got, ref in zip(out, refs):
        assert np.abs(got.astype(int) - ref.astype(int)).max() <= 2

    from incubator_mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        mximg.imdecode_resize_batch([b"not-an-image"], 8, 8)

    # payloads the native engine rejects re-run through the Python
    # chain transparently (NPY0 raw buffer mixed into a JPEG batch)
    raw = (rng.rand(20, 30, 3) * 255).astype(np.uint8)
    import io as _io
    bio = _io.BytesIO()
    np.save(bio, raw)
    npy_payload = b"NPY0" + bio.getvalue()
    mixed = mximg.imdecode_resize_batch([payloads[0], npy_payload], 24, 24)
    assert mixed.shape == (2, 24, 24, 3)

    # dims probe
    w_, h_ = ni.image_dims(payloads[0])
    assert (w_, h_) == (56, 40)


def test_libsvm_iter_and_io_aliases(tmp_path):
    """LibSVMIter emits CSR batches; reference alias names resolve."""
    from incubator_mxnet_tpu.io import (ImageDetRecordIter, LibSVMIter,
                                        MXIndexedRecordIO)
    from incubator_mxnet_tpu.ndarray.sparse import CSRNDArray

    p = tmp_path / "data.libsvm"
    p.write_text("1 0:1.5 3:2.0\n0 1:0.5\n1 2:3.0 3:1.0\n0 0:2.5\n")
    it = LibSVMIter(data_libsvm=str(p), data_shape=(4,), batch_size=2)
    assert it.provide_data[0].shape == (2, 4)      # Module.fit-ready
    batches = list(it)
    assert len(batches) == 2
    assert isinstance(batches[0].data[0], CSRNDArray)
    np.testing.assert_allclose(batches[0].data[0].asnumpy(),
                               [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    assert float(batches[0].label[0].asnumpy().ravel()[0]) == 1.0
    it.reset()
    assert len(list(it)) == 2
    # short final batch pads with empty CSR rows and reports pad
    it5 = LibSVMIter(data_libsvm=str(p), data_shape=4, batch_size=3)
    b = list(it5)
    assert len(b) == 2 and b[-1].pad == 2
    from incubator_mxnet_tpu.contrib.text.embedding import TokenEmbedding
    assert TokenEmbedding is not None
    assert MXIndexedRecordIO is recordio.IndexedRecordIO
    assert ImageDetRecordIter is not None


# --------------------------------------------------------------------- #
# PrefetchingIter failure surface (round 13): producer death propagates,
# transient IO errors retry bounded (docs/RESILIENCE.md)
# --------------------------------------------------------------------- #

class _FlakyIter(mx.io.DataIter):
    """Inner iterator whose reads fail in configurable ways."""

    def __init__(self, n=6, fail_at=None, exc=None):
        super().__init__(batch_size=2)
        self._n = n
        self._cur = 0
        self._fail_at = fail_at
        self._exc = exc

    def reset(self):
        self._cur = 0

    def next(self):
        if self._fail_at is not None and self._cur == self._fail_at:
            self._fail_at = None            # fire once
            raise self._exc
        if self._cur >= self._n:
            raise StopIteration
        i = self._cur
        self._cur += 1
        return DataBatch([nd.array(np.full((2, 1), i, np.float32))], [])


def test_prefetch_producer_exception_propagates():
    from incubator_mxnet_tpu.io import PrefetchingIter
    pf = PrefetchingIter(_FlakyIter(fail_at=2,
                                    exc=ValueError("reader exploded")))
    assert pf.next() is not None
    assert pf.next() is not None
    with pytest.raises(ValueError, match="reader exploded"):
        while True:
            pf.next()


def test_prefetch_producer_base_exception_propagates():
    # SystemExit in a reader thread previously died silently, hanging
    # the consumer on an empty queue forever
    from incubator_mxnet_tpu.io import PrefetchingIter
    pf = PrefetchingIter(_FlakyIter(fail_at=1, exc=SystemExit(3)))
    pf.next()
    with pytest.raises(SystemExit):
        while True:
            pf.next()


def test_prefetch_producer_silent_death_raises_not_hangs():
    from incubator_mxnet_tpu.base import MXNetError
    from incubator_mxnet_tpu.io import PrefetchingIter
    pf = PrefetchingIter(_FlakyIter(n=6))
    pf.next()
    # simulate abrupt producer death without a sentinel: cancel makes
    # the thread return sentinel-free (the reset() protocol), then
    # consume with the queue drained
    pf._cancel.set()
    pf._thread.join(timeout=5)
    assert not pf._thread.is_alive()
    while not pf._queue.empty():
        pf._queue.get_nowait()
    with pytest.raises(MXNetError, match="producer thread died"):
        pf.next()
    with pytest.raises(StopIteration):      # stays terminal, never wedges
        pf.next()


def test_prefetch_transient_io_error_retries_bounded(monkeypatch):
    from incubator_mxnet_tpu.io import PrefetchingIter
    monkeypatch.setenv("MXTPU_IO_FAIL_READS", "2")
    monkeypatch.setenv("MXTPU_IO_RETRY_ATTEMPTS", "3")
    monkeypatch.setenv("MXTPU_IO_RETRY_BACKOFF", "0.001")
    pf = PrefetchingIter(_FlakyIter(n=6))
    batches = list(pf)
    assert len(batches) == 6                # nothing lost to the blips
    assert pf.read_retries == 2


def test_prefetch_persistent_io_error_fails_loudly(monkeypatch):
    from incubator_mxnet_tpu.io import PrefetchingIter
    monkeypatch.setenv("MXTPU_IO_FAIL_READS", "50")
    monkeypatch.setenv("MXTPU_IO_RETRY_ATTEMPTS", "3")
    monkeypatch.setenv("MXTPU_IO_RETRY_BACKOFF", "0.001")
    pf = PrefetchingIter(_FlakyIter(n=6))
    with pytest.raises(OSError, match="injected transient"):
        pf.next()
    assert pf.read_retries == 2             # attempts-1 retries, then loud
