"""Paged KV cache: a shared page pool + host-side page allocator.

Layout (one pool pair per transformer layer):

    k_pool / v_pool : (num_pages, H, page_size, D)

chosen so each (page, head) slice is a contiguous (page_size, D) tile —
the ragged kernel's per-head dot operand (ops/ragged_attention.py) —
and so a tp mesh can shard the H axis with the existing
``parallel.mesh`` machinery without splitting any page.

Invariants (enforced by the engine, asserted in tests):
  - **Page 0 is the NULL page.** The allocator never hands it out; every
    dead page-table entry points at it; inactive slots' decode writes
    land in it. Its contents are garbage BY DESIGN — correctness relies
    on every read of it being masked by the slot's length, never on what
    it holds.
  - A slot at length L references exactly ceil(L / page_size) live
    pages, contiguous in its page-table row; entries past that are 0.
  - Pages are identity-free: eviction returns them to the free list and
    any slot may reuse them without clearing (the next writer overwrites
    the prefix it needs; the tail is masked).
  - **Pages are reference-counted.** A page may be mapped read-only into
    several slots' page tables at once (prefix sharing) and retained by
    the host-side prefix index; it returns to the free list only when
    the last reference drops. A shared page is NEVER written: decode
    writes land at positions >= the slot's prompt length, past every
    shared prefix page, and the first partial page after a matched
    prefix is COPIED into a private page before the slot writes it
    (copy-on-write at page granularity).

The allocator and the prefix index are deliberately host-side Python,
matching the scheduler split: device programs are occupancy-oblivious,
all allocation/sharing decisions ride in as int32 data.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..ops.quantization import (quantize_symmetric, requantize_symmetric,
                                symmetric_scale)

NULL_PAGE = 0

__all__ = ["NULL_PAGE", "PageAllocator", "PrefixIndex", "init_kv_pools",
           "write_token_kv", "write_prompt_kv", "write_block_kv",
           "KVQuantSpec", "kv_quant_spec", "page_scales",
           "write_token_kv_q", "write_prompt_kv_q", "write_block_kv_q"]


# --------------------------------------------------------------------- #
# quantized pool layout (int8 / fp8 payload + per-page symmetric scale)
#
# A quantized pool keeps the SAME (num_pages, H, page_size, D) page
# layout with a narrow payload dtype, plus ONE float32 absolute-max
# statistic per page per pool (``amax``, shape (num_pages,)) from which
# the page's symmetric dequantization scale derives
# (ops.quantization.symmetric_scale: amax / qmax, 1.0 on an untouched
# page). The amax array is PAGE METADATA: it rides next to the page
# table as data into every program that reads or writes pages (and on
# TPU down the same scalar-prefetch path — ops/ragged_attention.py), a
# shared prefix page's scale is shared exactly like the page itself,
# and the host resets a page's amax when the allocator hands it out
# (pages are identity-free; a recycled page must not inherit its
# previous owner's range).
#
# Incremental writes and the monotone-scale contract: decode and
# chunked prefill fill a page a few rows at a time, so a page's scale
# can only GROW (amax is scatter-max'd). When a write raises a page's
# amax, the page's existing codes are REQUANTIZED in place by
# ``old_scale / new_scale <= 1`` (ops.quantization.requantize_symmetric
# — a pure code rescale, never a dequant round trip), then the new rows
# are quantized at the new scale. Each rescale adds at most half a
# quantum of error to already-written rows; scales stabilize after the
# first few writes in practice (measured in BENCH_QUANT.json).
# --------------------------------------------------------------------- #

@dataclasses.dataclass(frozen=True)
class KVQuantSpec:
    """One quantized-KV flavour: the pool payload dtype and its
    saturation bound (int8: ±127; fp8_e4m3: ±448)."""
    name: str
    dtype: object
    qmax: float


def kv_quant_spec(kv_quant) -> Optional[KVQuantSpec]:
    """Resolve an engine's ``kv_quant`` knob: None/'none' → None
    (unquantized f32/bf16 pools), 'int8' → int8 payload (the portable
    default — the MXU int8 path on TPU, exact small-int arithmetic on
    CPU), 'fp8_e4m3' → float8 payload (TPU-targeted; needs a jax with
    float8 dtypes)."""
    if kv_quant is None or kv_quant == "none":
        return None
    if isinstance(kv_quant, KVQuantSpec):
        return kv_quant
    if kv_quant == "int8":
        return KVQuantSpec("int8", jnp.int8, 127.0)
    if kv_quant == "fp8_e4m3":
        if not hasattr(jnp, "float8_e4m3fn"):
            raise MXNetError("kv_quant='fp8_e4m3' needs a jax build "
                             "with float8 dtypes")
        return KVQuantSpec("fp8_e4m3", jnp.float8_e4m3fn, 448.0)
    raise MXNetError(f"kv_quant must be None|'int8'|'fp8_e4m3', got "
                     f"{kv_quant!r}")


def page_scales(amax, spec: KVQuantSpec):
    """(P,) per-page dequantization scales from the amax metadata."""
    return symmetric_scale(amax, spec.qmax)


def write_token_kv_q(pool, amax, new, pages, offsets, spec: KVQuantSpec):
    """Quantized twin of ``write_token_kv``: scatter one K (or V) row
    per entry into an int8/fp8 pool, growing the per-page scales.

    pool: (P, H, ps, D) codes; amax: (P,) f32; new: (N, H, D) float;
    pages/offsets: (N,) int32. Returns ``(pool, amax)`` updated.

    Three phases, all safe under duplicate page indices (several rows
    of one call landing in the same page — the verify window's block
    write flattens into this):
      1. scatter-max the new rows' |max| into ``amax`` (duplicates
         combine correctly by construction);
      2. requantize every TOUCHED page's existing codes by
         ``old_scale / new_scale`` — duplicate entries compute
         IDENTICAL page contents (same gathered codes, same final
         scale), so the unspecified scatter order cannot diverge;
      3. quantize the new rows at the final scale and scatter them at
         their (page, offset) cells — distinct cells except dead
         entries, which all land in the null page (garbage by design,
         same contract as the unquantized write)."""
    H = pool.shape[1]
    a_n = jnp.max(jnp.abs(new.astype(jnp.float32)), axis=(1, 2))  # (N,)
    new_amax = amax.at[pages].max(a_n)
    old_s = symmetric_scale(amax, spec.qmax)
    new_s = symmetric_scale(new_amax, spec.qmax)
    ratio = (old_s / new_s)[pages]                       # (N,) <= 1
    touched = requantize_symmetric(
        pool[pages], ratio[:, None, None, None], spec.dtype, spec.qmax)
    pool = pool.at[pages].set(touched)
    q = quantize_symmetric(new, new_s[pages][:, None, None],
                           spec.dtype, spec.qmax)        # (N, H, D)
    pool = pool.at[pages[:, None], jnp.arange(H)[None, :],
                   offsets[:, None], :].set(q)
    return pool, new_amax


def write_block_kv_q(pool, amax, new, pages, offsets, spec: KVQuantSpec):
    """Quantized twin of ``write_block_kv``: a (S, W) block of rows
    (the speculative verify window) flattened into the per-row
    quantized scatter — duplicate pages inside one slot's window are
    exactly the case ``write_token_kv_q``'s phases are built for."""
    S, W, H, D = new.shape
    return write_token_kv_q(pool, amax, new.reshape(S * W, H, D),
                            pages.reshape(S * W),
                            offsets.reshape(S * W), spec)


def write_prompt_kv_q(pool, amax, kv, pages, spec: KVQuantSpec):
    """Quantized twin of ``write_prompt_kv``: scatter a whole prompt's
    K (or V) into its pages with a FRESH per-page scale (each page's
    amax is overwritten, not grown — prefill is the page's first write,
    so a recycled page's stale range dies here). Dead entries all index
    the null page; whichever dead page's amax wins the duplicate
    scatter is garbage by design, like the payload."""
    n_pages = pages.shape[0]
    ps = pool.shape[2]
    paged = kv.astype(jnp.float32).reshape(n_pages, ps, kv.shape[1],
                                           kv.shape[2])
    a_p = jnp.max(jnp.abs(paged), axis=(1, 2, 3))        # (n_pages,)
    amax = amax.at[pages].set(a_p)
    s = symmetric_scale(a_p, spec.qmax)
    q = quantize_symmetric(paged, s[:, None, None, None],
                           spec.dtype, spec.qmax)
    q = q.transpose(0, 2, 1, 3)                 # (n_pages, H, ps, D)
    return pool.at[pages].set(q), amax


class PageAllocator:
    """Reference-counted free-list allocator over pages 1..num_pages-1
    (page 0 = null). ``alloc`` hands out a page at refcount 1;
    ``incref`` adds a sharer; ``free``/``decref`` drops one reference
    and returns the page to the free list when the last one goes.

    Corruption is refused loudly instead of silently poisoning the free
    list: freeing the null page, double-freeing a page already back on
    the free list, or dropping a refcount below zero all raise."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise MXNetError("need >= 2 pages (page 0 is the null page)")
        self.num_pages = num_pages
        # LIFO reuse keeps the working set of hot pages small
        self._free = list(range(num_pages - 1, 0, -1))
        self._rc = [0] * num_pages
        self._held: List[int] = []

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def held(self) -> Tuple[int, ...]:
        """Pages taken out of circulation by ``hold`` (chaos-harness
        allocator pressure) — accounted for by the engine's page audit."""
        return tuple(self._held)

    def hold(self, n: int) -> List[int]:
        """Take up to ``n`` pages out of circulation (refcount 1, owned
        by the holder): the deterministic allocator-pressure fault of
        serve/chaos.py — admission and tail allocation see a genuinely
        smaller pool, through the allocator's own bookkeeping so the
        page audit stays exact. Returns the pages actually held."""
        pages = [self.alloc() for _ in range(min(max(n, 0),
                                                 self.free_count))]
        self._held.extend(pages)
        return pages

    def release_held(self, pages=None) -> int:
        """Return held pages (default: all of them) to the free list."""
        if pages is None:
            pages = list(self._held)
        for p in pages:
            self._held.remove(p)
            self.decref(p)
        return len(pages)

    def _check(self, page) -> int:
        p = int(page)
        if p == NULL_PAGE:
            raise MXNetError("the null page (page 0) is never allocated, "
                             "shared, or freed")
        if not 0 < p < self.num_pages:
            raise MXNetError(f"page {p} outside pool [1, "
                             f"{self.num_pages})")
        return p

    def refcount(self, page) -> int:
        return self._rc[self._check(page)]

    def alloc(self) -> int:
        if not self._free:
            raise MXNetError("KV page pool exhausted — admission control "
                             "should have prevented this (engine bug)")
        p = self._free.pop()
        self._rc[p] = 1
        return p

    def incref(self, page) -> None:
        """Add a reference to a LIVE page (prefix sharing / index
        retention). Sharing a page that is on the free list would hand
        the same page to two owners — refused."""
        p = self._check(page)
        if self._rc[p] <= 0:
            raise MXNetError(f"incref on free page {p} — a page must be "
                             f"live to be shared")
        self._rc[p] += 1

    def decref(self, page) -> bool:
        """Drop one reference; returns True when the page went back to
        the free list. A decref on a page whose refcount is already zero
        is a double free (or a below-zero drop) and raises."""
        p = self._check(page)
        if self._rc[p] <= 0:
            raise MXNetError(
                f"double free: page {p} already has refcount 0 (it is "
                f"on the free list) — refusing to corrupt the free list")
        self._rc[p] -= 1
        if self._rc[p] == 0:
            self._free.append(p)
            return True
        return False

    def free(self, pages) -> None:
        for p in pages:
            self.decref(p)


@dataclasses.dataclass(eq=False)        # identity semantics: entries are
class _PrefixEntry:                     # tracked by object, and ndarray
    page: int                           # fields break generated __eq__
    tokens: np.ndarray          # the page's token ids (full page)
    depth: int                  # page index within its prompt chain
    last_use: int


class PrefixIndex:
    """Host-side hash-radix index over page-aligned prompt prefixes.

    A radix node is keyed by the BYTES OF THE WHOLE TOKEN PREFIX that
    precedes its pages (int32, fixed width — byte-prefix equality is
    token-prefix equality) and holds the SIBLING entries extending that
    prefix (several prompt families may diverge at the same depth), so
    lookups walk page by page exactly like a radix tree without storing
    child pointers. Each entry holds its page's own tokens for
    verification and the shared page id; the index owns one allocator
    reference per entry.

    Matching returns the longest cached page-aligned prefix as
    read-only shared pages plus (when the boundary page's leading
    tokens match) a partial page to copy — capped at ``t0 - 1`` tokens
    so the LAST prompt token is always recomputed: its logits seed
    first-token sampling, which cached K/V alone cannot provide.

    ``flush`` drops every entry (cached K/V is weight-dependent — the
    engine flushes on ``warm_start``); ``reclaim`` evicts
    least-recently-used entries whose pages nobody else references,
    which is how admission turns cache retention back into free pages
    under pressure."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        # radix node: preceding-prefix bytes -> sibling entries
        self._nodes: Dict[bytes, List[_PrefixEntry]] = {}
        self._clock = 0
        self.flushes = 0

    def __len__(self) -> int:
        return sum(len(b) for b in self._nodes.values())

    def held_pages(self) -> List[int]:
        return [e.page for b in self._nodes.values() for e in b]

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, prompt_ids, mutate: bool = True) \
            -> Tuple[List[int], Optional[Tuple[int, int]], int]:
        """Longest cached page-aligned prefix of ``prompt_ids``.

        Returns ``(shared, partial, cached_len)``: ``shared`` is the
        list of full pages to map read-only (the caller must incref
        them), ``partial`` is ``(src_page, n_tokens)`` for a boundary
        page whose first ``n_tokens`` match (to copy into a private
        page), or None, and ``cached_len == page_size * len(shared) +
        n_tokens`` is the number of prompt tokens whose K/V is already
        cached (always <= t0 - 1).

        ``mutate=False`` skips the LRU ``last_use`` ticks — the
        ``probe`` read, identical traversal, zero side effects."""
        ps = self.page_size
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        t0 = prompt.size
        shared: List[int] = []
        m = 0
        while True:
            siblings = self._nodes.get(prompt[:m * ps].tobytes())
            if not siblings:
                break
            rest = prompt[m * ps:]
            full = None
            if rest.size > ps:
                for ent in siblings:
                    if np.array_equal(ent.tokens, rest[:ps]):
                        full = ent
                        break
            if full is not None:
                # whole page matches and the prompt continues past it
                if mutate:
                    full.last_use = self._tick()
                shared.append(full.page)
                m += 1
                continue
            # boundary page: the sibling with the longest common
            # leading run, capped so at least one prompt token is left
            # to recompute (its logits seed first-token sampling)
            lim = min(ps, rest.size, t0 - 1 - m * ps)
            best, best_n = None, 0
            for ent in siblings:
                n = 0
                while n < lim and ent.tokens[n] == rest[n]:
                    n += 1
                if n > best_n:
                    best, best_n = ent, n
            if best is not None:
                if mutate:
                    best.last_use = self._tick()
                return shared, (best.page, best_n), m * ps + best_n
            break
        return shared, None, m * ps

    def probe(self, prompt_ids) -> int:
        """READ-ONLY twin of ``match``: how many leading tokens of
        ``prompt_ids`` are cached right now. Touches NOTHING — no
        refcounts (it returns no pages to pin), no LRU clock ticks —
        so a fleet router may probe every replica per admission
        without perturbing any replica's eviction order
        (serve/router.py's cache-affinity read; asserted
        side-effect-free in tests/test_router.py). One traversal
        serves both callers (``match(..., mutate=False)``), so the
        affinity estimate can never drift from what admission will
        actually reuse."""
        return self.match(prompt_ids, mutate=False)[2]

    def insert(self, prompt_ids, pages, allocator: PageAllocator) -> int:
        """Publish the prompt's FULL pages (``pages[j]`` holds tokens
        ``[j*ps, (j+1)*ps)``); the index increfs each newly-published
        page. An existing sibling with the same content is kept (first
        writer wins — duplicate K/V pages earn no second entry).
        Returns the number of new entries."""
        ps = self.page_size
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        added = 0
        for j in range(prompt.size // ps):
            key = prompt[:j * ps].tobytes()
            toks = prompt[j * ps:(j + 1) * ps]
            siblings = self._nodes.setdefault(key, [])
            dup = next((e for e in siblings
                        if np.array_equal(e.tokens, toks)), None)
            if dup is not None:
                dup.last_use = self._tick()
                continue
            allocator.incref(pages[j])
            siblings.append(_PrefixEntry(
                page=int(pages[j]), tokens=toks.copy(), depth=j,
                last_use=self._tick()))
            added += 1
        return added

    def reclaimable(self, allocator: PageAllocator) -> int:
        """Pages that ``reclaim`` could return to the free list right
        now: entries whose page nobody but the index references."""
        return sum(1 for b in self._nodes.values() for e in b
                   if allocator.refcount(e.page) == 1)

    def _drop(self, key: bytes, ent: _PrefixEntry,
              allocator: PageAllocator) -> int:
        """Remove one entry and its now-unreachable descendants (every
        entry under nodes whose key extends this entry's prefix).
        Returns pages actually returned to the free list — descendant
        pages still referenced by live slots merely lose the index's
        ref."""
        freed = 0
        child_prefix = key + ent.tokens.tobytes()
        for k in [k for k in self._nodes if k.startswith(child_prefix)]:
            for e in self._nodes.pop(k):
                if allocator.decref(e.page):
                    freed += 1
        bucket = self._nodes[key]
        bucket.remove(ent)
        if not bucket:
            del self._nodes[key]
        if allocator.decref(ent.page):
            freed += 1
        return freed

    def reclaim(self, n: int, allocator: PageAllocator) -> int:
        """Evict least-recently-used index-only entries until ``n``
        pages returned to the free list (or candidates run out)."""
        freed = 0
        order = sorted(
            [(k, e) for k, b in self._nodes.items() for e in b],
            key=lambda kv: (kv[1].last_use, -kv[1].depth))
        for key, ent in order:
            if freed >= n:
                break
            bucket = self._nodes.get(key)
            if bucket is None or ent not in bucket:
                continue                      # cascaded away already
            if allocator.refcount(ent.page) != 1:
                continue                      # a live slot still maps it
            freed += self._drop(key, ent, allocator)
        return freed

    def flush(self, allocator: PageAllocator) -> None:
        """Drop every entry (cached K/V is weight-dependent): pages held
        only by the index go back to the free list; pages still mapped
        by live slots survive through the slots' own references."""
        for bucket in self._nodes.values():
            for e in bucket:
                allocator.decref(e.page)
        self._nodes.clear()
        self.flushes += 1


def init_kv_pools(num_layers, num_pages, num_heads, page_size, head_dim,
                  dtype="float32", quant: Optional[KVQuantSpec] = None):
    """Fresh zeroed (k_pool, v_pool) pairs, one per layer. With a
    ``quant`` spec the payload dtype is the spec's narrow dtype (the
    per-page amax metadata is the ENGINE's to own — host-resettable
    page metadata, not pool state)."""
    dt = jnp.dtype(quant.dtype) if quant is not None else jnp.dtype(dtype)
    mk = lambda: jnp.zeros((num_pages, num_heads, page_size, head_dim), dt)
    return [(mk(), mk()) for _ in range(num_layers)]


def write_token_kv(pool, new, pages, offsets):
    """Scatter one K (or V) row per entry into the pool.

    pool: (P, H, ps, D); new: (N, H, D); pages/offsets: (N,) int32 —
    entry n writes ``new[n]`` to ``pool[pages[n], :, offsets[n], :]``.
    Serves both the decode step (one token per SLOT, N = num_slots;
    inactive slots carry pages[n] == NULL_PAGE) and chunked prefill
    (one row per CHUNK TOKEN of a single slot, N = chunk length; padded
    tokens carry NULL_PAGE) — either way dead writes land in the null
    page, harmless and never read unmasked. Static shapes; safe under
    jit."""
    H = pool.shape[1]
    return pool.at[pages[:, None], jnp.arange(H)[None, :],
                   offsets[:, None], :].set(new.astype(pool.dtype))


def write_block_kv(pool, new, pages, offsets):
    """Scatter a (S, W) BLOCK of K (or V) rows into the pool — the
    speculative verify step's write: W consecutive positions per slot
    (the last accepted token plus up to W-1 draft candidates).

    pool: (P, H, ps, D); new: (S, W, H, D); pages/offsets: (S, W)
    int32 — entry (s, w) writes ``new[s, w]`` to
    ``pool[pages[s, w], :, offsets[s, w], :]``. Dead entries (inactive
    slots, positions past a slot's real draft window) carry
    ``pages == NULL_PAGE`` and land harmlessly in the null page, same
    contract as ``write_token_kv`` (which this flattens into). Static
    shapes; safe under jit."""
    S, W, H, D = new.shape
    return write_token_kv(pool, new.reshape(S * W, H, D),
                          pages.reshape(S * W), offsets.reshape(S * W))


def write_prompt_kv(pool, kv, pages):
    """Scatter a whole prompt's K (or V) into its pages (prefill).

    pool: (P, H, ps, D); kv: (Tpad, H, D) with Tpad == len(pages) * ps;
    pages: (n_pages,) int32 with dead (beyond the prompt) entries
    NULL_PAGE — those whole-page writes land in the null page. Duplicate
    null indices are fine: the store order is unspecified but the value
    is never read unmasked."""
    n_pages = pages.shape[0]
    ps = pool.shape[2]
    paged = kv.reshape(n_pages, ps, kv.shape[1], kv.shape[2]) \
        .transpose(0, 2, 1, 3)                  # (n_pages, H, ps, D)
    return pool.at[pages].set(paged.astype(pool.dtype))
