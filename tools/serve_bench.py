"""Serving benchmark: continuous batching vs looped per-request decode,
plus the round-9 serving levers — prefix caching and chunked prefill.

Measures what the serve/ subsystem buys over the repo's previous only
inference path (per-request ``cached_generate`` over dense (B, Tmax)
KV buffers): requests arrive by a Poisson process, the engine packs
them into fixed decode slots with a paged KV cache, and the comparison
baseline serves the SAME request set one at a time. Reported:

  - tokens/s (generated tokens / wall-clock from first arrival to last
    completion) for both paths, and the speedup;
  - p50/p99 time-per-output-token (TPOT) across all generated tokens
    (each token is stamped with the decode-step wall time that emitted
    it; the first token carries its prefill time), AND p50/p99
    INTER-TOKEN latency from absolute token timestamps — unlike the
    per-step time, the gap between consecutive tokens of one request
    also captures stalls caused by OTHER requests' prefills, which is
    exactly the spike chunked prefill exists to fix;
  - steady-state compile discipline: the decode step must have compiled
    EXACTLY ONCE across the whole run despite occupancy churn, and
    every prefill/chunk bucket exactly once.

Round-9 workloads (banked next to the original comparison):

  - ``shared_prefix``: N personas × M requests (a long shared system
    prompt per persona + a short unique suffix) served cold
    (prefix_cache off) vs warm (on) over the SAME arrival trace —
    banks prefix-hit rate and the tokens/s win from paying prefill
    only for the suffix;
  - ``long_prompt_mixed``: a stream of short prompts decoding while
    long prompts arrive, monolithic prefill vs chunked
    (decode-interleaved under a token budget) — banks the inter-token
    p99 the long arrivals used to spike.

Round-10 workload (docs/RESILIENCE.md):

  - ``guard_overhead``: full-occupancy decode with the per-slot
    non-finite guard on vs off — two persistent engines stepped in
    strict alternation, pure decode steps timed, overhead = the ratio
    of per-step-time quantiles (p50 banked; at full occupancy
    tokens/s == slots / step-time) — banks what the always-on guard
    costs; the leave-it-on bar is <2%.

``--smoke`` is the CI guard (ci/run.sh servebench stage): fast runs
that exit non-zero on any steady-state decode retrace, on a cache-hit
admission compiling ANY new program, or on chunked prefill exceeding
its per-step token budget. CPU-measurable by design.

Fairness notes for the baseline: every request uses the same
(prompt_pad, total) shape so ``cached_generate`` compiles ONCE (warmed
outside the timed window) — the 3x bar is against its best case, not
its retrace pathology. Arrivals gate the baseline too: it may not start
a request before that request arrived. The cold/warm and
monolithic/chunked comparisons replay identical request sets and
arrival traces.

Usage:
  python tools/serve_bench.py                # full bench, banks
                                             # BENCH_SERVE.json
  python tools/serve_bench.py --smoke        # CI guard (fast, asserts)
  python tools/serve_bench.py --json OUT.json
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build(seed=0, vocab=64, max_length=256):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.models import gpt as g
    mx.random.seed(seed)
    model = g.gpt_mini(vocab_size=vocab, max_length=max_length)
    model.initialize()
    return model


def _build_round9(smoke):
    """Model for the prefix-caching / chunked-prefill workloads. The
    full run uses a 4-layer 256-unit model: on gpt_mini a whole prefill
    is DISPATCH-bound on CPU (one program call costs the same at 16 or
    104 tokens), which would understate a lever whose win is prompt
    COMPUTE skipped/split. Smoke keeps gpt_mini — it asserts contracts,
    not magnitudes."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.models import gpt as g
    from incubator_mxnet_tpu.models.gpt import GPTModel
    mx.random.seed(1)
    if smoke:
        model = g.gpt_mini(vocab_size=64, max_length=256)
    else:
        model = GPTModel(vocab_size=64, units=256, hidden_size=1024,
                         num_layers=4, num_heads=8, max_length=256)
    model.initialize()
    return model


def _make_requests(n, prompt_len, max_new, rate_hz, vocab, seed=0):
    """n requests, fixed shape (fair single-compile baseline), Poisson
    arrival times at ``rate_hz``."""
    import numpy as np
    from incubator_mxnet_tpu.serve import Request
    rng = np.random.RandomState(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_hz, size=n))
    arrivals[0] = 0.0                      # the clock starts at work
    reqs = [Request(rng.randint(0, vocab, size=(prompt_len,)),
                    max_new_tokens=max_new) for _ in range(n)]
    return reqs, arrivals.tolist()


def _percentile(xs, q):
    xs = sorted(xs)
    if not xs:
        return 0.0
    idx = min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1))))
    return xs[idx]


def _itl_gaps(reqs):
    """Inter-token latencies from absolute token timestamps: the gap a
    USER sees between consecutive tokens of one request — including
    stalls caused by other requests' prefills, which per-decode-step
    timing cannot see."""
    gaps = []
    for r in reqs:
        st = r.token_stamps
        gaps.extend(b - a for a, b in zip(st, st[1:]))
    return gaps


def _engine_stats(eng, reqs, wall, decode_steps0=0):
    """Stats for the timed window (``decode_steps0`` = steps already
    spent in an untimed warmup). Compile counts stay CUMULATIVE over the
    engine's whole lifetime — that is the jit-once contract."""
    tokens = sum(len(r.token_ids) for r in reqs)
    # every request's FIRST token is emitted by its prefill program, not
    # a decode step — exclude them so mean_occupancy is per-decode-step
    decode_tokens = tokens - len(reqs)
    steps = eng.decode_steps - decode_steps0
    tpot = [dt for r in reqs for dt in r.token_times]
    itl = _itl_gaps(reqs)
    return {
        "tokens": tokens,
        "wall_s": wall,
        "tokens_per_s": tokens / wall,
        "tpot_p50_ms": _percentile(tpot, 50) * 1e3,
        "tpot_p99_ms": _percentile(tpot, 99) * 1e3,
        "itl_p50_ms": _percentile(itl, 50) * 1e3,
        "itl_p99_ms": _percentile(itl, 99) * 1e3,
        "itl_max_ms": (max(itl) if itl else 0.0) * 1e3,
        "decode_steps": steps,
        "decode_trace_count": eng.decode_trace_count,
        "prefill_trace_count": eng.prefill_trace_count,
        "prefill_trace_counts": {f"{k[0]}{k[1]}": v for k, v in
                                 sorted(eng.prefill_trace_counts.items())},
        "mean_occupancy": decode_tokens / max(steps, 1),
    }


def bench_engine(model, reqs, arrivals, num_slots, page_size, **eng_kw):
    from incubator_mxnet_tpu.serve import InferenceEngine
    eng = InferenceEngine(model, num_slots=num_slots,
                          page_size=page_size, **eng_kw)
    t0 = time.perf_counter()
    eng.run(reqs, arrival_times=arrivals)
    wall = time.perf_counter() - t0
    return eng, _engine_stats(eng, reqs, wall)


def bench_baseline(model, reqs, arrivals, max_new):
    """Looped per-request cached_generate over the same arrival trace.
    One warmup call outside the timed window so the (single) shape is
    pre-compiled — the baseline pays no retraces, only its serial,
    dense-cache design."""
    import numpy as np
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.models.gpt import cached_generate
    prompt0 = np.asarray(reqs[0].prompt_ids, np.int32)[None, :]
    cached_generate(model, nd.array(prompt0, dtype="int32"),
                    max_new_tokens=max_new).asnumpy()    # warm compile
    t0 = time.perf_counter()
    tokens = 0
    tpot = []
    for req, arr in zip(reqs, arrivals):
        now = time.perf_counter() - t0
        if now < arr:                       # cannot start early
            time.sleep(arr - now)
        ids = np.asarray(req.prompt_ids, np.int32)[None, :]
        t1 = time.perf_counter()
        out = cached_generate(model, nd.array(ids, dtype="int32"),
                              max_new_tokens=max_new).asnumpy()
        dt = time.perf_counter() - t1
        n = out.shape[1] - ids.shape[1]
        tokens += n
        tpot.extend([dt / n] * n)
    wall = time.perf_counter() - t0
    return {
        "tokens": tokens,
        "wall_s": wall,
        "tokens_per_s": tokens / wall,
        "tpot_p50_ms": _percentile(tpot, 50) * 1e3,
        "tpot_p99_ms": _percentile(tpot, 99) * 1e3,
    }


# --------------------------------------------------------------------- #
# round-9 workloads
# --------------------------------------------------------------------- #

def _persona_requests(personas, per_persona, prefix_len, suffix_len,
                      max_new, rate_hz, vocab, seed=7, suffix_seed=11):
    """N personas × M requests: shared long prefix + unique suffix,
    interleaved round-robin over a Poisson arrival trace (so different
    personas churn through the slots together). ``seed`` fixes the
    persona heads and arrivals; ``suffix_seed`` varies the tails (a
    warmup set and a measured set share personas, never suffixes)."""
    import numpy as np
    from incubator_mxnet_tpu.serve import Request
    rng = np.random.RandomState(seed)
    heads = [rng.randint(0, vocab, size=(prefix_len,)).astype(np.int32)
             for _ in range(personas)]
    n = personas * per_persona
    arrivals = np.cumsum(rng.exponential(1.0 / rate_hz, size=n))
    arrivals[0] = 0.0
    srng = np.random.RandomState(suffix_seed)
    reqs = []
    for i in range(n):
        head = heads[i % personas]
        tail = srng.randint(0, vocab, size=(suffix_len,)).astype(np.int32)
        reqs.append(Request(np.concatenate([head, tail]),
                            max_new_tokens=max_new))
    return reqs, arrivals.tolist()


def bench_shared_prefix(model, *, personas, per_persona, prefix_len,
                        suffix_len, max_new, slots, page_size, rate_hz):
    """Cold (prefix_cache off) vs warm (on) over the SAME persona
    workload and arrival trace. Both engines first drain an untimed
    WARMUP set (same personas, different suffixes): it pre-compiles
    every program on both sides — the comparison is pure steady-state
    serving — and on the warm engine it also populates the prefix
    index, so the timed window measures the HIT path, exactly the
    production shape (personas live much longer than any one request)."""
    from incubator_mxnet_tpu.serve import InferenceEngine
    vocab = model.vocab_size
    engines = {"cold": InferenceEngine(model, num_slots=slots,
                                       page_size=page_size,
                                       prefix_cache=False),
               "warm": InferenceEngine(model, num_slots=slots,
                                       page_size=page_size,
                                       prefix_cache=True)}
    stats = {}
    hitinfo = {}
    for name, eng in engines.items():
        # TWO warmup rounds per persona: round one compiles the cold
        # path and populates the index, round two compiles the HIT path
        # (suffix chunks + COW copy) — the timed window then compiles
        # nothing on either engine (asserted by the smoke run)
        wreqs, _ = _persona_requests(personas, 2, prefix_len,
                                     suffix_len, max_new, rate_hz,
                                     vocab, suffix_seed=1011)
        eng.run(wreqs)                       # untimed warmup
        reqs, arrivals = _persona_requests(personas, per_persona,
                                           prefix_len, suffix_len,
                                           max_new, rate_hz, vocab)
        lookups0, hits0 = eng.prefix_lookups, eng.prefix_hits
        hit_toks0, steps0 = eng.prefix_hit_tokens, eng.decode_steps
        t0 = time.perf_counter()
        eng.run(reqs, arrival_times=arrivals)
        wall = time.perf_counter() - t0
        stats[name] = _engine_stats(eng, reqs, wall, steps0)
        prompt_tokens = sum(r.prompt_ids.size for r in reqs)
        hitinfo[name] = {
            "lookups": eng.prefix_lookups - lookups0,
            "hits": eng.prefix_hits - hits0,
            "hit_tokens": eng.prefix_hit_tokens - hit_toks0,
            "hit_rate": (eng.prefix_hit_tokens - hit_toks0) /
                        prompt_tokens,
        }
    out = {
        "config": {"personas": personas, "per_persona": per_persona,
                   "prefix_len": prefix_len, "suffix_len": suffix_len,
                   "max_new": max_new, "slots": slots,
                   "page_size": page_size, "rate_hz": rate_hz},
        "cold": stats["cold"],
        "warm": stats["warm"],
        "prefix_lookups": hitinfo["warm"]["lookups"],
        "prefix_hits": hitinfo["warm"]["hits"],
        "prefix_hit_tokens": hitinfo["warm"]["hit_tokens"],
        "prefix_hit_rate": hitinfo["warm"]["hit_rate"],
        "warm_over_cold_tokens_per_s": (stats["warm"]["tokens_per_s"] /
                                        stats["cold"]["tokens_per_s"]),
    }
    return engines["warm"], out


def _long_mixed_requests(n_short, short_len, short_new, n_long,
                         long_len, long_new, vocab, seed=9,
                         long_at0=0.4, long_gap=0.6):
    """Short prompts decoding while long prompts arrive mid-stream —
    ``long_at0``/``long_gap`` place the long arrivals INSIDE the
    shorts' decode window (no overlap, no stall, no signal)."""
    import numpy as np
    from incubator_mxnet_tpu.serve import Request
    rng = np.random.RandomState(seed)
    reqs, arrivals = [], []
    for i in range(n_short):
        reqs.append(Request(rng.randint(0, vocab, size=(short_len,))
                            .astype(np.int32), max_new_tokens=short_new))
        arrivals.append(0.02 * i)
    for j in range(n_long):
        reqs.append(Request(rng.randint(0, vocab, size=(long_len,))
                            .astype(np.int32), max_new_tokens=long_new))
        arrivals.append(long_at0 + long_gap * j)
    return reqs, arrivals


def bench_long_prompt_mixed(model, *, n_short, short_len, short_new,
                            n_long, long_len, long_new, slots,
                            page_size, chunk_pages, long_at0=0.4,
                            long_gap=0.6, repeats=3):
    """Monolithic vs chunked prefill over the SAME long-prompt-mixed
    trace; the metric is inter-token p99 — the decode stall a long
    arrival inflicts on every other active request. Both engines drain
    an untimed warmup (one short + one long request) so every program
    is pre-compiled and the timed windows compare pure prefill COMPUTE
    scheduling, not trace time.

    This host's CPU jitter is on the order of the effect (2 cores —
    the same problem ckpt_bench hit), so the comparison runs
    ``repeats`` PAIRED ALTERNATING windows (mono, chunked, mono,
    chunked, ...) on the two persistent engines and banks the
    per-engine elementwise MEDIAN — a single window can swing 2x
    either way."""
    import copy
    from incubator_mxnet_tpu.serve import InferenceEngine
    vocab = model.vocab_size
    reqs, arrivals = _long_mixed_requests(n_short, short_len, short_new,
                                          n_long, long_len, long_new,
                                          vocab, long_at0=long_at0,
                                          long_gap=long_gap)
    engines = {
        "monolithic": InferenceEngine(model, num_slots=slots,
                                      page_size=page_size,
                                      prefix_cache=False),
        "chunked": InferenceEngine(model, num_slots=slots,
                                   page_size=page_size,
                                   prefix_cache=False,
                                   chunk_pages=chunk_pages),
    }
    windows = {name: [] for name in engines}
    for name, eng in engines.items():
        wreqs, _ = _long_mixed_requests(1, short_len, 2, 1, long_len, 2,
                                        vocab, seed=33)
        eng.run(wreqs)                       # untimed warmup compile
    import gc
    for _ in range(repeats):
        for name, eng in engines.items():    # alternating pairs
            r = copy.deepcopy(reqs)
            gc.collect()                     # a GC pause mid-window
            steps0 = eng.decode_steps        # reads as a fake stall
            t0 = time.perf_counter()
            eng.run(r, arrival_times=list(arrivals))
            wall = time.perf_counter() - t0
            windows[name].append(_engine_stats(eng, r, wall, steps0))

    def _median_stats(ws):
        agg = dict(ws[-1])                   # non-numerics from last
        for k, v in ws[-1].items():
            if isinstance(v, (int, float)):
                vals = sorted(w[k] for w in ws)
                agg[k] = vals[len(vals) // 2]
        agg["windows_itl_p99_ms"] = [w["itl_p99_ms"] for w in ws]
        agg["windows_itl_max_ms"] = [w["itl_max_ms"] for w in ws]
        return agg

    mono = _median_stats(windows["monolithic"])
    chunked = _median_stats(windows["chunked"])
    # common-mode host drift hits both engines of a window pair alike —
    # the median of per-PAIR ratios is the drift-robust improvement
    def _pair_median(key):
        rs = sorted(m[key] / max(c[key], 1e-9) for m, c in
                    zip(windows["monolithic"], windows["chunked"]))
        return rs[len(rs) // 2]
    eng_c = engines["chunked"]
    out = {
        "config": {"n_short": n_short, "short_len": short_len,
                   "short_new": short_new, "n_long": n_long,
                   "long_len": long_len, "long_new": long_new,
                   "slots": slots, "page_size": page_size,
                   "chunk_pages": chunk_pages,
                   "token_budget": eng_c.token_budget,
                   "repeats": repeats},
        "monolithic": mono,
        "chunked": chunked,
        "max_step_prefill_tokens": eng_c.max_step_prefill_tokens,
        "itl_p99_improvement": _pair_median("itl_p99_ms"),
        "itl_max_improvement": _pair_median("itl_max_ms"),
    }
    return eng_c, out


def bench_guard_overhead(model, *, prompt_len, max_new, slots,
                         page_size, n_steps=600):
    """Round-10: what the per-slot non-finite guard COSTS on the steady
    serving path. The sign-encoded guard (docs/RESILIENCE.md) adds one
    logits isfinite-reduction + select to the decode program and
    NOTHING to its outputs or host syncs — this measures that the
    residual compute is <2% tokens/s, the bar for leaving it ON by
    default.

    Methodology — the effect is ~1% of a ~2 ms step on a host whose
    load spikes swing multi-second windows by 2x, so window-level A/B
    (the round-8/9 paired-window discipline) cannot resolve it; two
    such runs here disagreed on the SIGN. Instead: two persistent
    engines (guard on / off), both held at full slot occupancy
    (refilled as requests finish), stepped in STRICT ALTERNATION — the
    drift window is one step (~ms), common-mode by construction — with
    order flipped every iteration, timing each engine's ``step()``
    alone and excluding steps that ran an admission/prefill (the
    refill cost rides those; only pure decode steps compare). At full
    batch-drain occupancy tokens/s == slots / step-time, so the banked
    overhead is the ratio of per-step-time QUANTILES: p50 is primary
    (banked), min/p10/p25 corroborate (load spikes only ever ADD
    time, so low quantiles are the least contaminated)."""
    from incubator_mxnet_tpu.serve import InferenceEngine, Request
    import numpy as np
    vocab = model.vocab_size
    rng = np.random.RandomState(17)

    def _req():
        return Request(rng.randint(0, vocab, size=(prompt_len,))
                       .astype(np.int32), max_new_tokens=max_new)

    engines = {
        "guarded": InferenceEngine(model, num_slots=slots,
                                   page_size=page_size,
                                   prefix_cache=False,
                                   guard_nonfinite=True),
        "unguarded": InferenceEngine(model, num_slots=slots,
                                     page_size=page_size,
                                     prefix_cache=False,
                                     guard_nonfinite=False),
    }
    for eng in engines.values():             # compile + reach occupancy
        for _ in range(slots):
            eng.submit(_req())
        for _ in range(4):
            eng.step()
    times = {name: [] for name in engines}
    contaminated = {name: True for name in engines}  # first step: warm
    for i in range(n_steps):
        names = ("guarded", "unguarded") if i % 2 == 0 else \
            ("unguarded", "guarded")
        for name in names:
            eng = engines[name]
            t0 = time.perf_counter()
            eng.step()
            dt = time.perf_counter() - t0
            if not contaminated[name]:
                times[name].append(dt)
            contaminated[name] = False
            if eng.active_count < slots:     # refill: next step admits
                for _ in range(slots - eng.active_count):
                    eng.submit(_req())       # and prefills — untimed
                contaminated[name] = True
    for name in times:
        times[name].sort()

    def _q(xs, q):
        return xs[min(len(xs) - 1, int(q / 100.0 * len(xs)))]

    quantiles = {}
    for q in (0, 10, 25, 50):
        g, u = _q(times["guarded"], q), _q(times["unguarded"], q)
        quantiles[f"p{q}"] = {"guarded_ms": g * 1e3,
                              "unguarded_ms": u * 1e3,
                              "overhead_pct": (g / u - 1.0) * 100.0}
    out = {
        "config": {"prompt_len": prompt_len, "max_new": max_new,
                   "slots": slots, "page_size": page_size,
                   "n_steps": n_steps},
        "pure_decode_steps_timed": {n: len(t) for n, t in times.items()},
        "step_time_quantiles": quantiles,
        "decode_trace_counts": {n: e.decode_trace_count
                                for n, e in engines.items()},
        "prefill_trace_counts": {
            n: {f"{k[0]}{k[1]}": v
                for k, v in sorted(e.prefill_trace_counts.items())}
            for n, e in engines.items()},
        "guard_overhead_pct": quantiles["p50"]["overhead_pct"],
    }
    return engines["guarded"], out


def _check_compile_discipline(tag, stats, errors):
    if stats["decode_trace_count"] != 1:
        errors.append(f"{tag}: decode step compiled "
                      f"{stats['decode_trace_count']} times (must be 1)")
    bad = {k: v for k, v in stats["prefill_trace_counts"].items()
           if v != 1}
    if bad:
        errors.append(f"{tag}: prefill buckets retraced: {bad}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI guard: assert the jit-once contract, "
                         "zero-compile cache-hit admission, and the "
                         "chunked-prefill token budget")
    ap.add_argument("--json", default=None,
                    help="bank results here (default BENCH_SERVE.json "
                         "at the repo root for a full run)")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--rate", type=float, default=30.0,
                    help="Poisson arrival rate (req/s) — default keeps "
                         "~all 8 slots busy on a CPU host")
    args = ap.parse_args()

    errors = []

    if args.smoke:
        args.requests, args.max_new = 12, 12

    model = _build(max_length=args.prompt_len + args.max_new + 8)
    vocab = model.vocab_size
    reqs, arrivals = _make_requests(args.requests, args.prompt_len,
                                    args.max_new, args.rate, vocab)
    _, engine = bench_engine(model, reqs, arrivals, args.slots,
                             args.page_size)
    _check_compile_discipline("engine", engine, errors)

    result = {
        "config": {"requests": args.requests, "slots": args.slots,
                   "page_size": args.page_size,
                   "prompt_len": args.prompt_len,
                   "max_new": args.max_new, "rate_hz": args.rate,
                   "backend": os.environ.get("JAX_PLATFORMS", "cpu")},
        "engine": engine,
    }

    model9 = _build_round9(args.smoke)

    # ---- round-9: long-prompt-mixed (chunked prefill) -------------- #
    # runs FIRST after the model build: its inter-token percentiles are
    # the jitter-sensitive measurement, so it gets the quietest heap
    if args.smoke:
        lp_cfg = dict(n_short=4, short_len=8, short_new=24, n_long=1,
                      long_len=160, long_new=4, slots=4,
                      page_size=args.page_size, chunk_pages=2,
                      long_at0=0.03, repeats=1)
    else:
        # a stream of long arrivals landing while a few slots decode
        # for a long time, 8 stalls per window so a window's p99 sits
        # deep inside the stall cluster
        lp_cfg = dict(n_short=6, short_len=16, short_new=96, n_long=8,
                      long_len=224, long_new=4, slots=args.slots,
                      page_size=args.page_size, chunk_pages=4,
                      long_at0=0.15, long_gap=0.12, repeats=3)
    eng_c, longmix = bench_long_prompt_mixed(model9, **lp_cfg)
    _check_compile_discipline("long_prompt_mixed.monolithic",
                              longmix["monolithic"], errors)
    _check_compile_discipline("long_prompt_mixed.chunked",
                              longmix["chunked"], errors)
    if eng_c.max_step_prefill_tokens > eng_c.token_budget:
        errors.append(
            f"chunked prefill exceeded the per-step token budget: "
            f"{eng_c.max_step_prefill_tokens} > {eng_c.token_budget}")
    result["long_prompt_mixed"] = longmix

    # ---- round-9: shared-prefix (prefix caching) ------------------- #
    if args.smoke:
        sp_cfg = dict(personas=2, per_persona=3, prefix_len=40,
                      suffix_len=6, max_new=6, slots=4,
                      page_size=args.page_size, rate_hz=100.0)
    else:
        # long shared system prompt + short answer — the production
        # shape prefix caching targets; rate 300/s keeps the engine
        # compute-bound so tokens/s measures serving, not idle arrival
        # gaps
        sp_cfg = dict(personas=4, per_persona=6, prefix_len=224,
                      suffix_len=8, max_new=8, slots=args.slots,
                      page_size=args.page_size, rate_hz=300.0)
    eng_w, shared = bench_shared_prefix(model9, **sp_cfg)
    _check_compile_discipline("shared_prefix.cold", shared["cold"],
                              errors)
    _check_compile_discipline("shared_prefix.warm", shared["warm"],
                              errors)
    if shared["prefix_hits"] < (sp_cfg["personas"] *
                                (sp_cfg["per_persona"] - 1)) // 2:
        errors.append(f"shared_prefix: too few cache hits "
                      f"({shared['prefix_hits']}) — prefix index broken?")
    result["shared_prefix"] = shared

    # cache-hit admission on the WARM engine must compile NOTHING new:
    # every program (decode, chunk buckets, COW copy) already exists
    before = (eng_w.decode_trace_count, eng_w.prefill_trace_count,
              eng_w.copy_trace_count)
    hits_before = eng_w.prefix_hits
    import numpy as np
    from incubator_mxnet_tpu.serve import Request
    rng = np.random.RandomState(123)
    # rebuild persona heads deterministically (same seed as the workload)
    heads_rng = np.random.RandomState(7)
    heads = [heads_rng.randint(0, vocab,
                               size=(sp_cfg["prefix_len"],))
             .astype(np.int32) for _ in range(sp_cfg["personas"])]
    again = [Request(np.concatenate(
        [heads[i % sp_cfg["personas"]],
         rng.randint(0, vocab, size=(sp_cfg["suffix_len"],))
         .astype(np.int32)]), max_new_tokens=4)
        for i in range(sp_cfg["personas"])]
    eng_w.run(again)
    after = (eng_w.decode_trace_count, eng_w.prefill_trace_count,
             eng_w.copy_trace_count)
    result["shared_prefix"]["cache_hit_admission_new_programs"] = \
        sum(after) - sum(before)
    if after != before:
        errors.append(f"cache-hit admission compiled new programs: "
                      f"{before} -> {after}")
    if eng_w.prefix_hits != hits_before + len(again):
        errors.append(f"cache-hit admissions missed: "
                      f"{eng_w.prefix_hits - hits_before}/{len(again)}")

    # ---- round-10: non-finite guard overhead ----------------------- #
    # (docs/RESILIENCE.md) the guard ships ON by default — this banks
    # what it costs on the steady decode path
    if args.smoke:
        go_cfg = dict(prompt_len=args.prompt_len, max_new=10, slots=4,
                      page_size=args.page_size, n_steps=60)
    else:
        go_cfg = dict(prompt_len=args.prompt_len, max_new=args.max_new,
                      slots=args.slots, page_size=args.page_size,
                      n_steps=600)
    eng_g, guard = bench_guard_overhead(model, **go_cfg)
    for name, n in guard["decode_trace_counts"].items():
        if n != 1:
            errors.append(f"guard_overhead.{name}: decode step "
                          f"compiled {n} times (must be 1)")
        bad = {k: v for k, v in guard["prefill_trace_counts"][name]
               .items() if v != 1}
        if bad:
            errors.append(f"guard_overhead.{name}: prefill buckets "
                          f"retraced: {bad}")
    result["guard_overhead"] = guard

    # ---- baseline comparison (full runs only) ---------------------- #
    if not args.smoke:
        reqs_b, arrivals_b = _make_requests(
            args.requests, args.prompt_len, args.max_new, args.rate,
            vocab)
        baseline = bench_baseline(model, reqs_b, arrivals_b,
                                  args.max_new)
        result["baseline_cached_generate"] = baseline
        result["throughput_speedup"] = (
            engine["tokens_per_s"] / baseline["tokens_per_s"])

    print(json.dumps(result, indent=2))

    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    if not args.smoke:
        if result["throughput_speedup"] < 3.0:
            print(f"WARN: serving speedup "
                  f"{result['throughput_speedup']:.1f}x below the 3x "
                  f"bar", file=sys.stderr)
        if shared["warm_over_cold_tokens_per_s"] < 1.1:
            print(f"WARN: prefix caching won only "
                  f"{shared['warm_over_cold_tokens_per_s']:.2f}x "
                  f"tokens/s on the persona workload", file=sys.stderr)
        if longmix["itl_p99_improvement"] < 1.1:
            print(f"WARN: chunked prefill improved inter-token p99 "
                  f"only {longmix['itl_p99_improvement']:.2f}x",
                  file=sys.stderr)
        if guard["guard_overhead_pct"] >= 2.0:
            print(f"WARN: non-finite guard costs "
                  f"{guard['guard_overhead_pct']:.2f}% tokens/s — over "
                  f"the 2% leave-it-on bar", file=sys.stderr)

    out = args.json
    if out is None and not args.smoke:
        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_SERVE.json")
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        print(f"banked {out}")

    sys.exit(0 if not errors else 1)


if __name__ == "__main__":
    main()
