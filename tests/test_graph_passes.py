"""Symbol graph-pass tests (VERDICT r2 §1 L4: pass-level surface —
reference parity target: nnvm ApplyPass / graph_editor / custom-pass
plugin API)."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.symbol import apply_pass, list_passes, \
    register_pass, rewrite


def test_count_ops_analysis():
    a = mx.sym.Variable("a")
    s = mx.sym.relu(a + a) * 2.0
    counts = apply_pass(s, "CountOps")
    assert counts["null"] == 1
    assert counts["relu"] == 1


def test_eliminate_identity_preserves_values():
    a = mx.sym.Variable("a")
    s = mx.sym.relu(mx.sym.stop_gradient(a * 2.0))
    # default op set must NOT touch stop_gradient (backward semantics)
    kept = apply_pass(s, "EliminateIdentity")
    assert apply_pass(kept, "CountOps").get("BlockGrad", 0) == 1
    # explicit opt-in removes it (inference-only graphs)
    s2 = apply_pass(s, "EliminateIdentity", ops=("BlockGrad",))
    counts = apply_pass(s2, "CountOps")
    assert "BlockGrad" not in counts
    x = nd.array([[-1.0, 3.0]])
    np.testing.assert_allclose(s2.eval(a=x)[0].asnumpy(),
                               s.eval(a=x)[0].asnumpy())


def test_fold_transpose_pairs():
    a = mx.sym.Variable("a")
    s = mx.sym.relu(mx.sym.transpose(mx.sym.transpose(a, axes=(1, 0)),
                                     axes=(1, 0)))
    s2 = apply_pass(s, "FoldTransposePairs")
    assert apply_pass(s2, "CountOps").get("transpose", 0) == 0
    # double default (full reversal twice) cancels too
    t = mx.sym.transpose(mx.sym.transpose(a))
    t2 = apply_pass(t, "FoldTransposePairs")
    assert apply_pass(t2, "CountOps").get("transpose", 0) == 0
    # mixed explicit + default must NOT fold: composite depends on rank
    # (3-D counterexample: (0,2,1) then reversal = (1,2,0) != identity)
    u = mx.sym.transpose(mx.sym.transpose(a, axes=(0, 2, 1)))
    u2 = apply_pass(u, "FoldTransposePairs")
    assert apply_pass(u2, "CountOps").get("transpose", 0) == 2
    x3 = nd.array(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    np.testing.assert_allclose(u2.eval(a=x3)[0].asnumpy(),
                               u.eval(a=x3)[0].asnumpy())
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(s2.eval(a=x)[0].asnumpy(),
                               s.eval(a=x)[0].asnumpy())


def test_replace_op_pass():
    a = mx.sym.Variable("a")
    s = mx.sym.relu(a)
    s2 = apply_pass(s, "ReplaceOp", from_op="relu", to_op="sigmoid")
    x = nd.array([[0.5, -0.5]])
    np.testing.assert_allclose(
        s2.eval(a=x)[0].asnumpy(),
        1.0 / (1.0 + np.exp(-np.array([[0.5, -0.5]]))), rtol=1e-5)


def test_custom_registered_pass_and_rewrite():
    @register_pass("_test_double_scalars")
    def double_scalars(sym):
        def fn(node, new_inputs):
            if node.op == "_mul_scalar":
                attrs = dict(node.attrs)
                attrs["scalar"] = attrs["scalar"] * 2
                return (node.op, node.name, attrs, new_inputs)
            return None
        return rewrite(sym, fn)

    assert "_test_double_scalars" in list_passes()
    a = mx.sym.Variable("a")
    s = a * 3.0
    s2 = apply_pass(s, "_test_double_scalars")
    x = nd.array([2.0])
    np.testing.assert_allclose(s2.eval(a=x)[0].asnumpy(), [12.0])
    # duplicate registration is an error
    with pytest.raises(mx.MXNetError):
        register_pass("_test_double_scalars")(lambda s: s)


def test_unknown_pass_raises():
    a = mx.sym.Variable("a")
    with pytest.raises(mx.MXNetError, match="unknown pass"):
        apply_pass(a, "NoSuchPass")
