"""Summarize a jax.profiler chrome trace: top device ops + collective overlap.

Reads the `*.trace.json.gz` a `jax.profiler.trace(dir)` capture writes, keeps
only device-side events (process whose name mentions TPU/GPU/device), and
prints/writes:

  - total device-busy time over the capture window
  - top-N ops by accumulated duration
  - collective time (all-reduce / all-gather / reduce-scatter /
    collective-permute / all-to-all fusions), split into *overlapped*
    (concurrent with non-collective device work) and *exposed*

This is the 5-line perf-evidence summary BASELINE.md's measurement protocol
asks to sit next to each BENCH json (reference: upstream kept equivalent
evidence in profiler output checked by `docs/.../perf.md` instructions).

Usage: python tools/trace_summary.py TRACE_DIR [-o SUMMARY.md]
"""

import argparse
import glob
import gzip
import json
import os
import re
from collections import Counter

COLLECTIVE_MARKERS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all", "allreduce", "allgather",
)


def _find_trace_file(trace_dir):
    hits = sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not hits:
        raise FileNotFoundError(f"no *.trace.json.gz under {trace_dir}")
    return hits[-1]


# HLO instruction names as XLA:CPU's thunk tracer emits them (`dot.1`,
# `fusion.3`, `all-reduce`); excludes runtime/python infra lanes sharing
# the same threads (`Rendezvous`, `Wait: ...`, `$array.py:297 __float__`)
_HLO_NAME_RE = re.compile(r"^[a-z][a-z0-9._-]*$")


def _device_op_lanes(events):
    """((pid, tid) pairs for per-op device lanes, cpu_mode flag).

    TPU/GPU: the profiler emits one process per device with lanes `Steps`,
    `XLA Modules`, `XLA Ops`, `Async XLA Ops`, ... — the module lane wraps
    the whole step (counting it would double every op and make overlap
    trivially 100%), so keep only the op-level lanes.

    CPU (virtual host mesh): there is a single `/host:CPU` process whose
    `tf_XLAPjRtCpuClient/*` threadpool lanes carry the HLO thunk events
    for ALL virtual devices; cpu_mode tells the caller to filter those
    lanes down to HLO-named events.
    """
    dev_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = (e.get("args") or {}).get("name", "")
            if any(k in name.lower() for k in ("tpu", "gpu", "/device:")):
                dev_pids.add(e.get("pid"))
    lanes = set()
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "thread_name"
                and e.get("pid") in dev_pids):
            lane = (e.get("args") or {}).get("name", "")
            if "ops" in lane.lower() or "overlay" in lane.lower():
                lanes.add((e.get("pid"), e.get("tid")))
    if lanes:
        return lanes, False
    cpu_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            if "/host:cpu" in (e.get("args") or {}).get("name", "").lower():
                cpu_pids.add(e.get("pid"))
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "thread_name"
                and e.get("pid") in cpu_pids):
            lane = (e.get("args") or {}).get("name", "")
            # XLA:CPU client threadpool lane names vary by jax/xla
            # version: tf_XLAPjRtCpuClient/…, tf_XLATfrtCpuClient/…
            if lane.startswith("tf_XLA") and "CpuClient" in lane:
                lanes.add((e.get("pid"), e.get("tid")))
    return lanes, True


def _merge_intervals(spans):
    """Union of [start, end) intervals; returns merged list + total length."""
    if not spans:
        return [], 0.0
    spans = sorted(spans)
    merged = [list(spans[0])]
    for s, t in spans[1:]:
        if s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], t)
        else:
            merged.append([s, t])
    return merged, sum(t - s for s, t in merged)


def _overlap_len(spans, merged_other):
    """Total length of `spans` covered by the merged interval set."""
    total = 0.0
    import bisect
    starts = [s for s, _ in merged_other]
    for s, t in spans:
        i = bisect.bisect_right(starts, s) - 1
        i = max(i, 0)
        while i < len(merged_other) and merged_other[i][0] < t:
            os_, ot = merged_other[i]
            lo, hi = max(s, os_), min(t, ot)
            if hi > lo:
                total += hi - lo
            i += 1
    return total


def _scope_family(args_dict, hlo_name):
    """Human attribution for a device op: the innermost named jit scope
    from the op's tf_op metadata, tagged fwd/bwd (the AD-transpose
    transform marks backward ops), falling back to the HLO base name.
    This is what turns `transpose_jvp_jit__flash_backward___.5` into
    `_flash_backward [bwd]` in the scope table."""
    scope = (args_dict or {}).get("tf_op") or ""
    fns = re.findall(r"jit\(([A-Za-z_][\w.]*)\)", scope)
    fns = [f for f in fns if f not in ("step", "train_step", "main")]
    direction = " [bwd]" if "transpose(" in scope else ""
    if fns:
        return fns[-1] + direction
    base = re.sub(r"\.\d+$", "", hlo_name)
    return base + direction


def overlap_stats(trace_dir):
    """Machine-readable per-device-lane overlap split (the same lane
    attribution as ``summarize``): total compute/collective busy time,
    the collective time overlapped with the SAME lane's compute, and
    the wall-clock window. This is the hook tools/step_bench.py --mfu
    uses to bank an ``overlap_ratio`` next to each arm's MFU, and what
    the MFU section below feeds on (round 16, docs/TRAINING_PERF.md)."""
    path = _find_trace_file(trace_dir)
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    lanes, cpu_mode = _device_op_lanes(events)
    coll_by_dev, compute_by_dev = {}, {}
    t_min, t_max = float("inf"), float("-inf")
    for e in events:
        if e.get("ph") != "X" or (e.get("pid"), e.get("tid")) not in lanes:
            continue
        name, ts, dur = e.get("name", "?"), e.get("ts"), e.get("dur")
        if ts is None or dur is None:
            continue
        if cpu_mode and not _HLO_NAME_RE.match(name):
            continue
        t_min, t_max = min(t_min, ts), max(t_max, ts + dur)
        span = (ts, ts + dur)
        if cpu_mode:
            dev = (e.get("args") or {}).get("device_ordinal")
            pid = ("vdev", dev)
        else:
            pid = e.get("pid")
        if any(m in name.lower() for m in COLLECTIVE_MARKERS):
            coll_by_dev.setdefault(pid, []).append(span)
        else:
            compute_by_dev.setdefault(pid, []).append(span)
    # events with no device attribution cannot join a per-lane split —
    # unless NOTHING is attributed (older XLA:CPU emits no
    # device_ordinal), where the whole pool degrades to one lane and
    # the split is a pool-level UPPER BOUND on overlap (flagged)
    unattr_coll = coll_by_dev.pop(("vdev", None), None)
    unattr_comp = compute_by_dev.pop(("vdev", None), None)
    attribution = "per-lane"
    if not coll_by_dev and not compute_by_dev and (unattr_coll or
                                                   unattr_comp):
        attribution = "pool-upper-bound"
        if unattr_coll:
            coll_by_dev[("pool", 0)] = unattr_coll
        if unattr_comp:
            compute_by_dev[("pool", 0)] = unattr_comp
    busy_compute = busy_coll = overlapped = 0.0
    for pid, spans in compute_by_dev.items():
        _, b = _merge_intervals(spans)
        busy_compute += b
    for pid, spans in coll_by_dev.items():
        merged_c, b = _merge_intervals(spans)
        busy_coll += b
        merged_compute, _ = _merge_intervals(compute_by_dev.get(pid, []))
        overlapped += _overlap_len(merged_c, merged_compute)
    n_dev = len(set(coll_by_dev) | set(compute_by_dev))
    window = (t_max - t_min) if t_max > t_min else 0.0
    return {
        "cpu_mode": cpu_mode,
        "attribution": attribution,
        "n_device_lanes": n_dev,
        "window_us": window,
        "compute_us": busy_compute,
        "collective_us": busy_coll,
        "overlapped_us": overlapped,
        "exposed_us": busy_coll - overlapped,
        "overlap_ratio": (overlapped / busy_coll) if busy_coll else None,
    }


def remat_recipe(trace_dir, num_blocks):
    """Profile-driven remat plan for the pipelined step (round 19,
    docs/TRAINING_PERF.md): feed the per-lane overlap split of a real
    capture into ``models._remat.plan_remat_from_profile`` and return
    ``{"stats": ..., "remat_plan": [...]}`` — the list goes verbatim to
    ``SPMDTrainer(remat_plan=...)``. The heuristic keys on the EXPOSED
    fraction: hidden collectives → no remat; mild exposure → "dots"
    everywhere; heavy exposure → full remat on the earliest blocks
    (they backward last, exactly when the deep buckets drain)."""
    from incubator_mxnet_tpu.models._remat import plan_remat_from_profile
    stats = overlap_stats(trace_dir)
    return {"stats": stats,
            "remat_plan": plan_remat_from_profile(stats, num_blocks)}


def mfu_section(trace_dir, step_flops, n_steps=1, peak_flops=None):
    """Markdown MFU block from a capture of ``n_steps`` training steps
    whose analytic cost is ``step_flops`` each (utils/flops.py
    formulas). Two MFU readings are reported: against device-BUSY time
    (kernel efficiency) and against the WALL window (the honest number
    — dispatch gaps and exposed collectives count against it)."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from incubator_mxnet_tpu.utils.flops import peak_flops_per_device

    st = overlap_stats(trace_dir)
    peak = ({"flops": float(peak_flops), "source": "arg",
             "device_kind": "?"} if peak_flops
            else peak_flops_per_device())
    n_dev = max(st["n_device_lanes"], 1)
    total_flops = step_flops * n_steps
    lines = ["", "## MFU (analytic model FLOPs / hardware peak)", ""]
    # both denominators are AGGREGATE lane-time (device-seconds summed
    # over lanes): busy time is per-lane sums, and the wall window is
    # multiplied out to window × n_dev — so the per-device rate is
    # total_flops / aggregate_seconds, with NO further /n_dev (that
    # would understate MFU by another factor of n_dev)
    for label, us in (("device-busy",
                       st["compute_us"] + st["collective_us"]),
                      ("wall-window", st["window_us"] * n_dev)):
        if us <= 0:
            continue
        achieved = total_flops / (us * 1e-6)
        lines.append(
            f"- {label}: {achieved / 1e9:.2f} GFLOP/s/device over "
            f"{n_dev} lane(s) = **{100 * achieved / peak['flops']:.1f}%"
            f" MFU** (peak {peak['flops'] / 1e9:.0f} GFLOP/s,"
            f" {peak['source']})")
    if st["overlap_ratio"] is not None:
        lines.append(
            f"- collectives: {st['collective_us'] / 1e3:.2f} ms, "
            f"{100 * st['overlap_ratio']:.0f}% overlapped with the "
            f"owning lane's compute, "
            f"{st['exposed_us'] / 1e3:.2f} ms exposed")
    if st["cpu_mode"]:
        lines.append(
            "- CPU-backend caveat: peak is a measured large-matmul "
            "proxy, so MFU here is a RELATIVE regression number, not "
            "a hardware-utilization claim (docs/TRAINING_PERF.md)")
    return "\n".join(lines) + "\n"


def summarize(trace_dir, top=12):
    path = _find_trace_file(trace_dir)
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    lanes, cpu_mode = _device_op_lanes(events)

    per_scope = Counter()
    scope_count = Counter()
    per_op = Counter()
    per_module = Counter()
    module_count = Counter()
    coll_by_dev, compute_by_dev = {}, {}
    t_min, t_max = float("inf"), float("-inf")
    has_dev_ordinal = False
    for e in events:
        if e.get("ph") != "X" or (e.get("pid"), e.get("tid")) not in lanes:
            continue
        name, ts, dur = e.get("name", "?"), e.get("ts"), e.get("dur")
        if ts is None or dur is None:
            continue
        if cpu_mode and not _HLO_NAME_RE.match(name):
            continue
        per_op[name] += dur
        if not cpu_mode:  # CPU thunk events carry no tf_op scope metadata
            fam = _scope_family(e.get("args"), name)
            per_scope[fam] += dur
            scope_count[fam] += 1
        t_min, t_max = min(t_min, ts), max(t_max, ts + dur)
        span = (ts, ts + dur)
        if cpu_mode:
            # virtual host mesh: the threadpool is shared, but each thunk
            # event names its VIRTUAL device (device_ordinal) and program
            # (run_id/hlo_module) — attribute spans per virtual device
            # lane so the overlap split is per-device, not pool-level
            args = e.get("args") or {}
            dev = args.get("device_ordinal")
            if dev is not None:
                has_dev_ordinal = True
            pid = ("vdev", dev)
            mod = args.get("hlo_module")
            if mod is not None:
                per_module[mod] += dur
                module_count[mod] += 1
        else:
            pid = e.get("pid")
        if any(m in name.lower() for m in COLLECTIVE_MARKERS):
            coll_by_dev.setdefault(pid, []).append(span)
        else:
            compute_by_dev.setdefault(pid, []).append(span)

    if not per_op:
        return f"# Trace summary\n\nNo device events found in {path}\n"

    n_dev = len(set(coll_by_dev) | set(compute_by_dev))
    lines = [
        "# Trace summary",
        "",
        f"- source: `{os.path.relpath(path)}`",
    ]
    if cpu_mode and has_dev_ordinal:
        # virtual host mesh WITH per-thunk device attribution
        # (device_ordinal): compute the overlap split PER VIRTUAL DEVICE
        # lane, exactly like the hardware branch — a collective on
        # virtual device 4 counts as overlapped only when device 4
        # itself computes concurrently. This replaces the old pool-level
        # upper bound (VERDICT r5 item 5). Events WITHOUT a
        # device_ordinal must not masquerade as a lane (their spans from
        # different devices would interleave pool-style): pull them out
        # and report them separately.
        unattr_spans = coll_by_dev.pop(("vdev", None), []) + \
            compute_by_dev.pop(("vdev", None), [])
        n_dev = len(set(coll_by_dev) | set(compute_by_dev))
        busy_compute = busy_coll = overlapped = 0.0
        for pid, spans in compute_by_dev.items():
            _, b = _merge_intervals(spans)
            busy_compute += b
        for pid, spans in coll_by_dev.items():
            merged_c, b = _merge_intervals(spans)
            busy_coll += b
            merged_compute, _ = _merge_intervals(
                compute_by_dev.get(pid, []))
            overlapped += _overlap_len(merged_c, merged_compute)
        exposed = busy_coll - overlapped
        window = t_max - t_min
        lines += [
            "- **virtual host-mesh capture** (XLA:CPU): all virtual"
            " devices share one `/host:CPU` threadpool, but each thunk"
            " event names its virtual device (`device_ordinal`), so the"
            " overlap split below is PER DEVICE LANE — a collective"
            " counts as overlapped only when its own lane computes"
            " concurrently (pool-level interleaving no longer inflates"
            " it). Lane concurrency is still bounded by host cores, so"
            " absolute times are not TPU-predictive; the split is.",
            f"- capture window: {window / 1e3:.1f} ms wall-clock,"
            f" {n_dev} virtual device lane(s); device work — compute:"
            f" {busy_compute / 1e3:.1f} ms, collectives:"
            f" {busy_coll / 1e3:.2f} ms"
            f" ({100 * busy_coll / (busy_coll + busy_compute):.0f}% of"
            f" device work)",
            f"- collective time by lane: {busy_coll / 1e3:.2f} ms —"
            f" overlapped with that lane's compute:"
            f" {overlapped / 1e3:.2f} ms"
            f" ({(100 * overlapped / busy_coll) if busy_coll else 0:.0f}%),"
            f" exposed (lane idle but for the collective):"
            f" {exposed / 1e3:.2f} ms",
        ]
        if unattr_spans:
            unattr = sum(t - s for s, t in unattr_spans)
            lines.append(
                f"- {unattr / 1e3:.2f} ms of thunk work carried no"
                f" device_ordinal and is excluded from the per-lane"
                f" split above")
        lines.append("")
    elif cpu_mode:
        # One pid covers all virtual devices and concurrent spans from
        # different devices would collapse in an interval union, so
        # report device-WORK as raw sums (matching the op tables) and
        # use wall-clock interval algebra only for the interleaving
        # question: while a collective was in flight, was the pool also
        # computing?
        all_coll = [s for spans in coll_by_dev.values() for s in spans]
        all_comp = [s for spans in compute_by_dev.values() for s in spans]
        work_coll = sum(t - s for s, t in all_coll)
        work_comp = sum(t - s for s, t in all_comp)
        merged_c, wall_coll = _merge_intervals(all_coll)
        merged_comp, _ = _merge_intervals(all_comp)
        wall_overlap = _overlap_len(merged_c, merged_comp)
        wall_exposed = wall_coll - wall_overlap
        window = t_max - t_min
        lines += [
            "- **virtual host-mesh capture** (XLA:CPU, no per-thunk"
            " device attribution): all virtual devices share one"
            " `/host:CPU` threadpool; device-work numbers are raw"
            " per-op sums, the overlap split is wall-clock pool-level"
            " interleaving (an upper bound on per-device overlap).",
            f"- capture window: {window / 1e3:.1f} ms wall-clock,"
            f" {n_dev} trace process(es); device work — compute:"
            f" {work_comp / 1e3:.1f} ms, collectives:"
            f" {work_coll / 1e3:.2f} ms"
            f" ({100 * work_coll / (work_coll + work_comp):.0f}% of"
            f" device work)",
            f"- wall-clock with a collective in flight:"
            f" {wall_coll / 1e3:.2f} ms — concurrent with compute:"
            f" {wall_overlap / 1e3:.2f} ms"
            f" ({(100 * wall_overlap / wall_coll) if wall_coll else 0:.0f}%),"
            f" exposed (nothing but collectives running):"
            f" {wall_exposed / 1e3:.2f} ms",
            "",
        ]
    else:
        # overlap accounting is PER DEVICE (pid): a collective on chip 0
        # is only "overlapped" if chip 0 itself computes concurrently
        busy_compute = busy_coll = overlapped = 0.0
        for pid, spans in compute_by_dev.items():
            _, b = _merge_intervals(spans)
            busy_compute += b
        for pid, spans in coll_by_dev.items():
            merged_c, b = _merge_intervals(spans)
            busy_coll += b
            merged_compute, _ = _merge_intervals(
                compute_by_dev.get(pid, []))
            overlapped += _overlap_len(merged_c, merged_compute)
        exposed = busy_coll - overlapped
        window = (t_max - t_min) * max(n_dev, 1)  # device-seconds
        lines += [
            f"- capture window: {window / 1e3:.1f} device-ms across "
            f"{n_dev} device(s); busy (non-collective compute): "
            f"{busy_compute / 1e3:.1f} ms"
            f" ({100 * busy_compute / window:.1f}% of window)",
            f"- collective time: {busy_coll / 1e3:.2f} ms — overlapped"
            f" with compute: {overlapped / 1e3:.2f} ms"
            f" ({(100 * overlapped / busy_coll) if busy_coll else 0:.0f}%),"
            f" exposed: {exposed / 1e3:.2f} ms",
        ]
    lines += [
        "",
        f"Top {top} op families by accumulated time (per-layer clones like"
        " `fusion.N` grouped by base name):",
        "",
        "| op family | instances | total ms | % of busy |",
        "|---|---|---|---|",
    ]
    # On a host-mesh capture the virtual devices' ops run concurrently
    # across one threadpool, so raw per-op sums exceed the pool-merged
    # busy time; normalize shares by total device-work instead.
    total_busy = (sum(per_op.values()) if cpu_mode
                  else busy_compute + busy_coll)
    family = Counter()
    fam_count = Counter()
    for name, dur in per_op.items():
        base = re.sub(r"\.\d+$", "", name)
        family[base] += dur
        fam_count[base] += 1
    for name, dur in family.most_common(top):
        lines.append(
            f"| `{name[:70]}` | {fam_count[name]} | {dur / 1e3:.2f} | "
            f"{100 * dur / total_busy:.1f}% |")
    if not cpu_mode:  # CPU thunk events carry no tf_op scope metadata
        lines += ["", f"Top {top} source scopes (innermost named jit"
                  " scope from op metadata; [bwd] = under the"
                  " AD-transpose transform):", "",
                  "| scope | instances | total ms | % of busy |",
                  "|---|---|---|---|"]
        for name, dur in per_scope.most_common(top):
            lines.append(
                f"| `{name[:70]}` | {scope_count[name]} | "
                f"{dur / 1e3:.2f} | {100 * dur / total_busy:.1f}% |")
    elif per_module:
        lines += ["", "Device work per compiled program (hlo_module"
                  " from thunk metadata):", "",
                  "| program | instances | total ms | % of busy |",
                  "|---|---|---|---|"]
        for name, dur in per_module.most_common(top):
            lines.append(
                f"| `{name[:70]}` | {module_count[name]} | "
                f"{dur / 1e3:.2f} | {100 * dur / total_busy:.1f}% |")
    lines += ["", f"Top {top} individual ops:", "",
              "| op | total ms | % of busy |", "|---|---|---|"]
    for name, dur in per_op.most_common(top):
        lines.append(
            f"| `{name[:80]}` | {dur / 1e3:.2f} | "
            f"{100 * dur / total_busy:.1f}% |")
    return "\n".join(lines) + "\n"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("-o", "--out", default=None,
                    help="write the summary markdown here (default: stdout)")
    ap.add_argument("--top", type=int, default=12)
    ap.add_argument("--step-flops", type=float, default=None,
                    help="analytic model FLOPs per training step "
                         "(utils/flops.py) — appends an MFU section")
    ap.add_argument("--steps", type=int, default=1,
                    help="training steps inside the capture window")
    ap.add_argument("--peak-flops", type=float, default=None,
                    help="per-device peak FLOPs override (default: TPU "
                         "datasheet by device_kind, CPU measured proxy)")
    ap.add_argument("--remat-blocks", type=int, default=None,
                    help="number of pipeline blocks — appends the "
                         "profile-driven remat plan recipe for "
                         "SPMDTrainer(remat_plan=...)")
    args = ap.parse_args()
    md = summarize(args.trace_dir, top=args.top)
    if args.step_flops:
        md += mfu_section(args.trace_dir, args.step_flops,
                          n_steps=args.steps, peak_flops=args.peak_flops)
    if args.remat_blocks:
        rec = remat_recipe(args.trace_dir, args.remat_blocks)
        md += ("\n## Remat recipe (profile-driven)\n\n"
               f"exposed/compute = {rec['stats']['exposed_us']:.0f}/"
               f"{rec['stats']['compute_us']:.0f} us -> "
               f"`remat_plan={rec['remat_plan']!r}`\n")
    if args.out:
        with open(args.out, "w") as f:
            f.write(md)
        print(f"wrote {args.out}")
    else:
        print(md)


if __name__ == "__main__":
    main()
